"""Pallas TPU kernels for the container hot path.

The single hottest computation in the reference is the wide aggregation fold:
OR/AND/XOR 1024-word containers together, then popcount
(FastAggregation.java:541-602; BitmapContainer.java:657-678). Here it is one
Pallas kernel: a grid over row-tiles of the packed ``[N, 2048]`` uint32
container array, OR-accumulating into a VMEM output block that stays resident
across grid steps (TPU grids execute sequentially, so the output block is a
legal accumulator).

Mosaic (the Pallas TPU lowering) requires that the last two dimensions of
every block shape be divisible by (8, 128) respectively — or equal to the
corresponding overall array dimension. The grouped kernel therefore pads the
group axis up to a multiple of ``G_TILE=8`` and emits ``(8, 2048)`` output
blocks; block layouts are built by the testable ``wide_plan``/``grouped_plan``
helpers, and ``mosaic_block_ok`` encodes the rule so the suite can verify
every spec without TPU hardware (tests/test_device_ops.py).

Dispatch (``best_wide_reduce`` / ``best_grouped_reduce``) probes the kernel
once per (kind, op, shape) on the active backend and falls back to the XLA
reduction (ops/device.py) if lowering or execution fails, so an invalid
kernel can never take down a caller. Counters record which path served each
call (insights.dispatch_counters).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from . import device as dev
from .. import observe as _observe
from ..observe import compilewatch as _compilewatch

try:  # pallas is optional at import time (e.g. stripped CPU envs)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAS_PALLAS = True
except Exception:  # pragma: no cover  # rb-ok: exception-hygiene -- optional-dep probe: any import-time failure mode (stripped build, ABI skew) must mean "no pallas", never a crash
    HAS_PALLAS = False


def supports_dimension_semantics() -> bool:
    """Capability probe: does this jaxlib's pallas expose the Mosaic
    grid-dimension-semantics hint (``GridDimensionSemantics`` +
    ``CompilerParams``)? The ``dimsem`` kernel variants require it; callers
    (and the tier-1 variant tests) probe instead of crashing on older
    toolchains."""
    return HAS_PALLAS and hasattr(pltpu, "GridDimensionSemantics") and hasattr(
        pltpu, "CompilerParams"
    )

# VMEM is ~16 MiB/core on v5e. Wide blocks: ROW_TILE*2048*4 = 2 MiB.
# Grouped blocks: G_TILE*G_ROW_TILE*2048*4 = 4 MiB (double-buffered: 8 MiB).
ROW_TILE = 256
G_TILE = 8  # groups per grid step; Mosaic needs the second-minor block dim % 8 == 0
G_ROW_TILE = 64

# dispatch observability: ("wide"|"grouped"|..., "pallas"|"xla"|...) -> count.
# Registry-backed (rb_tpu_kernel_dispatch_total) since ISSUE 1; this module
# increments the metric directly, the CounterMap keeps the legacy mapping
# interface for insights.dispatch_counters() and external readers.
_DISPATCH_TOTAL = _observe.counter(
    _observe.KERNEL_DISPATCH_TOTAL,
    "Device aggregation dispatches by (kind, engine)",
    ("kind", "engine"),
)
DISPATCH_COUNTS = _observe.CounterMap(_DISPATCH_TOTAL)
# per-(kind, op, backend) probe conclusions; shape detail stays in _PROBED
_PROBE_TOTAL = _observe.counter(
    _observe.KERNEL_PROBE_TOTAL,
    "Pallas lowering-probe conclusions by (kind, op, backend, outcome)",
    ("kind", "op", "backend", "outcome"),
)
# lowering probe results: (kind, op, shape, backend) -> bool
_PROBED: Dict[Tuple, bool] = {}


# ---------------------------------------------------------------------------
# Mosaic block legality + kernel plans (hardware-independent, unit-tested)
# ---------------------------------------------------------------------------


def mosaic_block_ok(block_shape, array_shape, memory_space: str = "vmem") -> bool:
    """Mosaic's TPU block-mapping rule: the last two dims of a block shape
    must be divisible by (8, 128) respectively, or equal the corresponding
    overall array dim. (The round-2 BENCH crash was a (1, 2048) output block
    over a [66, 2048] array violating exactly this.)

    ``memory_space="smem"`` additionally rejects *blocked* 1-D SMEM
    operands: a 1-D s32[n] SMEM operand whose block is a strict slice of
    the array passed this divisibility rule yet failed on real hardware
    with an XLA(T(1024)) vs Mosaic(T(128)) tiled-layout mismatch (the
    round-3 segmented-scan saga, BENCH_NOTES.md). SMEM operands must be
    whole-array (block == array) — stream via program_id indexing inside
    the kernel instead, as seg_plan's bit-packed flags do."""
    if len(block_shape) != len(array_shape):
        return False
    if len(block_shape) == 0:
        return True
    if len(block_shape) == 1:
        if memory_space == "smem":
            return tuple(block_shape) == tuple(array_shape)
        return block_shape[0] % 128 == 0 or block_shape[0] == array_shape[0]
    bs, bl = block_shape[-2], block_shape[-1]
    as_, al = array_shape[-2], array_shape[-1]
    return (bs % 8 == 0 or bs == as_) and (bl % 128 == 0 or bl == al)


def _check_w_tile(w_tile: int, w: int) -> None:
    """A word-axis split must both divide the width and stay Mosaic-legal
    as a block minor dim (% 128 — the round-2 crash class; catching it here
    costs nothing, catching it on chip costs minutes of remote compile)."""
    if w % w_tile:
        raise ValueError(f"w_tile {w_tile} must divide the word width {w}")
    if w_tile % 128:
        raise ValueError(f"w_tile {w_tile} must be a multiple of 128 (Mosaic minor dim)")


def wide_plan(n: int, w: int, row_tile: int = ROW_TILE, w_tile: int | None = None):
    """Block layout for the flat [N, w] -> [w] reduction.

    ``w_tile`` splits the word axis into an extra *outer* (parallel) grid
    dim: smaller blocks pipeline DMA better on shapes where the one-column
    grid stalls (the wide family's measured ~58 GB/s plateau, BENCH_NOTES)."""
    n_pad = n + (-n) % row_tile
    if w_tile is None or w_tile >= w:
        return {
            "pad_rows": n_pad - n,
            "grid": (n_pad // row_tile,),
            "in_array": (n_pad, w),
            "in_block": (row_tile, w),
            "in_index": lambda i: (i, 0),
            "out_array": (1, w),
            "out_block": (1, w),  # block == array: legal by the full-dim clause
            "out_index": lambda i: (0, 0),
            "m_dim": 0,
        }
    _check_w_tile(w_tile, w)
    return {
        "pad_rows": n_pad - n,
        "grid": (w // w_tile, n_pad // row_tile),  # N innermost: accumulator
        "in_array": (n_pad, w),
        "in_block": (row_tile, w_tile),
        "in_index": lambda wi, ni: (ni, wi),
        "out_array": (1, w),
        "out_block": (1, w_tile),
        "out_index": lambda wi, ni: (0, wi),
        "m_dim": 1,
    }


def grouped_plan(
    g: int,
    m: int,
    w: int,
    g_tile: int = G_TILE,
    row_tile: int = G_ROW_TILE,
    w_tile: int | None = None,
):
    """Block layout for the padded grouped [G, M, w] -> [G, w] reduction.

    The group axis is padded to a multiple of ``g_tile`` (8) so the output
    block (g_tile, w) satisfies Mosaic divisibility for any G; the M axis is
    innermost in the grid so each group-tile's output block stays resident
    in VMEM as the accumulator across its row tiles.

    ``w_tile`` adds a word-axis grid dim between G and M (both outer dims
    are embarrassingly parallel; only M carries the accumulator), shrinking
    each block by w/w_tile — staged against the measured 3x XLA gap at the
    flagship [66, 1450, 2048] shape (VERDICT r3 #2: smaller double-buffered
    blocks may pipeline HBM reads where the full-width grid could not)."""
    g_pad = g + (-g) % g_tile
    m_pad = m + (-m) % row_tile
    if w_tile is None or w_tile >= w:
        return {
            "pad_groups": g_pad - g,
            "pad_rows": m_pad - m,
            "grid": (g_pad // g_tile, m_pad // row_tile),
            "in_array": (g_pad, m_pad, w),
            "in_block": (g_tile, row_tile, w),
            "in_index": lambda gi, mi: (gi, mi, 0),
            "out_array": (g_pad, w),
            "out_block": (g_tile, w),
            "out_index": lambda gi, mi: (gi, 0),
            "m_dim": 1,
        }
    _check_w_tile(w_tile, w)
    return {
        "pad_groups": g_pad - g,
        "pad_rows": m_pad - m,
        "grid": (g_pad // g_tile, w // w_tile, m_pad // row_tile),
        "in_array": (g_pad, m_pad, w),
        "in_block": (g_tile, row_tile, w_tile),
        "in_index": lambda gi, wi, mi: (gi, mi, wi),
        "out_array": (g_pad, w),
        "out_block": (g_tile, w_tile),
        "out_index": lambda gi, wi, mi: (gi, wi),
        "m_dim": 2,
    }


def plan_ok(plan) -> bool:
    return mosaic_block_ok(plan["in_block"], plan["in_array"]) and mosaic_block_ok(
        plan["out_block"], plan["out_array"]
    )


def _fold_axis(x, op, axis: int):
    """Logarithmic fold along one axis of a static, power-of-two-sized block."""
    n = x.shape[axis]
    if n & (n - 1):
        # halving with x[:half] op x[half:2*half] silently drops the tail of
        # an odd-length level; tiles are padded to the tile size, so this is
        # purely a bad row_tile/g_tile argument
        raise ValueError(f"tile size {n} must be a power of two")
    while n > 1:
        half = n // 2
        lo = lax.slice_in_dim(x, 0, half, axis=axis)
        hi = lax.slice_in_dim(x, half, 2 * half, axis=axis)
        x = op(lo, hi)
        n = half
    return lax.squeeze(x, (axis,))


def _make_wide_kernel(op, m_dim: int = 0, fold: str = "log"):
    # seed_ref: SMEM (1,) uint32 XOR'd into every loaded word — the fused
    # input-perturbation hook (production passes 0; steady-state timing
    # passes a carry-dependent 0 so XLA cannot hoist the loop body).
    # m_dim: which grid dim walks the reduced (N) axis — 0 for the classic
    # one-column grid, 1 when wide_plan splits the word axis.
    def kernel(seed_ref, x_ref, o_ref):
        i = pl.program_id(m_dim)
        x = x_ref[...] ^ seed_ref[0]
        if fold == "linear":
            tile = x[0]
            for r in range(1, x.shape[0]):
                tile = op(tile, x[r])
        else:
            tile = _fold_axis(x, op, axis=0)

        @pl.when(i == 0)
        def _init():
            o_ref[0, :] = tile

        @pl.when(i != 0)
        def _acc():
            o_ref[0, :] = op(o_ref[0, :], tile)

    return kernel


def _make_grouped_kernel(op, fold: str = "log", m_dim: int = 1):
    # fold="log": halving fold (log2(row_tile) vector ops over shrinking
    # temporaries). fold="linear": straight accumulate (row_tile-1 ops, no
    # temporaries) — staged to measure whether the log-fold's VMEM
    # temporaries are what keeps the Pallas grid behind XLA's reduce
    # (BENCH_NOTES per-tile table: 137 vs 423 GB/s at the flagship shape).
    # m_dim: which grid dim walks the reduced (M) axis — 1 for the classic
    # (G, M) grid, 2 when grouped_plan splits the word axis into (G, W, M).
    def kernel(seed_ref, x_ref, o_ref):
        mi = pl.program_id(m_dim)
        x = x_ref[...] ^ seed_ref[0]
        if fold == "linear":
            tile = x[:, 0]
            for r in range(1, x.shape[1]):
                tile = op(tile, x[:, r])
        else:
            tile = _fold_axis(x, op, axis=1)  # [G_TILE, w]

        @pl.when(mi == 0)
        def _init():
            o_ref[...] = tile

        @pl.when(mi != 0)
        def _acc():
            o_ref[...] = op(o_ref[...], tile)

    return kernel


def _grid_compiler_params(plan, dimsem: bool):
    """Optional Mosaic dimension-semantics hint: every grid dim except the
    reduced (accumulator-carrying) one is embarrassingly parallel — output
    blocks at different positions are disjoint. Staged as an opt-in so the
    round-3-validated default lowering is untouched until the sweep measures
    it (VERDICT r3 #2)."""
    if not dimsem:
        return None
    sem = [
        pltpu.GridDimensionSemantics.ARBITRARY
        if d == plan["m_dim"]
        else pltpu.GridDimensionSemantics.PARALLEL
        for d in range(len(plan["grid"]))
    ]
    return pltpu.CompilerParams(dimension_semantics=sem)


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit, static_argnames=("op", "interpret", "row_tile", "w_tile", "fold", "dimsem")
)
@_compilewatch.tracked("wide_reduce_pallas")
def wide_reduce_pallas(
    words,
    op: str = "or",
    interpret: bool = False,
    row_tile: int = ROW_TILE,
    seed=None,
    w_tile: int | None = None,
    fold: str = "log",
    dimsem: bool = False,
):
    """Reduce ``[N, 2048]`` uint32 -> ``[2048]`` with a Pallas kernel.

    Pads N up to a row_tile multiple with the op identity so every grid step
    sees a full block. ``seed`` (uint32 scalar, runtime value must be 0) is
    the steady-state-timing hook: it is XOR'd into every loaded word inside
    the kernel, making a timing loop's body carry-dependent without an extra
    HBM pass (padded rows are perturbed too, so a nonzero seed would break
    and/xor identity padding — hence the must-be-0 contract).

    ``w_tile``/``fold``/``dimsem`` are the sweep-staged variants (wide_plan,
    _make_wide_kernel, _grid_compiler_params)."""
    if fold not in ("log", "linear"):
        raise ValueError(f"fold must be 'log' or 'linear', got {fold!r}")
    fn = {"or": lax.bitwise_or, "and": lax.bitwise_and, "xor": lax.bitwise_xor}[op]
    n, w = words.shape
    plan = wide_plan(n, w, row_tile, w_tile)
    if plan["pad_rows"]:
        words = jnp.pad(
            words, ((0, plan["pad_rows"]), (0, 0)), constant_values=dev._INIT[op]
        )
    if seed is None:
        seed = jnp.uint32(0)
    out = pl.pallas_call(
        _make_wide_kernel(fn, m_dim=plan["m_dim"], fold=fold),
        out_shape=jax.ShapeDtypeStruct(plan["out_array"], words.dtype),
        grid=plan["grid"],
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(plan["in_block"], plan["in_index"], memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            plan["out_block"], plan["out_index"], memory_space=pltpu.VMEM
        ),
        compiler_params=_grid_compiler_params(plan, dimsem),
        interpret=interpret,
    )(jnp.reshape(seed.astype(words.dtype), (1,)), words)
    return out[0]


@functools.partial(
    jax.jit, static_argnames=("op", "interpret", "row_tile", "w_tile", "fold", "dimsem")
)
@_compilewatch.tracked("wide_reduce_cardinality_pallas")
def wide_reduce_cardinality_pallas(
    words,
    op: str = "or",
    interpret: bool = False,
    row_tile: int = ROW_TILE,
    seed=None,
    w_tile: int | None = None,
    fold: str = "log",
    dimsem: bool = False,
):
    """Fused wide reduce + cardinality (popcount of the reduced row)."""
    red = wide_reduce_pallas(
        words,
        op=op,
        interpret=interpret,
        row_tile=row_tile,
        seed=seed,
        w_tile=w_tile,
        fold=fold,
        dimsem=dimsem,
    )
    card = jnp.sum(lax.population_count(red).astype(jnp.int32))
    return red, card


@functools.partial(
    jax.jit,
    static_argnames=("op", "interpret", "g_tile", "row_tile", "fold", "w_tile", "dimsem"),
)
@_compilewatch.tracked("grouped_reduce_pallas")
def grouped_reduce_pallas(
    words3,
    op: str = "or",
    interpret: bool = False,
    g_tile: int = G_TILE,
    row_tile: int = G_ROW_TILE,
    seed=None,
    fold: str = "log",
    w_tile: int | None = None,
    dimsem: bool = False,
):
    """Padded grouped reduce ``[G, M, 2048] -> [G, 2048]`` as one kernel.

    Grid is (G-tiles, M-tiles) with the M axis innermost, so for each tile of
    g_tile groups the output block stays resident in VMEM as the accumulator
    across its row tiles (TPU grids run sequentially). This is the device
    analogue of ParallelAggregation's per-key fold, all keys in one launch.
    ``seed``: see wide_reduce_pallas (runtime value must be 0).
    ``w_tile``/``dimsem``: sweep-staged variants against the 3x XLA gap
    (grouped_plan, _grid_compiler_params)."""
    if fold not in ("log", "linear"):
        raise ValueError(f"fold must be 'log' or 'linear', got {fold!r}")
    fn = {"or": lax.bitwise_or, "and": lax.bitwise_and, "xor": lax.bitwise_xor}[op]
    g, m, w = words3.shape
    plan = grouped_plan(g, m, w, g_tile, row_tile, w_tile)
    if plan["pad_groups"] or plan["pad_rows"]:
        words3 = jnp.pad(
            words3,
            ((0, plan["pad_groups"]), (0, plan["pad_rows"]), (0, 0)),
            constant_values=dev._INIT[op],
        )
    if seed is None:
        seed = jnp.uint32(0)
    out = pl.pallas_call(
        _make_grouped_kernel(fn, fold, m_dim=plan["m_dim"]),
        out_shape=jax.ShapeDtypeStruct(plan["out_array"], words3.dtype),
        grid=plan["grid"],
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(plan["in_block"], plan["in_index"], memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            plan["out_block"], plan["out_index"], memory_space=pltpu.VMEM
        ),
        compiler_params=_grid_compiler_params(plan, dimsem),
        interpret=interpret,
    )(jnp.reshape(seed.astype(words3.dtype), (1,)), words3)
    return out[:g]


@functools.partial(
    jax.jit,
    static_argnames=("op", "interpret", "g_tile", "row_tile", "fold", "w_tile", "dimsem"),
)
@_compilewatch.tracked("grouped_reduce_cardinality_pallas")
def grouped_reduce_cardinality_pallas(
    words3,
    op: str = "or",
    interpret: bool = False,
    g_tile: int = G_TILE,
    row_tile: int = G_ROW_TILE,
    seed=None,
    fold: str = "log",
    w_tile: int | None = None,
    dimsem: bool = False,
):
    """Fused grouped reduce + per-group cardinality."""
    red = grouped_reduce_pallas(
        words3,
        op=op,
        interpret=interpret,
        g_tile=g_tile,
        row_tile=row_tile,
        seed=seed,
        fold=fold,
        w_tile=w_tile,
        dimsem=dimsem,
    )
    card = jnp.sum(lax.population_count(red).astype(jnp.int32), axis=-1)
    return red, card


# ---------------------------------------------------------------------------
# segmented reduce (the skewed-group layout, ops/device.segmented_reduce)
# ---------------------------------------------------------------------------
#
# The XLA path is a flagged lax.associative_scan: O(N log N) word-ops and
# ~2·log2(N) full passes over the [N, 2048] array through HBM. TPU grids
# execute sequentially, so a Pallas kernel can instead carry the running
# segment accumulator in a VMEM scratch across row tiles: one read and one
# write per row — the O(N) streaming bound. Same contract as the XLA
# version: out[i] = inclusive segment prefix at row i (callers gather the
# segment-end rows host-side via group_offsets).

SEG_ROW_TILE = 128


def seg_plan(n: int, w: int, row_tile: int = SEG_ROW_TILE):
    # flags ride in SMEM as one whole [n_tiles, row_tile/32] uint32
    # bit-mask array (block == array, indexed by program_id in the kernel;
    # bit r%32 of word [i, r/32] flags row r of tile i). Why this shape: a
    # blocked 1-D s32[n_pad] operand hits an XLA(T(1024)) vs Mosaic(T(128))
    # layout mismatch on real chips, a (1, row_tile) block violates the
    # (8,128) rule (enforced for SMEM operands too), and an unpacked
    # whole-array int32 would keep O(4*n) bytes resident in the small SMEM —
    # the bit-pack keeps the whole-array layout at n/8 bytes
    if row_tile % 32:
        raise ValueError(f"row_tile {row_tile} must be a multiple of 32")
    n_pad = n + (-n) % row_tile
    n_tiles = n_pad // row_tile
    return {
        "pad_rows": n_pad - n,
        "grid": (n_tiles,),
        "rows_array": (n_pad, w),
        "rows_block": (row_tile, w),
        "rows_index": lambda i: (i, 0),
        "flags_array": (n_tiles, row_tile // 32),
        "flags_block": (n_tiles, row_tile // 32),
        "flags_index": lambda i: (0, 0),
    }


def _make_seg_kernel(op, fill, row_tile: int):
    def kernel(flags_ref, words_ref, out_ref, acc_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            # op identity, so rows before the first True flag fold to the
            # same result as the XLA associative scan (seg_start[0]=False
            # is legal input even though prepare_reduce never produces it)
            acc_ref[...] = jnp.full_like(acc_ref, fill)

        acc = acc_ref[0]
        for r in range(row_tile):
            row = words_ref[r]
            start = ((flags_ref[i, r // 32] >> (r % 32)) & 1) != 0
            acc = jnp.where(start, row, op(acc, row))
            out_ref[r] = acc
        acc_ref[0] = acc

    return kernel


@functools.partial(jax.jit, static_argnames=("op", "interpret", "row_tile"))
@_compilewatch.tracked("segmented_reduce_pallas")
def segmented_reduce_pallas(
    words, seg_start, op: str = "or", interpret: bool = False, row_tile: int = SEG_ROW_TILE
):
    """Segmented inclusive scan ``[N, 2048] -> [N, 2048]`` in one HBM pass.

    ``seg_start``: bool [N], True at each segment's first row. Rows are
    padded to the tile with flag=True so padding never leaks into a real
    segment (each padded row restarts its own segment)."""
    fn = {"or": lax.bitwise_or, "and": lax.bitwise_and, "xor": lax.bitwise_xor}[op]
    n, w = words.shape
    plan = seg_plan(n, w, row_tile)
    if plan["pad_rows"]:
        words = jnp.pad(words, ((0, plan["pad_rows"]), (0, 0)))
        seg_start = jnp.pad(seg_start, (0, plan["pad_rows"]), constant_values=True)
    # bit-pack the flags: word [i, j] carries rows i*row_tile + 32j .. +31
    bits32 = seg_start.astype(jnp.uint32).reshape(-1, 32)
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))[None, :]
    flags = jnp.sum(bits32 * weights, axis=1, dtype=jnp.uint32).reshape(
        plan["flags_array"]
    )
    out = pl.pallas_call(
        _make_seg_kernel(fn, dev._INIT[op], row_tile),
        grid=plan["grid"],
        in_specs=[
            pl.BlockSpec(
                plan["flags_block"], plan["flags_index"], memory_space=pltpu.SMEM
            ),
            pl.BlockSpec(
                plan["rows_block"], plan["rows_index"], memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            plan["rows_block"], plan["rows_index"], memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct(plan["rows_array"], words.dtype),
        scratch_shapes=[pltpu.VMEM((1, w), words.dtype)],
        interpret=interpret,
    )(flags, words)
    return out[:n]


def best_segmented_reduce(words, seg_start, op: str = "or"):
    """Pallas one-pass segmented scan on TPU (probed, with fallback to the
    XLA associative scan)."""
    from ..robust import faults as _faults

    _faults.fault_point("ops.dispatch")
    if HAS_PALLAS and on_tpu():
        out = _probed_call("segmented", segmented_reduce_pallas, (words, seg_start), op)
        if out is not None:
            _DISPATCH_TOTAL.inc(1, ("segmented", "pallas"))
            return out
    _DISPATCH_TOTAL.inc(1, ("segmented", "xla"))
    return dev.segmented_reduce(words, seg_start, op=op)


# ---------------------------------------------------------------------------
# fused O'Neil BSI compare (models/bsi.py o_neil_math as one kernel)
# ---------------------------------------------------------------------------
#
# The XLA version is a lax.scan over the slice axis whose (GT, LT, EQ)
# [K, 2048] carry round-trips through HBM on every step: ~4 reads + 3
# writes of the state per slice on top of the slice read itself. Here the
# state lives in VMEM registers across an unrolled slice loop, so each
# slice word is read exactly ONCE from HBM and the state never leaves the
# core — the memory-bound north-star compare approaches the S*K*8KB
# streaming lower bound.

# O'Neil walk tiling, crowned on chip 2026-07-31 (chip_artifacts/
# 20260731T023500Z/oneil_tiling_probe.json): at the 100M-row [32,1526,2048]
# shape the old (k_tile=8, whole word axis) default measured 64.9 GB/s
# while (16, 512) reached 113.6 — more, smaller grid cells pipeline the
# sequential 32-slice walk far better; the w-split is legal because the
# recurrence is elementwise over (K, w).
ONEIL_K_TILE = 16  # key-chunks per grid step
ONEIL_W_TILE = 512  # word-axis split (0 = whole axis); must divide w, %128


def oneil_plan(s: int, k: int, w: int, k_tile: int = ONEIL_K_TILE, w_tile: int = -1):
    """Block layout for the [S, K, w] O'Neil walk; K padded to k_tile.

    ``w_tile`` splits the word axis into an extra grid dimension: the
    recurrence is elementwise over (K, w), so (k_tile, w_tile) cells are
    independent — more grid steps with smaller double-buffered blocks, the
    same axis the wide/grouped kernels call w_tile. Must divide w and
    satisfy the %128 lane rule. ``-1`` (the default — single source of
    truth for kernel, tests, and sweep) resolves to the crowned
    ONEIL_W_TILE when it divides w, else the whole axis; ``0`` forces the
    whole axis."""
    if w_tile < 0:
        w_tile = ONEIL_W_TILE if (ONEIL_W_TILE and w % ONEIL_W_TILE == 0) else 0
    if w_tile:
        if w % w_tile or w_tile % 128:
            raise ValueError(f"w_tile {w_tile} must divide {w} and be a multiple of 128")
    else:
        w_tile = w
    k_pad = k + (-k) % k_tile
    return {
        "pad_chunks": k_pad - k,
        "grid": (k_pad // k_tile, w // w_tile),
        "slices_array": (s, k_pad, w),
        "slices_block": (s, k_tile, w_tile),
        "slices_index": lambda i, j: (0, i, j),
        "kw_array": (k_pad, w),
        "kw_block": (k_tile, w_tile),
        "kw_index": lambda i, j: (i, j),
    }


def _make_oneil_kernel(s_count: int, op_name: str, dual: bool):
    """Unrolled slice walk; ``dual`` runs both RANGE recurrences (GE lo,
    LE hi) in the same pass over the slices. bits live in SMEM, ordered
    high slice -> low (bits_rev), lo-walk first when dual. ``seed_ref``:
    SMEM (1,) uint32 XOR'd into the EQ initialization — the steady-state
    timing hook (runtime value must be 0; see wide_reduce_pallas)."""

    def kernel(seed_ref, bits_ref, slices_ref, ebm_ref, fixed_ref, out_ref):
        eq = ebm_ref[...] ^ seed_ref[0]
        lt = jnp.zeros_like(eq)
        gt = jnp.zeros_like(eq)
        if dual:
            eq2, lt2 = eq, jnp.zeros_like(eq)
        for j in range(s_count):
            sl = slices_ref[s_count - 1 - j]
            bit = bits_ref[j] != 0
            lt = jnp.where(bit, lt | (eq & ~sl), lt)
            gt = jnp.where(bit, gt, gt | (eq & sl))
            eq = jnp.where(bit, eq & sl, eq & ~sl)
            if dual:
                bit2 = bits_ref[s_count + j] != 0
                lt2 = jnp.where(bit2, lt2 | (eq2 & ~sl), lt2)
                eq2 = jnp.where(bit2, eq2 & sl, eq2 & ~sl)
        fixed = fixed_ref[...]
        if dual:  # RANGE = GE(lo) & LE(hi)
            out = ((gt | eq) & (lt2 | eq2)) & fixed
        else:
            eq = eq & fixed
            if op_name == "EQ":
                out = eq
            elif op_name == "NEQ":
                out = fixed & ~eq
            elif op_name == "GT":
                out = gt & fixed
            elif op_name == "LT":
                out = lt & fixed
            elif op_name == "LE":
                out = (lt | eq) & fixed
            else:  # GE
                out = (gt | eq) & fixed
        out_ref[...] = out

    return kernel


@functools.partial(jax.jit, static_argnames=("op", "interpret", "k_tile", "w_tile"))
@_compilewatch.tracked("oneil_compare_pallas")
def oneil_compare_pallas(
    slices_w,
    bits_rev,
    ebm_w,
    fixed_w,
    op: str = "GE",
    interpret: bool = False,
    k_tile: int = ONEIL_K_TILE,
    w_tile: int = -1,
    seed=None,
):
    """Fused O'Neil compare: ([S, K, 2048], bits, [K, 2048], [K, 2048]) ->
    ([K, 2048] result, [K] cards). ``bits_rev`` is bool [S] (or [2, S] for
    op="RANGE", lo-walk first), matching models/bsi.o_neil_math.
    ``w_tile=-1`` takes the crowned ONEIL_W_TILE when it divides w."""
    s, k, w = slices_w.shape
    dual = op == "RANGE"
    plan = oneil_plan(s, k, w, k_tile, w_tile)
    if plan["pad_chunks"]:
        pad = plan["pad_chunks"]
        slices_w = jnp.pad(slices_w, ((0, 0), (0, pad), (0, 0)))
        ebm_w = jnp.pad(ebm_w, ((0, pad), (0, 0)))
        fixed_w = jnp.pad(fixed_w, ((0, pad), (0, 0)))
    bits_smem = bits_rev.reshape(-1).astype(jnp.int32)
    if seed is None:
        seed = jnp.uint32(0)
    out = pl.pallas_call(
        _make_oneil_kernel(s, op, dual),
        grid=plan["grid"],
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                plan["slices_block"], plan["slices_index"], memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(plan["kw_block"], plan["kw_index"], memory_space=pltpu.VMEM),
            pl.BlockSpec(plan["kw_block"], plan["kw_index"], memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            plan["kw_block"], plan["kw_index"], memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((plan["kw_array"][0], w), slices_w.dtype),
        interpret=interpret,
    )(jnp.reshape(seed.astype(slices_w.dtype), (1,)), bits_smem, slices_w, ebm_w, fixed_w)
    out = out[:k]
    cards = jnp.sum(lax.population_count(out).astype(jnp.int32), axis=-1)
    return out, cards


def best_oneil_compare(slices_w, bits_rev, ebm_w, fixed_w, op_name: str):
    """Pallas O'Neil on TPU (probed, with fallback to the fused XLA scan)."""
    if HAS_PALLAS and on_tpu():
        out = _probed_call(
            "oneil", oneil_compare_pallas, (slices_w, bits_rev, ebm_w, fixed_w), op_name
        )
        if out is not None:
            _DISPATCH_TOTAL.inc(1, ("oneil", "pallas"))
            return out
    _DISPATCH_TOTAL.inc(1, ("oneil", "xla"))
    from ..models.bsi import _o_neil_compare_fused

    return _o_neil_compare_fused(slices_w, bits_rev, ebm_w, fixed_w, op_name)


# ---------------------------------------------------------------------------
# dispatch: probe once, fall back to XLA on any failure
# ---------------------------------------------------------------------------


def on_tpu() -> bool:
    try:
        return jax.default_backend() not in ("cpu",)
    except RuntimeError:  # backend init failure (e.g. stale axon env) -> no TPU
        return False


def _probed_call(kind: str, fn, args, op: str, key_extra: Tuple = ()):
    """Run a Pallas entry point with a one-time per-shape lowering probe.

    Mosaic lowering errors surface at (synchronous) compile time on the
    first call; the probe also blocks on the result once to flush deferred
    runtime failures. Any failure marks the (kind, op, shape, backend[,
    key_extra]) key bad so subsequent calls go straight to XLA —
    ``key_extra`` carries the dispatcher's tiling config so changing it
    re-probes instead of reusing a stale verdict."""
    backend = jax.default_backend()
    key = (kind, op, tuple(args[0].shape), backend, *key_extra)
    ok = _PROBED.get(key)
    if ok is False:
        return None
    try:
        out = fn(*args, op=op)
        if ok is None:
            from .. import tracing

            with tracing.op_timer(f"kernel.probe.{kind}"):
                jax.block_until_ready(out)
            _PROBED[key] = True
            _PROBE_TOTAL.inc(1, (kind, str(op), backend, "ok"))
        return out
    except Exception:  # rb-ok: exception-hygiene -- the probe's whole job: a Mosaic lowering/compile failure of ANY type marks the shape bad and degrades to XLA; outcome is counted in rb_tpu_kernel_probe_total
        _PROBED[key] = False
        _PROBE_TOTAL.inc(1, (kind, str(op), backend, "failed"))
        return None


# Wide-family dispatch policy (the measured winner at the flat [N, 2048]
# shape — the family stuck at ~58 GB/s in round 3, with the two-stage XLA
# reduce and the w-split/linear Pallas variants staged to be measured):
#   "pallas"    — the Pallas kernel at WIDE_CONFIG's tiling, probed, XLA
#                 fallback (the round-3 default);
#   "two_stage" — dev.wide_reduce_two_stage at WIDE_CONFIG (stage_groups=);
#   "xla"       — the stock one-shot XLA reduce.
# Set both the policy and WIDE_CONFIG per the sweep digest, as with
# GROUPED_PREFER_XLA / GROUPED_PALLAS_CONFIG.
# The 2026-07-31 sweep briefly crowned pallas rt256/w512 (59.9 vs 56.6 GB/s
# at [16384, 2048]), but the same-window scaling probe
# (chip_artifacts/20260731T013545Z/wide_scaling_probe.json) showed that
# 128 MiB shape is fixed-cost-bound (every engine lands at 28-59 GB/s) while
# at real sizes XLA wins decisively: 228 vs 109 GB/s at 512 MiB, 318 vs 186
# at 1 GiB. Policy rides on the at-scale numbers.
WIDE_DISPATCH = "xla"
WIDE_CONFIG: Dict = {}

_WIDE_CONFIG_KEYS = {
    "pallas": {"row_tile", "w_tile", "fold", "dimsem"},
    "two_stage": {"stage_groups"},
    "xla": set(),
}
GROUPED_CONFIG_KEYS = {"g_tile", "row_tile", "w_tile", "fold", "dimsem"}


def _validated_key_extra(cfg: Dict, valid_keys, name: str) -> Tuple:
    """Validate a dispatcher config loudly and derive its probe-key token.
    A typo'd key or unhashable value must raise here, BEFORE the probed
    call — inside it, the blanket probe except would record the TypeError
    as a lowering failure and silently pin the XLA fallback."""
    bad = set(cfg) - set(valid_keys)
    if bad:
        raise ValueError(
            f"{name} has unknown keys {sorted(bad)}; valid: {sorted(valid_keys)}"
        )
    key_extra = (tuple(sorted(cfg.items())),)
    try:
        hash(key_extra)
    except TypeError as e:
        raise ValueError(f"{name} values must be hashable: {e}") from None
    return key_extra


def best_wide_reduce(words, op: str = "or"):
    """Measured-best wide reduce per WIDE_DISPATCH: the Pallas kernel (with
    lowering probe + automatic XLA fallback) by default, the two-stage or
    one-shot XLA reduce when the sweep crowns them. Off-TPU always serves
    the XLA reduce."""
    policy = WIDE_DISPATCH
    if policy not in _WIDE_CONFIG_KEYS:
        raise ValueError(f"WIDE_DISPATCH must be pallas/two_stage/xla, got {policy!r}")
    bad = set(WIDE_CONFIG) - _WIDE_CONFIG_KEYS[policy]
    if bad:
        raise ValueError(
            f"WIDE_CONFIG has keys {sorted(bad)} invalid for policy {policy!r}; "
            f"valid: {sorted(_WIDE_CONFIG_KEYS[policy])}"
        )
    if on_tpu():
        if policy == "pallas" and HAS_PALLAS:
            key_extra = _validated_key_extra(
                WIDE_CONFIG, _WIDE_CONFIG_KEYS["pallas"], "WIDE_CONFIG"
            )
            out = _probed_call(
                "wide",
                functools.partial(wide_reduce_cardinality_pallas, **WIDE_CONFIG),
                (words,),
                op,
                key_extra=key_extra,
            )
            if out is not None:
                _DISPATCH_TOTAL.inc(1, ("wide", "pallas"))
                return out
        elif policy == "two_stage":
            _DISPATCH_TOTAL.inc(1, ("wide", "two_stage"))
            return dev.wide_reduce_two_stage(words, op=op, **WIDE_CONFIG)
    _DISPATCH_TOTAL.inc(1, ("wide", "xla"))
    return dev.wide_reduce_with_cardinality(words, op=op)


# Measured on v5e-1 (scripts/tile_sweep.py steady-state, BENCH_NOTES.md):
# the XLA grouped reduce sustains 423 GB/s at the flagship [66,1450,2048]
# shape vs 137 GB/s for the Pallas kernel (and 112.7 vs 83.1 at [66,512];
# tie at [512,64]) — XLA's reduction schedule pipelines the small-G shapes
# better than the (G/8, M/rt) sequential grid. The dispatcher therefore
# prefers XLA for grouped reduces; the Pallas kernel stays available
# explicitly and as the probe-validated alternative.
GROUPED_PREFER_XLA = True

# When a sweep crowns a non-default Pallas config (scripts/sweep_digest.py
# flagship verdict), set the winning kwargs here alongside flipping
# GROUPED_PREFER_XLA — the dispatcher applies them on every probed call,
# so the flip actually serves the measured-best variant, not the default
# tiling (e.g. {"row_tile": 128, "w_tile": 512, "fold": "linear"}).
GROUPED_PALLAS_CONFIG: Dict = {}


def best_grouped_reduce(words3, op: str = "or"):
    """Measured-best grouped reduce: XLA by default (see GROUPED_PREFER_XLA),
    the Pallas kernel — at GROUPED_PALLAS_CONFIG's tiling — with lowering
    probe + automatic XLA fallback when preferred."""
    from ..robust import faults as _faults

    _faults.fault_point("ops.dispatch")
    if not GROUPED_PREFER_XLA and HAS_PALLAS and on_tpu():
        key_extra = _validated_key_extra(
            GROUPED_PALLAS_CONFIG, GROUPED_CONFIG_KEYS, "GROUPED_PALLAS_CONFIG"
        )
        out = _probed_call(
            "grouped",
            functools.partial(grouped_reduce_cardinality_pallas, **GROUPED_PALLAS_CONFIG),
            (words3,),
            op,
            key_extra=key_extra,
        )
        if out is not None:
            _DISPATCH_TOTAL.inc(1, ("grouped", "pallas"))
            return out
    _DISPATCH_TOTAL.inc(1, ("grouped", "xla"))
    return dev.grouped_reduce_with_cardinality(words3, op=op)


@functools.partial(jax.jit, static_argnames=("g", "m", "op", "fill"))
@_compilewatch.tracked("fused_gather_reduce")
def _fused_gather_reduce_jit(flat, src_map, g, m, op, fill):
    # identity row appended so out-of-range pad slots (index n) read the op
    # identity — jit-safe stand-in for take(mode="fill"), whose fill_value
    # must be a static hashable under trace
    ident = jnp.full((1, dev.DEVICE_WORDS), fill, dtype=jnp.uint32)
    padded = jnp.concatenate([flat, ident], axis=0)[src_map].reshape(
        g, m, dev.DEVICE_WORDS
    )
    red = lax.reduce(padded, dev._INIT[op], dev._OPS[op], dimensions=(1,))
    return red, jnp.sum(lax.population_count(red).astype(jnp.int32), axis=-1)


def fused_gather_reduce(flat, src_map, g: int, m: int, op: str = "or",
                        fill: int = 0):
    """One-shot grouped reduce straight off the flat rows: the dense-pad
    gather fuses INTO the reduction (one jit), so the padded [G, M, W]
    block is never materialized — XLA streams each flat row through the
    fold. Half the memory traffic of gather-then-reduce (measured 0.38 s
    vs 0.69 + 0.15 s on the 2500-bitmap census quarter), which is exactly
    what a COLD single-shot aggregation wants; repeat traffic should
    still build the resident padded block once and ride the cheaper
    [G, M, W] reduce (store.prepare_reduce owns that tiering). Same
    ``ops.dispatch`` fault site as the other reduce dispatchers."""
    from ..robust import faults as _faults

    _faults.fault_point("ops.dispatch")
    _DISPATCH_TOTAL.inc(1, ("grouped_fused", "xla"))
    return _fused_gather_reduce_jit(
        flat, jnp.asarray(src_map), g=int(g), m=int(m), op=op,
        fill=int(fill),
    )


@functools.partial(jax.jit, static_argnames=("op",))
@_compilewatch.tracked("pair_rows_reduce")
def _pair_rows_jit(rows_a, ia, rows_b, ib, op):
    # OOB pad ids read zero rows (take mode="fill"; the fill_value must
    # be a static hashable under trace — a python literal, not jnp): every
    # op maps (0, 0) -> 0, so pad slots popcount to 0 and slice off
    # host-side
    a = jnp.take(rows_a, ia, axis=0, mode="fill", fill_value=0)
    b = jnp.take(rows_b, ib, axis=0, mode="fill", fill_value=0)
    # rb-ok: trace-safety -- op is a static_argnames operand: the branch
    # resolves at trace time, one specialization per op
    if op == "and":
        out = a & b
    elif op == "or":
        out = a | b
    elif op == "xor":
        out = a ^ b
    else:  # andnot
        out = a & ~b
    cards = jnp.sum(lax.population_count(out).astype(jnp.int32), axis=-1)
    return out, cards


def pair_rows_reduce(rows_a, ia, rows_b, ib, op: str):
    """Columnar device tier (ISSUE 10): the word-parallel pairwise classes
    as ONE fused gather + bitwise-op + popcount dispatch over the resident
    flat row blocks. ``ia[j]``/``ib[j]`` select pair j's rows; the fused
    per-row popcount IS the batched format selection (the host builds
    array-vs-bitmap containers card-driven, no re-count). Index streams
    pad to pow2 with the out-of-range id (retrace-bounded like every
    marshal kernel); returns host ``(words_u32 [n, 2048], cards int64 [n])``
    sliced back to the live pair count. Same ``ops.dispatch`` fault site
    as the reduce dispatchers — the columnar ladder degrades this bucket
    to the columnar-CPU word matrices bit-exactly."""
    from ..robust import faults as _faults

    _faults.fault_point("ops.dispatch")
    n = int(len(ia))
    oob_a = int(rows_a.shape[0])
    oob_b = int(rows_b.shape[0])
    ia_p = dev.pad_pow2(np.asarray(ia, dtype=np.int32), oob_a)
    ib_p = dev.pad_pow2(np.asarray(ib, dtype=np.int32), oob_b)
    _DISPATCH_TOTAL.inc(1, ("pair_rows", "xla"))
    words, cards = _pair_rows_jit(
        rows_a, jnp.asarray(ia_p), rows_b, jnp.asarray(ib_p), op
    )
    return (
        np.asarray(words)[:n],
        np.asarray(cards)[:n].astype(np.int64),
    )


def concat_rows(blocks):
    """Concatenate flat device row blocks ``[n_i, W]`` into one combined
    block padded to a pow2 row count — the cross-query fusion tier's
    combined operand (ISSUE 13): a window's per-query resident blocks
    become ONE gather source so ``pair_rows_reduce`` serves every
    query's pairs in a single launch. One ``jnp.concatenate`` dispatch;
    pad rows are zero (they are only ever gathered by pad indices, whose
    results the host wrappers slice off). The pow2 padding bounds
    retraces of the downstream gather to one compile per combined-block
    size class, the same discipline as every index stream."""
    blocks = list(blocks)
    if not blocks:
        raise ValueError("concat_rows needs at least one block")
    total = sum(int(b.shape[0]) for b in blocks)
    padded = dev.pow2(max(1, total))
    if len(blocks) == 1 and padded == total:
        return blocks[0]
    parts = blocks
    if padded > total:
        parts = blocks + [
            jnp.zeros((padded - total, blocks[0].shape[1]), dtype=blocks[0].dtype)
        ]
    return jnp.concatenate(parts, axis=0)


# ---------------------------------------------------------------------------
# marshal kernels (ISSUE 8): device-side container expansion + donated
# delta scatter
# ---------------------------------------------------------------------------
#
# The r08 flight recorder pinned the marshal wall to two host costs: the
# container->word expansion (92% of the cold pack) and the full-tensor copy
# behind the k-row delta ``.at[rows].set`` (99.9% of the delta repack).
# Both fixes live here so every store path shares one implementation:
#
# * ``expand_rows_device`` — expand compact container payloads (array
#   values, run intervals, bitmap words) into the flat uint32 [n, 2048]
#   row block in ONE fused jit dispatch. Array values scatter-add their
#   bit masks (distinct values within a container make bitwise-or == add);
#   run intervals scatter start/stop *toggle* bits into a compact per-run-
#   row block and a prefix-XOR circuit (5 doubling shifts within each
#   word + a cross-word cumulative-parity carry) turns the toggles into
#   the filled interval — the interval-fill analogue of the bit-sliced
#   adder trick, with no per-run loop; bitmap rows are a dynamic-update
#   row copy. Expressed as jit/XLA rather than hand-Pallas: every grouped
#   dispatch sweep to date crowned XLA at real sizes (see GROUPED_PREFER_XLA
#   above), and the scatter/DUS mix here is exactly the shape XLA schedules
#   well; a Pallas variant can ride the same probe harness if a sweep ever
#   disagrees.
# * ``scatter_rows_donated`` — the delta fix: a donated jit row scatter.
#   ``donate_argnums=(0,)`` lets XLA reuse the input buffer, so a k-row
#   delta writes O(k * 2048) words in place instead of copying the whole
#   flat tensor. The input array is CONSUMED — callers must drop every
#   reference to it (parallel/store.py bumps the pack's buffer generation).
#
# All variable-length inputs arrive padded to power-of-two lengths with
# out-of-range ids (scatter ``mode="drop"`` discards them), so the jit
# caches retrace per pow2 bucket, not per exact payload size.


def _parity_u32(x):
    """Per-word bit parity (popcount & 1) via 5 folding shifts."""
    x = x ^ (x >> 16)
    x = x ^ (x >> 8)
    x = x ^ (x >> 4)
    x = x ^ (x >> 2)
    x = x ^ (x >> 1)
    return x & jnp.uint32(1)


@functools.partial(jax.jit, static_argnums=(0,))
@_compilewatch.tracked("expand_rows_device")
def _expand_rows_jit(n_rows, bmp_rows, bmp_words, val_idx, val_bits,
                     run_rows, tog_s_idx, tog_s_bits, tog_e_idx, tog_e_bits):
    out = jnp.zeros((n_rows * dev.DEVICE_WORDS,), jnp.uint32)
    out = out.at[val_idx].add(val_bits, mode="drop")
    out = out.reshape(n_rows, dev.DEVICE_WORDS)
    # rb-ok: trace-safety -- branches on STATIC operand shapes: resolved at
    # trace time, no traced value ever reaches python control flow
    if bmp_rows.shape[0]:
        out = out.at[bmp_rows].set(bmp_words, mode="drop")
    if run_rows.shape[0]:
        n_run = run_rows.shape[0]
        # start and stop toggles accumulate SEPARATELY: within each side
        # sorted disjoint runs make every bit distinct (add == or), and
        # the XOR cancels a stop landing on the next run's start bit
        # (adjacent runs), where a single scatter-add would carry
        flat = jnp.zeros((n_run * dev.DEVICE_WORDS,), jnp.uint32)
        tog_s = flat.at[tog_s_idx].add(tog_s_bits, mode="drop")
        tog_e = flat.at[tog_e_idx].add(tog_e_bits, mode="drop")
        tog = (tog_s ^ tog_e).reshape(n_run, dev.DEVICE_WORDS)
        fill = tog
        # rb-ok: trace-safety -- static 5-step doubling unroll (u32 width)
        for s in (1, 2, 4, 8, 16):
            fill = fill ^ (fill << s)
        par = _parity_u32(tog).astype(jnp.int32)
        carry = (jnp.cumsum(par, axis=1) - par) & 1  # exclusive parity
        filled = fill ^ (carry.astype(jnp.uint32) * jnp.uint32(0xFFFFFFFF))
        out = out.at[run_rows].set(filled, mode="drop")
    return out


def expand_rows_device(n_rows, bmp_rows, bmp_words_u32, val_idx, val_bits,
                       run_rows, tog_s_idx, tog_s_bits, tog_e_idx, tog_e_bits):
    """Fused device-side expansion of compact container payloads into the
    flat ``uint32 [n_rows, 2048]`` row block (see the section comment).
    Host arrays in (already pow2-padded, out-of-range ids = drop), device
    rows out. Raises ``TierUnavailable`` when the flat int32 word indexing
    would overflow (> ~1M rows) — the caller's ladder degrades to the host
    expansion path."""
    if n_rows * dev.DEVICE_WORDS >= (1 << 31):
        from ..robust.errors import TierUnavailable

        raise TierUnavailable(
            f"expand_rows_device: {n_rows} rows overflow int32 word indexing"
        )
    _DISPATCH_TOTAL.inc(1, ("expand_rows", "xla"))
    return _expand_rows_jit(
        int(n_rows),
        jnp.asarray(bmp_rows), jnp.asarray(bmp_words_u32),
        jnp.asarray(val_idx), jnp.asarray(val_bits),
        jnp.asarray(run_rows),
        jnp.asarray(tog_s_idx), jnp.asarray(tog_s_bits),
        jnp.asarray(tog_e_idx), jnp.asarray(tog_e_bits),
    )


@functools.partial(jax.jit, donate_argnums=(0,))
@_compilewatch.tracked("scatter_rows_donated")
def _scatter_rows_jit(dst, rows, new_rows):
    return dst.at[rows].set(new_rows, mode="drop")


def scatter_rows_donated(dst, rows, new_rows_u32):
    """Donated in-place row scatter: replace ``rows`` of the flat device
    block with ``new_rows_u32``. ``dst`` is CONSUMED (donate_argnums) — on
    backends honoring donation XLA writes the k rows into the existing
    buffer (O(k * 2048) words, the delta-inversion fix); on backends that
    do not, XLA falls back to a copy with identical semantics. Callers
    must treat ``dst`` as dead either way and serve only the returned
    array (store bumps the pack's buffer generation). Rows are padded to
    pow2 with the out-of-range id ``n`` (dropped) to bound retraces."""
    k = int(len(rows))
    n = int(dst.shape[0])
    rows_pad = dev.pad_pow2(np.asarray(rows, dtype=np.int32), n)
    kp = len(rows_pad)
    vals = np.zeros((kp, int(dst.shape[1])), dtype=np.uint32)
    if k:
        vals[:k] = new_words_view(new_rows_u32, int(dst.shape[1]))
    _DISPATCH_TOTAL.inc(1, ("delta_scatter", "donated"))
    return _scatter_rows_jit(dst, jnp.asarray(rows_pad), jnp.asarray(vals))


def new_words_view(rows_u32, width: int) -> np.ndarray:
    """Normalize delta rows to the destination's uint32 row width (host
    uint64 [k, 1024] and device uint32 [k, 2048] views are interchangeable
    little-endian)."""
    a = np.ascontiguousarray(rows_u32)
    if a.dtype != np.uint32:
        a = a.view(np.uint32)
    return a.reshape(-1, width)
