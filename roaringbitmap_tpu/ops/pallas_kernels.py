"""Pallas TPU kernels for the container hot path.

The single hottest computation in the reference is the wide aggregation fold:
OR/AND/XOR 1024-word containers together, then popcount
(FastAggregation.java:541-602; BitmapContainer.java:657-678). Here it is one
Pallas kernel: a grid over row-tiles of the packed ``[N, 2048]`` uint32
container array, OR-accumulating into a VMEM output block that stays resident
across grid steps (TPU grids execute sequentially, so the output block is a
legal accumulator).

Falls back to the XLA ``lax.reduce`` path (ops/device.py) off-TPU; tests run
the kernel in interpreter mode on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from . import device as dev

try:  # pallas is optional at import time (e.g. stripped CPU envs)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAS_PALLAS = True
except Exception:  # pragma: no cover
    HAS_PALLAS = False

ROW_TILE = 256  # rows of 2048 uint32 words per grid step: 2 MiB per block in VMEM


def _reduce_rows(x, op):
    """Logarithmic fold over the row axis of a static-shaped block."""
    n = x.shape[0]
    while n > 1:
        half = n // 2
        x = op(x[:half], x[half : 2 * half])
        n = half
    return x[0]


def _make_kernel(op, grouped: bool = False):
    """Init/accumulate reduction kernel. ``grouped`` blocks are
    [1, ROW_TILE, W] with the row-tile axis as grid dim 1 (innermost, so
    the output block is the per-group VMEM accumulator); wide blocks are
    [ROW_TILE, W] with the tile axis as grid dim 0."""

    def kernel(x_ref, o_ref):
        i = pl.program_id(1 if grouped else 0)
        tile = _reduce_rows(x_ref[0] if grouped else x_ref[...], op)

        @pl.when(i == 0)
        def _init():
            o_ref[0, :] = tile

        @pl.when(i != 0)
        def _acc():
            o_ref[0, :] = op(o_ref[0, :], tile)

    return kernel


@functools.partial(jax.jit, static_argnames=("op", "interpret"))
def wide_reduce_pallas(words, op: str = "or", interpret: bool = False):
    """Reduce ``[N, 2048]`` uint32 -> ``[2048]`` with a Pallas kernel.

    Pads N up to a ROW_TILE multiple with the op identity so every grid step
    sees a full block.
    """
    fn = {"or": lax.bitwise_or, "and": lax.bitwise_and, "xor": lax.bitwise_xor}[op]
    n, w = words.shape
    pad = (-n) % ROW_TILE
    if pad:
        fill = dev._INIT[op]
        words = jnp.concatenate(
            [words, jnp.full((pad, w), fill, dtype=words.dtype)], axis=0
        )
    n_padded = words.shape[0]
    grid = (n_padded // ROW_TILE,)
    out = pl.pallas_call(
        _make_kernel(fn),
        out_shape=jax.ShapeDtypeStruct((1, w), words.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROW_TILE, w), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((1, w), lambda i: (0, 0), memory_space=pltpu.VMEM),
        interpret=interpret,
    )(words)
    return out[0]


@functools.partial(jax.jit, static_argnames=("op", "interpret"))
def wide_reduce_cardinality_pallas(words, op: str = "or", interpret: bool = False):
    """Fused wide reduce + cardinality (popcount of the reduced row)."""
    red = wide_reduce_pallas(words, op=op, interpret=interpret)
    card = jnp.sum(lax.population_count(red).astype(jnp.int32))
    return red, card


@functools.partial(jax.jit, static_argnames=("op", "interpret"))
def grouped_reduce_pallas(words3, op: str = "or", interpret: bool = False):
    """Padded grouped reduce ``[G, M, 2048] -> [G, 2048]`` as one kernel.

    Grid is (G, M-tiles) with the M axis innermost, so for each group the
    output block stays resident in VMEM as the accumulator across its row
    tiles (TPU grids run sequentially). This is the device analogue of
    ParallelAggregation's per-key fold, all keys in one launch."""
    fn = {"or": lax.bitwise_or, "and": lax.bitwise_and, "xor": lax.bitwise_xor}[op]
    g, m, w = words3.shape
    pad = (-m) % ROW_TILE
    if pad:
        fill = dev._INIT[op]
        words3 = jnp.concatenate(
            [words3, jnp.full((g, pad, w), fill, dtype=words3.dtype)], axis=1
        )
    m_tiles = words3.shape[1] // ROW_TILE
    out = pl.pallas_call(
        _make_kernel(fn, grouped=True),
        out_shape=jax.ShapeDtypeStruct((g, w), words3.dtype),
        grid=(g, m_tiles),
        in_specs=[
            pl.BlockSpec(
                (1, ROW_TILE, w), lambda gi, mi: (gi, mi, 0), memory_space=pltpu.VMEM
            )
        ],
        out_specs=pl.BlockSpec((1, w), lambda gi, mi: (gi, 0), memory_space=pltpu.VMEM),
        interpret=interpret,
    )(words3)
    return out


@functools.partial(jax.jit, static_argnames=("op", "interpret"))
def grouped_reduce_cardinality_pallas(words3, op: str = "or", interpret: bool = False):
    """Fused grouped reduce + per-group cardinality."""
    red = grouped_reduce_pallas(words3, op=op, interpret=interpret)
    card = jnp.sum(lax.population_count(red).astype(jnp.int32), axis=-1)
    return red, card


def on_tpu() -> bool:
    return jax.default_backend() not in ("cpu",)


def best_wide_reduce(words, op: str = "or"):
    """Pick the Pallas kernel on TPU, XLA reduce elsewhere."""
    if HAS_PALLAS and on_tpu():
        return wide_reduce_cardinality_pallas(words, op=op)
    return dev.wide_reduce_with_cardinality(words, op=op)


def best_grouped_reduce(words3, op: str = "or"):
    """Pick the Pallas grouped kernel on TPU, XLA reduce elsewhere."""
    if HAS_PALLAS and on_tpu():
        return grouped_reduce_cardinality_pallas(words3, op=op)
    return dev.grouped_reduce_with_cardinality(words3, op=op)
