from . import device

__all__ = ["device"]
