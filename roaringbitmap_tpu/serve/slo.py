"""Per-tenant serving-tier SLO telemetry (ISSUE 14 tentpole, leg 2).

Every prior PR measured the pipeline from a single caller's perspective;
a serving tier answers a different question — "what latency and
throughput does each *tenant* see, and who is eating the machine" —
which needs per-tenant labeled series on the existing registry/histogram
substrate:

* ``rb_tpu_serve_latency_seconds{tenant, phase}`` — log-bucketed
  latency histograms (phase ``queue`` = the admission wall including any
  backpressure wait, ``execute`` = query execution), answering
  p50/p90/p99 per tenant straight from the registry snapshot;
* ``rb_tpu_serve_qps{tenant}`` — rolling per-tenant throughput gauges
  (sliding-window request rate, window ``QPS_WINDOW_S``);
* ``rb_tpu_serve_requests_total{tenant, outcome}`` — request volume by
  outcome (``ok`` | ``shed`` | ``error``);
* ``rb_tpu_serve_queue_count`` / ``rb_tpu_serve_inflight_count`` — the
  admission controller's live depth gauges (the saturation signals the
  ISSUE-12/13 closure notes promised the sentinel);
* ``rb_tpu_serve_saturation_ratio{tenant}`` — per-tenant token-bucket
  depletion (0 = full budget available, 1 = quota exhausted);
* ``rb_tpu_serve_tenant_bytes{tenant}`` — the tenant's byte share of
  the resident PACK_CACHE working sets (entries serving several
  tenants' overlapping working sets are charged to each — it is a
  share, not a partition; see :func:`note_tenant_bytes`);
* ``rb_tpu_serve_slo_budget_seconds{tenant}`` — the declared p99
  latency budget from the tenant's latency class (ISSUE 19): tenants
  declare ``interactive`` / ``balanced`` / ``batch`` with a per-class
  default budget (:data:`LATENCY_CLASSES`, overridable per tenant), and
  the budget becomes a *priced input* — admission bounds an interactive
  queue wait by it, the fusion hedge verdict prices window-vs-solo
  against it, and the ``serving-p99-pressure`` rule judges measured p99
  against it.

**The bounded tenant registry.** Tenant label values are the classic
unbounded-cardinality trap (every user id as a label value melts the
scrape backend), so they come from :data:`TENANTS` — a capacity-bounded
*declared* registry: ``TENANTS.declare(name, ...)`` registers a tenant
(loudly failing past ``max_tenants``), and ``TENANTS[name]`` returns the
canonical label value, raising ``KeyError`` for anything undeclared.
Metric mutations throughout the serve tier spell tenant label values as
``TENANTS[tenant]`` — the metric-naming analysis rule (ISSUE 14
satellite) rejects a bare ``tenant`` variable in a label tuple exactly
like a trace id, and accepts the declared-registry subscript.

Off mode: ``configure(enabled=False)`` reduces :func:`record` and the
gauge updates to one module-bool check (the bench's serving off-mode
twin bounds the cost under the house <1 % budget).

Lock discipline: the SLO lock is a LEAF — it guards only the tenant
table and the per-tenant QPS rings; every metric bump happens outside
it, so recording while holding other framework locks nests safely
(tests/test_serve.py hammers this under the lock witness).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

from ..observe import registry as _registry
from ..observe.histogram import latency_histogram

# rolling-QPS window: long enough to smooth a drained fusion window's
# burstiness, short enough that the gauge tracks load shifts the sentinel
# should see within a few ticks
QPS_WINDOW_S = 5.0
DEFAULT_MAX_TENANTS = 64

# declared latency classes (ISSUE 19): every tenant picks one, with a
# default p99 budget it may override at declare(). The class is the
# coarse scheduling signal (interactive = latency-gold, hedges out of a
# forming fusion window that would blow its budget; batch = throughput-
# gold, rides every window); the BUDGET is the priced input — admission
# bounds an interactive queue wait by it and the fusion hedge verdict
# prices window-vs-solo against it.
LATENCY_CLASSES: Dict[str, float] = {
    "interactive": 25.0,   # p99 budget ms: human-in-the-loop lookups
    "balanced": 100.0,     # dashboards, near-line consumers
    "batch": 1000.0,       # offline scans: throughput over latency
}
DEFAULT_LATENCY_CLASS = "batch"

# request phases and outcomes (declared label sets; the latency histogram
# registers with labelnames ("tenant", "phase"))
PHASES = ("queue", "execute")
OUTCOMES = ("ok", "shed", "error")

_LATENCY = latency_histogram(
    _registry.SERVE_LATENCY_SECONDS,
    "Serving-tier request latency by tenant and phase (queue = admission "
    "wall incl. backpressure wait, execute = query execution)",
    ("tenant", "phase"),
)
_QPS = _registry.gauge(
    _registry.SERVE_QPS,
    "Rolling per-tenant request throughput (sliding-window rate over "
    "QPS_WINDOW_S seconds)",
    ("tenant",),
)
_REQUESTS_TOTAL = _registry.counter(
    _registry.SERVE_REQUESTS_TOTAL,
    "Serving-tier requests by tenant and outcome (ok | shed | error)",
    ("tenant", "outcome"),
)
_TENANT_BYTES = _registry.gauge(
    _registry.SERVE_TENANT_BYTES,
    "Per-tenant byte share of the resident PACK_CACHE working sets "
    "(overlapping working sets charge every tenant that touches them)",
    ("tenant",),
)
_SLO_BUDGET = _registry.gauge(
    _registry.SERVE_SLO_BUDGET_SECONDS,
    "Per-tenant declared p99 latency budget (seconds) from the tenant's "
    "latency class — what the serving-p99-pressure rule judges measured "
    "p99 against",
    ("tenant",),
)

_ENABLED = True


def configure(enabled: Optional[bool] = None) -> None:
    """``enabled=False`` is the serving off-mode twin's kill switch:
    :func:`record` and the gauge updates reduce to one bool check."""
    global _ENABLED
    if enabled is not None:
        _ENABLED = bool(enabled)


def enabled() -> bool:
    return _ENABLED


class TenantRegistry:
    """Capacity-bounded declared tenant set — the source of every tenant
    metric label value. ``declare()`` past ``max_tenants`` raises (a
    tenant set that grows without bound is the same cardinality bug as a
    trace-id label, just slower); ``registry[name]`` canonicalizes a
    tenant to its declared label value and raises ``KeyError`` for
    anything undeclared, so a typo'd tenant can never mint a series."""

    def __init__(self, max_tenants: int = DEFAULT_MAX_TENANTS):
        if max_tenants < 1:
            raise ValueError(f"max_tenants must be >= 1, got {max_tenants}")
        self.max_tenants = int(max_tenants)
        self._lock = threading.Lock()  # leaf: guards the tables below only
        self._tenants: Dict[str, dict] = {}  # guarded-by: self._lock
        # per-tenant completion-timestamp rings for the rolling QPS gauge
        self._ticks: Dict[str, "deque[float]"] = {}  # guarded-by: self._lock

    def declare(
        self,
        name: str,
        quota_qps: float = 100.0,
        burst: Optional[float] = None,
        latency_class: str = DEFAULT_LATENCY_CLASS,
        p99_budget_ms: Optional[float] = None,
    ) -> str:
        """Register a tenant with its admission quota (token-bucket rate
        ``quota_qps`` and ``burst`` capacity, default 2x the rate) and
        its latency SLO: a declared ``latency_class`` with a p99 budget
        (class default unless ``p99_budget_ms`` overrides it). Idempotent
        for an identical name (quota and SLO update); loud past
        capacity."""
        name = str(name)
        if not name:
            raise ValueError("tenant name must be non-empty")
        if latency_class not in LATENCY_CLASSES:
            raise ValueError(
                f"unknown latency class {latency_class!r} "
                f"(known: {sorted(LATENCY_CLASSES)})"
            )
        budget_ms = (
            float(p99_budget_ms) if p99_budget_ms is not None
            else LATENCY_CLASSES[latency_class]
        )
        spec = {
            "quota_qps": float(quota_qps),
            "burst": float(burst) if burst is not None else 2.0 * float(quota_qps),
            "latency_class": latency_class,
            "p99_budget_ms": budget_ms,
        }
        if spec["quota_qps"] <= 0 or spec["burst"] <= 0:
            raise ValueError(f"tenant {name!r} quota/burst must be > 0: {spec}")
        if budget_ms <= 0:
            raise ValueError(
                f"tenant {name!r} p99 budget must be > 0 ms, got {budget_ms}"
            )
        with self._lock:
            if name not in self._tenants and len(self._tenants) >= self.max_tenants:
                raise ValueError(
                    f"tenant registry full ({self.max_tenants}): declaring "
                    f"{name!r} would unbound the tenant label set"
                )
            self._tenants[name] = spec
            self._ticks.setdefault(name, deque())
        # budget gauge outside the leaf lock, like every metric bump here
        if _ENABLED:
            _SLO_BUDGET.set(round(budget_ms / 1e3, 6), (name,))
        return name

    def __getitem__(self, name: str) -> str:
        """Canonical label value for a declared tenant (KeyError for
        anything undeclared — the bounded-cardinality guarantee)."""
        with self._lock:
            if name not in self._tenants:
                raise KeyError(
                    f"undeclared tenant {name!r} (declared: {sorted(self._tenants)})"
                )
        return name

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._tenants

    def quota(self, name: str) -> dict:
        with self._lock:
            spec = self._tenants.get(name)
            if spec is None:
                raise KeyError(f"undeclared tenant {name!r}")
            return dict(spec)

    def latency_class(self, name: str) -> str:
        with self._lock:
            spec = self._tenants.get(name)
            if spec is None:
                raise KeyError(f"undeclared tenant {name!r}")
            return spec["latency_class"]

    def p99_budget_ms(self, name: str) -> float:
        """The tenant's declared p99 latency budget (ms) — the priced
        input the fusion hedge verdict and the serving-p99-pressure rule
        judge against."""
        with self._lock:
            spec = self._tenants.get(name)
            if spec is None:
                raise KeyError(f"undeclared tenant {name!r}")
            return float(spec["p99_budget_ms"])

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    def reset(self) -> None:
        """Drop every declared tenant (tests, bench windows)."""
        with self._lock:
            self._tenants.clear()
            self._ticks.clear()

    # -- rolling QPS ---------------------------------------------------------

    def _note_request(self, tenant: str, now: float) -> float:
        """Append one completion tick and return the tenant's current
        windowed rate (requests in the window / window seconds)."""
        floor = now - QPS_WINDOW_S
        with self._lock:
            ring = self._ticks.get(tenant)
            if ring is None:
                raise KeyError(f"undeclared tenant {tenant!r}")
            ring.append(now)
            while ring and ring[0] < floor:
                ring.popleft()
            n = len(ring)
        return n / QPS_WINDOW_S

    def qps(self, tenant: str, now: Optional[float] = None) -> float:
        """The tenant's current windowed request rate (reads only)."""
        if now is None:
            now = time.monotonic()
        floor = now - QPS_WINDOW_S
        with self._lock:
            ring = self._ticks.get(tenant)
            if ring is None:
                raise KeyError(f"undeclared tenant {tenant!r}")
            n = sum(1 for t in ring if t >= floor)
        return n / QPS_WINDOW_S


# The process-wide tenant registry (harness profiles, admission quotas,
# and every serve-tier metric label value resolve through this).
TENANTS = TenantRegistry()


def record(
    tenant: str,
    outcome: str,
    queue_s: Optional[float] = None,
    execute_s: Optional[float] = None,
    now: Optional[float] = None,
) -> None:
    """Record one served request: phase latencies into the per-tenant
    histograms, the outcome counter, and the rolling QPS gauge. Metric
    bumps happen outside the SLO lock (leaf discipline); disabled mode is
    one bool check."""
    if not _ENABLED:
        return
    if outcome not in OUTCOMES:
        raise ValueError(f"unknown serve outcome {outcome!r} (known: {OUTCOMES})")
    canon = TENANTS[tenant]
    _REQUESTS_TOTAL.inc(1, (TENANTS[tenant], str(outcome)))
    if outcome == "ok":
        # the rolling-QPS gauge is served THROUGHPUT (the help text and
        # the harness's served/wall rows agree on this); offered volume
        # incl. sheds rides the requests counter above — a 100%-shed
        # tenant must read ~0 qps in the serving panel, not healthy
        rate = TENANTS._note_request(
            canon, time.monotonic() if now is None else now
        )
        _QPS.set(round(rate, 3), (TENANTS[tenant],))
    if queue_s is not None:
        _LATENCY.observe(queue_s, (TENANTS[tenant], "queue"))
    if execute_s is not None:
        _LATENCY.observe(execute_s, (TENANTS[tenant], "execute"))


def note_tenant_bytes(tenant: str, leaves: Iterable) -> int:
    """Charge ``tenant`` with the resident PACK_CACHE bytes attributable
    to its working set (the bitmaps its query profile touches): entries
    whose key embeds any of the leaves' fingerprints. Returns the byte
    share and exports it as ``rb_tpu_serve_tenant_bytes{tenant}``."""
    if not _ENABLED:
        return 0
    from ..parallel import store as _store

    fps = {bm.fingerprint() for bm in leaves}
    share = _store.PACK_CACHE.resident_bytes_for(fps)
    _TENANT_BYTES.set(int(share), (TENANTS[tenant],))
    return int(share)


def quantiles(tenant: str, phase: str) -> dict:
    """p50/p90/p99 snapshot for one (tenant, phase) latency series —
    the harness's cross-check against its own collected latencies."""
    return _LATENCY.quantiles((TENANTS[tenant], str(phase)))


def tenant_rows() -> Dict[str, dict]:
    """Per-tenant rollup (the rb_top serving panel's rows): rolling QPS,
    p50/p99 per phase, request outcomes, byte share."""
    out: Dict[str, dict] = {}
    req = _REQUESTS_TOTAL.series()
    bytes_g = _TENANT_BYTES.series()
    qps_g = _QPS.series()
    for tenant in TENANTS.names():
        spec = TENANTS.quota(tenant)
        row = {
            "qps": qps_g.get((tenant,), 0.0),
            "bytes": bytes_g.get((tenant,), 0),
            "latency_class": spec.get("latency_class"),
            "p99_budget_ms": spec.get("p99_budget_ms"),
            "outcomes": {
                lv[1]: v for lv, v in req.items() if lv[0] == tenant
            },
        }
        for phase in PHASES:
            st = _LATENCY.get((tenant, phase))
            if st is not None:
                row[phase] = {
                    "count": st["count"],
                    **_LATENCY.quantiles((tenant, phase)),
                }
        out[tenant] = row
    return out


def reset() -> None:
    """Drop tenant declarations and QPS rings (tests, bench windows);
    registry metric series reset via observe.reset like everything
    else."""
    TENANTS.reset()
