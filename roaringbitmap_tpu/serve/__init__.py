"""Serving tier (ISSUE 14): multi-tenant load harness, per-tenant SLO
telemetry, and priced admission control over the fused query path.

The three legs (see each module's docstring):

* ``harness.py`` — the multi-threaded load generator: seeded
  multi-tenant request schedules with overlapping predicates over a
  shared corpus, every request under its own trace scope, driven
  through admission into the :class:`~roaringbitmap_tpu.query.FusionExecutor`;
* ``slo.py`` — the bounded declared tenant registry and the per-tenant
  labeled telemetry (``rb_tpu_serve_latency_seconds{tenant, phase}``
  p50/p99, rolling QPS gauges, saturation, PACK_CACHE byte shares);
* ``admission.py`` — token-bucket per-tenant quotas + a global
  in-flight cap with shed-or-queue backpressure, every verdict priced
  at the ``serve.admit`` decision site and scored by the
  decision–outcome ledger (the sixth cost authority,
  ``cost/admission.py``).

The health sentinel's ``serving-p99-breach`` and ``tenant-saturation``
rules (observe/health.py) watch the telemetry this tier emits — the
serving-shaped signals the ISSUE-12 closure note promised.

Since ISSUE 15 the tier also owns the WRITE path:

* ``ingest.py`` — the batched mutation log: stamped per-tenant batches
  accumulate while readers keep serving the current epoch untouched;
* ``epochs.py`` — snapshot-isolated epoch publication: readers pin the
  epoch they were admitted under, the flip drains the log through the
  sorted-stream writer surface into ONE O(k) delta repack per touched
  working set, and every published batch's ingest->queryable lag lands
  in ``rb_tpu_serve_freshness_seconds{tenant}``. The flip is a priced
  ``epoch.flip`` decision (the seventh ``cost/`` authority), and the
  ``freshness-lag-breach`` / ``epoch-flip-stall`` sentinel rules watch
  the new signals.
"""

from .admission import CONTROLLER, AdmissionController, ShedRejection, Ticket
from .epochs import EpochStore, EpochTicket, FLIP_STAGES, current_store
from .harness import (
    HarnessReport,
    LoadHarness,
    Request,
    TenantProfile,
    TenantStats,
    build_requests,
    default_mix,
)
from .ingest import IngestLog, MutationBatch
from .slo import TENANTS, TenantRegistry
from . import admission, epochs, harness, ingest, slo

__all__ = [
    "AdmissionController",
    "CONTROLLER",
    "EpochStore",
    "EpochTicket",
    "FLIP_STAGES",
    "HarnessReport",
    "IngestLog",
    "LoadHarness",
    "MutationBatch",
    "Request",
    "ShedRejection",
    "TENANTS",
    "TenantProfile",
    "TenantRegistry",
    "TenantStats",
    "Ticket",
    "admission",
    "build_requests",
    "current_store",
    "default_mix",
    "epochs",
    "harness",
    "ingest",
    "slo",
]
