"""Multi-tenant serving load harness (ISSUE 14 tentpole, leg 1).

Everything before this PR measured the pipeline one caller at a time;
the observability stack (trace ids, decision/outcome ledgers, the
sentinel, the fusion window) was built for *concurrent* traffic that did
not exist. This module generates it: a multi-threaded load harness
driving the fused query path over a shared corpus with a seeded
multi-tenant workload mix.

* **Workload** — :func:`build_requests` derives, from one seed, a
  deterministic request schedule over declared tenant profiles: each
  tenant gets a query mix over the shared corpus with *overlapping
  predicates* (a hot shared conjunction rides under every tenant's
  distinct predicates — ONE hash-consed node across tenants, which is
  exactly what the fusion window dedups across concurrent submitters).
  The same seed always produces the same query multiset, which is what
  makes the concurrent-vs-serial differential (fuzz family 28) and the
  bench's bit-exactness assertion possible.

* **Drive** — :meth:`LoadHarness.run` executes the schedule on
  ``threads`` worker threads (closed-loop by default; ``target_qps``
  paces an open-loop schedule instead). Every request runs under its own
  ``trace_scope`` — admission decisions, SLO instants, and the serve
  spans all carry the request's trace id, so per-trace attribution
  stays 100 % under contention (the bench asserts it) — and passes
  admission (``serve.admit`` priced verdict) before submitting to the
  shared :class:`~roaringbitmap_tpu.query.FusionExecutor` (or the plain
  executor with ``use_fusion=False``).

* **Account** — phase latencies land in
  ``rb_tpu_serve_latency_seconds{tenant, phase}`` (queue = admission
  wall incl. backpressure, execute = query execution), outcomes in the
  request counter, rolling QPS in the per-tenant gauge, and each
  tenant's PACK_CACHE byte share in ``rb_tpu_serve_tenant_bytes`` —
  the signals the ``serving-p99-breach`` / ``tenant-saturation``
  sentinel rules judge.

A shed request yields a :class:`~.admission.ShedRejection` *in the
result slot* — typed, inspectable, and never a bitmap — so the serial
differential can assert "every served result is bit-exact and every
unserved one is loudly a shed" (shed-never-loses-a-result).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..observe import context as _context
from ..observe import timeline as _timeline
from . import slo as _slo
from .admission import CONTROLLER, AdmissionController, ShedRejection
from .slo import TENANTS


@dataclass(frozen=True)
class TenantProfile:
    """One tenant's declared traffic shape: its share of the request mix
    (``weight``), its admission quota, and its query profile over the
    shared corpus (``mix`` draws one expression from a seeded rng)."""

    name: str
    weight: float = 1.0
    quota_qps: float = 1000.0
    burst: Optional[float] = None
    mix: Optional[Callable] = None  # (rng, corpus, shared) -> Expr


@dataclass
class Request:
    """One scheduled request (the multiset element the serial oracle
    replays)."""

    idx: int
    tenant: str
    expr: object
    start_s: Optional[float] = None  # open-loop schedule offset


@dataclass
class TenantStats:
    served: int = 0
    shed: int = 0
    queued: int = 0
    queue_s: List[float] = field(default_factory=list)
    execute_s: List[float] = field(default_factory=list)

    def quantile_ms(self, phase: str, q: float) -> Optional[float]:
        vals = sorted(self.queue_s if phase == "queue" else self.execute_s)
        if not vals:
            return None
        i = min(len(vals) - 1, int(q * len(vals)))
        return round(vals[i] * 1e3, 3)


def default_mix(rng, corpus, shared):
    """The serving-shaped default query profile: the hot shared
    conjunction under this draw's own predicates (the overlap the fusion
    window exists to exploit), occasionally a pure own-predicate scan."""
    from ..query import Q

    a = Q.leaf(corpus[int(rng.integers(0, len(corpus)))])
    b = Q.leaf(corpus[int(rng.integers(0, len(corpus)))])
    kind = int(rng.integers(0, 4))
    if kind == 0:
        return shared | a
    if kind == 1:
        return (shared | a) - b
    if kind == 2:
        return shared | (a & b)
    return a | b


def build_requests(
    corpus: Sequence,
    profiles: Sequence[TenantProfile],
    n_requests: int,
    seed: int = 0,
    target_qps: Optional[float] = None,
) -> List[Request]:
    """The deterministic request schedule: tenants drawn by weight, each
    tenant's queries from its own seeded stream (so two tenants never
    share an rng and the multiset is reproducible per seed), the shared
    hot conjunction built from the corpus head. ``target_qps`` stamps
    open-loop start offsets; None leaves the schedule closed-loop."""
    from ..query import Q

    if len(corpus) < 4:
        raise ValueError(f"serving corpus needs >= 4 bitmaps, got {len(corpus)}")
    if not profiles:
        raise ValueError("at least one tenant profile is required")
    shared = Q.leaf(corpus[0]) & Q.leaf(corpus[1])
    weights = np.asarray([max(1e-9, p.weight) for p in profiles], dtype=np.float64)
    weights /= weights.sum()
    pick_rng = np.random.default_rng(seed)
    tenant_rngs = {
        p.name: np.random.default_rng((seed << 8) ^ zlib_crc(p.name))
        for p in profiles
    }
    out: List[Request] = []
    for i in range(int(n_requests)):
        p = profiles[int(pick_rng.choice(len(profiles), p=weights))]
        mix = p.mix or default_mix
        expr = mix(tenant_rngs[p.name], corpus, shared)
        start = (i / target_qps) if target_qps else None
        out.append(Request(idx=i, tenant=p.name, expr=expr, start_s=start))
    return out


def zlib_crc(s: str) -> int:
    import zlib

    return zlib.crc32(s.encode())


class LoadHarness:
    """The serving-tier load generator. Construct with the shared corpus
    and tenant profiles (declared into the tenant registry), then
    :meth:`run` a request schedule across worker threads."""

    def __init__(
        self,
        corpus: Sequence,
        profiles: Sequence[TenantProfile],
        threads: int = 4,
        use_fusion: bool = True,
        window: Optional[int] = None,
        max_wait_ms: Optional[float] = None,
        admission: Optional[AdmissionController] = None,
        cache_entries: int = 256,
    ):
        if threads < 1:
            raise ValueError(f"threads must be >= 1, got {threads}")
        self.corpus = list(corpus)
        self.profiles = list(profiles)
        self.threads = int(threads)
        self.use_fusion = bool(use_fusion)
        self.window = window
        self.max_wait_ms = max_wait_ms
        self.admission = admission if admission is not None else CONTROLLER
        self.cache_entries = int(cache_entries)
        for p in self.profiles:
            TENANTS.declare(p.name, quota_qps=p.quota_qps, burst=p.burst)

    # -- the drive -----------------------------------------------------------

    def run(self, requests: Sequence[Request]) -> "HarnessReport":
        """Execute the schedule: ``threads`` workers pull requests from a
        shared cursor (contention by construction), each request under
        its own trace scope through admission -> fused execution -> SLO
        accounting. Returns the report with per-request results (bitmap
        or ShedRejection) and per-tenant stats."""
        from ..query import FusionExecutor, ResultCache
        from ..query import exec as _exec

        requests = list(requests)
        # results are POSITIONAL in the schedule as passed (so any
        # sub-slice of a built schedule lines up with its own serial
        # oracle), not keyed by Request.idx
        results: List[object] = [None] * len(requests)
        stats: Dict[str, TenantStats] = {p.name: TenantStats() for p in self.profiles}
        stats_lock = threading.Lock()  # leaf: guards the stats dict only
        cursor = {"i": 0}
        cursor_lock = threading.Lock()  # leaf: guards the cursor only
        errors: List[BaseException] = []
        cache = ResultCache(max_entries=self.cache_entries)
        executor = (
            FusionExecutor(
                window=self.window, max_wait_ms=self.max_wait_ms, cache=cache
            )
            if self.use_fusion
            else None
        )
        t_open = time.perf_counter()

        def _next() -> Optional[tuple]:
            with cursor_lock:
                i = cursor["i"]
                if i >= len(requests):
                    return None
                cursor["i"] = i + 1
            return i, requests[i]

        def _serve_one(pos: int, req: Request) -> None:
            with _context.trace_scope():
                if req.start_s is not None:  # open-loop pacing
                    delay = (t_open + req.start_s) - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                with _timeline.tspan(
                    "serve.request", "serve", tenant=req.tenant, idx=req.idx,
                ):
                    t0 = time.perf_counter()
                    ticket = self.admission.admit(req.tenant)
                    queue_s = time.perf_counter() - t0
                    if not ticket.admitted:
                        results[pos] = ShedRejection(req.tenant, "admission")
                        _slo.record(req.tenant, "shed", queue_s=queue_s)
                        with stats_lock:
                            stats[req.tenant].shed += 1
                        return
                    try:
                        t1 = time.perf_counter()
                        if executor is not None:
                            out = executor.submit(req.expr).result()
                        else:
                            out = _exec.execute(req.expr, cache=cache)
                        execute_s = time.perf_counter() - t1
                    except Exception:
                        _slo.record(req.tenant, "error", queue_s=queue_s)
                        raise
                    finally:
                        ticket.release()
                    results[pos] = out
                    _slo.record(
                        req.tenant, "ok", queue_s=queue_s, execute_s=execute_s
                    )
                    with stats_lock:
                        st = stats[req.tenant]
                        st.served += 1
                        st.queue_s.append(queue_s)
                        st.execute_s.append(execute_s)
                        if ticket.verdict == "queue":
                            st.queued += 1

        def _worker() -> None:
            while True:
                nxt = _next()
                if nxt is None:
                    return
                try:
                    _serve_one(*nxt)
                except BaseException as e:  # rb-ok: exception-hygiene -- a worker must drain the schedule and surface EVERY failure to the caller afterwards; swallowing one would silently shrink the served multiset the differential checks
                    with stats_lock:
                        errors.append(e)

        workers = [
            threading.Thread(target=_worker, name=f"rb-serve-{i}", daemon=True)
            for i in range(self.threads)
        ]
        t0 = time.perf_counter()
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        wall_s = time.perf_counter() - t0
        if executor is not None:
            executor.close()
        if errors:
            raise errors[0]
        # per-tenant PACK_CACHE byte share: the tenant's reachable corpus
        # is the whole shared corpus under the default mixes — charge the
        # resident entries its leaves appear in
        for p in self.profiles:
            _slo.note_tenant_bytes(p.name, self.corpus)
        return HarnessReport(requests, results, stats, wall_s)

    def run_serial(self, requests: Sequence[Request]) -> List[object]:
        """The serial oracle: the same query multiset, one at a time, no
        admission, no fusion, no shared cache — what the concurrent run
        must be bit-exact against (fuzz family 28 / the bench gate)."""
        from ..query import exec as _exec

        return [_exec.execute(r.expr, cache=None) for r in requests]


class HarnessReport:
    """One run's outcome: per-request results aligned with the schedule,
    per-tenant stats, and the aggregate wall."""

    def __init__(self, requests, results, stats, wall_s):
        self.requests = requests
        self.results = results
        self.stats = stats
        self.wall_s = wall_s

    @property
    def served(self) -> int:
        return sum(st.served for st in self.stats.values())

    @property
    def shed(self) -> int:
        return sum(st.shed for st in self.stats.values())

    def aggregate_qps(self) -> float:
        return round(self.served / self.wall_s, 1) if self.wall_s > 0 else 0.0

    def tenant_rows(self) -> Dict[str, dict]:
        """Per-tenant decomposition: served/shed/queued volume, achieved
        QPS, and harness-side p50/p99 per phase (the registry histograms
        carry the same answer — tests pin the two within one bucket
        ratio)."""
        out = {}
        for tenant, st in sorted(self.stats.items()):
            out[tenant] = {
                "served": st.served,
                "shed": st.shed,
                "queued": st.queued,
                "qps": round(st.served / self.wall_s, 1) if self.wall_s else 0.0,
                "queue_p50_ms": st.quantile_ms("queue", 0.5),
                "queue_p99_ms": st.quantile_ms("queue", 0.99),
                "execute_p50_ms": st.quantile_ms("execute", 0.5),
                "execute_p99_ms": st.quantile_ms("execute", 0.99),
            }
        return out
