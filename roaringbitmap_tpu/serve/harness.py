"""Multi-tenant serving load harness (ISSUE 14 tentpole, leg 1).

Everything before this PR measured the pipeline one caller at a time;
the observability stack (trace ids, decision/outcome ledgers, the
sentinel, the fusion window) was built for *concurrent* traffic that did
not exist. This module generates it: a multi-threaded load harness
driving the fused query path over a shared corpus with a seeded
multi-tenant workload mix.

* **Workload** — :func:`build_requests` derives, from one seed, a
  deterministic request schedule over declared tenant profiles: each
  tenant gets a query mix over the shared corpus with *overlapping
  predicates* (a hot shared conjunction rides under every tenant's
  distinct predicates — ONE hash-consed node across tenants, which is
  exactly what the fusion window dedups across concurrent submitters).
  The same seed always produces the same query multiset, which is what
  makes the concurrent-vs-serial differential (fuzz family 28) and the
  bench's bit-exactness assertion possible.

* **Drive** — :meth:`LoadHarness.run` executes the schedule on
  ``threads`` worker threads (closed-loop by default; ``target_qps``
  paces an open-loop schedule instead). Every request runs under its own
  ``trace_scope`` — admission decisions, SLO instants, and the serve
  spans all carry the request's trace id, so per-trace attribution
  stays 100 % under contention (the bench asserts it) — and passes
  admission (``serve.admit`` priced verdict) before submitting to the
  shared :class:`~roaringbitmap_tpu.query.FusionExecutor` (or the plain
  executor with ``use_fusion=False``).

* **Account** — phase latencies land in
  ``rb_tpu_serve_latency_seconds{tenant, phase}`` (queue = admission
  wall incl. backpressure, execute = query execution), outcomes in the
  request counter, rolling QPS in the per-tenant gauge, and each
  tenant's PACK_CACHE byte share in ``rb_tpu_serve_tenant_bytes`` —
  the signals the ``serving-p99-breach`` / ``tenant-saturation``
  sentinel rules judge.

A shed request yields a :class:`~.admission.ShedRejection` *in the
result slot* — typed, inspectable, and never a bitmap — so the serial
differential can assert "every served result is bit-exact and every
unserved one is loudly a shed" (shed-never-loses-a-result).
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..observe import context as _context
from ..observe import timeline as _timeline
from . import slo as _slo
from .admission import CONTROLLER, AdmissionController, ShedRejection
from .slo import TENANTS


@dataclass(frozen=True)
class TenantProfile:
    """One tenant's declared traffic shape: its share of the request mix
    (``weight``), its admission quota, and its query profile over the
    shared corpus (``mix`` draws one expression from a seeded rng).

    ``writes`` makes the tenant a WRITER (ISSUE 15): that fraction of its
    requests are stamped mutation batches into the epoch store's ingest
    log instead of queries (``write_values`` values per batch, drawn into
    the touched bitmap's existing chunk keys so the flip's repack stays
    on the O(k) delta path).

    ``latency_class``/``p99_budget_ms`` declare the tenant's latency SLO
    (ISSUE 19): the class default budget unless overridden — what the
    fusion hedge verdict, the interactive admission clamp, and the
    serving-p99-pressure rule judge this tenant against. The default
    ``batch`` keeps pre-existing all-batch schedules byte-identical."""

    name: str
    weight: float = 1.0
    quota_qps: float = 1000.0
    burst: Optional[float] = None
    mix: Optional[Callable] = None  # (rng, corpus, shared) -> Expr
    writes: float = 0.0
    write_values: int = 8
    latency_class: str = _slo.DEFAULT_LATENCY_CLASS
    p99_budget_ms: Optional[float] = None


@dataclass
class Request:
    """One scheduled request (the multiset element the serial oracle
    replays). ``kind`` is ``query`` or ``write``; a write carries its
    per-bitmap-index ``mutations`` instead of an expression."""

    idx: int
    tenant: str
    expr: object
    start_s: Optional[float] = None  # open-loop schedule offset
    kind: str = "query"
    mutations: Optional[Dict[int, object]] = None


@dataclass
class TenantStats:
    served: int = 0
    shed: int = 0
    queued: int = 0
    writes: int = 0
    queue_s: List[float] = field(default_factory=list)
    execute_s: List[float] = field(default_factory=list)

    def quantile_ms(self, phase: str, q: float) -> Optional[float]:
        vals = sorted(self.queue_s if phase == "queue" else self.execute_s)
        if not vals:
            return None
        i = min(len(vals) - 1, int(q * len(vals)))
        return round(vals[i] * 1e3, 3)

    def total_quantile_ms(self, q: float) -> Optional[float]:
        """End-to-end (queue + execute) latency quantile — what a
        tenant's declared p99 budget is judged against (the two phase
        lists are appended pairwise under the stats lock, so zipping
        them reconstructs per-request totals)."""
        vals = sorted(a + b for a, b in zip(self.queue_s, self.execute_s))
        if not vals:
            return None
        i = min(len(vals) - 1, int(q * len(vals)))
        return round(vals[i] * 1e3, 3)


def default_mix(rng, corpus, shared):
    """The serving-shaped default query profile: the hot shared
    conjunction under this draw's own predicates (the overlap the fusion
    window exists to exploit), occasionally a pure own-predicate scan."""
    from ..query import Q

    a = Q.leaf(corpus[int(rng.integers(0, len(corpus)))])
    b = Q.leaf(corpus[int(rng.integers(0, len(corpus)))])
    kind = int(rng.integers(0, 4))
    if kind == 0:
        return shared | a
    if kind == 1:
        return (shared | a) - b
    if kind == 2:
        return shared | (a & b)
    return a | b


def default_write(rng, corpus, n_values: int = 8):
    """The default mutation draw for a writer tenant: a few values into
    ONE bitmap's existing chunk keys (mutating resident containers in
    place is what keeps the epoch flip's repack on the O(k) delta path;
    a fresh-key write would legitimately force a structural repack)."""
    idx = int(rng.integers(0, len(corpus)))
    hlc = corpus[idx].high_low_container
    if hlc.size:
        hb = int(hlc.keys[int(rng.integers(0, hlc.size))])
    else:
        hb = 0
    lows = rng.integers(0, 1 << 16, size=max(1, int(n_values)))
    return {idx: ((hb << 16) | lows).astype(np.int64)}


def build_requests(
    corpus: Sequence,
    profiles: Sequence[TenantProfile],
    n_requests: int,
    seed: int = 0,
    target_qps: Optional[float] = None,
) -> List[Request]:
    """The deterministic request schedule: tenants drawn by weight, each
    tenant's queries from its own seeded stream (so two tenants never
    share an rng and the multiset is reproducible per seed), the shared
    hot conjunction built from the corpus head. Writer tenants
    (``writes > 0``) interleave seeded mutation batches with their
    queries — same determinism, so the epoch-replay oracle
    (:meth:`LoadHarness.run_serial_epochs`) rebuilds the exact schedule
    over a cloned corpus. ``target_qps`` stamps open-loop start offsets;
    None leaves the schedule closed-loop."""
    from ..query import Q

    if len(corpus) < 4:
        raise ValueError(f"serving corpus needs >= 4 bitmaps, got {len(corpus)}")
    if not profiles:
        raise ValueError("at least one tenant profile is required")
    shared = Q.leaf(corpus[0]) & Q.leaf(corpus[1])
    weights = np.asarray([max(1e-9, p.weight) for p in profiles], dtype=np.float64)
    weights /= weights.sum()
    pick_rng = np.random.default_rng(seed)
    tenant_rngs = {
        p.name: np.random.default_rng((seed << 8) ^ zlib_crc(p.name))
        for p in profiles
    }
    out: List[Request] = []
    for i in range(int(n_requests)):
        p = profiles[int(pick_rng.choice(len(profiles), p=weights))]
        rng = tenant_rngs[p.name]
        start = (i / target_qps) if target_qps else None
        if p.writes > 0 and float(rng.random()) < p.writes:
            muts = default_write(rng, corpus, n_values=p.write_values)
            out.append(Request(
                idx=i, tenant=p.name, expr=None, start_s=start,
                kind="write", mutations=muts,
            ))
            continue
        mix = p.mix or default_mix
        expr = mix(rng, corpus, shared)
        out.append(Request(idx=i, tenant=p.name, expr=expr, start_s=start))
    return out


def zlib_crc(s: str) -> int:
    import zlib

    return zlib.crc32(s.encode())


class LoadHarness:
    """The serving-tier load generator. Construct with the shared corpus
    and tenant profiles (declared into the tenant registry), then
    :meth:`run` a request schedule across worker threads."""

    def __init__(
        self,
        corpus: Sequence,
        profiles: Sequence[TenantProfile],
        threads: int = 4,
        use_fusion: bool = True,
        window: Optional[int] = None,
        max_wait_ms: Optional[float] = None,
        admission: Optional[AdmissionController] = None,
        cache_entries: int = 256,
        epoch_store=None,
    ):
        if threads < 1:
            raise ValueError(f"threads must be >= 1, got {threads}")
        self.corpus = list(corpus)
        self.profiles = list(profiles)
        self.threads = int(threads)
        self.use_fusion = bool(use_fusion)
        self.window = window
        self.max_wait_ms = max_wait_ms
        self.admission = admission if admission is not None else CONTROLLER
        self.cache_entries = int(cache_entries)
        # the epoch store (ISSUE 15): when given, every query runs under
        # a reader pin (snapshot isolation) and write requests feed its
        # ingest log; required when any profile is a writer
        self.epoch_store = epoch_store
        if epoch_store is not None and (
            len(epoch_store.corpus) != len(self.corpus)
            or any(
                a is not b for a, b in zip(epoch_store.corpus, self.corpus)
            )  # identity, not content: a content compare of serving-scale
               # bitmaps would cost more than the run it guards
        ):
            raise ValueError("epoch store must wrap the harness corpus")
        if epoch_store is None and any(p.writes > 0 for p in self.profiles):
            raise ValueError("writer tenants need an epoch_store")
        for p in self.profiles:
            TENANTS.declare(
                p.name, quota_qps=p.quota_qps, burst=p.burst,
                latency_class=p.latency_class, p99_budget_ms=p.p99_budget_ms,
            )

    # -- the drive -----------------------------------------------------------

    def run(self, requests: Sequence[Request]) -> "HarnessReport":
        """Execute the schedule: ``threads`` workers pull requests from a
        shared cursor (contention by construction), each request under
        its own trace scope through admission -> fused execution -> SLO
        accounting. Returns the report with per-request results (bitmap
        or ShedRejection) and per-tenant stats."""
        from ..query import FusionExecutor, ResultCache
        from ..query import exec as _exec

        requests = list(requests)
        # results are POSITIONAL in the schedule as passed (so any
        # sub-slice of a built schedule lines up with its own serial
        # oracle), not keyed by Request.idx
        results: List[object] = [None] * len(requests)
        # per-position admitted epoch (queries) and minted batch id
        # (writes) — the epoch-replay oracle's join keys (ISSUE 15)
        epochs: List[Optional[int]] = [None] * len(requests)
        batch_ids: List[Optional[int]] = [None] * len(requests)
        stats: Dict[str, TenantStats] = {p.name: TenantStats() for p in self.profiles}
        stats_lock = threading.Lock()  # leaf: guards the stats dict only
        cursor = {"i": 0}
        cursor_lock = threading.Lock()  # leaf: guards the cursor only
        errors: List[BaseException] = []
        epoch_start = (
            self.epoch_store.current() if self.epoch_store is not None else 0
        )
        cache = ResultCache(max_entries=self.cache_entries)
        executor = (
            FusionExecutor(
                window=self.window, max_wait_ms=self.max_wait_ms, cache=cache
            )
            if self.use_fusion
            else None
        )
        t_open = time.perf_counter()

        def _next() -> Optional[tuple]:
            with cursor_lock:
                i = cursor["i"]
                if i >= len(requests):
                    return None
                cursor["i"] = i + 1
            return i, requests[i]

        def _serve_one(pos: int, req: Request) -> None:
            with _context.trace_scope():
                if req.start_s is not None:  # open-loop pacing
                    delay = (t_open + req.start_s) - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                ambient_epoch = (
                    self.epoch_store.current()
                    if self.epoch_store is not None else None
                )
                with _timeline.tspan(
                    "serve.request", "serve", tenant=req.tenant, idx=req.idx,
                    kind=req.kind,
                ) as span:
                    t0 = time.perf_counter()
                    ticket = self.admission.admit(
                        req.tenant, epoch=ambient_epoch
                    )
                    queue_s = time.perf_counter() - t0
                    if not ticket.admitted:
                        results[pos] = ShedRejection(req.tenant, "admission")
                        _slo.record(req.tenant, "shed", queue_s=queue_s)
                        with stats_lock:
                            stats[req.tenant].shed += 1
                        return
                    try:
                        t1 = time.perf_counter()
                        if req.kind == "write":
                            # the WRITE path (ISSUE 15): a stamped batch
                            # into the ingest log — readers untouched —
                            # then the priced flip-now-vs-accumulate
                            # verdict; the flip itself (when taken) is
                            # the only corpus mutation point
                            batch = self.epoch_store.submit(
                                req.tenant, req.mutations
                            )
                            self.epoch_store.maybe_flip(reason="ingest")
                            out = ("write", batch.batch_id if batch else None)
                            batch_ids[pos] = out[1]
                        else:
                            # snapshot isolation: the reader pin fixes
                            # the epoch for the whole execution and the
                            # epoch id rides the request's span attrs
                            pin = (
                                self.epoch_store.reader()
                                if self.epoch_store is not None
                                else contextlib.nullcontext()
                            )
                            with pin as tk:
                                if tk is not None:
                                    epochs[pos] = tk.epoch
                                    if span is not None:  # off-mode: no span
                                        span.attr(epoch=tk.epoch)
                                if executor is not None:
                                    # the tenant rides along so the
                                    # executor can price the request's
                                    # slack against its declared SLO
                                    # (ISSUE 19)
                                    out = executor.submit(
                                        req.expr, tenant=req.tenant
                                    ).result()
                                else:
                                    out = _exec.execute(req.expr, cache=cache)
                        execute_s = time.perf_counter() - t1
                    except Exception:
                        _slo.record(req.tenant, "error", queue_s=queue_s)
                        raise
                    finally:
                        ticket.release()
                    results[pos] = out
                    _slo.record(
                        req.tenant, "ok", queue_s=queue_s, execute_s=execute_s
                    )
                    with stats_lock:
                        st = stats[req.tenant]
                        st.served += 1
                        st.queue_s.append(queue_s)
                        st.execute_s.append(execute_s)
                        if req.kind == "write":
                            st.writes += 1
                        if ticket.verdict == "queue":
                            st.queued += 1

        def _worker() -> None:
            while True:
                nxt = _next()
                if nxt is None:
                    return
                try:
                    _serve_one(*nxt)
                except BaseException as e:  # rb-ok: exception-hygiene -- a worker must drain the schedule and surface EVERY failure to the caller afterwards; swallowing one would silently shrink the served multiset the differential checks
                    with stats_lock:
                        errors.append(e)

        workers = [
            threading.Thread(target=_worker, name=f"rb-serve-{i}", daemon=True)
            for i in range(self.threads)
        ]
        t0 = time.perf_counter()
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        wall_s = time.perf_counter() - t0
        # run-end drain (ISSUE 15), AFTER the wall: every accepted batch
        # becomes queryable — trailing freshness is observed and the
        # epoch-replay oracle sees a complete lineage. The serving wall
        # covers the schedule; a steady-state server amortizes this flip
        # over the traffic that follows, which a bounded window cannot
        if self.epoch_store is not None and self.epoch_store.log.depth():
            self.epoch_store.flip(reason="run-end")
        if executor is not None:
            executor.close()
        if errors:
            raise errors[0]
        # per-tenant PACK_CACHE byte share: the tenant's reachable corpus
        # is the whole shared corpus under the default mixes — charge the
        # resident entries its leaves appear in
        for p in self.profiles:
            _slo.note_tenant_bytes(p.name, self.corpus)
        lineage = (
            self.epoch_store.lineage() if self.epoch_store is not None else []
        )
        return HarnessReport(
            requests, results, stats, wall_s,
            epochs=epochs, batch_ids=batch_ids, lineage=lineage,
            epoch_start=epoch_start, profiles=self.profiles,
        )

    def run_serial(self, requests: Sequence[Request]) -> List[object]:
        """The serial oracle: the same query multiset, one at a time, no
        admission, no fusion, no shared cache — what the concurrent run
        must be bit-exact against (fuzz family 28 / the bench gate).
        Read-only schedules only; read-write schedules use
        :meth:`run_serial_epochs`."""
        from ..query import exec as _exec

        if any(r.kind == "write" for r in requests):
            raise ValueError(
                "run_serial replays read-only schedules; use "
                "run_serial_epochs for a read-write schedule"
            )
        return [_exec.execute(r.expr, cache=None) for r in requests]  # rb-ok: epoch-pin -- serial oracle: replays a read-only schedule against a quiesced corpus with no concurrent flips, so there is no epoch to pin

    @staticmethod
    def run_serial_epochs(
        clone_requests: Sequence[Request],
        clone_corpus: Sequence,
        report: "HarnessReport",
    ) -> List[object]:
        """The epoch-replay oracle (ISSUE 15): replay the concurrent
        run's ADMITTED-EPOCH schedule serially over a cloned corpus.

        ``clone_requests`` is the same seeded schedule rebuilt over
        ``clone_corpus`` (``build_requests`` is a pure function of the
        seed, so expressions map 1:1 by position with leaf identity
        swapped to the clones; the clone must predate the concurrent
        run). The oracle walks epochs in lineage order: it evaluates
        every query the concurrent run admitted under epoch ``e``
        against the clone's epoch-``e`` state, then applies the lineage
        record's included batches (by the write positions that minted
        them) to advance the clone to ``e+1``. A query whose concurrent
        result matches neither its admitted epoch's bits is a TORN READ
        — the zero-torn-reads gate (fuzz family 29 / meta.epochs) diffs
        the two result lists positionally."""
        from ..query import exec as _exec
        from . import ingest as _ingest_mod

        clone_requests = list(clone_requests)
        if len(clone_requests) != len(report.results):
            raise ValueError("oracle schedule does not match the report")
        # batch id -> the clone-schedule position that minted it
        pos_of_batch = {
            bid: pos for pos, bid in enumerate(report.batch_ids)
            if bid is not None
        }
        by_epoch: Dict[int, List[int]] = {}
        for pos, ep in enumerate(report.epochs):
            if ep is not None:
                by_epoch.setdefault(ep, []).append(pos)
        results: List[object] = [None] * len(clone_requests)
        for pos, bid in enumerate(report.batch_ids):
            if bid is not None:
                results[pos] = ("write", bid)
        epoch = report.epoch_start
        # only flips that happened DURING this run advance the clone (the
        # lineage ring may retain older records from previous windows)
        lineage = [
            r for r in report.lineage
            if r.get("outcome") == "flipped" and r["parent"] >= epoch
        ]
        for rec in lineage + [None]:  # None = the final (current) epoch
            for pos in by_epoch.get(epoch, ()):
                results[pos] = _exec.execute(  # rb-ok: epoch-pin -- serial oracle: single-threaded lineage replay on a clone store; flips are applied between steps by this loop itself, never concurrently
                    clone_requests[pos].expr, cache=None
                )
            if rec is None:
                break
            for bid in rec["batches"]:
                wpos = pos_of_batch.get(bid)
                if wpos is None:
                    raise ValueError(
                        f"lineage batch {bid} has no write position in the "
                        "schedule (foreign submit during the run?)"
                    )
                _ingest_mod.apply_batches(
                    clone_corpus,
                    [_ingest_mod.MutationBatch(
                        clone_requests[wpos].tenant,
                        clone_requests[wpos].mutations,
                    )],
                )
            epoch = rec["epoch"]
        return results


class HarnessReport:
    """One run's outcome: per-request results aligned with the schedule,
    per-tenant stats, the aggregate wall, and — for epoch-store runs —
    the admitted-epoch schedule (per-position epoch for queries, minted
    batch id for writes) plus the lineage the run published, which is
    exactly what :meth:`LoadHarness.run_serial_epochs` replays."""

    def __init__(self, requests, results, stats, wall_s,
                 epochs=None, batch_ids=None, lineage=None, epoch_start=0,
                 profiles=None):
        self.requests = requests
        self.results = results
        self.stats = stats
        self.wall_s = wall_s
        self.epochs = epochs if epochs is not None else [None] * len(requests)
        self.batch_ids = (
            batch_ids if batch_ids is not None else [None] * len(requests)
        )
        self.lineage = lineage or []
        self.epoch_start = int(epoch_start)
        self.profiles = list(profiles) if profiles is not None else []

    @property
    def served(self) -> int:
        return sum(st.served for st in self.stats.values())

    @property
    def shed(self) -> int:
        return sum(st.shed for st in self.stats.values())

    @property
    def writes(self) -> int:
        return sum(st.writes for st in self.stats.values())

    def aggregate_qps(self) -> float:
        return round(self.served / self.wall_s, 1) if self.wall_s > 0 else 0.0

    def tenant_rows(self) -> Dict[str, dict]:
        """Per-tenant decomposition: served/shed/queued volume, achieved
        QPS, and harness-side p50/p99 per phase (the registry histograms
        carry the same answer — tests pin the two within one bucket
        ratio)."""
        by_name = {p.name: p for p in self.profiles}
        out = {}
        for tenant, st in sorted(self.stats.items()):
            prof = by_name.get(tenant)
            budget_ms = None
            if prof is not None:
                budget_ms = (
                    prof.p99_budget_ms
                    if prof.p99_budget_ms is not None
                    else _slo.LATENCY_CLASSES[prof.latency_class]
                )
            total_p99 = st.total_quantile_ms(0.99)
            out[tenant] = {
                "served": st.served,
                "shed": st.shed,
                "queued": st.queued,
                "writes": st.writes,
                "qps": round(st.served / self.wall_s, 1) if self.wall_s else 0.0,
                "queue_p50_ms": st.quantile_ms("queue", 0.5),
                "queue_p99_ms": st.quantile_ms("queue", 0.99),
                "execute_p50_ms": st.quantile_ms("execute", 0.5),
                "execute_p99_ms": st.quantile_ms("execute", 0.99),
                "latency_class": prof.latency_class if prof else None,
                "p99_budget_ms": budget_ms,
                "total_p99_ms": total_p99,
                "slo_ok": (
                    None if budget_ms is None or total_p99 is None
                    else bool(total_p99 <= budget_ms)
                ),
            }
        return out

    def class_rows(self) -> Dict[str, dict]:
        """Per-latency-class rollup (ISSUE 19): tenants pooled by their
        declared class, end-to-end p50/p99 over the pooled per-request
        totals, and the tightest budget in the class — the frontier
        gate's `every tenant's p99 holds its declared SLO` is judged per
        tenant in :meth:`tenant_rows`; this is the workload-level view
        (interactive vs batch) the rb_top latency panel renders."""
        pooled: Dict[str, TenantStats] = {}
        budgets: Dict[str, float] = {}
        members: Dict[str, List[str]] = {}
        for p in self.profiles:
            st = self.stats.get(p.name)
            if st is None:
                continue
            agg = pooled.setdefault(p.latency_class, TenantStats())
            agg.served += st.served
            agg.shed += st.shed
            agg.queue_s.extend(st.queue_s)
            agg.execute_s.extend(st.execute_s)
            budget = (
                p.p99_budget_ms if p.p99_budget_ms is not None
                else _slo.LATENCY_CLASSES[p.latency_class]
            )
            prev = budgets.get(p.latency_class)
            budgets[p.latency_class] = (
                budget if prev is None else min(prev, budget)
            )
            members.setdefault(p.latency_class, []).append(p.name)
        out = {}
        for cls, agg in sorted(pooled.items()):
            out[cls] = {
                "tenants": sorted(members[cls]),
                "served": agg.served,
                "shed": agg.shed,
                "budget_ms": budgets[cls],
                "p50_ms": agg.total_quantile_ms(0.5),
                "p99_ms": agg.total_quantile_ms(0.99),
            }
        return out
