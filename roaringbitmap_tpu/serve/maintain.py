"""Background maintenance tier: priced compaction over the live corpus
(ISSUE 16 tentpole, leg 2 — closing ROADMAP item 4).

The structure observatory (observe/structure.py) *sees* corpus-shape
drift; this module *acts* on it. A maintenance pass:

* re-runs format selection over the write-hot keys whose actual
  serialized size exceeds the size-rule optimum (the ledger's
  ``drift_targets`` — ``run_optimize`` per container, Container.java:882,
  never a full-corpus walk),
* merges the accumulated epoch deltas (the pass rides
  ``EpochStore.flip`` with a ``rewrite`` body, so the pending mutation
  log drains in the same writer-exclusive window),
* and re-packs the touched working sets through the pack cache (the
  flip's own working-set refresh).

**Every pass is a priced decision** (``serve.maintain`` — the EIGHTH
``cost/`` authority, cost/compaction.py): compact-now (predicted pass
wall from the authority's measured curves) vs let-it-ride (the
bytes-over-optimal drift priced at the declared exchange rate, scaled
by the delta accretion depth). A taken pass joins its measured wall in
the decision–outcome ledger — error-ratio rows, drift, and refit
exactly like every other authority.

**Snapshot isolation for free**: the pass runs inside the epoch-flip
machinery — a compaction is just a flip whose batches are rewrites, so
readers keep the old epoch until publish and can never observe a
half-compacted corpus. **Bit-identity is the oracle**: every rewrite is
audited value-for-value against the container it replaces before it is
installed; a mismatching rewrite is dropped (the old container stays)
and counted as an anomaly — compaction may change *representation*,
never *content* (fuzz family 30 hammers this against a no-compaction
twin).

Fault site ``serve.maintain`` (ISSUE 7 discipline): a non-fatal failure
at the pass entry fails CLOSED to the uncompacted epoch — the pass
aborts, the corpus keeps serving exactly the bits it already had, the
degrade is noted on the ladder, and the ``structure-drift`` /
``delta-accretion`` sentinel rules own the "drifting too long" signal.

The sentinel actuates this module (actuation kind ``maintain`` under
cooldown, observe/sentinel.py); bench/tests call :func:`run_pass`
directly.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..cost import compaction as _compaction_cost
from ..observe import decisions as _decisions
from ..observe import outcomes as _outcomes
from ..observe import registry as _registry
from ..observe import structure as _structure
from ..robust import errors as _rerrors
from ..robust import faults as _faults
from ..robust import ladder as _ladder
from . import epochs as _epochs

# pass outcomes (rb_tpu_serve_maintain_total)
PASS_OUTCOMES = ("compacted", "rode", "aborted", "noop")

_MAINTAIN_TOTAL = _registry.counter(
    _registry.SERVE_MAINTAIN_TOTAL,
    "Maintenance passes by outcome (compacted | rode = priced let-it-ride "
    "| aborted = fault/stall, uncompacted epoch kept | noop = nothing "
    "watched)",
    ("outcome",),
)
_MAINTAIN_SECONDS = _registry.histogram(
    _registry.SERVE_MAINTAIN_SECONDS,
    "Wall time of taken maintenance passes (the compaction flip end to "
    "end: drain + rewrite + working-set refresh + publish)",
)
_RECLAIMED_BYTES_TOTAL = _registry.counter(
    _registry.SERVE_MAINTAIN_RECLAIMED_BYTES_TOTAL,
    "Serialized bytes reclaimed by maintenance-pass format re-selection",
)
_KEYS_TOTAL = _registry.counter(
    _registry.SERVE_MAINTAIN_KEYS_TOTAL,
    "Chunk keys rewritten by maintenance passes",
)


def _rewrite_body(
    targets: List[Tuple[object, int, int]], corpus: List
) -> Tuple[callable, Dict]:
    """Build the flip's ``rewrite`` callable over the ledger's drift
    targets. The shared ``stats`` dict is filled in place when the flip
    runs the body (inside the writer-exclusive window)."""
    index_of = {id(bm): i for i, bm in enumerate(corpus)}
    stats: Dict = {
        "rewritten_keys": 0, "reclaimed_bytes": 0,
        "audited": 0, "anomalies": 0,
    }

    def rewrite(live_corpus):
        touched = set()
        for bm, key, _excess in targets:
            idx = index_of.get(id(bm))
            if idx is None:
                continue  # working set no longer part of this corpus
            hlc = bm.high_low_container
            i = hlc.get_index(key)
            if i < 0:
                continue  # key removed since the ledger last looked
            old = hlc.get_container_at_index(i)
            new = old.run_optimize()
            if new is old:
                continue  # already optimal (drifted back before the pass)
            # bit-identity audit: representation may change, content
            # never — a lossy rewrite is dropped (old container stays,
            # fail closed per key) and surfaced as an anomaly
            stats["audited"] += 1
            if new.cardinality != old.cardinality or not np.array_equal(
                new.to_array(), old.to_array()
            ):
                stats["anomalies"] += 1
                continue
            saved = old.serialized_size() - new.serialized_size()
            hlc.set_container_at_index(i, new)
            touched.add(idx)
            stats["rewritten_keys"] += 1
            stats["reclaimed_bytes"] += int(saved)
        return touched, stats

    return rewrite, stats


def run_pass(
    store: Optional["_epochs.EpochStore"] = None,
    reason: str = "manual",
    force: bool = False,
    now: Optional[float] = None,
) -> dict:
    """One priced maintenance pass over the current epoch store's corpus.
    Returns a record whose ``outcome`` is one of :data:`PASS_OUTCOMES`
    (a taken pass also carries the compaction flip's lineage record as
    ``record["flip"]``). ``force=True`` skips the price gate (bench's
    maintained twin and the fuzz family's forced passes), never the
    fault gate or the identity audit."""
    if store is None:
        store = _epochs.current_store()
    if store is None or not _structure.LEDGER.watched():
        _MAINTAIN_TOTAL.inc(1, ("noop",))
        return {"outcome": "noop", "reason": reason}
    try:
        _faults.fault_point("serve.maintain")
    except Exception as e:
        if _rerrors.classify(e) == _rerrors.FATAL:
            raise
        # fail CLOSED to the uncompacted epoch: the corpus keeps serving
        # exactly the bits it already had; drift keeps accruing and the
        # structure-drift / delta-accretion rules own "too long"
        _ladder.LADDER.note_degrade("serve.maintain", "compact", "ride", e)
        _MAINTAIN_TOTAL.inc(1, ("aborted",))
        _decisions.record_decision(
            "serve.maintain", "aborted", reason=reason,
            error=type(e).__name__,
        )
        return {"outcome": "aborted", "reason": reason,
                "error": type(e).__name__}
    # refresh the books (O(dirty keys)) and price the pass
    stats = _structure.LEDGER.refresh()
    targets = _structure.LEDGER.drift_targets()
    excess = sum(t[2] for t in targets)
    depth = int(stats.get("accretion_depth") or 0)
    log_depth = store.log.depth()
    predicted = _compaction_cost.MODEL.predict_us(
        "compact", keys=len(targets), batches=log_depth,
    )
    ride = _compaction_cost.MODEL.ride_cost_us(excess, depth=depth)
    verdict = "compact" if force or ride >= predicted else "ride"
    seq = _decisions.record_decision(
        "serve.maintain", verdict,
        outcome=(verdict == "compact" and _outcomes.enabled()),
        est_us={"compact": predicted, "ride": ride},
        drift_keys=len(targets), excess_bytes=int(excess),
        accretion_depth=depth, log_batches=log_depth, forced=bool(force),
    )
    if verdict == "ride":
        _MAINTAIN_TOTAL.inc(1, ("rode",))
        return {
            "outcome": "rode", "reason": reason,
            "drift_keys": len(targets), "excess_bytes": int(excess),
            "est_us": {"compact": predicted, "ride": ride},
        }
    rewrite, rw_stats = _rewrite_body(targets, store.corpus)
    t0 = time.perf_counter()
    flip = store.flip(reason=f"maintain:{reason}", now=now, rewrite=rewrite)
    wall_s = time.perf_counter() - t0
    if flip["outcome"] != "flipped":
        # the flip failed closed (its own fault gate, or a reader-drain
        # stall): the uncompacted epoch stands, nothing was rewritten
        _MAINTAIN_TOTAL.inc(1, ("aborted",))
        return {"outcome": "aborted", "reason": reason, "flip": flip}
    if seq is not None:
        _outcomes.resolve(seq, "serve.maintain", wall_s, engine="compact")
    _MAINTAIN_TOTAL.inc(1, ("compacted",))
    _MAINTAIN_SECONDS.observe(wall_s)
    if rw_stats["reclaimed_bytes"] > 0:
        _RECLAIMED_BYTES_TOTAL.inc(rw_stats["reclaimed_bytes"])
    if rw_stats["rewritten_keys"] > 0:
        _KEYS_TOTAL.inc(rw_stats["rewritten_keys"])
    # the accumulated deltas are merged and the shape rewritten: settle
    # the accretion depth and re-export the gauges from the fresh books
    _structure.LEDGER.settle_accretion()
    _structure.LEDGER.refresh()
    record = {
        "outcome": "compacted", "reason": reason, "wall_s": round(wall_s, 6),
        "flip": flip, **rw_stats,
        "est_us": {"compact": predicted, "ride": ride},
    }
    _LAST.update(record)
    return record


# the last taken/priced pass (rb_top's structure panel + insights feed);
# plain dict, read-copied by callers
_LAST: Dict = {}


def last_pass() -> dict:
    """The most recent compacted pass's record ({} before any)."""
    return dict(_LAST)


def reset() -> None:
    """Forget the last-pass record (tests/bench isolation)."""
    _LAST.clear()
