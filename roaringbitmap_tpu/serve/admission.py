"""Priced admission control: per-tenant token-bucket quotas + a global
in-flight cap with shed-or-queue backpressure (ISSUE 14 tentpole,
leg 3).

Every request entering the serving tier passes one
:meth:`AdmissionController.admit` call, which yields one of three
verdicts:

* ``admit`` — the tenant's token bucket has budget and a global
  in-flight slot is free: the request proceeds immediately;
* ``queue`` — the tenant has quota budget but the global in-flight cap
  is full and the backpressure queue has room: the caller blocks until
  a slot frees (or its wait budget expires, which degrades the verdict
  to a shed with the token refunded — a late answer the client gave up
  on is a shed, not a success). Quota exhaustion itself never queues:
  quotas are hard limits, so an empty bucket sheds immediately — the
  queue absorbs CAPACITY pressure, not quota breaches;
* ``shed`` — quota exhausted and the queue is full (or the wait budget
  expired): the request is REJECTED with a typed
  :class:`ShedRejection`. A shed never returns a wrong answer — it
  returns no answer, loudly, which is the whole point of admission
  control (tests/test_serve.py pins the shed-never-loses-a-result
  semantics).

**Priced verdicts** (the sixth cost authority, cost/admission.py): every
admit/queue verdict records a ``serve.admit`` decision carrying the
predicted admission wall (``est_us[verdict]`` — admit bookkeeping cost,
or ``depth * queue_slot_us`` expected backpressure wait) and resolves it
with the measured wall on grant, so the decision–outcome ledger scores
the admission curve exactly like every other pricing authority
(predicted queue wait vs measured — error-ratio rows, drift, refit).
Shed verdicts are decision-logged but not joined (nothing executes).

**Fault site** ``serve.admit`` (ISSUE 7 discipline): an injected or real
non-fatal failure inside the verdict path fails OPEN — the request is
admitted with the degradation noted — because admission is a
load-management optimization, never a correctness gate; losing it must
degrade to "serve everything" (fuzz family 28 pins bit-exactness under
``RB_TPU_FAULTS`` schedules over this site).

Lock discipline: the controller's condition lock is a LEAF — it guards
the buckets/in-flight/queue counters only; decision records, outcome
joins, metric bumps, and the fault point all run outside it, so admit()
nests safely under callers holding other framework locks (hammered
under the lock witness in tests/test_serve.py).

Determinism: the clock is injectable (``clock=`` at construction and
``now=`` per call), so quota arithmetic replays exactly under a fake
clock — the admission-determinism tests drive verdict sequences with no
real time at all.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from ..observe import decisions as _decisions
from ..observe import outcomes as _outcomes
from ..observe import registry as _registry
from ..observe import timeline as _timeline
from ..robust import errors as _rerrors
from ..robust import faults as _faults
from ..robust import ladder as _ladder
from ..cost import admission as _admission_cost
from . import slo as _slo
from .slo import TENANTS

DEFAULT_QUEUE_LIMIT = 64
DEFAULT_QUEUE_TIMEOUT_S = 5.0

VERDICTS = ("admit", "queue", "shed")

_ADMIT_TOTAL = _registry.counter(
    _registry.SERVE_ADMIT_TOTAL,
    "Admission verdicts by tenant (admit | queue | shed); queue counts "
    "requests that waited in the backpressure queue before a grant",
    ("tenant", "verdict"),
)
_QUEUE_COUNT = _registry.gauge(
    _registry.SERVE_QUEUE_COUNT,
    "Requests currently parked in the admission backpressure queue",
)
_INFLIGHT_COUNT = _registry.gauge(
    _registry.SERVE_INFLIGHT_COUNT,
    "Requests currently holding a global in-flight slot",
)
_SATURATION = _registry.gauge(
    _registry.SERVE_SATURATION_RATIO,
    "Per-tenant token-bucket depletion (0 = full quota budget available, "
    "1 = quota exhausted — the tenant-saturation sentinel rule's gauge)",
    ("tenant",),
)


class ShedRejection(Exception):
    """Typed admission rejection: the request was NOT served (quota
    exhausted / queue full / wait budget expired). Carries the tenant
    and the reason so callers can retry, downgrade, or surface a 429 —
    never mistakable for a result."""

    def __init__(self, tenant: str, reason: str):
        super().__init__(f"request shed for tenant {tenant!r}: {reason}")
        self.tenant = tenant
        self.reason = reason


class _Bucket:
    """Per-tenant token bucket (pure arithmetic; the controller's lock
    owns all mutation)."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.stamp = float(now)

    def refill(self, now: float) -> None:
        # the stamp only ever advances: admit() reads the clock OUTSIDE
        # the controller lock, so a racing older `now` must not rewind
        # the stamp and re-credit an already-credited interval
        if now > self.stamp:
            self.tokens = min(self.burst, self.tokens + (now - self.stamp) * self.rate)
            self.stamp = now

    def take(self) -> bool:
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def saturation(self) -> float:
        return round(1.0 - self.tokens / self.burst, 4)


class Ticket:
    """One admission grant (or rejection). ``verdict`` is the recorded
    decision; ``admitted`` is whether the caller may proceed. Use as a
    context manager so the in-flight slot always releases."""

    __slots__ = ("controller", "tenant", "verdict", "admitted", "queue_s", "degraded")

    def __init__(self, controller, tenant, verdict, admitted, queue_s, degraded=False):
        self.controller = controller
        self.tenant = tenant
        self.verdict = verdict
        self.admitted = admitted
        self.queue_s = queue_s
        self.degraded = degraded

    def release(self) -> None:
        if self.admitted:
            self.controller._release()
            self.admitted = False

    def __enter__(self) -> "Ticket":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def _default_inflight() -> int:
    raw = os.environ.get("RB_TPU_SERVE_INFLIGHT")
    try:
        if raw:
            return max(1, int(raw))
    except ValueError:
        pass
    return 2 * (os.cpu_count() or 1)


class AdmissionController:
    """Token-bucket quotas (from the declared tenant registry) + a global
    in-flight cap with a bounded backpressure queue."""

    def __init__(
        self,
        max_inflight: Optional[int] = None,
        queue_limit: int = DEFAULT_QUEUE_LIMIT,
        queue_timeout_s: float = DEFAULT_QUEUE_TIMEOUT_S,
        clock=time.monotonic,
    ):
        self.max_inflight = (
            int(max_inflight) if max_inflight is not None else _default_inflight()
        )
        if self.max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got {self.max_inflight}")
        self.queue_limit = max(0, int(queue_limit))
        self.queue_timeout_s = float(queue_timeout_s)
        # the PROCESS tenant registry, deliberately not injectable: every
        # metric label value below is the lint-enforced TENANTS[tenant]
        # spelling, so a controller over a foreign registry would take an
        # in-flight slot and then KeyError on the label lookup
        self.tenants = TENANTS
        self._clock = clock
        self._cond = threading.Condition()  # leaf: guards the fields below only
        self._buckets: Dict[str, _Bucket] = {}  # guarded-by: self._cond
        self._inflight = 0  # guarded-by: self._cond
        self._queued = 0  # guarded-by: self._cond

    # -- internals (all called with self._cond held) ------------------------

    def _bucket(self, tenant: str, now: float, quota: dict) -> _Bucket:
        # quota is prefetched by admit() OUTSIDE this lock: reading the
        # tenant registry here would nest its leaf lock under ours and
        # break the leaf claim the witness hammer pins
        b = self._buckets.get(tenant)
        if b is None:
            b = self._buckets[tenant] = _Bucket(quota["quota_qps"], quota["burst"], now)
        elif b.rate != quota["quota_qps"] or b.burst != quota["burst"]:
            # the registry documents declare() as idempotent-with-update:
            # a live quota change must reach the cached bucket, or the
            # tenant keeps being shed at the old rate until a reset()
            b.rate = quota["quota_qps"]
            b.burst = quota["burst"]
            b.tokens = min(b.tokens, b.burst)
        b.refill(now)
        return b

    def _release(self) -> None:
        with self._cond:
            self._inflight -= 1
            inflight = self._inflight
            # notify_all, not notify: a single wake can land on a waiter
            # that already timed out (it stays in the waiter list until
            # it reacquires the lock), parking the freed slot while live
            # waiters sleep out their full budget
            self._cond.notify_all()
        _INFLIGHT_COUNT.set(inflight)

    # -- the verdict ---------------------------------------------------------

    def admit(
        self,
        tenant: str,
        now: Optional[float] = None,
        wait: bool = True,
        epoch: Optional[int] = None,
    ) -> Ticket:
        """One admission verdict for ``tenant`` (must be declared in the
        tenant registry). ``now`` pins the quota clock (fake-clock
        determinism); ``wait=False`` makes a queue verdict return
        immediately un-admitted instead of blocking (the determinism
        tests' non-blocking form); ``epoch`` stamps the serving epoch the
        request was admitted under into the decision inputs (ISSUE 15 —
        the outcomes ledger then decomposes admission joins by epoch).
        Returns a :class:`Ticket`; a shed verdict's ticket has
        ``admitted=False`` — callers that cannot degrade raise
        :class:`ShedRejection` via :meth:`admit_or_raise`."""
        canon = self.tenants[tenant]
        extra = {} if epoch is None else {"epoch": int(epoch)}
        try:
            _faults.fault_point("serve.admit")
        except Exception as e:
            if _rerrors.classify(e) == _rerrors.FATAL:
                raise
            # fail OPEN: admission is load management, not correctness —
            # a broken quota path must degrade to "serve everything",
            # never to dropping or corrupting requests
            _ladder.LADDER.note_degrade("serve.admit", "quota", "fail-open", e)
            with self._cond:
                self._inflight += 1
                inflight = self._inflight
            _INFLIGHT_COUNT.set(inflight)
            _ADMIT_TOTAL.inc(1, (TENANTS[tenant], "admit"))
            _decisions.record_decision(
                "serve.admit", "admit", tenant=canon, degraded=True, **extra,
            )
            return Ticket(self, canon, "admit", True, 0.0, degraded=True)
        t0 = time.perf_counter()
        if now is None:
            now = self._clock()
        quota = self.tenants.quota(canon)  # registry leaf lock, pre-cond
        with self._cond:
            b = self._bucket(canon, now, quota)
            has_token = b.take()
            saturation = b.saturation()
            depth = self._queued
            if has_token and self._inflight < self.max_inflight:
                verdict = "admit"
                self._inflight += 1
            elif has_token and depth < self.queue_limit:
                verdict = "queue"
                self._queued += 1
            else:
                verdict = "shed"
                if has_token:  # capacity shed, not quota: refund the token
                    b.tokens = min(b.burst, b.tokens + 1.0)
                    saturation = b.saturation()
            inflight, queued = self._inflight, self._queued
        # telemetry + decision outside the leaf lock
        _INFLIGHT_COUNT.set(inflight)
        _QUEUE_COUNT.set(queued)
        _SATURATION.set(saturation, (TENANTS[tenant],))
        # verdict counters count each request ONCE, by FINAL outcome: a
        # queue verdict is counted only when it resolves below (grant ->
        # "queue", timeout -> "shed") — double-counting would dilute the
        # tenant-saturation rule's shed fraction to <= 0.5 during a
        # complete timeout-driven outage
        if verdict != "queue":
            _ADMIT_TOTAL.inc(1, (TENANTS[tenant], str(verdict)))
        if verdict == "shed":
            _decisions.record_decision(
                "serve.admit", "shed", tenant=canon, depth=depth,
                inflight=inflight, saturation=saturation, **extra,
            )
            _timeline.instant(
                "serve.shed", "serve", tenant=canon, depth=depth,
            )
            return Ticket(self, canon, "shed", False, 0.0)
        predicted = _admission_cost.MODEL.predict_us(verdict, depth)
        seq = _decisions.record_decision(
            "serve.admit", verdict, outcome=_outcomes.enabled(),
            est_us={verdict: predicted}, tenant=canon, depth=depth,
            inflight=inflight, saturation=saturation, **extra,
        )
        if verdict == "admit":
            _outcomes.resolve(
                seq, "serve.admit", time.perf_counter() - t0, engine="admit",
            )
            return Ticket(self, canon, "admit", True, 0.0)
        # queue verdict: wait for an in-flight slot (bounded). An
        # interactive tenant's wait is additionally clamped to its
        # declared p99 budget (ISSUE 19): queueing past the whole SLO
        # just delivers a guaranteed breach — shedding at the budget
        # lets the caller retry or degrade while the answer could still
        # matter. Other classes keep the plain capacity timeout.
        wait_budget_s = self.queue_timeout_s
        if quota.get("latency_class") == "interactive":
            budget_ms = quota.get("p99_budget_ms")
            if budget_ms:
                wait_budget_s = min(wait_budget_s, float(budget_ms) / 1e3)
        granted = False
        if wait:
            deadline = time.perf_counter() + wait_budget_s
            with self._cond:
                while True:
                    if self._inflight < self.max_inflight:
                        self._inflight += 1
                        granted = True
                        break
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                self._queued -= 1
                inflight, queued = self._inflight, self._queued
            _INFLIGHT_COUNT.set(inflight)
            _QUEUE_COUNT.set(queued)
        else:
            with self._cond:
                self._queued -= 1
                queued = self._queued
                # nothing was served: refund the token (the capacity-shed
                # discipline — quota must only be spent on served work)
                b.tokens = min(b.burst, b.tokens + 1.0)
            _QUEUE_COUNT.set(queued)
            # non-blocking form: the verdict IS queue (would-block); the
            # caller declined the wait, so there is no timeout shed here
            _ADMIT_TOTAL.inc(1, (TENANTS[tenant], "queue"))
            _outcomes.resolve(
                seq, "serve.admit", time.perf_counter() - t0, engine="queue",
            )
            return Ticket(self, canon, "queue", False, 0.0)
        queue_s = time.perf_counter() - t0
        # the queue verdict's measured join: predicted backpressure wait
        # vs the wall the request actually waited (granted or not — the
        # wait happened either way and the curve is scored on it)
        _outcomes.resolve(seq, "serve.admit", queue_s, engine="queue")
        if granted:
            _ADMIT_TOTAL.inc(1, (TENANTS[tenant], "queue"))
            return Ticket(self, canon, "queue", True, queue_s)
        with self._cond:
            # timed out un-served: refund the token (see the non-blocking
            # branch above — quota is only spent on served work)
            b.tokens = min(b.burst, b.tokens + 1.0)
        _ADMIT_TOTAL.inc(1, (TENANTS[tenant], "shed"))
        _timeline.instant(
            "serve.shed", "serve", tenant=canon, reason="queue-timeout",
        )
        return Ticket(self, canon, "shed", False, queue_s)

    def admit_or_raise(self, tenant: str, now: Optional[float] = None) -> Ticket:
        t = self.admit(tenant, now=now)
        if not t.admitted:
            raise ShedRejection(t.tenant, "queue-timeout" if t.queue_s else "quota")
        return t

    # -- read APIs -----------------------------------------------------------

    def stats(self) -> dict:
        with self._cond:
            return {
                "max_inflight": self.max_inflight,
                "inflight": self._inflight,
                "queued": self._queued,
                "queue_limit": self.queue_limit,
                "saturation": {
                    t: b.saturation() for t, b in sorted(self._buckets.items())
                },
            }

    def reset(self) -> None:
        """Drop bucket state (tests, bench windows); quotas re-read from
        the tenant registry on next admit."""
        with self._cond:
            self._buckets.clear()
            self._inflight = 0
            self._queued = 0
            self._cond.notify_all()
        _INFLIGHT_COUNT.set(0)
        _QUEUE_COUNT.set(0)


# The process-wide controller the harness (and rb_top's demo) drive.
CONTROLLER = AdmissionController()
