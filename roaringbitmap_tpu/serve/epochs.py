"""Epoch ledger: snapshot-isolated publication of the streaming write
path (ISSUE 15 tentpole, leg 2 — closing ROADMAP item 1).

The serving corpus advances in **epochs**. Readers are admitted under
the current epoch and pinned to it for the whole execution; writers only
ever append stamped batches to the ingest log (serve/ingest.py). The
corpus bitmaps are mutated at exactly one place — the **epoch flip** —
inside a writer-exclusive window, in four stages (each a
``rb_tpu_serve_flip_stage_seconds{stage}`` latency sample AND a timeline
span):

* ``drain``   — seal admission (new readers park on the store condition)
  and wait for in-flight readers of the current epoch to finish. After
  drain, nobody is reading, so the in-place mutation below cannot tear
  anyone: a reader sees exactly pre-flip or post-flip bits, never a
  mixture (the **snapshot-isolation contract**, pinned by the
  concurrency hammer in tests/test_epochs.py and fuzz family 29).
* ``repack``  — drain the mutation log, stream the merged batches through
  the sorted-stream writer surface (``BitmapWriter(into=...)`` — every
  flush lands through the attributed mutators, so per-key dirty tracking
  stays truthful), then refresh each registered working set through
  ``store.packed_for``: the PR 8/11 delta machinery turns k mutated
  containers into ONE O(k) ``apply_delta`` scatter per touched working
  set — no full repacks on the flip path (the lineage record carries the
  delta-vs-full evidence from the pack-cache counters).
* ``publish`` — bump the epoch, append the lineage record (epoch id,
  parent, included batch ids, flip wall), export the epoch gauge, and
  observe every published batch's ingest->queryable lag into
  ``rb_tpu_serve_freshness_seconds{tenant}`` — data freshness becomes a
  first-class serving signal next to the latency SLOs.
* ``reclaim`` — reopen admission (parked readers wake under the NEW
  epoch) and settle gauges.

**Validated publication across epochs**: the flip composes with the
in-flight table's contract (query/inflight.py) rather than replacing it.
Readers pinned by :meth:`EpochStore.reader` can never overlap the
mutation window, and any publication raced from OUTSIDE a reader pin is
still dropped by fingerprint re-validation — the flip's writer bumps
every touched bitmap's ``fingerprint()``, so a result computed against
epoch N can never publish under epoch N+1's keys (regression-pinned in
tests/test_epochs.py).

**The flip is a priced decision** (``epoch.flip`` — the SEVENTH ``cost/``
authority, cost/epoch.py): :meth:`EpochStore.maybe_flip` weighs
flip-now (predicted flip wall from the authority's measured curves)
against accumulate-more (pending staleness priced at the declared
exchange rate), records the verdict with its inputs, and joins a taken
flip's measured wall in the decision–outcome ledger — error-ratio rows,
drift, and refit exactly like every other authority.

Epoch ids are process-unbounded: they ride the lineage ledger, timeline
span attrs, and decision inputs — NEVER metric label values (the
metric-naming rule enforces this like trace ids and tenant names).

Fault site ``epoch.flip`` (ISSUE 7 discipline): a non-fatal failure at
the flip entry fails CLOSED to the OLD epoch — the flip aborts, the log
keeps accumulating, readers keep serving the last published snapshot
(stale but never torn), and the degrade is noted on the ladder. The
``freshness-lag-breach`` / ``epoch-flip-stall`` sentinel rules own the
"stale for too long" signal.

Lock discipline: the store condition is a LEAF — it guards the epoch
counter, reader count, flip flag, and lineage ring only. The repack
stage runs OUTSIDE it (admission is sealed by the flag, so the window is
writer-exclusive without holding the lock across pack work), and every
metric bump / decision record happens outside too (hammered under the
lock witness in tests/test_epochs.py).
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from ..observe import decisions as _decisions
from ..observe import outcomes as _outcomes
from ..observe import registry as _registry
from ..observe import structure as _structure
from ..observe import timeline as _timeline
from ..observe.histogram import latency_histogram
from ..robust import errors as _rerrors
from ..robust import faults as _faults
from ..robust import ladder as _ladder
from ..cost import epoch as _epoch_cost
from . import ingest as _ingest
from .ingest import IngestLog

# the declared flip-stage label set (rb_tpu_serve_flip_stage_seconds)
FLIP_STAGES = ("drain", "repack", "publish", "reclaim")
# flip outcomes (rb_tpu_serve_epoch_flip_total)
FLIP_OUTCOMES = ("flipped", "noop", "aborted", "stalled")

DEFAULT_MAX_LINEAGE = 256
# a drain that cannot complete within this window is a stall, not a wait:
# the flip aborts (stale-but-consistent) and the stall is visible to the
# epoch-flip-stall sentinel rule via the still-nonzero mutlog gauge
DEFAULT_DRAIN_TIMEOUT_S = 30.0

FLIP_STAGE_SECONDS = latency_histogram(
    _registry.SERVE_FLIP_STAGE_SECONDS,
    "Epoch flip stage walls (drain = seal + wait for in-flight readers, "
    "repack = writer stream + O(k) delta repack per touched working set, "
    "publish = epoch bump + lineage + freshness, reclaim = reopen "
    "admission)",
    ("stage",),
)
_FLIP_TOTAL = _registry.counter(
    _registry.SERVE_EPOCH_FLIP_TOTAL,
    "Epoch flips by outcome (flipped | noop = empty log | aborted = "
    "fault/degrade, old epoch kept | stalled = reader drain timed out)",
    ("outcome",),
)
_EPOCH_COUNT = _registry.gauge(
    _registry.SERVE_EPOCH_COUNT,
    "Current published epoch id of the serving corpus (a gauge VALUE — "
    "epoch ids are unbounded and never metric label values)",
)

# the most recently constructed store: the rb_top epoch panel's and the
# flight bundle's lineage source (a weakref — tests constructing many
# stores never leak them through this module)
_CURRENT: Optional["weakref.ref[EpochStore]"] = None


def current_store() -> Optional["EpochStore"]:
    """The live process EpochStore (newest constructed), or None."""
    ref = _CURRENT
    return ref() if ref is not None else None


class EpochTicket:
    """One reader admission: pins the epoch the reader was admitted
    under until :meth:`release` (use as a context manager). The flip's
    drain stage waits on these pins — holding one guarantees the corpus
    cannot mutate under the reader."""

    __slots__ = ("store", "epoch", "_released")

    def __init__(self, store: "EpochStore", epoch: int):
        self.store = store
        self.epoch = epoch
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self.store._release_reader()

    def __enter__(self) -> "EpochTicket":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class EpochStore:
    """The epoch-versioned serving corpus: a list of bitmaps, the ingest
    log feeding it, and the flip machinery publishing new epochs."""

    def __init__(
        self,
        corpus: Sequence,
        log: Optional[IngestLog] = None,
        max_lineage: int = DEFAULT_MAX_LINEAGE,
        drain_timeout_s: float = DEFAULT_DRAIN_TIMEOUT_S,
        clock=time.monotonic,
    ):
        global _CURRENT
        if not len(corpus):
            raise ValueError("epoch store needs a non-empty corpus")
        self.corpus = list(corpus)
        self.log = log if log is not None else IngestLog()
        self.drain_timeout_s = float(drain_timeout_s)
        self._clock = clock
        self._cond = threading.Condition()  # leaf: guards the fields below only
        self._epoch = 0  # guarded-by: self._cond
        self._readers = 0  # guarded-by: self._cond
        self._flipping = False  # guarded-by: self._cond
        self._lineage: "deque[dict]" = deque(maxlen=int(max_lineage))  # guarded-by: self._cond
        # registered working sets: tuples of corpus indices the repack
        # stage refreshes through the pack cache (default: the whole
        # corpus as one working set)
        self._working_sets: List[Tuple[int, ...]] = [  # guarded-by: self._cond
            tuple(range(len(self.corpus)))
        ]
        # the attached durable store (ISSUE 17): when set, every
        # published flip runs its priced persist verdict post-publish
        self._durable = None
        _EPOCH_COUNT.set(0)
        _CURRENT = weakref.ref(self)

    # -- durable attachment (ISSUE 17) ---------------------------------------

    def attach_durable(self, durable) -> None:
        """Attach a ``durable.DurableStore``: after every published
        flip, its :meth:`~..durable.store.DurableStore.on_flip` hook
        refreshes the persist backlog gauge and runs the priced
        persist-now-vs-skip verdict. Detach with ``None``."""
        self._durable = durable

    def restore(self, epoch: int, lineage: Sequence[dict]) -> None:
        """Resume this store at a recovered epoch (durable/recovery.py):
        the epoch counter jumps to the persisted value and the lineage
        ledger is rehydrated, so replay oracles and the observatory see
        an unbroken history across the restart. Only valid before the
        first flip (a freshly constructed store)."""
        with self._cond:
            if self._epoch != 0 or self._lineage:
                raise ValueError(
                    "restore() requires a freshly constructed store"
                )
            self._epoch = int(epoch)
            for rec in lineage:
                self._lineage.append(dict(rec))
        _EPOCH_COUNT.set(int(epoch))

    # -- reader admission ----------------------------------------------------

    def current(self) -> int:
        with self._cond:
            return self._epoch

    def reader(self, timeout_s: Optional[float] = None) -> EpochTicket:
        """Admit one reader under the current epoch (parks while a flip
        is publishing; a bounded park — past ``timeout_s`` it raises
        rather than deadlocking on a wedged flip)."""
        deadline = (
            None if timeout_s is None
            else time.perf_counter() + float(timeout_s)
        )
        with self._cond:
            while self._flipping:
                remaining = (
                    None if deadline is None
                    else deadline - time.perf_counter()
                )
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        "epoch reader admission timed out waiting for an "
                        "in-progress flip"
                    )
                self._cond.wait(remaining)
            self._readers += 1
            return EpochTicket(self, self._epoch)

    def _release_reader(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers <= 0:
                self._cond.notify_all()

    def readers(self) -> int:
        with self._cond:
            return self._readers

    # -- working sets --------------------------------------------------------

    def register_working_set(self, indices: Sequence[int]) -> None:
        """Register a working set (corpus indices) the flip keeps
        delta-fresh in the pack cache. The whole corpus is registered by
        default; callers with finer-grained resident sets narrow the
        repack to what is actually resident."""
        ws = tuple(sorted({int(i) for i in indices}))
        if not ws:
            raise ValueError("working set must name at least one bitmap")
        if ws[0] < 0 or ws[-1] >= len(self.corpus):
            raise IndexError(f"working set {ws} outside the corpus")
        full = tuple(range(len(self.corpus)))
        with self._cond:
            if self._working_sets == [full]:
                if ws == full:
                    return  # the default already covers it
                # first narrower registration replaces the default
                self._working_sets = []
            if ws not in self._working_sets:
                self._working_sets.append(ws)

    # -- ingestion (delegates to the log) ------------------------------------

    def submit(self, tenant: str, mutations: Dict, stamp=None):
        """Append one stamped mutation batch (readers unaffected)."""
        return self.log.submit(tenant, mutations, stamp=stamp)

    # -- the flip ------------------------------------------------------------

    def flip(
        self,
        reason: str = "manual",
        now: Optional[float] = None,
        rewrite=None,
    ) -> dict:
        """Publish a new epoch from the pending mutation log. Returns the
        flip record (also appended to the lineage ledger when the flip
        publishes): ``outcome`` is one of :data:`FLIP_OUTCOMES`.

        ``rewrite`` turns the flip into a **compaction** (ISSUE 16): a
        callable run inside the repack stage's writer-exclusive window,
        after the drained batches are applied — it may rewrite corpus
        containers IN PLACE as long as every rewrite is bit-identical
        (a compaction is just a flip whose batches are rewrites; the
        maintenance pass audits identity per container). It returns
        ``(touched_indices, stats_dict)``; the indices join the batch
        set for the working-set refresh and the stats land on the
        lineage record as ``record["rewrite"]``. A rewrite flip
        publishes even when the mutation log is empty — the new epoch
        IS the compacted corpus."""
        try:
            _faults.fault_point("epoch.flip")
        except Exception as e:
            if _rerrors.classify(e) == _rerrors.FATAL:
                raise
            # fail CLOSED to the old epoch: readers keep serving the last
            # published snapshot (stale, never torn); the log accumulates
            # and the sentinel owns the "stale too long" signal
            _ladder.LADDER.note_degrade("epoch.flip", "flip", "accumulate", e)
            _FLIP_TOTAL.inc(1, ("aborted",))
            with self._cond:
                epoch = self._epoch
            _decisions.record_decision(
                "epoch.flip", "aborted", reason=reason, epoch=epoch,
                error=type(e).__name__,
            )
            return {"outcome": "aborted", "epoch": epoch, "reason": reason}
        if now is None:
            now = self._clock()
        t_flip = time.perf_counter()
        with _timeline.tspan("epoch.flip", "epoch", reason=reason):
            # ---- drain: seal admission, wait out in-flight readers ----
            stalled = False
            batches = []
            with _timeline.stage(
                FLIP_STAGE_SECONDS, "drain", "epoch.drain", cat="epoch",
            ):
                deadline = time.perf_counter() + self.drain_timeout_s
                with self._cond:
                    while self._flipping:  # serialize concurrent flips
                        if not self._cond.wait(deadline - time.perf_counter()):
                            break
                    if self._flipping:
                        stalled = True
                    else:
                        self._flipping = True
                        while self._readers > 0:
                            remaining = deadline - time.perf_counter()
                            if remaining <= 0 or not self._cond.wait(remaining):
                                if self._readers > 0:
                                    stalled = True
                                    self._flipping = False
                                    self._cond.notify_all()
                                break
                    epoch = self._epoch
                if not stalled:
                    # the log drain is part of the drain stage: after it
                    # the writer-exclusive window owns every batch
                    batches = self.log.drain()
            if stalled:
                _FLIP_TOTAL.inc(1, ("stalled",))
                _decisions.record_decision(
                    "epoch.flip", "stalled", reason=reason, epoch=epoch,
                )
                return {"outcome": "stalled", "epoch": epoch, "reason": reason}
            try:
                if not batches and rewrite is None:
                    _FLIP_TOTAL.inc(1, ("noop",))
                    return {"outcome": "noop", "epoch": epoch, "reason": reason}
                # ---- repack: writer stream + O(k) delta per working set ----
                with _timeline.stage(
                    FLIP_STAGE_SECONDS, "repack", "epoch.repack", cat="epoch",
                    batches=len(batches),
                ):
                    merged = _ingest.merge_batches(batches)
                    touched = sorted(merged)
                    _ingest.apply_merged(self.corpus, merged)
                    rewrite_stats = None
                    if rewrite is not None:
                        # the compaction body: runs AFTER the drained
                        # batches land so it re-selects the post-merge
                        # containers, BEFORE the working-set refresh so
                        # the pack cache sees the rewritten rows
                        rewritten, rewrite_stats = rewrite(self.corpus)
                        touched = sorted(set(touched) | set(rewritten))
                    delta = self._repack_working_sets(touched)
                # ---- publish: bump epoch, lineage, freshness ----
                with _timeline.stage(
                    FLIP_STAGE_SECONDS, "publish", "epoch.publish",
                    cat="epoch", epoch=epoch + 1,
                ):
                    record = {
                        "outcome": "flipped",
                        "epoch": epoch + 1,
                        "parent": epoch,
                        "reason": reason,
                        "batches": [b.batch_id for b in batches],
                        "tenants": sorted({b.tenant for b in batches}),
                        "values": int(sum(b.n_values for b in batches)),
                        "touched_bitmaps": touched,
                        "delta": delta,
                        "ts": now,
                    }
                    if rewrite_stats is not None:
                        record["rewrite"] = rewrite_stats
                    with self._cond:
                        self._epoch = epoch + 1
                        self._lineage.append(record)
                    _EPOCH_COUNT.set(epoch + 1)
                    _ingest.observe_freshness(batches, now=self._clock())
                    if batches:
                        # the structure observatory's accretion-depth
                        # gauge: delta batches folded into the corpus
                        # since the last maintenance pass settled it
                        _structure.LEDGER.accrete(len(batches))
            finally:
                # ---- reclaim: reopen admission (parked readers wake
                # under the new epoch), settle state on EVERY exit path —
                # an exception inside repack/publish must not wedge
                # admission shut
                with _timeline.stage(
                    FLIP_STAGE_SECONDS, "reclaim", "epoch.reclaim",
                    cat="epoch",
                ):
                    with self._cond:
                        self._flipping = False
                        self._cond.notify_all()
        record["wall_s"] = round(time.perf_counter() - t_flip, 6)
        _FLIP_TOTAL.inc(1, ("flipped",))
        durable = self._durable
        if durable is not None:
            # post-publish durability hook (ISSUE 17): the persist
            # verdict is priced and fails CLOSED inside the durable
            # store (only FATAL propagates), so an aborted persist
            # leaves this flip's record — and the published epoch —
            # untouched in memory
            durable_rec = durable.on_flip(self, record)
            record["durable"] = durable_rec.get("outcome")
        return record

    def _repack_working_sets(self, touched: List[int]) -> dict:
        """Refresh every registered working set that intersects the
        touched bitmaps through the pack cache (ONE get_packed per set —
        a warm mutated set takes the O(k) ``apply_delta`` path). Each
        refresh is classified through ``PackCache.last_route`` (a
        thread-local read, so concurrent non-epoch cache users cannot
        pollute the lineage's delta-vs-full evidence)."""
        from ..parallel import store as _store

        touched_set = set(touched)
        sets_repacked = 0
        delta_rows = 0
        full_repacks = 0
        with self._cond:
            working_sets = list(self._working_sets)
        for ws in working_sets:
            if not touched_set.intersection(ws):
                continue
            _store.packed_for([self.corpus[i] for i in ws])
            sets_repacked += 1
            route = _store.PACK_CACHE.last_route()
            if route is not None:
                kind, rows = route
                delta_rows += int(rows)
                if kind == "full":
                    full_repacks += 1
        return {
            "working_sets": sets_repacked,
            "delta_rows": delta_rows,
            "full_repacks": full_repacks,
        }

    # -- the priced verdict (the seventh cost authority) ---------------------

    def maybe_flip(
        self, reason: str = "ingest", now: Optional[float] = None
    ) -> dict:
        """The flip-now-vs-accumulate-more verdict, priced by the
        ``epoch-flip`` cost authority: flip when the pending batches'
        staleness (priced at the declared exchange rate) outweighs the
        predicted flip wall. A taken flip's decision is joined with its
        measured wall; an accumulate verdict is decision-logged but not
        joined (nothing executes)."""
        if now is None:
            now = self._clock()
        depth = self.log.depth()
        if depth == 0:
            return {"outcome": "noop", "epoch": self.current()}
        stamps = self.log.stamps()
        staleness_s = max(0.0, now - min(stamps)) if stamps else 0.0
        values = self.log.pending_values()
        with self._cond:
            epoch = self._epoch
            readers = self._readers
        predicted_flip = _epoch_cost.MODEL.predict_us(
            "flip", rows=values, readers=readers
        )
        accumulate_cost = _epoch_cost.MODEL.staleness_cost_us(
            staleness_s, depth
        )
        verdict = "flip" if accumulate_cost >= predicted_flip else "accumulate"
        seq = _decisions.record_decision(
            "epoch.flip", verdict,
            outcome=(verdict == "flip" and _outcomes.enabled()),
            est_us={"flip": predicted_flip, "accumulate": accumulate_cost},
            depth=depth, values=values, readers=readers,
            staleness_ms=round(staleness_s * 1e3, 3), epoch=epoch,
        )
        if verdict == "accumulate":
            return {
                "outcome": "accumulate", "epoch": epoch, "depth": depth,
                "staleness_s": round(staleness_s, 6),
            }
        t0 = time.perf_counter()
        record = self.flip(reason=reason, now=now)
        if record["outcome"] == "flipped" and seq is not None:
            _outcomes.resolve(
                seq, "epoch.flip", time.perf_counter() - t0, engine="flip",
            )
        return record

    # -- read APIs -----------------------------------------------------------

    def lineage(self, n: Optional[int] = None) -> List[dict]:
        """The epoch lineage ledger tail (newest last): each published
        epoch's id, parent, included batch ids, touched bitmaps, delta
        evidence, and flip wall."""
        with self._cond:
            entries = list(self._lineage)
        if n is not None:
            entries = entries[-int(n):] if n > 0 else []
        return [dict(e) for e in entries]

    def stats(self) -> dict:
        # the log depth is read OUTSIDE the store cond: both locks are
        # leaves, so neither may ever be held while taking the other
        # (the witness hammer pins it)
        depth = self.log.depth()
        with self._cond:
            return {
                "epoch": self._epoch,
                "readers": self._readers,
                "flipping": self._flipping,
                "lineage_len": len(self._lineage),
                "working_sets": len(self._working_sets),
                "log_depth": depth,
            }
