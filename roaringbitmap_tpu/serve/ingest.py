"""Batched streaming ingestion: the serving tier's WRITE path
(ISSUE 15 tentpole, leg 1).

PR 14 closed the serving READ path; this module is the other half of
ROADMAP item 1 — mutations enter the system as **stamped batches** in a
bounded mutation log while readers keep serving the current epoch's
packs untouched, and the epoch flip (serve/epochs.py) drains the log
through the ``models/writer.py`` sorted-stream bulk-add surface into ONE
O(k) PACK_CACHE delta repack per touched working set.

* :class:`MutationBatch` — one tenant's batch of per-bitmap additions,
  stamped at ingest (``stamp``, injectable for the staleness demo and
  fake-clock tests). The stamp is what makes **data freshness** a
  first-class serving signal: at publish time the epoch flip observes
  ``now - stamp`` into ``rb_tpu_serve_freshness_seconds{tenant}`` for
  every batch the new epoch makes queryable — ingest→queryable lag
  p50/p99 next to the latency SLOs.

* :class:`IngestLog` — the thread-safe bounded log. ``submit()`` is the
  only write entry point (one leaf-lock append + a counter bump —
  writers never touch the corpus, so readers are never blocked by
  ingestion); ``drain()`` is called by the flip, under its
  writer-exclusive window, and empties the log. The live depth rides
  ``rb_tpu_serve_mutlog_count`` (pending batches — the
  ``epoch-flip-stall`` sentinel rule's gauge).

* :func:`apply_batches` — the flip's repack-side helper: merges the
  drained batches per bitmap index and streams each bitmap's merged,
  sorted values through a ``BitmapWriter(into=bitmap)`` (the
  constant-memory sorted-stream path of the reference's
  ``RoaringBitmapWriter``; arXiv:1709.07821's bulk-construction
  argument) so every flushed chunk lands through the attributed mutators
  and the later ``store.packed_for`` repack takes the O(k) delta path.

Tenant label values resolve through the declared ``TENANTS`` registry
(the metric-naming discipline); batch ids and epoch ids are unbounded
and live only in the lineage ledger / decision attrs, never in labels.

Lock discipline: the log lock nests over the metrics-registry lock ONLY
(the PACK_CACHE precedent: ``pack.cache -> observe.registry``, witnessed
cycle-free): the depth gauge is set while the lock is held, because a
submit racing a drain could otherwise overwrite the drain's ``set(0)``
with its own stale pre-drain depth — wedging the gauge nonzero over an
empty log and firing the ``epoch-flip-stall`` rule on phantom backlog.
The counter bumps stay outside.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from ..observe import registry as _registry
from ..observe.histogram import latency_histogram
from .slo import TENANTS

DEFAULT_MAX_BATCHES = 4096

FRESHNESS = latency_histogram(
    _registry.SERVE_FRESHNESS_SECONDS,
    "Data freshness: ingest->queryable lag per tenant, observed at epoch "
    "publish for every mutation batch the new epoch makes queryable",
    ("tenant",),
)
_INGEST_TOTAL = _registry.counter(
    _registry.SERVE_INGEST_TOTAL,
    "Mutation batches accepted into the ingest log by tenant",
    ("tenant",),
)
_MUTLOG_COUNT = _registry.gauge(
    _registry.SERVE_MUTLOG_COUNT,
    "Mutation batches currently pending in the ingest log (drained to 0 "
    "by each epoch flip — the epoch-flip-stall sentinel rule's gauge)",
)

# process-unique batch ids (atomic under the GIL); lineage-ledger /
# decision-attr material, never a metric label value
_BATCH_IDS = itertools.count(1)


class MutationBatch:
    """One stamped mutation batch: ``{bitmap_index: uint32 values}`` from
    one tenant. ``stamp`` is ``time.monotonic()`` at ingest unless
    injected (staleness demos, fake clocks)."""

    __slots__ = ("batch_id", "tenant", "mutations", "stamp", "n_values")

    def __init__(self, tenant: str, mutations: Dict[int, np.ndarray],
                 stamp: Optional[float] = None):
        self.batch_id = next(_BATCH_IDS)
        self.tenant = str(tenant)
        self.mutations: Dict[int, np.ndarray] = {}
        n = 0
        for idx, values in mutations.items():
            v = np.asarray(values, dtype=np.int64).ravel()
            if v.size == 0:
                continue
            if v.min() < 0 or v.max() >= 1 << 32:
                raise ValueError(
                    f"batch values for bitmap {idx} outside unsigned 32-bit "
                    "range"
                )
            self.mutations[int(idx)] = v
            n += int(v.size)
        self.stamp = time.monotonic() if stamp is None else float(stamp)
        self.n_values = n

    def touched(self) -> List[int]:
        return sorted(self.mutations)

    def __repr__(self) -> str:
        return (f"MutationBatch(id={self.batch_id}, tenant={self.tenant!r}, "
                f"bitmaps={self.touched()}, values={self.n_values})")


class IngestLog:
    """Thread-safe bounded mutation log. ``submit`` appends (loudly
    failing past ``max_batches`` — backpressure belongs to admission, not
    silent drops); ``drain`` empties it for the flip."""

    def __init__(self, max_batches: int = DEFAULT_MAX_BATCHES):
        if max_batches < 1:
            raise ValueError(f"max_batches must be >= 1, got {max_batches}")
        self.max_batches = int(max_batches)
        # nests over the registry lock only (the depth gauge is set under
        # it — see the module docstring for why); witnessed cycle-free
        self._lock = threading.Lock()
        self._batches: "deque[MutationBatch]" = deque()  # guarded-by: self._lock
        self._total = 0  # guarded-by: self._lock

    def submit(
        self,
        tenant: str,
        mutations: Dict[int, np.ndarray],
        stamp: Optional[float] = None,
    ) -> Optional[MutationBatch]:
        """Append one stamped batch for a DECLARED tenant; returns the
        batch (None for an empty mutation set). The corpus is untouched —
        readers keep serving the current epoch's packs."""
        canon = TENANTS[tenant]
        batch = MutationBatch(canon, mutations, stamp=stamp)
        if not batch.mutations:
            return None
        with self._lock:
            if len(self._batches) >= self.max_batches:
                raise OverflowError(
                    f"ingest log full ({self.max_batches} batches): flip or "
                    "shed before submitting more"
                )
            self._batches.append(batch)
            self._total += 1
            # gauge set UNDER the lock: racing a drain outside it could
            # overwrite the drain's 0 with this stale pre-drain depth
            _MUTLOG_COUNT.set(len(self._batches))
        _INGEST_TOTAL.inc(1, (TENANTS[tenant],))
        return batch

    def drain(self) -> List[MutationBatch]:
        """Pop every pending batch (oldest first). Called by the epoch
        flip under its writer-exclusive window; the depth gauge drops to
        0 so a stall (depth with no flip) is visible to the sentinel."""
        with self._lock:
            batches = list(self._batches)
            self._batches.clear()
            _MUTLOG_COUNT.set(0)
        return batches

    def depth(self) -> int:
        with self._lock:
            return len(self._batches)

    def pending_values(self) -> int:
        with self._lock:
            return sum(b.n_values for b in self._batches)

    def total(self) -> int:
        """Batches ever accepted (pending + drained)."""
        with self._lock:
            return self._total

    def stamps(self) -> List[float]:
        with self._lock:
            return [b.stamp for b in self._batches]

    def clear(self) -> None:
        with self._lock:
            self._batches.clear()
            self._total = 0
            _MUTLOG_COUNT.set(0)


def merge_batches(
    batches: Sequence[MutationBatch],
) -> Dict[int, np.ndarray]:
    """Coalesce drained batches into one sorted, deduplicated value array
    per touched bitmap index — the flip pays ONE writer stream per bitmap
    regardless of how many batches accumulated (the repack-amortization
    half of the flip-vs-accumulate trade)."""
    per_bitmap: Dict[int, List[np.ndarray]] = {}
    for b in batches:
        for idx, v in b.mutations.items():
            per_bitmap.setdefault(idx, []).append(v)
    return {
        idx: np.unique(np.concatenate(chunks))
        for idx, chunks in sorted(per_bitmap.items())
    }


def apply_merged(corpus: Sequence, merged: Dict[int, np.ndarray]) -> int:
    """Stream pre-merged per-bitmap values into the corpus through the
    sorted-stream writer surface (``BitmapWriter(into=...)``), one writer
    per touched bitmap, with per-container format re-selection on the
    touched keys (``optimise_runs`` — the serving-path ``runOptimize``
    gap, ISSUE 16: without it sustained ingest lands every write-hot
    chunk as a fragmented array/bitmap forever). MUST only run inside
    the flip's writer-exclusive window (no readers admitted). Returns
    the number of touched bitmaps."""
    from ..models.writer import BitmapWriter

    for idx, values in merged.items():
        if not 0 <= idx < len(corpus):
            raise IndexError(
                f"mutation batch touches bitmap {idx} outside the corpus "
                f"(size {len(corpus)})"
            )
        w = BitmapWriter(into=corpus[idx], optimise_runs=True)
        w.add_many(values)
        w.flush()
    return len(merged)


def apply_batches(corpus: Sequence, batches: Sequence[MutationBatch]) -> int:
    """Merge-then-apply convenience over :func:`apply_merged` (the flip
    merges once itself — it needs the touched set — and applies the
    merged dict directly; oracles and tests use this form)."""
    return apply_merged(corpus, merge_batches(batches))


def observe_freshness(
    batches: Iterable[MutationBatch], now: Optional[float] = None
) -> int:
    """Record ingest->queryable lag for every published batch (called by
    the flip's publish stage). Returns the number of observations."""
    if now is None:
        now = time.monotonic()
    n = 0
    for b in batches:
        tenant = b.tenant
        FRESHNESS.observe(max(0.0, now - b.stamp), (TENANTS[tenant],))
        n += 1
    return n
