"""RoaringFormatSpec serialization — the portable wire/checkpoint format.

Byte-exact implementation of the reference's portable format
(RoaringArray.serialize, RoaringArray.java:851-940; spec README.md:47):

* cookie ``12347`` (``SERIAL_COOKIE``, RoaringArray.java:23) packed with
  ``size-1`` in the high 16 bits when any run container is present, followed
  by a run-marker bitset of ``ceil(size/8)`` bytes;
* cookie ``12346`` (``SERIAL_COOKIE_NO_RUNCONTAINER``) + 4-byte size
  otherwise;
* descriptive header: per container ``uint16 key, uint16 cardinality-1``;
* offset header (4-byte absolute offsets): always present without runs;
  with runs only when ``size >= NO_OFFSET_THRESHOLD`` (=4,
  RoaringArray.java:25);
* payloads in key order: sorted ``uint16`` values (array), 1024 ``uint64``
  words (bitmap), or ``uint16 n_runs`` + (start, length) pairs (run).
  Non-run containers with cardinality > 4096 are bitmaps — the same rule
  readers use to pick the decoder.

All integers little-endian. Untrusted input is validated the way the
reference's cookie checks are (InvalidRoaringFormat, RoaringArray.java:276+),
exercised against the reference's ``crashproneinput*.bin`` corpus.

This format is also this framework's checkpoint/resume story (SURVEY §5) and
the host<->device marshalling boundary: ``parallel/store.py`` packs device
arrays straight from the parsed container payloads.
"""

from __future__ import annotations

import struct
import sys
from typing import Union

import numpy as np

from . import observe as _observe
from .utils import bits as _bits
from .models.container import (
    ARRAY_MAX_SIZE,
    ArrayContainer,
    BitmapContainer,
    Container,
    RunContainer,
)
from .models.roaring import RoaringBitmap

SERIAL_COOKIE = 12347  # RoaringArray.java:23
SERIAL_COOKIE_NO_RUNCONTAINER = 12346  # RoaringArray.java:24
NO_OFFSET_THRESHOLD = 4  # RoaringArray.java:25
_MAX_CONTAINERS = 1 << 16

# wire-format byte accounting (ISSUE 1): bytes produced by serialize and
# consumed by the parsers, by direction — the checkpoint/interop traffic
# ledger next to store's host->device one
_SERIAL_BYTES = _observe.counter(
    _observe.SERIAL_BYTES_TOTAL,
    "RoaringFormatSpec bytes by direction (serialize | deserialize)",
    ("direction",),
)


class InvalidRoaringFormat(ValueError):
    """Raised on malformed serialized input (InvalidRoaringFormat.java)."""


def _container_payload(c: Container) -> bytes:
    # Payload kind follows the spec's reader rule (run marker, else
    # cardinality > 4096 -> bitmap, else array) — independent of the
    # in-memory class, so low-cardinality BitmapContainers round-trip.
    if isinstance(c, RunContainer):
        n = c.num_runs()
        out = struct.pack("<H", n)
        if n:
            pairs = np.empty(2 * n, dtype=np.uint16)
            pairs[0::2] = c.starts
            pairs[1::2] = c.lengths
            out += pairs.astype("<u2").tobytes()
        return out
    if c.cardinality > ARRAY_MAX_SIZE:
        if isinstance(c, BitmapContainer):
            return c.words.astype("<u8").tobytes()
        return c.to_words().astype("<u8").tobytes()
    return c.to_array().astype("<u2").tobytes()


def _payload_size(c: Container) -> int:
    if isinstance(c, RunContainer):
        return 2 + 4 * c.num_runs()
    if c.cardinality > ARRAY_MAX_SIZE:
        return 8192
    return 2 * c.cardinality


def serialized_size_in_bytes(bm: RoaringBitmap) -> int:
    """Size of serialize(bm) without materializing it
    (RoaringBitmap.serializedSizeInBytes)."""
    hlc = bm.high_low_container
    size = hlc.size
    has_run = any(isinstance(c, RunContainer) for c in hlc.containers)
    if has_run:
        total = 4 + (size + 7) // 8 + 4 * size
        if size >= NO_OFFSET_THRESHOLD:
            total += 4 * size
    else:
        total = 8 + 4 * size + 4 * size
    return total + sum(_payload_size(c) for c in hlc.containers)


def serialize(bm: RoaringBitmap) -> bytes:
    """Portable serialization (RoaringArray.serialize, RoaringArray.java:851-887)."""
    hlc = bm.high_low_container
    size = hlc.size
    containers = hlc.containers
    keys = hlc.keys
    has_run = any(isinstance(c, RunContainer) for c in containers)

    parts = []
    if has_run:
        parts.append(struct.pack("<I", SERIAL_COOKIE | ((size - 1) << 16)))
        marker = bytearray((size + 7) // 8)
        for i, c in enumerate(containers):
            if isinstance(c, RunContainer):
                marker[i // 8] |= 1 << (i % 8)
        parts.append(bytes(marker))
        header_size = 4 + len(marker) + 4 * size
        include_offsets = size >= NO_OFFSET_THRESHOLD
        if include_offsets:
            header_size += 4 * size
    else:
        parts.append(struct.pack("<II", SERIAL_COOKIE_NO_RUNCONTAINER, size))
        header_size = 8 + 4 * size + 4 * size
        include_offsets = True

    desc = np.empty(2 * size, dtype="<u2")
    for i, (k, c) in enumerate(zip(keys, containers)):
        desc[2 * i] = k
        desc[2 * i + 1] = c.cardinality - 1
    parts.append(desc.tobytes())

    if include_offsets:
        offsets = np.empty(size, dtype="<u4")
        pos = header_size
        for i, c in enumerate(containers):
            offsets[i] = pos
            pos += _payload_size(c)
        parts.append(offsets.tobytes())

    for c in containers:
        parts.append(_container_payload(c))
    out = b"".join(parts)
    _SERIAL_BYTES.inc(len(out), ("serialize",))
    return out


def _need(buf: memoryview, pos: int, n: int) -> None:
    if pos + n > len(buf):
        raise InvalidRoaringFormat(
            f"truncated input: need {n} bytes at offset {pos}, have {len(buf) - pos}"
        )


def deserialize(
    data: Union[bytes, bytearray, memoryview, np.ndarray], copy: bool = True
) -> RoaringBitmap:
    """Parse the portable format (RoaringArray.deserialize,
    RoaringArray.java:276/361/547), validating untrusted input.

    ``copy=False`` keeps container payloads as zero-copy views into
    ``data`` (see :func:`read_into`) — the mmap consumers' contract."""
    bm = RoaringBitmap()
    read_into(bm, data, copy=copy)
    return bm


def read_exact(stream, n: int) -> bytes:
    """Read exactly ``n`` bytes from a binary file-like object, looping
    over short reads: unbuffered sources (raw sockets/pipes) may legally
    return fewer than n bytes per read; only b"" means EOF (the io
    contract). Shared by every stream deserializer — a single bare
    ``read(n)`` would spuriously report truncation mid-packet."""
    parts = []
    got = 0
    while got < n:
        b = stream.read(n - got)
        if b is None:  # non-blocking source with no data YET — not EOF
            raise BlockingIOError(
                "deserialize_from needs a blocking stream (read returned None)"
            )
        if not b:
            raise InvalidRoaringFormat(f"truncated stream: wanted {n} bytes, got {got}")
        parts.append(b)
        got += len(b)
    return b"".join(parts) if len(parts) != 1 else parts[0]


def read_from_stream(bm: RoaringBitmap, stream) -> int:
    """Fill ``bm`` from a binary file-like object, consuming EXACTLY one
    serialized bitmap with forward-only reads (works on sockets/pipes; no
    seek). The wire format's own descriptors bound every read: cookie ->
    container count + run marker -> per-container cardinalities -> payload
    sizes. Bytes are then re-validated through read_into. Returns bytes
    consumed."""

    def need(n: int) -> bytes:
        return read_exact(stream, n)

    head = need(4)
    (cookie,) = struct.unpack("<I", head)
    chunks = [head]
    if (cookie & 0xFFFF) == SERIAL_COOKIE:
        size = (cookie >> 16) + 1
        marker = need((size + 7) // 8)
        chunks.append(marker)
        has_run = True
    elif cookie == SERIAL_COOKIE_NO_RUNCONTAINER:
        b = need(4)
        chunks.append(b)
        (size,) = struct.unpack("<I", b)
        has_run = False
        marker = b""
    else:
        raise InvalidRoaringFormat(f"invalid cookie {cookie}")
    if size > _MAX_CONTAINERS:
        raise InvalidRoaringFormat(f"container count {size} exceeds 65536")
    desc = need(4 * size)
    chunks.append(desc)
    cards = np.frombuffer(desc, dtype="<u2")[1::2].astype(np.int64) + 1
    if (not has_run) or size >= NO_OFFSET_THRESHOLD:
        chunks.append(need(4 * size))  # offset table
    for i in range(size):
        if has_run and marker[i // 8] & (1 << (i % 8)):
            nb = need(2)
            chunks.append(nb)
            (n_runs,) = struct.unpack("<H", nb)
            chunks.append(need(4 * n_runs))
        elif cards[i] > ARRAY_MAX_SIZE:
            chunks.append(need(8192))
        else:
            chunks.append(need(2 * int(cards[i])))
    return read_into(bm, b"".join(chunks))


def read_into(bm: RoaringBitmap, data, copy: bool = True) -> int:
    """Fill ``bm`` from serialized bytes; returns bytes consumed.

    ``copy=False`` (ISSUE 17 satellite) builds the containers as
    **zero-copy views** into ``data`` — the ``np.frombuffer(...).astype``
    default path silently copies every payload (astype always
    materializes), which defeats serving straight off an mmap. The view
    path accepts read-only buffers (an ``mmap.ACCESS_READ`` map, a bytes
    object) and produces read-only numpy arrays, so it is an explicit
    opt-in for FROZEN consumers (``durable.format.MappedCorpus``, the
    recovery path): mutating a container built this way (e.g.
    ``BitmapContainer.add`` patches ``words`` in place) raises numpy's
    read-only error instead of corrupting the backing file. Big-endian
    hosts fall back to copying — a byte-swapped view would feed the
    container kernels non-native dtypes."""
    if copy or sys.byteorder != "little":
        copy = True
    if isinstance(data, np.ndarray):
        # tobytes() copies even when the array is already contiguous
        # bytes; the view path wraps the existing buffer
        if copy:
            data = data.tobytes()
        else:
            data = data.data if data.flags["C_CONTIGUOUS"] else data.tobytes()
    buf = memoryview(data).cast("B")
    pos = 0
    _need(buf, pos, 4)
    (cookie,) = struct.unpack_from("<I", buf, pos)
    pos += 4

    if (cookie & 0xFFFF) == SERIAL_COOKIE:
        size = (cookie >> 16) + 1
        has_run = True
        _need(buf, pos, (size + 7) // 8)
        run_marker = bytes(buf[pos : pos + (size + 7) // 8])
        pos += (size + 7) // 8
    elif cookie == SERIAL_COOKIE_NO_RUNCONTAINER:
        _need(buf, pos, 4)
        (size,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        has_run = False
        run_marker = b""
    else:
        raise InvalidRoaringFormat(f"invalid cookie {cookie}")

    if size > _MAX_CONTAINERS:
        raise InvalidRoaringFormat(f"container count {size} exceeds 65536")

    _need(buf, pos, 4 * size)
    desc = np.frombuffer(buf, dtype="<u2", count=2 * size, offset=pos)
    pos += 4 * size
    keys = desc[0::2].astype(np.int64)
    cards = desc[1::2].astype(np.int64) + 1
    if size and np.any(np.diff(keys) <= 0):
        raise InvalidRoaringFormat("container keys not strictly increasing")

    include_offsets = (not has_run) or size >= NO_OFFSET_THRESHOLD
    if include_offsets:
        _need(buf, pos, 4 * size)
        pos += 4 * size  # offsets are redundant for sequential parse

    hlc = bm.high_low_container
    hlc.keys = []
    hlc.containers = []
    # this refill path rebinds the lists directly (bypassing the mutator
    # methods), so record a wholesale mutation — a stale fingerprint here
    # would let the query result cache serve pre-deserialize results, and a
    # key-attributed bump would let the pack cache delta-repack rows that
    # were in fact replaced wholesale (mark_all_dirty forces a full repack)
    hlc.mark_all_dirty()
    for i in range(size):
        key = int(keys[i])
        card = int(cards[i])
        is_run = has_run and bool(run_marker[i // 8] & (1 << (i % 8)))
        if is_run:
            _need(buf, pos, 2)
            (n_runs,) = struct.unpack_from("<H", buf, pos)
            pos += 2
            _need(buf, pos, 4 * n_runs)
            pairs = np.frombuffer(buf, dtype="<u2", count=2 * n_runs, offset=pos)
            if copy:
                pairs = pairs.astype(np.uint16)
            pos += 4 * n_runs
            starts, lengths = pairs[0::2], pairs[1::2]
            if n_runs and not _bits.validate_runs_u16(pairs):
                # overlapping/touching runs, or an end past the universe
                raise InvalidRoaringFormat("invalid run container")
            c: Container = RunContainer(starts, lengths)
        elif card > ARRAY_MAX_SIZE:
            _need(buf, pos, 8192)
            words = np.frombuffer(buf, dtype="<u8", count=1024, offset=pos)
            if copy:
                words = words.astype(np.uint64)
            pos += 8192
            actual = _bits.cardinality_of_words(words)
            if actual != card:
                raise InvalidRoaringFormat(
                    f"bitmap container cardinality {card} != popcount {actual}"
                )
            c = BitmapContainer(words, card)
        else:
            _need(buf, pos, 2 * card)
            values = np.frombuffer(buf, dtype="<u2", count=card, offset=pos)
            if copy:
                values = values.astype(np.uint16)
            pos += 2 * card
            if card > 1 and not _bits.validate_sorted_u16(values):
                raise InvalidRoaringFormat("array container values not sorted/unique")
            c = ArrayContainer(values)
        hlc.keys.append(key)
        hlc.containers.append(c)
    _SERIAL_BYTES.inc(pos, ("deserialize",))
    return pos


def maximum_serialized_size(cardinality: int, universe_size: int) -> int:
    """Upper bound on serialized size for any bitmap of the given cardinality
    over [0, universe_size) (RoaringBitmap.maximumSerializedSize,
    RoaringBitmap.java:3030; closed form README.md:486-496)."""
    cardinality = int(cardinality)
    universe_size = int(universe_size)
    contnbr = (universe_size + 65535) // 65536
    if contnbr > cardinality:
        contnbr = cardinality
        # we cannot have more containers than values
    headermax = max(8, 4 + (contnbr + 7) // 8) + 8 * contnbr
    valsarray = 2 * cardinality
    valsbitmap = contnbr * 8192
    return headermax + min(valsarray, valsbitmap)
