"""Profiling/tracing subsystem (SURVEY §5: the reference externalizes all
performance work to jmh + simplebenchmark; the TPU equivalent is
``jax.profiler`` traces plus library-level counters).

Three layers:

* ``trace(logdir)`` — context manager around ``jax.profiler.trace``; the
  resulting TensorBoard/XProf dump shows XLA op timings and HBM transfers
  for everything inside. ``benchmarks/run.py --profile`` wraps whole suites
  in this.
* ``annotate(name)`` — ``jax.profiler.TraceAnnotation`` wrapper so host-side
  phases (packing, unpack/stream-back) show up as named spans between the
  device ops. No-ops gracefully when jax is unavailable.
* ``op_timer(name)`` / ``timings()`` — wall-clock accounting of host-visible
  phases, queryable without a profile dump. Combined with
  ``insights.dispatch_counters()`` (engine/layout/backend choices +
  host->device transfer bytes) this answers "where did the time go, which
  path served it, how many bytes moved" — the observability the reference
  exposes via its introspection API (RoaringBitmap.getSizeInBytes etc.).

Since ISSUE 1 the recording substrate is ``observe/``: every ``op_timer``
block lands in the locked registry histogram ``rb_tpu_host_op_seconds``
(flat name) and, via ``observe.spans``, in ``rb_tpu_span_seconds`` (nested
``/``-joined path), so the JSONL/Prometheus exporters and the bench
sidecar see host phases with no extra wiring. ``timings()`` is a thin
facade over the registry with the pre-migration shape. The old module
global ``_TIMINGS`` is kept for back-compat readers and is now
lock-protected — the bare ``defaultdict`` mutation could lose increments
under concurrent timers.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from typing import Dict, Iterator

from . import observe as _observe
from .observe import spans as _spans

_OP_SECONDS = _observe.histogram(
    _observe.HOST_OP_SECONDS,
    "Wall time of named host-side phases (op_timer)",
    ("name",),
)

# legacy accounting, kept so pre-registry readers of _TIMINGS stay correct;
# all mutation goes through _TIMINGS_LOCK (the ISSUE 1 thread-safety fix)
_TIMINGS_LOCK = threading.Lock()
# name -> [count, total_s]
_TIMINGS: Dict[str, list] = defaultdict(lambda: [0, 0.0])  # guarded-by: _TIMINGS_LOCK


@contextlib.contextmanager
def trace(logdir: str = "/tmp/rb_tpu_trace") -> Iterator[None]:
    """jax.profiler trace over the enclosed block (view with TensorBoard)."""
    import jax

    with jax.profiler.trace(logdir):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span in the device trace (falls back to a plain timer).

    Only jax being missing or stripped (ImportError/AttributeError)
    downgrades to the plain timer — a real failure inside
    ``TraceAnnotation`` propagates instead of being silently swallowed."""
    try:
        import jax

        ctx = jax.profiler.TraceAnnotation(name)
    except (ImportError, AttributeError):  # jax missing or stripped build
        ctx = contextlib.nullcontext()
    with ctx, op_timer(name):
        yield


@contextlib.contextmanager
def op_timer(name: str) -> Iterator[None]:
    """Accumulate wall time for a named host-side phase.

    Records into the registry (flat ``rb_tpu_host_op_seconds`` histogram +
    nested ``rb_tpu_span_seconds`` via the span stack) and the
    lock-protected legacy ``_TIMINGS`` dict."""
    t0 = time.perf_counter()
    try:
        with _spans.span(name):
            yield
    finally:
        dt = time.perf_counter() - t0
        _OP_SECONDS.observe(dt, (name,))
        with _TIMINGS_LOCK:
            rec = _TIMINGS[name]
            rec[0] += 1
            rec[1] += dt


def timings() -> Dict[str, Dict[str, float]]:
    """{name: {count, total_s, mean_ms}} for all recorded phases (facade
    over the ``rb_tpu_host_op_seconds`` registry histogram)."""
    return {
        name: {
            "count": st["count"],
            "total_s": round(st["sum"], 6),
            "mean_ms": round(st["sum"] / st["count"] * 1e3, 3) if st["count"] else 0.0,
        }
        for (name,), st in _OP_SECONDS.series().items()
    }


def reset_timings() -> None:
    _OP_SECONDS.clear()
    _spans.reset_spans()
    with _TIMINGS_LOCK:
        _TIMINGS.clear()
