"""Profiling/tracing subsystem (SURVEY §5: the reference externalizes all
performance work to jmh + simplebenchmark; the TPU equivalent is
``jax.profiler`` traces plus library-level counters).

Three layers:

* ``trace(logdir)`` — context manager around ``jax.profiler.trace``; the
  resulting TensorBoard/XProf dump shows XLA op timings and HBM transfers
  for everything inside. ``benchmarks/run.py --profile`` wraps whole suites
  in this.
* ``annotate(name)`` — ``jax.profiler.TraceAnnotation`` wrapper so host-side
  phases (packing, unpack/stream-back) show up as named spans between the
  device ops. No-ops gracefully when jax is unavailable.
* ``op_timer(name)`` / ``timings()`` — lightweight wall-clock accounting of
  host-visible phases, queryable without a profile dump. Combined with
  ``insights.dispatch_counters()`` (engine/layout/backend choices +
  host->device transfer bytes) this answers "where did the time go, which
  path served it, how many bytes moved" — the observability the reference
  exposes via its introspection API (RoaringBitmap.getSizeInBytes etc.).
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, Iterator

_TIMINGS: Dict[str, list] = defaultdict(lambda: [0, 0.0])  # name -> [count, total_s]


@contextlib.contextmanager
def trace(logdir: str = "/tmp/rb_tpu_trace") -> Iterator[None]:
    """jax.profiler trace over the enclosed block (view with TensorBoard)."""
    import jax

    with jax.profiler.trace(logdir):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span in the device trace (falls back to a plain timer)."""
    try:
        import jax

        ctx = jax.profiler.TraceAnnotation(name)
    except Exception:  # jax missing or stripped build
        ctx = contextlib.nullcontext()
    with ctx, op_timer(name):
        yield


@contextlib.contextmanager
def op_timer(name: str) -> Iterator[None]:
    """Accumulate wall time for a named host-side phase."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        rec = _TIMINGS[name]
        rec[0] += 1
        rec[1] += time.perf_counter() - t0


def timings() -> Dict[str, Dict[str, float]]:
    """{name: {count, total_s, mean_ms}} for all recorded phases."""
    return {
        name: {
            "count": c,
            "total_s": round(total, 6),
            "mean_ms": round(total / c * 1e3, 3) if c else 0.0,
        }
        for name, (c, total) in _TIMINGS.items()
    }


def reset_timings() -> None:
    _TIMINGS.clear()
