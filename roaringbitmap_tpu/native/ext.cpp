// CPython extension wrapper over the L0 kernels (kernels.cpp).
//
// The ctypes bindings cost ~4-13 us per call (ndpointer validation +
// argument marshalling + output copies) — more than the kernels themselves
// on container-sized inputs, which is exactly the CPU fast path's regime.
// This module exposes the same entry points through the CPython/numpy C
// API at ~0.2-0.4 us per call. native/__init__.py prefers it when it
// builds, falling back to ctypes, then numpy.

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <numpy/arrayobject.h>

#include "kernels.cpp"  // single TU: reuse the extern "C" kernels directly

namespace {

// Borrowed, validated views ------------------------------------------------

static bool as_u16(PyObject* o, const uint16_t** p, int32_t* n) {
  PyArrayObject* a = reinterpret_cast<PyArrayObject*>(o);
  if (!PyArray_Check(o) || PyArray_TYPE(a) != NPY_UINT16 ||
      !PyArray_IS_C_CONTIGUOUS(a) || PyArray_NDIM(a) != 1) {
    PyErr_SetString(PyExc_TypeError, "expected C-contiguous 1-D uint16 array");
    return false;
  }
  *p = static_cast<const uint16_t*>(PyArray_DATA(a));
  *n = static_cast<int32_t>(PyArray_DIM(a, 0));
  return true;
}

static bool as_u64(PyObject* o, const uint64_t** p, int64_t* n) {
  PyArrayObject* a = reinterpret_cast<PyArrayObject*>(o);
  if (!PyArray_Check(o) || PyArray_TYPE(a) != NPY_UINT64 ||
      !PyArray_IS_C_CONTIGUOUS(a) || PyArray_NDIM(a) != 1) {
    PyErr_SetString(PyExc_TypeError, "expected C-contiguous 1-D uint64 array");
    return false;
  }
  *p = static_cast<const uint64_t*>(PyArray_DATA(a));
  *n = PyArray_DIM(a, 0);
  return true;
}

static PyObject* new_u16(npy_intp n) {
  return PyArray_SimpleNew(1, &n, NPY_UINT16);
}

// Sorted-set algebra -------------------------------------------------------

typedef int32_t (*setop_fn)(const uint16_t*, int32_t, const uint16_t*, int32_t,
                            uint16_t*);

// output capacity regimes: intersect <= min(na, nb); union/xor <= na + nb;
// difference (a \ b) <= na
enum CapMode { CAP_MIN = 0, CAP_SUM = 1, CAP_FIRST = 2 };

template <setop_fn FN, CapMode CAP>
static PyObject* setop(PyObject*, PyObject* args) {
  PyObject *ao, *bo;
  if (!PyArg_ParseTuple(args, "OO", &ao, &bo)) return nullptr;
  const uint16_t *a, *b;
  int32_t na, nb;
  if (!as_u16(ao, &a, &na) || !as_u16(bo, &b, &nb)) return nullptr;
  npy_intp cap = CAP == CAP_SUM   ? (npy_intp)na + nb
                 : CAP == CAP_FIRST ? (npy_intp)na
                                    : (npy_intp)(na < nb ? na : nb);
  PyObject* out = new_u16(cap);
  if (!out) return nullptr;
  int32_t n = FN(a, na, b, nb,
                 static_cast<uint16_t*>(PyArray_DATA((PyArrayObject*)out)));
  // shrink in place: resize to the produced length (refcount is 1)
  PyArray_Dims d;
  npy_intp len = n;
  d.ptr = &len;
  d.len = 1;
  PyObject* ok = PyArray_Resize((PyArrayObject*)out, &d, 0, NPY_CORDER);
  if (!ok) {
    Py_DECREF(out);
    return nullptr;
  }
  Py_DECREF(ok);
  return out;
}

static PyObject* intersect_cardinality(PyObject*, PyObject* args) {
  PyObject *ao, *bo;
  if (!PyArg_ParseTuple(args, "OO", &ao, &bo)) return nullptr;
  const uint16_t *a, *b;
  int32_t na, nb;
  if (!as_u16(ao, &a, &na) || !as_u16(bo, &b, &nb)) return nullptr;
  return PyLong_FromLong(rb_intersect_card_u16(a, na, b, nb));
}

static PyObject* advance_until(PyObject*, PyObject* args) {
  PyObject* ao;
  int pos, minv;
  if (!PyArg_ParseTuple(args, "Oii", &ao, &pos, &minv)) return nullptr;
  const uint16_t* a;
  int32_t na;
  if (!as_u16(ao, &a, &na)) return nullptr;
  return PyLong_FromLong(rb_advance_until(a, na, pos, (uint16_t)minv));
}

static PyObject* contains_many(PyObject*, PyObject* args) {
  PyObject *so, *qo;
  if (!PyArg_ParseTuple(args, "OO", &so, &qo)) return nullptr;
  const uint16_t *s, *q;
  int32_t ns, nq;
  if (!as_u16(so, &s, &ns) || !as_u16(qo, &q, &nq)) return nullptr;
  npy_intp n = nq;
  PyObject* out = PyArray_SimpleNew(1, &n, NPY_BOOL);
  if (!out) return nullptr;
  rb_contains_many_u16(s, ns, q, nq,
                       static_cast<uint8_t*>(PyArray_DATA((PyArrayObject*)out)));
  return out;
}

// Scalar point-probe fast paths --------------------------------------------
// One C call does the whole membership test (search + compare + boolean),
// so the Python side pays a single frame instead of search-then-numpy-index.
// These exist purely for per-call latency (simplebenchmark contains row;
// Util.java:697 unsignedBinarySearch serves this role in the JVM).

static PyObject* contains_u16(PyObject*, PyObject* args) {
  PyObject* ao;
  int x;
  if (!PyArg_ParseTuple(args, "Oi", &ao, &x)) return nullptr;
  const uint16_t* a;
  int32_t na;
  if (!as_u16(ao, &a, &na)) return nullptr;
  int32_t i = rb_advance_until(a, na, -1, (uint16_t)x);
  if (i < na && a[i] == (uint16_t)x) Py_RETURN_TRUE;
  Py_RETURN_FALSE;
}

static PyObject* word_bit(PyObject*, PyObject* args) {
  PyObject* wo;
  int x;
  if (!PyArg_ParseTuple(args, "Oi", &wo, &x)) return nullptr;
  const uint64_t* w;
  int64_t nw;
  if (!as_u64(wo, &w, &nw)) return nullptr;
  int64_t idx = (int64_t)((uint32_t)x >> 6);
  if (idx >= nw) {
    PyErr_SetString(PyExc_IndexError, "bit index beyond word array");
    return nullptr;
  }
  if ((w[idx] >> (x & 63)) & 1) Py_RETURN_TRUE;
  Py_RETURN_FALSE;
}

static PyObject* run_contains(PyObject*, PyObject* args) {
  PyObject *so, *lo;
  int x;
  if (!PyArg_ParseTuple(args, "OOi", &so, &lo, &x)) return nullptr;
  const uint16_t *s, *l;
  int32_t ns, nl;
  if (!as_u16(so, &s, &ns) || !as_u16(lo, &l, &nl)) return nullptr;
  if (ns != nl) {
    PyErr_SetString(PyExc_ValueError, "starts/lengths size mismatch");
    return nullptr;
  }
  int32_t i = rb_advance_until(s, ns, -1, (uint16_t)x);  // first start >= x
  if (i < ns && s[i] == (uint16_t)x) Py_RETURN_TRUE;
  if (i == 0) Py_RETURN_FALSE;
  if ((uint16_t)x - s[i - 1] <= l[i - 1]) Py_RETURN_TRUE;
  Py_RETURN_FALSE;
}

// Word-level kernels -------------------------------------------------------

static PyObject* cardinality_of_words(PyObject*, PyObject* args) {
  PyObject* wo;
  if (!PyArg_ParseTuple(args, "O", &wo)) return nullptr;
  const uint64_t* w;
  int64_t n;
  if (!as_u64(wo, &w, &n)) return nullptr;
  return PyLong_FromLongLong(rb_popcount_words(w, n));
}

static PyObject* words_from_values(PyObject*, PyObject* args) {
  PyObject* vo;
  int n_words;
  if (!PyArg_ParseTuple(args, "Oi", &vo, &n_words)) return nullptr;
  const uint16_t* v;
  int32_t nv;
  if (!as_u16(vo, &v, &nv)) return nullptr;
  npy_intp n = n_words;
  PyObject* out = PyArray_ZEROS(1, &n, NPY_UINT64, 0);
  if (!out) return nullptr;
  rb_words_from_values(v, nv,
                       static_cast<uint64_t*>(PyArray_DATA((PyArrayObject*)out)));
  return out;
}

static PyObject* values_from_words(PyObject*, PyObject* args) {
  PyObject* wo;
  if (!PyArg_ParseTuple(args, "O", &wo)) return nullptr;
  const uint64_t* w;
  int64_t n;
  if (!as_u64(wo, &w, &n)) return nullptr;
  npy_intp card = rb_popcount_words(w, n);
  PyObject* out = new_u16(card);
  if (!out) return nullptr;
  rb_values_from_words(w, (int32_t)n,
                       static_cast<uint16_t*>(PyArray_DATA((PyArrayObject*)out)));
  return out;
}

static PyObject* num_runs_in_words(PyObject*, PyObject* args) {
  PyObject* wo;
  if (!PyArg_ParseTuple(args, "O", &wo)) return nullptr;
  const uint64_t* w;
  int64_t n;
  if (!as_u64(wo, &w, &n)) return nullptr;
  return PyLong_FromLong(rb_num_runs_words(w, (int32_t)n));
}

static PyObject* select_in_words(PyObject*, PyObject* args) {
  PyObject* wo;
  int j;
  if (!PyArg_ParseTuple(args, "Oi", &wo, &j)) return nullptr;
  const uint64_t* w;
  int64_t n;
  if (!as_u64(wo, &w, &n)) return nullptr;
  int32_t r = rb_select_words(w, (int32_t)n, j);
  if (r < 0) {
    PyErr_SetString(PyExc_IndexError, "select out of range");
    return nullptr;
  }
  return PyLong_FromLong(r);
}

static PyObject* cardinality_in_range(PyObject*, PyObject* args) {
  PyObject* wo;
  int start, end;
  if (!PyArg_ParseTuple(args, "Oii", &wo, &start, &end)) return nullptr;
  const uint64_t* w;
  int64_t n;
  if (!as_u64(wo, &w, &n)) return nullptr;
  return PyLong_FromLongLong(rb_cardinality_in_range(w, start, end));
}

// Deserialization validators (single pass, no temporaries) ----------------

static PyObject* is_strictly_increasing(PyObject*, PyObject* args) {
  PyObject* vo;
  if (!PyArg_ParseTuple(args, "O", &vo)) return nullptr;
  const uint16_t* v;
  int32_t n;
  if (!as_u16(vo, &v, &n)) return nullptr;
  for (int32_t i = 1; i < n; ++i)
    if (v[i] <= v[i - 1]) Py_RETURN_FALSE;
  Py_RETURN_TRUE;
}

static PyObject* runs_valid(PyObject*, PyObject* args) {
  // interleaved (start, length) pairs: sorted, disjoint, non-touching,
  // ends within the 2^16 universe (serialization.py's run checks)
  PyObject* po;
  if (!PyArg_ParseTuple(args, "O", &po)) return nullptr;
  const uint16_t* p;
  int32_t n2;
  if (!as_u16(po, &p, &n2)) return nullptr;
  if (n2 % 2) {
    PyErr_SetString(PyExc_ValueError, "odd-length pair array");
    return nullptr;
  }
  int32_t prev_end = -1;
  for (int32_t i = 0; i < n2 / 2; ++i) {
    int32_t s = p[2 * i];
    int32_t e = s + p[2 * i + 1];
    if (s <= prev_end || e > 0xFFFF) Py_RETURN_FALSE;
    prev_end = e;
  }
  Py_RETURN_TRUE;
}

static PyMethodDef Methods[] = {
    {"intersect_sorted", setop<rb_intersect_u16, CAP_MIN>, METH_VARARGS, nullptr},
    {"merge_sorted_unique", setop<rb_union_u16, CAP_SUM>, METH_VARARGS, nullptr},
    {"difference_sorted", setop<rb_difference_u16, CAP_FIRST>, METH_VARARGS, nullptr},
    {"xor_sorted", setop<rb_xor_u16, CAP_SUM>, METH_VARARGS, nullptr},
    {"intersect_cardinality", intersect_cardinality, METH_VARARGS, nullptr},
    {"advance_until", advance_until, METH_VARARGS, nullptr},
    {"contains_many", contains_many, METH_VARARGS, nullptr},
    {"contains_u16", contains_u16, METH_VARARGS, nullptr},
    {"word_bit", word_bit, METH_VARARGS, nullptr},
    {"run_contains", run_contains, METH_VARARGS, nullptr},
    {"cardinality_of_words", cardinality_of_words, METH_VARARGS, nullptr},
    {"words_from_values", words_from_values, METH_VARARGS, nullptr},
    {"values_from_words", values_from_words, METH_VARARGS, nullptr},
    {"num_runs_in_words", num_runs_in_words, METH_VARARGS, nullptr},
    {"select_in_words", select_in_words, METH_VARARGS, nullptr},
    {"cardinality_in_range", cardinality_in_range, METH_VARARGS, nullptr},
    {"is_strictly_increasing", is_strictly_increasing, METH_VARARGS, nullptr},
    {"runs_valid", runs_valid, METH_VARARGS, nullptr},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef Module = {PyModuleDef_HEAD_INIT, "_rb_ext",
                                    "CPython fast path over the L0 kernels",
                                    -1, Methods};

}  // namespace

PyMODINIT_FUNC PyInit__rb_ext(void) {
  import_array();
  return PyModule_Create(&Module);
}
