// Native host-side L0 kernels for roaringbitmap_tpu.
//
// C++ re-expression of the reference's JIT-intrinsic word/array kernels
// (reference: RoaringBitmap/src/main/java/org/roaringbitmap/Util.java —
// unsignedIntersect2by2 :890 with the galloping variant :934,
// unsignedUnion2by2 :1116, unsignedDifference, unsignedExclusiveUnion2by2,
// advanceUntil :64-analogue, select(long,int) :564 — and
// BitmapContainer.java's Long.bitCount loops). The TPU device path lives in
// ops/device.py + ops/pallas_kernels.py; this library is the CPU fast path
// for small/irregular containers, where Python/numpy call overhead dominates.
//
// Exposed via ctypes (native/__init__.py); every function has a numpy
// fallback in utils/bits.py with identical semantics, used as the
// differential-test oracle (tests/test_native.py).

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// sorted uint16 set algebra
// ---------------------------------------------------------------------------

// Exponential (galloping) search: smallest index i in [pos, n) with
// a[i] >= min, else n. Mirrors Util.advanceUntil's exponential+binary probe.
static int32_t gallop(const uint16_t* a, int32_t pos, int32_t n, uint16_t min) {
  int32_t lo = pos;
  if (lo >= n || a[lo] >= min) return lo;
  int32_t span = 1;
  while (lo + span < n && a[lo + span] < min) span <<= 1;
  int32_t hi = (lo + span < n) ? lo + span : n - 1;
  lo = lo + (span >> 1);
  if (a[hi] < min) return n;
  // binary search in (lo, hi]
  while (lo + 1 < hi) {
    int32_t mid = lo + ((hi - lo) >> 1);
    if (a[mid] < min) lo = mid; else hi = mid;
  }
  return hi;
}

int32_t rb_advance_until(const uint16_t* a, int32_t n, int32_t pos, uint16_t min) {
  return gallop(a, pos + 1, n, min);
}

// One-sided galloping intersection: |small| * 64 < |large|
// (Util.java:890-932's THRESHOLD=64 dispatch to the galloping variant :934).
static int32_t intersect_gallop(const uint16_t* s, int32_t ns, const uint16_t* l,
                                int32_t nl, uint16_t* out) {
  int32_t k = 0, j = 0;
  for (int32_t i = 0; i < ns; ++i) {
    j = gallop(l, j, nl, s[i]);
    if (j == nl) break;
    if (l[j] == s[i]) {
      if (out) out[k] = s[i];
      ++k;
    }
  }
  return k;
}

int32_t rb_intersect_u16(const uint16_t* a, int32_t na, const uint16_t* b,
                         int32_t nb, uint16_t* out) {
  if (na == 0 || nb == 0) return 0;
  if ((int64_t)na * 64 < nb) return intersect_gallop(a, na, b, nb, out);
  if ((int64_t)nb * 64 < na) return intersect_gallop(b, nb, a, na, out);
  int32_t i = 0, j = 0, k = 0;
  while (i < na && j < nb) {
    uint16_t x = a[i], y = b[j];
    if (x < y) ++i;
    else if (y < x) ++j;
    else { if (out) out[k] = x; ++k; ++i; ++j; }
  }
  return k;
}

int32_t rb_intersect_card_u16(const uint16_t* a, int32_t na, const uint16_t* b,
                              int32_t nb) {
  return rb_intersect_u16(a, na, b, nb, nullptr);
}

int32_t rb_union_u16(const uint16_t* a, int32_t na, const uint16_t* b,
                     int32_t nb, uint16_t* out) {
  int32_t i = 0, j = 0, k = 0;
  while (i < na && j < nb) {
    uint16_t x = a[i], y = b[j];
    if (x < y) { out[k++] = x; ++i; }
    else if (y < x) { out[k++] = y; ++j; }
    else { out[k++] = x; ++i; ++j; }
  }
  while (i < na) out[k++] = a[i++];
  while (j < nb) out[k++] = b[j++];
  return k;
}

int32_t rb_difference_u16(const uint16_t* a, int32_t na, const uint16_t* b,
                          int32_t nb, uint16_t* out) {
  int32_t i = 0, j = 0, k = 0;
  while (i < na && j < nb) {
    uint16_t x = a[i], y = b[j];
    if (x < y) { out[k++] = x; ++i; }
    else if (y < x) ++j;
    else { ++i; ++j; }
  }
  while (i < na) out[k++] = a[i++];
  return k;
}

int32_t rb_xor_u16(const uint16_t* a, int32_t na, const uint16_t* b, int32_t nb,
                   uint16_t* out) {
  int32_t i = 0, j = 0, k = 0;
  while (i < na && j < nb) {
    uint16_t x = a[i], y = b[j];
    if (x < y) { out[k++] = x; ++i; }
    else if (y < x) { out[k++] = y; ++j; }
    else { ++i; ++j; }
  }
  while (i < na) out[k++] = a[i++];
  while (j < nb) out[k++] = b[j++];
  return k;
}

// membership of each query value in a sorted array -> byte mask
void rb_contains_many_u16(const uint16_t* sorted, int32_t n, const uint16_t* q,
                          int32_t nq, uint8_t* out) {
  for (int32_t i = 0; i < nq; ++i) {
    int32_t j = gallop(sorted, 0, n, q[i]);
    out[i] = (j < n && sorted[j] == q[i]) ? 1 : 0;
  }
}

// ---------------------------------------------------------------------------
// uint64 word-bitset kernels (1024 words per container, but n is generic)
// ---------------------------------------------------------------------------

int64_t rb_popcount_words(const uint64_t* w, int64_t n) {
  int64_t c = 0;
  for (int64_t i = 0; i < n; ++i) c += __builtin_popcountll(w[i]);
  return c;
}

void rb_words_from_values(const uint16_t* v, int32_t n, uint64_t* words) {
  for (int32_t i = 0; i < n; ++i) words[v[i] >> 6] |= 1ULL << (v[i] & 63);
}

int32_t rb_values_from_words(const uint64_t* words, int32_t n_words,
                             uint16_t* out) {
  int32_t k = 0;
  for (int32_t w = 0; w < n_words; ++w) {
    uint64_t x = words[w];
    int32_t base = w << 6;
    while (x) {
      out[k++] = (uint16_t)(base + __builtin_ctzll(x));
      x &= x - 1;
    }
  }
  return k;
}

// number of runs: popcount(x & ~(x<<1 | carry)) with cross-word carry
// (BitmapContainer.numberOfRuns' branchless per-word form).
int32_t rb_num_runs_words(const uint64_t* words, int32_t n_words) {
  int32_t runs = 0;
  uint64_t carry = 0;
  for (int32_t w = 0; w < n_words; ++w) {
    uint64_t x = words[w];
    runs += __builtin_popcountll(x & ~((x << 1) | carry));
    carry = x >> 63;
  }
  return runs;
}

// position of the j-th (0-based) set bit, or -1
int32_t rb_select_words(const uint64_t* words, int32_t n_words, int32_t j) {
  for (int32_t w = 0; w < n_words; ++w) {
    int32_t c = __builtin_popcountll(words[w]);
    if (j < c) {
      uint64_t x = words[w];
      for (; j > 0; --j) x &= x - 1;  // peel j set bits (Util.select :564)
      return (w << 6) + __builtin_ctzll(x);
    }
    j -= c;
  }
  return -1;
}

// popcount of bits [start, end) over the word array
int64_t rb_cardinality_in_range(const uint64_t* words, int32_t start,
                                int32_t end) {
  if (start >= end) return 0;
  int32_t first = start >> 6, last = (end - 1) >> 6;
  uint64_t lo = ~0ULL << (start & 63);
  uint64_t hi = ~0ULL >> (63 - ((end - 1) & 63));
  if (first == last) return __builtin_popcountll(words[first] & lo & hi);
  int64_t c = __builtin_popcountll(words[first] & lo) +
              __builtin_popcountll(words[last] & hi);
  for (int32_t w = first + 1; w < last; ++w)
    c += __builtin_popcountll(words[w]);
  return c;
}

// fold rows of an [n_rows, n_words] matrix: op 0=OR 1=AND 2=XOR; also returns
// the popcount of the result (the lazy-cardinality "repair" fused in, cf.
// Container.lazyIOR/repairAfterLazy Container.java:717/873).
int64_t rb_wide_op_words(const uint64_t* rows, int64_t n_rows, int64_t n_words,
                         int32_t op, uint64_t* out) {
  if (n_rows == 0) {
    memset(out, 0, (size_t)n_words * 8);
    return 0;
  }
  memcpy(out, rows, (size_t)n_words * 8);
  for (int64_t r = 1; r < n_rows; ++r) {
    const uint64_t* row = rows + r * n_words;
    switch (op) {
      case 0: for (int64_t i = 0; i < n_words; ++i) out[i] |= row[i]; break;
      case 1: for (int64_t i = 0; i < n_words; ++i) out[i] &= row[i]; break;
      default: for (int64_t i = 0; i < n_words; ++i) out[i] ^= row[i]; break;
    }
  }
  return rb_popcount_words(out, n_words);
}

// ---------------------------------------------------------------------------
// runs
// ---------------------------------------------------------------------------

// (starts, lengths) from sorted unique values; returns run count.
// lengths follow the spec convention: run covers [start, start+length].
int32_t rb_runs_from_values(const uint16_t* v, int32_t n, uint16_t* starts,
                            uint16_t* lengths) {
  if (n == 0) return 0;
  int32_t r = 0;
  uint16_t start = v[0], prev = v[0];
  for (int32_t i = 1; i < n; ++i) {
    if (v[i] != (uint16_t)(prev + 1)) {
      starts[r] = start;
      lengths[r] = (uint16_t)(prev - start);
      ++r;
      start = v[i];
    }
    prev = v[i];
  }
  starts[r] = start;
  lengths[r] = (uint16_t)(prev - start);
  return r + 1;
}

int32_t rb_num_runs_values(const uint16_t* v, int32_t n) {
  if (n == 0) return 0;
  int32_t r = 1;
  for (int32_t i = 1; i < n; ++i) r += (v[i] != (uint16_t)(v[i - 1] + 1));
  return r;
}

// Fill a 1024-word bitset from disjoint half-open [start, end) intervals —
// the RunContainer -> words expansion (RunContainer.toBitmapContainer
// analogue). The numpy boundary-cumsum fallback pays ~200us in the int8 ->
// int32 cumsum; this is a direct masked-word fill.
void rb_words_from_intervals(const int64_t* starts, const int64_t* ends,
                             int32_t n, uint64_t* words) {
  for (int32_t i = 0; i < n; ++i) {
    int64_t s = starts[i], e = ends[i];
    // clamp to the 2^16 sub-universe: a hostile mapped run payload
    // (e.g. start=0xFFFF, length=0xFFFF) must not write past words[1023]
    if (s < 0) s = 0;
    if (e > 65536) e = 65536;
    if (e <= s) continue;
    int64_t sw = s >> 6, ew = (e - 1) >> 6;
    uint64_t first = ~0ULL << (s & 63);
    uint64_t last = ~0ULL >> (63 - ((e - 1) & 63));
    if (sw == ew) {
      words[sw] |= first & last;
    } else {
      words[sw] |= first;
      for (int64_t w = sw + 1; w < ew; ++w) words[w] = ~0ULL;
      words[ew] |= last;
    }
  }
}

// ---------------------------------------------------------------------------
// columnar batched pairwise algebra (ISSUE 5)
//
// One call executes a whole batch of sorted-u16 container ops: pair j reads
// avals[aoffs[j]:aoffs[j+1]] x bvals[boffs[j]:boffs[j+1]] and writes its
// result at out + out_offs[j] (caller-computed worst-case bounds, so pairs
// are independent and the loop parallelizes). This is the per-type-pair
// kernel loop of the reference (Util.java unsigned*2by2 driven by
// RoaringBitmap's key merge) with the Python dispatch hoisted out of the
// per-container path entirely.
// ---------------------------------------------------------------------------

// op codes shared with columnar/kernels.py: 0=and 1=or 2=xor 3=andnot
void rb_batch_pairwise_u16(const uint16_t* avals, const int64_t* aoffs,
                           const uint16_t* bvals, const int64_t* boffs,
                           int64_t n_pairs, int32_t op,
                           const int64_t* out_offs, uint16_t* out,
                           int64_t* counts) {
#pragma omp parallel for schedule(dynamic, 64)
  for (int64_t j = 0; j < n_pairs; ++j) {
    const uint16_t* a = avals + aoffs[j];
    const uint16_t* b = bvals + boffs[j];
    int32_t na = (int32_t)(aoffs[j + 1] - aoffs[j]);
    int32_t nb = (int32_t)(boffs[j + 1] - boffs[j]);
    uint16_t* o = out + out_offs[j];
    switch (op) {
      case 0: counts[j] = rb_intersect_u16(a, na, b, nb, o); break;
      case 1: counts[j] = rb_union_u16(a, na, b, nb, o); break;
      case 2: counts[j] = rb_xor_u16(a, na, b, nb, o); break;
      default: counts[j] = rb_difference_u16(a, na, b, nb, o); break;
    }
  }
}

// ---- run-unified batch (arrays enter as length-0 runs) --------------------
//
// A container side is a sorted disjoint run list (start, length), run =
// [start, start+length]; an array container is its values with length 0.
// This single representation lets ONE kernel serve the aa/ar/ra/rr classes
// of AND/ANDNOT — the 4 of the reference's 9 type-pair kernels that matter
// for intersection-shaped ops — emitting result VALUES (intersections are
// small by construction; the or/xor classes go through the word path).

// intervals of (A AND B) as (start, length) pairs; returns interval count,
// accumulates result cardinality into *card. os==nullptr: card only.
static int64_t run_and_intervals(const uint16_t* as, const uint16_t* al,
                                 int32_t na, const uint16_t* bs,
                                 const uint16_t* bl, int32_t nb, uint16_t* os,
                                 uint16_t* ol, int64_t* card) {
  int32_t i = 0, j = 0;
  int64_t k = 0, c = 0;
  while (i < na && j < nb) {
    int32_t a0 = as[i], a1 = a0 + al[i];
    int32_t b0 = bs[j], b1 = b0 + bl[j];
    int32_t lo = a0 > b0 ? a0 : b0;
    int32_t hi = a1 < b1 ? a1 : b1;
    if (hi >= lo) {
      c += hi - lo + 1;
      if (os) {
        os[k] = (uint16_t)lo;
        ol[k] = (uint16_t)(hi - lo);
      }
      ++k;
    }
    if (a1 < b1) ++i; else ++j;
  }
  *card = c;
  return k;
}

// intervals of (A ANDNOT B)
static int64_t run_andnot_intervals(const uint16_t* as, const uint16_t* al,
                                    int32_t na, const uint16_t* bs,
                                    const uint16_t* bl, int32_t nb,
                                    uint16_t* os, uint16_t* ol, int64_t* card) {
  int32_t j = 0;
  int64_t k = 0, c = 0;
  for (int32_t i = 0; i < na; ++i) {
    int32_t a0 = as[i], a1 = a0 + al[i];
    while (j < nb && (int32_t)(bs[j] + bl[j]) < a0) ++j;
    int32_t jj = j, cur = a0;
    while (cur <= a1) {
      if (jj < nb && (int32_t)bs[jj] <= cur) {
        int32_t be = bs[jj] + bl[jj];
        ++jj;
        if (be >= cur) cur = be + 1;
        continue;
      }
      int32_t stop = a1;
      if (jj < nb && (int32_t)bs[jj] <= a1) stop = bs[jj] - 1;
      c += stop - cur + 1;
      if (os) {
        os[k] = (uint16_t)cur;
        ol[k] = (uint16_t)(stop - cur);
      }
      ++k;
      cur = stop + 1;
    }
  }
  *card = c;
  return k;
}

// Whole-batch run-unified pairwise: pair j reads run lists
// (as, al)[aoffs[j]:aoffs[j+1]] x (bs, bl)[boffs[j]:boffs[j+1]] and writes
// result INTERVALS at out_s/out_l + out_offs[j] (bounds: na+nb intervals —
// payload-sized, never cardinality-sized, so run-shaped results stay
// compressed end to end). op: 0=and 3=andnot. out_s==nullptr -> cards only.
void rb_batch_run_pairwise(const uint16_t* as, const uint16_t* al,
                           const int64_t* aoffs, const uint16_t* bs,
                           const uint16_t* bl, const int64_t* boffs,
                           int64_t n_pairs, int32_t op, const int64_t* out_offs,
                           uint16_t* out_s, uint16_t* out_l, int64_t* counts,
                           int64_t* cards) {
#pragma omp parallel for schedule(dynamic, 64)
  for (int64_t j = 0; j < n_pairs; ++j) {
    const uint16_t* a_s = as + aoffs[j];
    const uint16_t* a_l = al + aoffs[j];
    int32_t na = (int32_t)(aoffs[j + 1] - aoffs[j]);
    const uint16_t* b_s = bs + boffs[j];
    const uint16_t* b_l = bl + boffs[j];
    int32_t nb = (int32_t)(boffs[j + 1] - boffs[j]);
    uint16_t* os = out_s ? out_s + out_offs[j] : nullptr;
    uint16_t* ol = out_l ? out_l + out_offs[j] : nullptr;
    counts[j] = (op == 0)
                    ? run_and_intervals(a_s, a_l, na, b_s, b_l, nb, os, ol,
                                        cards + j)
                    : run_andnot_intervals(a_s, a_l, na, b_s, b_l, nb, os, ol,
                                           cards + j);
  }
}

// cardinality-only AND batch: no output buffer, no materialization
void rb_batch_intersect_card_u16(const uint16_t* avals, const int64_t* aoffs,
                                 const uint16_t* bvals, const int64_t* boffs,
                                 int64_t n_pairs, int64_t* counts) {
#pragma omp parallel for schedule(dynamic, 64)
  for (int64_t j = 0; j < n_pairs; ++j) {
    counts[j] = rb_intersect_u16(
        avals + aoffs[j], (int32_t)(aoffs[j + 1] - aoffs[j]),
        bvals + boffs[j], (int32_t)(boffs[j + 1] - boffs[j]), nullptr);
  }
}

// per-row popcount of an [n_rows, n_words] matrix (batched result
// cardinalities; rows are independent)
void rb_popcount_rows(const uint64_t* words, int64_t n_rows, int64_t n_words,
                      int64_t* out) {
#pragma omp parallel for schedule(static)
  for (int64_t r = 0; r < n_rows; ++r)
    out[r] = rb_popcount_words(words + r * n_words, n_words);
}

// Scatter sorted-u16 container values into [*, 1024]-word rows, container j
// targeting row row_ids[j] with combine op 0=or 1=xor 2=clear (andnot).
// SERIAL over containers: unlike rb_pack_array_rows, row_ids may repeat
// (fold accumulators), so the parallel-for would race.
void rb_scatter_values_rows(const int64_t* row_ids, const int64_t* offsets,
                            int64_t n_containers, const uint16_t* vals,
                            uint64_t* out, int32_t op) {
  for (int64_t j = 0; j < n_containers; ++j) {
    uint64_t* row = out + row_ids[j] * 1024;
    for (int64_t i = offsets[j]; i < offsets[j + 1]; ++i) {
      uint16_t v = vals[i];
      uint64_t bit = 1ULL << (v & 63);
      switch (op) {
        case 0: row[v >> 6] |= bit; break;
        case 1: row[v >> 6] ^= bit; break;
        default: row[v >> 6] &= ~bit; break;
      }
    }
  }
}

// Fill disjoint half-open [start, end) intervals into word rows: container
// j's runs (starts/ends[run_offs[j]:run_offs[j+1]]) land in row row_ids[j]
// with op 0=or 1=xor. The batched twin of rb_words_from_intervals — one
// call expands every run container of a working set. Serial: rows repeat.
void rb_fill_intervals_rows(const int64_t* row_ids, const int64_t* run_offs,
                            int64_t n_containers, const int64_t* starts,
                            const int64_t* ends, uint64_t* out, int32_t op) {
  for (int64_t j = 0; j < n_containers; ++j) {
    uint64_t* words = out + row_ids[j] * 1024;
    for (int64_t i = run_offs[j]; i < run_offs[j + 1]; ++i) {
      int64_t s = starts[i], e = ends[i];
      if (s < 0) s = 0;
      if (e > 65536) e = 65536;
      if (e <= s) continue;
      int64_t sw = s >> 6, ew = (e - 1) >> 6;
      uint64_t first = ~0ULL << (s & 63);
      uint64_t last = ~0ULL >> (63 - ((e - 1) & 63));
      if (op == 0) {
        if (sw == ew) {
          words[sw] |= first & last;
        } else {
          words[sw] |= first;
          for (int64_t w = sw + 1; w < ew; ++w) words[w] = ~0ULL;
          words[ew] |= last;
        }
      } else {  // xor: runs within a container are disjoint, so ^= is exact
        if (sw == ew) {
          words[sw] ^= first & last;
        } else {
          words[sw] ^= first;
          for (int64_t w = sw + 1; w < ew; ++w) words[w] ^= ~0ULL;
          words[ew] ^= last;
        }
      }
    }
  }
}

// ---------------------------------------------------------------------------
// batch packing (device-store marshal)
// ---------------------------------------------------------------------------

// Scatter many array containers' values into an [n_rows, 1024]-word matrix
// in one pass: container j (values vals[offsets[j]:offsets[j+1]]) lands in
// row row_ids[j]. The SoA packing hot loop of parallel/store.pack_rows_host.
void rb_pack_array_rows(const int64_t* row_ids, const int64_t* offsets,
                        int64_t n_containers, const uint16_t* vals,
                        uint64_t* out) {
  // each container owns its output row exclusively, so the container loop
  // parallelizes race-free (the pack of a 10k-bitmap working set scatters
  // into ~600 MB and was the dominant one-time setup cost)
#pragma omp parallel for schedule(dynamic, 64)
  for (int64_t j = 0; j < n_containers; ++j) {
    uint64_t* row = out + row_ids[j] * 1024;
    for (int64_t i = offsets[j]; i < offsets[j + 1]; ++i) {
      uint16_t v = vals[i];
      row[v >> 6] |= 1ull << (v & 63);
    }
  }
}

}  // extern "C"
