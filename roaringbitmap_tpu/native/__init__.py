"""Native host runtime: compiled C++ L0 kernels behind ctypes.

The reference's hot host-side loops are JIT-compiled Java intrinsics
(Util.java galloping searches, Long.bitCount folds); this framework's
equivalents are a small C++ library (``kernels.cpp``) compiled on first use
with the system toolchain and loaded via ctypes — no build-time dependency,
no pybind11. Every entry point has an identical-semantics numpy fallback in
``utils/bits.py``; ``utils/bits.py`` transparently dispatches here when the
library is available (disable with ``ROARINGBITMAP_TPU_NO_NATIVE=1``).

The TPU compute path (ops/) never goes through this module — it exists for
the CPU fast path, where the reference wins on ns-scale small-container ops
and Python/numpy call overhead would otherwise dominate.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "kernels.cpp")
_LIB_NAME = "_rb_kernels.so"

_lock = threading.Lock()
_lib = None
_tried = False


def _build(out_path: str) -> bool:
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        "-fno-exceptions", "-fno-rtti", "-fopenmp",
        _SRC, "-o", out_path,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
        if proc.returncode == 0 and os.path.exists(out_path):
            return True
        # toolchains without libgomp still get the serial build
        cmd.remove("-fopenmp")
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
        return proc.returncode == 0 and os.path.exists(out_path)
    except (OSError, subprocess.SubprocessError):
        return False


def _declare(lib: ctypes.CDLL) -> None:
    u16p = np.ctypeslib.ndpointer(np.uint16, flags="C_CONTIGUOUS")
    u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    i32, i64 = ctypes.c_int32, ctypes.c_int64
    u16 = ctypes.c_uint16

    lib.rb_advance_until.restype = i32
    lib.rb_advance_until.argtypes = [u16p, i32, i32, u16]
    lib.rb_intersect_u16.restype = i32
    lib.rb_intersect_u16.argtypes = [u16p, i32, u16p, i32, u16p]
    lib.rb_intersect_card_u16.restype = i32
    lib.rb_intersect_card_u16.argtypes = [u16p, i32, u16p, i32]
    for name in ("rb_union_u16", "rb_difference_u16", "rb_xor_u16"):
        fn = getattr(lib, name)
        fn.restype = i32
        fn.argtypes = [u16p, i32, u16p, i32, u16p]
    lib.rb_contains_many_u16.restype = None
    lib.rb_contains_many_u16.argtypes = [u16p, i32, u16p, i32, u8p]
    lib.rb_popcount_words.restype = i64
    lib.rb_popcount_words.argtypes = [u64p, i64]
    lib.rb_words_from_values.restype = None
    lib.rb_words_from_values.argtypes = [u16p, i32, u64p]
    lib.rb_values_from_words.restype = i32
    lib.rb_values_from_words.argtypes = [u64p, i32, u16p]
    lib.rb_num_runs_words.restype = i32
    lib.rb_num_runs_words.argtypes = [u64p, i32]
    lib.rb_select_words.restype = i32
    lib.rb_select_words.argtypes = [u64p, i32, i32]
    lib.rb_cardinality_in_range.restype = i64
    lib.rb_cardinality_in_range.argtypes = [u64p, i32, i32]
    lib.rb_wide_op_words.restype = i64
    lib.rb_wide_op_words.argtypes = [u64p, i64, i64, i32, u64p]
    lib.rb_runs_from_values.restype = i32
    lib.rb_runs_from_values.argtypes = [u16p, i32, u16p, u16p]
    lib.rb_num_runs_values.restype = i32
    lib.rb_num_runs_values.argtypes = [u16p, i32]
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.rb_pack_array_rows.restype = None
    lib.rb_pack_array_rows.argtypes = [i64p, i64p, i64, u16p, u64p]
    lib.rb_words_from_intervals.restype = None
    lib.rb_words_from_intervals.argtypes = [i64p, i64p, ctypes.c_int32, u64p]


def _load():
    global _lib, _tried
    if _tried:
        return _lib
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("ROARINGBITMAP_TPU_NO_NATIVE"):
            return None
        path = os.path.join(_DIR, _LIB_NAME)
        try:
            if not os.path.exists(path) or os.path.getmtime(path) < os.path.getmtime(_SRC):
                if not _build(path):
                    # Package dir may be read-only; build into a fresh private
                    # temp dir. Never load a pre-existing library from a
                    # shared/predictable location — /tmp is world-writable.
                    path = os.path.join(tempfile.mkdtemp(prefix="rb_kernels_"), _LIB_NAME)
                    if not _build(path):
                        return None
            lib = ctypes.CDLL(path)
            _declare(lib)
            _lib = lib
        except (OSError, AttributeError):
            # AttributeError: a stale .so missing newly-declared symbols
            _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def lib() -> ctypes.CDLL:
    l = _load()
    if l is None:
        raise RuntimeError("native kernels unavailable")
    return l


# ---------------------------------------------------------------------------
# numpy-facing wrappers (same signatures as the utils/bits.py fallbacks)
# ---------------------------------------------------------------------------


def _c16(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.uint16)


def intersect_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    a, b = _c16(a), _c16(b)
    out = np.empty(min(a.size, b.size), dtype=np.uint16)
    n = lib().rb_intersect_u16(a, a.size, b, b.size, out)
    return out[:n].copy()  # copy: don't pin the oversized scratch buffer


def intersect_cardinality(a: np.ndarray, b: np.ndarray) -> int:
    a, b = _c16(a), _c16(b)
    return int(lib().rb_intersect_card_u16(a, a.size, b, b.size))


def merge_sorted_unique(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    a, b = _c16(a), _c16(b)
    out = np.empty(a.size + b.size, dtype=np.uint16)
    n = lib().rb_union_u16(a, a.size, b, b.size, out)
    return out[:n].copy()


def difference_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    a, b = _c16(a), _c16(b)
    out = np.empty(a.size, dtype=np.uint16)
    n = lib().rb_difference_u16(a, a.size, b, b.size, out)
    return out[:n].copy()


def xor_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    a, b = _c16(a), _c16(b)
    out = np.empty(a.size + b.size, dtype=np.uint16)
    n = lib().rb_xor_u16(a, a.size, b, b.size, out)
    return out[:n].copy()


def contains_many(sorted_vals: np.ndarray, queries: np.ndarray) -> np.ndarray:
    s, q = _c16(sorted_vals), _c16(queries)
    out = np.empty(q.size, dtype=np.uint8)
    lib().rb_contains_many_u16(s, s.size, q, q.size, out)
    return out.astype(bool)


def advance_until(a: np.ndarray, pos: int, min_val: int) -> int:
    a = _c16(a)
    return int(lib().rb_advance_until(a, a.size, pos, min_val))


def cardinality_of_words(words: np.ndarray) -> int:
    w = np.ascontiguousarray(words, dtype=np.uint64)
    return int(lib().rb_popcount_words(w, w.size))


def words_from_values(values: np.ndarray, n_words: int = 1024) -> np.ndarray:
    v = _c16(values)
    words = np.zeros(n_words, dtype=np.uint64)
    lib().rb_words_from_values(v, v.size, words)
    return words


def values_from_words(words: np.ndarray) -> np.ndarray:
    w = np.ascontiguousarray(words, dtype=np.uint64)
    out = np.empty(w.size * 64, dtype=np.uint16)
    n = lib().rb_values_from_words(w, w.size, out)
    # copy: a [:n] view would pin the full 64*w.size-element buffer inside
    # long-lived containers (observed as O(rows) appender memory)
    return out[:n].copy()


def num_runs_in_words(words: np.ndarray) -> int:
    w = np.ascontiguousarray(words, dtype=np.uint64)
    return int(lib().rb_num_runs_words(w, w.size))


def select_in_words(words: np.ndarray, j: int) -> int:
    w = np.ascontiguousarray(words, dtype=np.uint64)
    r = int(lib().rb_select_words(w, w.size, j))
    if r < 0:
        raise IndexError(f"select({j}) out of range")
    return r


def cardinality_in_range(words: np.ndarray, start: int, end: int) -> int:
    w = np.ascontiguousarray(words, dtype=np.uint64)
    return int(lib().rb_cardinality_in_range(w, start, end))


def wide_op_words(rows: np.ndarray, op: str = "or"):
    """Fold an [n_rows, n_words] matrix; returns (out_words, cardinality)."""
    r = np.ascontiguousarray(rows, dtype=np.uint64)
    n_rows, n_words = r.shape
    out = np.empty(n_words, dtype=np.uint64)
    opc = {"or": 0, "and": 1, "xor": 2}[op]
    card = lib().rb_wide_op_words(r.reshape(-1), n_rows, n_words, opc, out)
    return out, int(card)


def runs_from_values(values: np.ndarray):
    v = _c16(values)
    if v.size == 0:
        return np.empty(0, dtype=np.uint16), np.empty(0, dtype=np.uint16)
    starts = np.empty(v.size, dtype=np.uint16)
    lengths = np.empty(v.size, dtype=np.uint16)
    n = lib().rb_runs_from_values(v, v.size, starts, lengths)
    # copies, not views: RunContainers outlive the oversized scratch buffers
    return starts[:n].copy(), lengths[:n].copy()


def num_runs_in_values(values: np.ndarray) -> int:
    v = _c16(values)
    return int(lib().rb_num_runs_values(v, v.size))


def words_from_intervals(starts: np.ndarray, ends: np.ndarray) -> np.ndarray:
    s = np.ascontiguousarray(starts, dtype=np.int64)
    e = np.ascontiguousarray(ends, dtype=np.int64)
    words = np.zeros(1024, dtype=np.uint64)
    lib().rb_words_from_intervals(s, e, np.int32(s.size), words)
    return words


def pack_array_rows(
    row_ids: np.ndarray, offsets: np.ndarray, vals: np.ndarray, out64: np.ndarray
) -> None:
    """Scatter concatenated array-container values into [n_rows, 1024]-word
    matrix rows in one native pass (parallel/store.pack_rows_host hot loop)."""
    rows = np.ascontiguousarray(row_ids, dtype=np.int64)
    offs = np.ascontiguousarray(offsets, dtype=np.int64)
    v = _c16(vals)
    lib().rb_pack_array_rows(rows, offs, rows.size, v, out64.reshape(-1))
