"""Native host runtime: compiled C++ L0 kernels in three tiers.

The reference's hot host-side loops are JIT-compiled Java intrinsics
(Util.java galloping searches, Long.bitCount folds); this framework's
equivalents are a small C++ library (``kernels.cpp``) compiled on first use
with the system toolchain — no build-time dependency, no pybind11. Two
bindings serve it: a CPython/numpy C-API extension (``ext.cpp``,
~0.2-1 us/call — the tier that matters at container sizes) and ctypes
(~4-13 us/call, the portable fallback and the batch entry points). Every
entry point also has an identical-semantics numpy fallback in
``utils/bits.py``; ``utils/bits.py`` transparently dispatches here when a
native tier is available (disable with ``ROARINGBITMAP_TPU_NO_NATIVE=1``;
``ROARINGBITMAP_TPU_NO_EXT=1`` pins ctypes). ``backend_tier()`` reports
which tier is live.

The TPU compute path (ops/) never goes through this module — it exists for
the CPU fast path, where the reference wins on ns-scale small-container ops
and Python/numpy call overhead would otherwise dominate.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "kernels.cpp")
_LIB_NAME = "_rb_kernels.so"

_lock = threading.Lock()
_lib = None  # guarded-by: _lock
_tried = False  # guarded-by: _lock


def _build(out_path: str) -> bool:
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        "-fno-exceptions", "-fno-rtti", "-fopenmp",
        _SRC, "-o", out_path,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
        if proc.returncode == 0 and os.path.exists(out_path):
            return True
        # toolchains without libgomp still get the serial build
        cmd.remove("-fopenmp")
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
        return proc.returncode == 0 and os.path.exists(out_path)
    except (OSError, subprocess.SubprocessError):
        return False


def _declare(lib: ctypes.CDLL) -> None:
    u16p = np.ctypeslib.ndpointer(np.uint16, flags="C_CONTIGUOUS")
    u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    i32, i64 = ctypes.c_int32, ctypes.c_int64
    u16 = ctypes.c_uint16

    lib.rb_advance_until.restype = i32
    lib.rb_advance_until.argtypes = [u16p, i32, i32, u16]
    lib.rb_intersect_u16.restype = i32
    lib.rb_intersect_u16.argtypes = [u16p, i32, u16p, i32, u16p]
    lib.rb_intersect_card_u16.restype = i32
    lib.rb_intersect_card_u16.argtypes = [u16p, i32, u16p, i32]
    for name in ("rb_union_u16", "rb_difference_u16", "rb_xor_u16"):
        fn = getattr(lib, name)
        fn.restype = i32
        fn.argtypes = [u16p, i32, u16p, i32, u16p]
    lib.rb_contains_many_u16.restype = None
    lib.rb_contains_many_u16.argtypes = [u16p, i32, u16p, i32, u8p]
    lib.rb_popcount_words.restype = i64
    lib.rb_popcount_words.argtypes = [u64p, i64]
    lib.rb_words_from_values.restype = None
    lib.rb_words_from_values.argtypes = [u16p, i32, u64p]
    lib.rb_values_from_words.restype = i32
    lib.rb_values_from_words.argtypes = [u64p, i32, u16p]
    lib.rb_num_runs_words.restype = i32
    lib.rb_num_runs_words.argtypes = [u64p, i32]
    lib.rb_select_words.restype = i32
    lib.rb_select_words.argtypes = [u64p, i32, i32]
    lib.rb_cardinality_in_range.restype = i64
    lib.rb_cardinality_in_range.argtypes = [u64p, i32, i32]
    lib.rb_wide_op_words.restype = i64
    lib.rb_wide_op_words.argtypes = [u64p, i64, i64, i32, u64p]
    lib.rb_runs_from_values.restype = i32
    lib.rb_runs_from_values.argtypes = [u16p, i32, u16p, u16p]
    lib.rb_num_runs_values.restype = i32
    lib.rb_num_runs_values.argtypes = [u16p, i32]
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.rb_pack_array_rows.restype = None
    lib.rb_pack_array_rows.argtypes = [i64p, i64p, i64, u16p, u64p]
    lib.rb_words_from_intervals.restype = None
    lib.rb_words_from_intervals.argtypes = [i64p, i64p, ctypes.c_int32, u64p]
    # columnar batched pairwise (ISSUE 5): declared with raw pointers, not
    # ndpointer — these are called several times per *pairwise op* (not per
    # working set), and ndpointer's from_param validation costs ~10 µs per
    # array argument, which at 5-9 arguments would hand back most of the
    # dispatch win the batch kernels exist to create. The wrappers below
    # own the dtype/contiguity guarantees instead.
    vp = ctypes.c_void_p
    lib.rb_batch_pairwise_u16.restype = None
    lib.rb_batch_pairwise_u16.argtypes = [vp, vp, vp, vp, i64, i32, vp, vp, vp]
    lib.rb_batch_intersect_card_u16.restype = None
    lib.rb_batch_intersect_card_u16.argtypes = [vp, vp, vp, vp, i64, vp]
    lib.rb_batch_run_pairwise.restype = None
    lib.rb_batch_run_pairwise.argtypes = [
        vp, vp, vp, vp, vp, vp, i64, i32, vp, vp, vp, vp, vp,
    ]
    lib.rb_popcount_rows.restype = None
    lib.rb_popcount_rows.argtypes = [vp, i64, i64, vp]
    lib.rb_scatter_values_rows.restype = None
    lib.rb_scatter_values_rows.argtypes = [vp, vp, i64, vp, vp, i32]
    lib.rb_fill_intervals_rows.restype = None
    lib.rb_fill_intervals_rows.argtypes = [vp, vp, i64, vp, vp, vp, i32]


def _load():
    global _lib, _tried
    if _tried:
        return _lib
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("ROARINGBITMAP_TPU_NO_NATIVE"):
            return None
        path = os.path.join(_DIR, _LIB_NAME)
        try:
            if not os.path.exists(path) or os.path.getmtime(path) < os.path.getmtime(_SRC):
                if not _build(path):
                    # Package dir may be read-only; build into a fresh private
                    # temp dir. Never load a pre-existing library from a
                    # shared/predictable location — /tmp is world-writable.
                    path = os.path.join(tempfile.mkdtemp(prefix="rb_kernels_"), _LIB_NAME)
                    if not _build(path):
                        return None
            lib = ctypes.CDLL(path)
            _declare(lib)
            _lib = lib
        except (OSError, AttributeError):
            # AttributeError: a stale .so missing newly-declared symbols
            _lib = None
    return _lib


def available() -> bool:
    try:
        # ISSUE 7 fault site: the C-tier entry probe. An injected (or
        # classified-transient) failure here flips every caller onto the
        # identical-semantics numpy fallbacks — the native→numpy chain
        # exercised as a degradation, not a crash.
        from ..robust import faults as _faults

        _faults.fault_point("native.entry")
    except Exception as e:
        from ..robust import errors as _rerrors
        from ..robust import ladder as _ladder

        if _rerrors.classify(e) == _rerrors.FATAL:
            raise
        _ladder.LADDER.note_degrade("native.entry", "native", "numpy", e)
        return False
    ok = _load() is not None
    if ok:
        _bind_ext_once()
    return ok


def lower_bound(a: np.ndarray, x: int) -> int:
    """First index with a[i] >= x (sorted uint16). Ext-or-numpy ONLY (the
    validate_* pattern): through ctypes the call overhead exceeds the
    np.searchsorted this replaces, so the ctypes tier is never a win here.
    pos=-1 because advance_until searches strictly AFTER pos
    (Util.advanceUntil semantics) — pos=0 would skip index 0."""
    e = _load_ext()
    if e is not None:
        try:
            return e.advance_until(a, -1, int(x))
        except TypeError:
            return e.advance_until(_c16(a), -1, int(x))
    from ..utils import bits as _bits

    return _bits.lower_bound_numpy(a, x)


def validate_sorted_u16(values: np.ndarray) -> bool:
    """True iff strictly increasing (deserialization's array-container
    check; single C pass when the extension is built, else the shared
    numpy fallback in utils/bits)."""
    e = _load_ext()
    if e is not None:
        try:
            return bool(e.is_strictly_increasing(values))
        except TypeError:
            return bool(e.is_strictly_increasing(_c16(values)))
    from ..utils import bits as _bits

    return _bits.validate_sorted_u16_numpy(values)


def validate_runs_u16(pairs: np.ndarray) -> bool:
    """True iff interleaved (start, length) runs are sorted, disjoint,
    non-touching, and end inside the 2^16 universe."""
    e = _load_ext()
    if e is not None:
        try:
            return bool(e.runs_valid(pairs))
        except TypeError:
            return bool(e.runs_valid(_c16(pairs)))
    from ..utils import bits as _bits

    return _bits.validate_runs_u16_numpy(pairs)


def backend_tier() -> str:
    """Which host-kernel tier serves the CPU fast path: 'ext' (CPython C
    extension), 'ctypes', 'numpy' (pure fallback), or 'unloaded' (nothing
    has triggered the lazy resolution yet). Reports state only — a
    read-only observability call must never block on a g++ build."""
    if _ext is not None:
        return "ext"
    if _lib is not None:
        return "ctypes"
    return "numpy" if _tried else "unloaded"


def lib() -> ctypes.CDLL:
    l = _load()
    if l is None:
        raise RuntimeError("native kernels unavailable")
    return l


# ---------------------------------------------------------------------------
# CPython extension fast path (ext.cpp)
#
# ctypes costs ~4-13 us per call (ndpointer validation + marshalling +
# output copies) — more than the kernels themselves at container sizes. The
# extension serves the same entry points through the CPython/numpy C API at
# ~0.2-0.4 us; when it builds, the per-container functions below rebind to
# it (batch entry points like pack_array_rows stay on ctypes, where the
# call overhead is amortized). utils/bits resolves through this module's
# attributes, so the rebind propagates everywhere automatically.
# ---------------------------------------------------------------------------

_EXT_SRC = os.path.join(_DIR, "ext.cpp")


def _ext_name() -> str:
    # ABI-tagged (e.g. _rb_ext.cpython-312-x86_64-linux-gnu.so) so multiple
    # interpreters sharing this checkout each build and load their own
    import sysconfig

    return "_rb_ext" + (sysconfig.get_config_var("EXT_SUFFIX") or ".so")


_ext = None  # guarded-by: _lock
_ext_tried = False  # guarded-by: _lock
_ext_bound = False  # guarded-by: _lock


def _build_ext(out_path: str) -> bool:
    import sysconfig

    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-fopenmp",
        "-I" + sysconfig.get_paths()["include"],
        "-I" + np.get_include(),
        _EXT_SRC, "-o", out_path,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=180)
        if proc.returncode == 0 and os.path.exists(out_path):
            return True
        cmd.remove("-fopenmp")
        proc = subprocess.run(cmd, capture_output=True, timeout=180)
        return proc.returncode == 0 and os.path.exists(out_path)
    except (OSError, subprocess.SubprocessError):
        return False


def _load_ext():
    global _ext, _ext_tried
    if _ext_tried:
        return _ext
    with _lock:
        if _ext_tried:
            return _ext
        _ext_tried = True
        if os.environ.get("ROARINGBITMAP_TPU_NO_NATIVE") or os.environ.get(
            "ROARINGBITMAP_TPU_NO_EXT"
        ):
            return None
        name = _ext_name()
        path = os.path.join(_DIR, name)
        try:
            src_m = max(os.path.getmtime(_EXT_SRC), os.path.getmtime(_SRC))
            if not os.path.exists(path) or os.path.getmtime(path) < src_m:
                if not _build_ext(path):
                    path = os.path.join(tempfile.mkdtemp(prefix="rb_ext_"), name)
                    if not _build_ext(path):
                        return None
            _ext = _import_ext(path)
        except Exception:  # rb-ok: exception-hygiene -- degrade-not-crash contract: any load/ABI failure of the cached .so falls through to the rebuild ladder below
            # a cached build that fails to load gets a rebuild IN PLACE
            # first (self-healing the package-dir cache so later processes
            # don't re-pay this), then one private-dir attempt (read-only
            # checkouts), before the process settles on the ctypes tier
            _ext = None
            for retry in (
                os.path.join(_DIR, name),
                os.path.join(tempfile.mkdtemp(prefix="rb_ext_"), name),
            ):
                try:
                    if _build_ext(retry):
                        _ext = _import_ext(retry)
                        break
                except Exception:  # rb-ok: exception-hygiene -- each rung of the rebuild ladder may fail for its own reason (read-only dir, bad toolchain); the ctypes tier is the documented landing
                    continue
    return _ext


def _import_ext(path: str):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "roaringbitmap_tpu.native._rb_ext", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # smoke-test: a stale ABI or missing symbol surfaces now (a plain if,
    # not assert — must fire under python -O too)
    if int(mod.cardinality_of_words(np.ones(1, dtype=np.uint64))) != 1:
        raise ImportError("_rb_ext smoke-test failed")
    return mod


def _bind_ext_once() -> None:
    global _ext_bound, _ext
    if _ext_bound:
        return
    e = _load_ext()
    if e is None:
        return
    # _load_ext has released _lock here; take it again for the publication
    # writes (the lock-discipline pass caught the original unlocked writes)
    try:
        _bind_ext(e)
    except Exception:  # rb-ok: exception-hygiene -- a partial module must degrade to the ctypes path, never raise out of available() (degrade-not-crash contract)
        with _lock:
            _ext = None
        return
    with _lock:
        _ext_bound = True


def _bind_ext(e) -> None:
    g = globals()

    # the extension validates dtype/contiguity itself and raises TypeError;
    # converting only on that path keeps the flexible input contract of the
    # ctypes wrappers while the common uint16/uint64 case stays copy-free
    def _pair(name):
        fn = getattr(e, name)

        def run(a, b, _fn=fn):
            try:
                return _fn(a, b)
            except TypeError:
                return _fn(_c16(a), _c16(b))

        run.__name__ = name
        return run

    for _n in ("intersect_sorted", "merge_sorted_unique", "difference_sorted",
               "xor_sorted", "intersect_cardinality", "contains_many"):
        g[_n] = _pair(_n)

    def advance_until(a, pos, min_val, _fn=e.advance_until):
        try:
            return _fn(a, int(pos), int(min_val))
        except TypeError:
            return _fn(_c16(a), int(pos), int(min_val))

    def _w64(x):
        return np.ascontiguousarray(x, dtype=np.uint64)

    def cardinality_of_words(words, _fn=e.cardinality_of_words):
        try:
            return _fn(words)
        except TypeError:
            return _fn(_w64(words))

    def words_from_values(values, n_words=1024, _fn=e.words_from_values):
        try:
            return _fn(values, int(n_words))
        except TypeError:
            return _fn(_c16(values), int(n_words))

    def values_from_words(words, _fn=e.values_from_words):
        try:
            return _fn(words)
        except TypeError:
            return _fn(_w64(words))

    def num_runs_in_words(words, _fn=e.num_runs_in_words):
        try:
            return _fn(words)
        except TypeError:
            return _fn(_w64(words))

    def select_in_words(words, j, _fn=e.select_in_words):
        try:
            return _fn(words, int(j))
        except TypeError:
            return _fn(_w64(words), int(j))

    def cardinality_in_range(words, start, end, _fn=e.cardinality_in_range):
        try:
            return _fn(words, int(start), int(end))
        except TypeError:
            return _fn(_w64(words), int(start), int(end))

    for _f in (advance_until, cardinality_of_words, words_from_values,
               values_from_words, num_runs_in_words, select_in_words,
               cardinality_in_range):
        g[_f.__name__] = _f


# ---------------------------------------------------------------------------
# numpy-facing wrappers (same signatures as the utils/bits.py fallbacks)
# ---------------------------------------------------------------------------


def _c16(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.uint16)


def intersect_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    a, b = _c16(a), _c16(b)
    out = np.empty(min(a.size, b.size), dtype=np.uint16)
    n = lib().rb_intersect_u16(a, a.size, b, b.size, out)
    return out[:n].copy()  # copy: don't pin the oversized scratch buffer


def intersect_cardinality(a: np.ndarray, b: np.ndarray) -> int:
    a, b = _c16(a), _c16(b)
    return int(lib().rb_intersect_card_u16(a, a.size, b, b.size))


def merge_sorted_unique(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    a, b = _c16(a), _c16(b)
    out = np.empty(a.size + b.size, dtype=np.uint16)
    n = lib().rb_union_u16(a, a.size, b, b.size, out)
    return out[:n].copy()


def difference_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    a, b = _c16(a), _c16(b)
    out = np.empty(a.size, dtype=np.uint16)
    n = lib().rb_difference_u16(a, a.size, b, b.size, out)
    return out[:n].copy()


def xor_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    a, b = _c16(a), _c16(b)
    out = np.empty(a.size + b.size, dtype=np.uint16)
    n = lib().rb_xor_u16(a, a.size, b, b.size, out)
    return out[:n].copy()


def contains_many(sorted_vals: np.ndarray, queries: np.ndarray) -> np.ndarray:
    s, q = _c16(sorted_vals), _c16(queries)
    out = np.empty(q.size, dtype=np.uint8)
    lib().rb_contains_many_u16(s, s.size, q, q.size, out)
    return out.astype(bool)


def advance_until(a: np.ndarray, pos: int, min_val: int) -> int:
    a = _c16(a)
    return int(lib().rb_advance_until(a, a.size, pos, min_val))


def cardinality_of_words(words: np.ndarray) -> int:
    w = np.ascontiguousarray(words, dtype=np.uint64)
    return int(lib().rb_popcount_words(w, w.size))


def words_from_values(values: np.ndarray, n_words: int = 1024) -> np.ndarray:
    v = _c16(values)
    words = np.zeros(n_words, dtype=np.uint64)
    lib().rb_words_from_values(v, v.size, words)
    return words


def or_values_into_words(words: np.ndarray, values: np.ndarray) -> np.ndarray:
    """OR values into the caller's accumulator — rb_words_from_values ORs
    into its output buffer, so the same C loop serves both entry points.
    Always the ctypes path (the ext module has no or-into variant)."""
    v = _c16(values)
    # tier parity: the numpy fallback raises on a short or read-only
    # accumulator; the C loop would corrupt the heap instead
    if words.size < 1024:
        raise IndexError(f"accumulator has {words.size} words, need 1024")
    if not words.flags.writeable:
        raise ValueError("accumulator is read-only")
    if words.dtype != np.uint64 or not words.flags.c_contiguous:
        w = np.ascontiguousarray(words, dtype=np.uint64)
        lib().rb_words_from_values(v, v.size, w)
        words[:] = w
        return words
    lib().rb_words_from_values(v, v.size, words)
    return words


def values_from_words(words: np.ndarray) -> np.ndarray:
    w = np.ascontiguousarray(words, dtype=np.uint64)
    out = np.empty(w.size * 64, dtype=np.uint16)
    n = lib().rb_values_from_words(w, w.size, out)
    # copy: a [:n] view would pin the full 64*w.size-element buffer inside
    # long-lived containers (observed as O(rows) appender memory)
    return out[:n].copy()


def num_runs_in_words(words: np.ndarray) -> int:
    w = np.ascontiguousarray(words, dtype=np.uint64)
    return int(lib().rb_num_runs_words(w, w.size))


def select_in_words(words: np.ndarray, j: int) -> int:
    w = np.ascontiguousarray(words, dtype=np.uint64)
    r = int(lib().rb_select_words(w, w.size, j))
    if r < 0:
        raise IndexError(f"select({j}) out of range")
    return r


def cardinality_in_range(words: np.ndarray, start: int, end: int) -> int:
    w = np.ascontiguousarray(words, dtype=np.uint64)
    return int(lib().rb_cardinality_in_range(w, start, end))


def wide_op_words(rows: np.ndarray, op: str = "or"):
    """Fold an [n_rows, n_words] matrix; returns (out_words, cardinality)."""
    r = np.ascontiguousarray(rows, dtype=np.uint64)
    n_rows, n_words = r.shape
    out = np.empty(n_words, dtype=np.uint64)
    opc = {"or": 0, "and": 1, "xor": 2}[op]
    card = lib().rb_wide_op_words(r.reshape(-1), n_rows, n_words, opc, out)
    return out, int(card)


def runs_from_values(values: np.ndarray):
    v = _c16(values)
    if v.size == 0:
        return np.empty(0, dtype=np.uint16), np.empty(0, dtype=np.uint16)
    starts = np.empty(v.size, dtype=np.uint16)
    lengths = np.empty(v.size, dtype=np.uint16)
    n = lib().rb_runs_from_values(v, v.size, starts, lengths)
    # copies, not views: RunContainers outlive the oversized scratch buffers
    return starts[:n].copy(), lengths[:n].copy()


def num_runs_in_values(values: np.ndarray) -> int:
    v = _c16(values)
    return int(lib().rb_num_runs_values(v, v.size))


def words_from_intervals(starts: np.ndarray, ends: np.ndarray) -> np.ndarray:
    s = np.ascontiguousarray(starts, dtype=np.int64)
    e = np.ascontiguousarray(ends, dtype=np.int64)
    words = np.zeros(1024, dtype=np.uint64)
    lib().rb_words_from_intervals(s, e, np.int32(s.size), words)
    return words


_BATCH_OPS = {"and": 0, "or": 1, "xor": 2, "andnot": 3}
_SCATTER_OPS = {"or": 0, "xor": 1, "clear": 2}


def _c64i(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int64)


def _p(a: np.ndarray) -> int:
    # raw data pointer for the void_p-declared batch entry points; every
    # caller below has already forced dtype + C-contiguity, and the array
    # stays referenced by the calling frame for the duration of the call
    return a.ctypes.data


def batch_pairwise_u16(
    avals: np.ndarray,
    aoffs: np.ndarray,
    bvals: np.ndarray,
    boffs: np.ndarray,
    op: str,
    out_offs: np.ndarray,
    out_size: int,
):
    """One call = one whole batch of sorted-u16 container ops (columnar
    engine, ISSUE 5). Pair j reads avals[aoffs[j]:aoffs[j+1]] x
    bvals[boffs[j]:boffs[j+1]] and writes at out[out_offs[j]:]; returns
    ``(out_scratch, counts)`` — caller slices out_scratch per pair."""
    a, b = _c16(avals), _c16(bvals)
    ao, bo, oo = _c64i(aoffs), _c64i(boffs), _c64i(out_offs)
    n = ao.size - 1
    out = np.empty(max(1, int(out_size)), dtype=np.uint16)
    counts = np.empty(max(1, n), dtype=np.int64)
    lib().rb_batch_pairwise_u16(
        _p(a), _p(ao), _p(b), _p(bo), n, _BATCH_OPS[op], _p(oo), _p(out), _p(counts)
    )
    return out, counts[:n]


def batch_run_pairwise(
    astarts: np.ndarray,
    alens: np.ndarray,
    aoffs: np.ndarray,
    bstarts: np.ndarray,
    blens: np.ndarray,
    boffs: np.ndarray,
    op: str,
    out_offs,
    out_size: int,
):
    """Run-unified batch AND/ANDNOT (arrays as length-0 runs): one call
    executes every (array|run) x (array|run) pair of a bucket, emitting
    result INTERVALS (payload-sized buffers, never cardinality-sized).
    ``out_offs=None`` cards only; returns ``(out_starts_or_None,
    out_lengths_or_None, interval_counts, cards)``."""
    a_s, a_l = _c16(astarts), _c16(alens)
    b_s, b_l = _c16(bstarts), _c16(blens)
    ao, bo = _c64i(aoffs), _c64i(boffs)
    n = ao.size - 1
    counts = np.empty(max(1, n), dtype=np.int64)
    cards = np.empty(max(1, n), dtype=np.int64)
    if out_offs is None:
        lib().rb_batch_run_pairwise(
            _p(a_s), _p(a_l), _p(ao), _p(b_s), _p(b_l), _p(bo),
            n, _BATCH_OPS[op], None, None, None, _p(counts), _p(cards),
        )
        return None, None, counts[:n], cards[:n]
    oo = _c64i(out_offs)
    out_s = np.empty(max(1, int(out_size)), dtype=np.uint16)
    out_l = np.empty(max(1, int(out_size)), dtype=np.uint16)
    lib().rb_batch_run_pairwise(
        _p(a_s), _p(a_l), _p(ao), _p(b_s), _p(b_l), _p(bo),
        n, _BATCH_OPS[op], _p(oo), _p(out_s), _p(out_l), _p(counts), _p(cards),
    )
    return out_s, out_l, counts[:n], cards[:n]


def batch_intersect_card_u16(
    avals: np.ndarray, aoffs: np.ndarray, bvals: np.ndarray, boffs: np.ndarray
) -> np.ndarray:
    """Per-pair AND cardinalities, no materialization."""
    a, b = _c16(avals), _c16(bvals)
    ao, bo = _c64i(aoffs), _c64i(boffs)
    n = ao.size - 1
    counts = np.empty(max(1, n), dtype=np.int64)
    lib().rb_batch_intersect_card_u16(_p(a), _p(ao), _p(b), _p(bo), n, _p(counts))
    return counts[:n]


def popcount_rows(mat: np.ndarray) -> np.ndarray:
    """Per-row popcount of an [n_rows, n_words] uint64 matrix."""
    m = np.ascontiguousarray(mat, dtype=np.uint64)
    n_rows, n_words = m.shape
    out = np.empty(max(1, n_rows), dtype=np.int64)
    lib().rb_popcount_rows(_p(m), n_rows, n_words, _p(out))
    return out[:n_rows]


def scatter_values_rows(
    row_ids: np.ndarray, offsets: np.ndarray, vals: np.ndarray,
    out64: np.ndarray, op: str = "or",
) -> None:
    """Scatter concatenated u16 container values into [*, 1024]-word rows
    with or/xor/clear combine; row_ids may repeat (fold accumulators)."""
    rows, offs = _c64i(row_ids), _c64i(offsets)
    v = _c16(vals)
    if out64.dtype != np.uint64 or not out64.flags.c_contiguous:
        raise ValueError("scatter_values_rows needs a C-contiguous uint64 target")
    lib().rb_scatter_values_rows(
        _p(rows), _p(offs), rows.size, _p(v), _p(out64), _SCATTER_OPS[op]
    )


def fill_intervals_rows(
    row_ids: np.ndarray, run_offs: np.ndarray, starts: np.ndarray,
    ends: np.ndarray, out64: np.ndarray, op: str = "or",
) -> None:
    """Expand many run containers' [start, end) intervals into word rows in
    one call — the batched twin of words_from_intervals."""
    rows, offs = _c64i(row_ids), _c64i(run_offs)
    s, e = _c64i(starts), _c64i(ends)
    if out64.dtype != np.uint64 or not out64.flags.c_contiguous:
        raise ValueError("fill_intervals_rows needs a C-contiguous uint64 target")
    lib().rb_fill_intervals_rows(
        _p(rows), _p(offs), rows.size, _p(s), _p(e), _p(out64), _SCATTER_OPS[op]
    )


def pack_array_rows(
    row_ids: np.ndarray, offsets: np.ndarray, vals: np.ndarray, out64: np.ndarray
) -> None:
    """Scatter concatenated array-container values into [n_rows, 1024]-word
    matrix rows in one native pass (parallel/store.pack_rows_host hot loop)."""
    rows = np.ascontiguousarray(row_ids, dtype=np.int64)
    offs = np.ascontiguousarray(offsets, dtype=np.int64)
    v = _c16(vals)
    lib().rb_pack_array_rows(rows, offs, rows.size, v, out64.reshape(-1))
