"""The frozen epoch artifact: a zero-copy mmap corpus format (ISSUE 17).

One file holds one epoch's whole corpus. Per-bitmap payloads are the
**portable interoperable format** our ``serialization.py`` implements
byte-exactly (arXiv:1709.07821 §Appendix; the reference's
``ImmutableRoaringBitmap`` serves queries straight off this layout), so
a mapped corpus needs **no parse step**: each slice feeds
``models/immutable.ImmutableRoaringBitmap`` directly, container payloads
stay OS-paged views, and ``store.ship_rows``/``pack_groups`` build
device payloads straight from the map.

Layout (all little-endian, the portable format's own byte order)::

    header   16 B   magic b"RBTD" | u16 version=1 | u16 flags=0
                    | u32 n_bitmaps | u32 reserved=0
    directory n*16 B per-bitmap {u64 offset, u64 length} — offset is
                    absolute in the file, 8-byte aligned
    payloads        portable serialize() bytes per bitmap, each padded
                    to the next 8-byte boundary

The 8-byte alignment is load-bearing: a BitmapContainer's 1024 ``<u8``
words must be aligned for the zero-copy ``np.frombuffer`` view (an
unaligned u64 view works on x86 but is a silent copy-or-trap hazard
elsewhere), and the descriptive header + offset table inside each
payload are all 2/4-byte fields, so aligning the payload start aligns
everything after it for the cookie scheme's fixed offsets.

The directory doubles as the key directory: the corpus IS an ordered
list (serve/epochs.py), so a bitmap's key is its corpus index and the
directory entry at index *i* locates bitmap *i*. Integrity is owned one
level up — durable/store.py manifests the artifact with a sha256 and
recovery re-verifies before mapping — so this module only validates
structure (magic, version, extents), never content.
"""

from __future__ import annotations

import mmap as _mmap
import os
import struct
from typing import Dict, List, Sequence

from ..models.immutable import ImmutableRoaringBitmap
from ..serialization import InvalidRoaringFormat, serialize as _serialize

MAGIC = b"RBTD"
VERSION = 1
HEADER = struct.Struct("<4sHHII")  # magic, version, flags, n, reserved
DIRENT = struct.Struct("<QQ")  # absolute offset, payload length
ALIGN = 8


def _pad(n: int) -> int:
    return (-n) % ALIGN


def write_corpus(path: str, bitmaps: Sequence) -> dict:
    """Write one frozen corpus artifact to ``path`` (header + directory
    + aligned portable payloads), fsync it, and return its stats
    (``{"n", "payload_bytes", "artifact_bytes"}``). Accepts any mix of
    heap and mapped bitmaps — a mapped operand's ``serialize()`` is its
    backing slice, so re-persisting an unmodified mapped corpus never
    re-encodes payloads."""
    payloads: List[bytes] = []
    for bm in bitmaps:
        if isinstance(bm, (bytes, bytearray, memoryview)):
            # pre-serialized payload (durable/store.py snapshots the
            # corpus to bytes under a reader ticket, then writes here
            # OUTSIDE the ticket so disk I/O never delays a flip drain)
            payloads.append(bytes(bm))
        elif isinstance(bm, ImmutableRoaringBitmap):
            payloads.append(bm.serialize())
        else:
            payloads.append(_serialize(bm))
    n = len(payloads)
    directory = bytearray(DIRENT.size * n)
    offset = HEADER.size + len(directory)
    offset += _pad(offset)
    for i, p in enumerate(payloads):
        DIRENT.pack_into(directory, DIRENT.size * i, offset, len(p))
        offset += len(p) + _pad(len(p))
    with open(path, "wb") as f:
        f.write(HEADER.pack(MAGIC, VERSION, 0, n, 0))
        f.write(directory)
        pos = HEADER.size + len(directory)
        f.write(b"\x00" * _pad(pos))
        pos += _pad(pos)
        for p in payloads:
            f.write(p)
            pos += len(p)
            f.write(b"\x00" * _pad(len(p)))
            pos += _pad(len(p))
        f.flush()
        os.fsync(f.fileno())
    return {
        "n": n,
        "payload_bytes": sum(len(p) for p in payloads),
        "artifact_bytes": pos,
    }


class MappedCorpus:
    """A frozen epoch corpus served straight off its mmap.

    Construction validates structure only (O(n) directory scan, no
    payload reads); ``bitmap(i)`` lazily wraps slice *i* as a memoized
    :class:`ImmutableRoaringBitmap` whose container payloads are
    zero-copy views the OS pages in on demand. The mapped bitmaps carry
    ``("static", id)`` fingerprints, so ``packed_for``/``PACK_CACHE``
    admit them like any other operand — the warm-restart path packs
    device payloads directly from the map."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            self._mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
        buf = memoryview(self._mm)
        if len(buf) < HEADER.size:
            raise InvalidRoaringFormat("truncated corpus header")
        magic, version, flags, n, _reserved = HEADER.unpack_from(buf, 0)
        if magic != MAGIC:
            raise InvalidRoaringFormat(f"bad corpus magic {magic!r}")
        if version != VERSION:
            raise InvalidRoaringFormat(f"unsupported corpus version {version}")
        if flags:
            raise InvalidRoaringFormat(f"unknown corpus flags {flags:#x}")
        end_dir = HEADER.size + DIRENT.size * n
        if end_dir > len(buf):
            raise InvalidRoaringFormat("truncated corpus directory")
        self._dir: List[tuple] = []
        for i in range(n):
            off, length = DIRENT.unpack_from(buf, HEADER.size + DIRENT.size * i)
            if off % ALIGN or off + length > len(buf) or off < end_dir:
                raise InvalidRoaringFormat(
                    f"corpus payload {i} out of bounds or unaligned"
                )
            self._dir.append((off, length))
        self._buf = buf
        self._cache: Dict[int, ImmutableRoaringBitmap] = {}
        self.artifact_bytes = len(buf)

    def __len__(self) -> int:
        return len(self._dir)

    def payload(self, i: int) -> memoryview:
        """Bitmap *i*'s portable-format bytes as a zero-copy view."""
        off, length = self._dir[i]
        return self._buf[off : off + length]

    def bitmap(self, i: int) -> ImmutableRoaringBitmap:
        bm = self._cache.get(i)
        if bm is None:
            off, _length = self._dir[i]
            # offset into the shared map (not the payload slice) keeps
            # every view anchored on one exported buffer
            bm = ImmutableRoaringBitmap(self._mm, offset=off)
            self._cache[i] = bm
        return bm

    def __getitem__(self, i: int) -> ImmutableRoaringBitmap:
        return self.bitmap(i)

    def bitmaps(self) -> List[ImmutableRoaringBitmap]:
        """All bitmaps, materialized (header parse only — payloads stay
        mapped). The warm-restart corpus handed to the epoch store."""
        return [self.bitmap(i) for i in range(len(self._dir))]

    def close(self) -> None:
        """Drop memoized views and close the map. Fails loudly
        (``BufferError``) while numpy views into the map are still
        alive elsewhere — a mapped corpus must outlive its consumers.
        The memoized bitmaps' container tables are reference cycles, so
        dropping the cache needs a collect before their exported
        buffers actually die; external holders still raise."""
        self._cache.clear()
        self._buf.release()
        try:
            self._mm.close()
        except BufferError:
            import gc

            gc.collect()
            self._mm.close()

    def __repr__(self):
        return (
            f"MappedCorpus(n={len(self._dir)}, "
            f"bytes={self.artifact_bytes}, path={self.path!r})"
        )
