"""Atomic epoch persistence: the durable half of the epoch store
(ISSUE 17 tentpole, leg 2).

A :class:`DurableStore` owns one on-disk root of frozen epoch
artifacts::

    <root>/epoch_00000042/
        corpus.rbd      the frozen mmap corpus (durable/format.py)
        lineage.json    epoch id + the lineage ledger tail at persist
        MANIFEST.json   schema + {bytes, sha256} per file — written LAST

**Atomicity** reuses observe/bundle.py's idiom, hardened for
durability: everything lands in a hidden ``.tmp-epoch_…`` sibling
first, data files are fsynced, the manifest is written last *inside*
the tmp dir, then one ``os.rename`` publishes the directory and the
parent dir is fsynced. A crash at ANY point leaves either the previous
complete epoch or a ``.tmp-`` orphan the next persist sweeps — never a
half-readable artifact (recovery additionally re-verifies the manifest
sha256s, so even a torn rename on a non-atomic filesystem degrades to
"skip this epoch, use its parent").

**Persistence is a priced decision** (``durable.persist`` — the epoch
authority's second engine pair, cost/epoch.py): :meth:`maybe_persist`
weighs persist-now (predicted snapshot wall from the artifact size
curve) against skip (published-but-unpersisted lineage priced at the
declared durability exchange rate), records the verdict, and joins a
taken persist's measured wall — drift/refit exactly like the flip side.

**Fault site** ``durable.persist`` fails CLOSED: a non-fatal failure
aborts the persist, the published epoch stays memory-only (the pending
gauge keeps counting, the ``epoch-persist-stall`` sentinel owns "behind
for too long"), and nothing on disk is disturbed. The fault point is
probed at every stage boundary, so one schedule can kill a subprocess
at any of the five crash points (fuzz family 31 drives exactly that).

**Snapshot consistency**: the corpus is serialized under a reader
ticket (:meth:`EpochStore.reader`) — the flip's drain stage waits out
reader pins before mutating, so a persist admitted under epoch N reads
exactly epoch N's bits from any thread. Disk I/O happens OUTSIDE the
ticket; only the in-memory serialize holds a pin.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time
import weakref
from typing import List, Optional

from ..cost import epoch as _epoch_cost
from ..observe import decisions as _decisions
from ..observe import outcomes as _outcomes
from ..observe import registry as _registry
from ..observe import timeline as _timeline
from ..observe.histogram import latency_histogram
from ..robust import errors as _rerrors
from ..robust import faults as _faults
from ..robust import ladder as _ladder
from ..serialization import serialize as _serialize
from . import format as _format

SCHEMA = "rb_tpu_durable/1"
MANIFEST_NAME = "MANIFEST.json"
CORPUS_NAME = "corpus.rbd"
LINEAGE_NAME = "lineage.json"

# the declared persist-stage label set (rb_tpu_durable_persist_stage_seconds):
# snapshot = serialize under a reader ticket + write + fsync the corpus,
# lineage = ledger tail write + fsync, manifest = sha256 index written
# last inside tmp, publish = atomic rename + parent fsync + old-epoch GC
PERSIST_STAGES = ("snapshot", "lineage", "manifest", "publish")
PERSIST_OUTCOMES = ("persisted", "skipped", "aborted")
DEFAULT_KEEP = 2

PERSIST_STAGE_SECONDS = latency_histogram(
    _registry.DURABLE_PERSIST_STAGE_SECONDS,
    "Durable persist stage walls (snapshot = corpus serialize + write + "
    "fsync, lineage = ledger write + fsync, manifest = sha256 index, "
    "publish = atomic rename + GC)",
    ("stage",),
)
_PERSIST_TOTAL = _registry.counter(
    _registry.DURABLE_PERSIST_TOTAL,
    "Epoch persists by outcome (persisted | skipped = priced skip "
    "verdict | aborted = fault, epoch stays memory-only)",
    ("outcome",),
)
_PERSIST_BYTES = _registry.counter(
    _registry.DURABLE_PERSIST_BYTES_TOTAL,
    "Artifact bytes written by completed persists (corpus + lineage + "
    "manifest)",
)
_EPOCH_GAUGE = _registry.gauge(
    _registry.DURABLE_EPOCH_COUNT,
    "Newest durably persisted epoch id (a gauge VALUE — epoch ids are "
    "unbounded and never metric label values); -1 until the first "
    "persist completes",
)
_ARTIFACT_GAUGE = _registry.gauge(
    _registry.DURABLE_ARTIFACT_BYTES,
    "Size of the newest complete epoch artifact on disk",
)
_PENDING_GAUGE = _registry.gauge(
    _registry.DURABLE_PENDING_COUNT,
    "Published epochs not yet durable (serving epoch minus persisted "
    "epoch) — the epoch-persist-stall sentinel's depth signal",
)
_WALL_GAUGE = _registry.gauge(
    _registry.DURABLE_PERSIST_WALL_SECONDS,
    "Wall seconds of the last completed persist",
)

# the most recently constructed durable store: the rb_top durable
# panel's and insights.durable()'s live source (a weakref — tests
# constructing many stores never leak them through this module)
_CURRENT: Optional["weakref.ref[DurableStore]"] = None


def current_store() -> Optional["DurableStore"]:
    """The live process DurableStore (newest constructed), or None."""
    ref = _CURRENT
    return ref() if ref is not None else None


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def epoch_dir_name(epoch: int) -> str:
    return f"epoch_{int(epoch):08d}"


class DurableStore:
    """One on-disk root of frozen epoch artifacts + the persist policy."""

    def __init__(self, root: str, keep: int = DEFAULT_KEEP):
        global _CURRENT
        self.root = root
        self.keep = max(1, int(keep))
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()  # leaf: guards the fields below only
        self._last_epoch: Optional[int] = None  # guarded-by: self._lock
        self._last_dir: Optional[str] = None  # guarded-by: self._lock
        self._last_wall_s: Optional[float] = None  # guarded-by: self._lock
        self._last_bytes = 0  # guarded-by: self._lock
        self._persists = 0  # guarded-by: self._lock
        _CURRENT = weakref.ref(self)

    # -- the atomic persist --------------------------------------------------

    def persist(self, store, reason: str = "flip") -> dict:
        """Persist ``store``'s current epoch (corpus + lineage tail)
        atomically. Returns the persist record; ``outcome`` is one of
        :data:`PERSIST_OUTCOMES` (never ``skipped`` here — pricing lives
        in :meth:`maybe_persist`). Safe from any thread: the snapshot is
        serialized under a reader ticket, so it can never tear against a
        concurrent flip."""
        t0 = time.perf_counter()
        try:
            # crash point 1: before anything touches disk
            _faults.fault_point("durable.persist")
            with _timeline.tspan("durable.persist", "durable", reason=reason):
                with _timeline.stage(
                    PERSIST_STAGE_SECONDS, "snapshot", "durable.snapshot",
                    cat="durable",
                ):
                    with store.reader():
                        epoch = store.current()
                        blobs: List[bytes] = [
                            bm.serialize()
                            if isinstance(bm, _format.ImmutableRoaringBitmap)
                            else _serialize(bm)
                            for bm in store.corpus
                        ]
                        lineage = store.lineage()
                    final = os.path.join(self.root, epoch_dir_name(epoch))
                    tmp = os.path.join(
                        self.root, f".tmp-{epoch_dir_name(epoch)}"
                    )
                    self._sweep_tmp()
                    if os.path.isdir(final):
                        # this epoch is already durable (idempotent
                        # re-persist, e.g. a retried schedule)
                        _PERSIST_TOTAL.inc(1, ("persisted",))
                        return {
                            "outcome": "persisted", "epoch": epoch,
                            "dir": final, "fresh": False,
                        }
                    os.makedirs(tmp)
                    stats = _format.write_corpus(
                        os.path.join(tmp, CORPUS_NAME), blobs
                    )
                # crash point 2: corpus written, no lineage/manifest yet
                _faults.fault_point("durable.persist")
                with _timeline.stage(
                    PERSIST_STAGE_SECONDS, "lineage", "durable.lineage",
                    cat="durable",
                ):
                    lineage_path = os.path.join(tmp, LINEAGE_NAME)
                    with open(lineage_path, "w") as f:
                        json.dump(
                            {
                                "schema": SCHEMA,
                                "epoch": epoch,
                                "reason": reason,
                                "ts": time.time(),
                                "lineage": lineage,
                            },
                            f,
                        )
                        f.flush()
                        os.fsync(f.fileno())
                # crash point 3: data files down, manifest missing (torn)
                _faults.fault_point("durable.persist")
                with _timeline.stage(
                    PERSIST_STAGE_SECONDS, "manifest", "durable.manifest",
                    cat="durable",
                ):
                    files = {}
                    for fname in (CORPUS_NAME, LINEAGE_NAME):
                        p = os.path.join(tmp, fname)
                        files[fname] = {
                            "bytes": os.path.getsize(p),
                            "sha256": _sha256_file(p),
                        }
                    manifest = {
                        "schema": SCHEMA,
                        "epoch": epoch,
                        "reason": reason,
                        "ts": time.time(),
                        "n_bitmaps": stats["n"],
                        "files": files,
                    }
                    with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
                        json.dump(manifest, f, indent=1)
                        f.flush()
                        os.fsync(f.fileno())
                # crash point 4: manifest complete but still in .tmp-
                _faults.fault_point("durable.persist")
                with _timeline.stage(
                    PERSIST_STAGE_SECONDS, "publish", "durable.publish",
                    cat="durable", epoch=epoch,
                ):
                    os.rename(tmp, final)
                    _fsync_dir(self.root)
                    self._gc(keep_epoch=epoch)
                # crash point 5: published — recovery MUST find this epoch
                _faults.fault_point("durable.persist")
        except Exception as e:
            if _rerrors.classify(e) == _rerrors.FATAL:
                raise
            # fail CLOSED: the published epoch stays memory-only, disk
            # keeps the previous complete artifact, the pending gauge
            # keeps counting and the epoch-persist-stall sentinel owns
            # the "behind for too long" signal
            _ladder.LADDER.note_degrade(
                "durable.persist", "persist", "memory-only", e
            )
            _PERSIST_TOTAL.inc(1, ("aborted",))
            _decisions.record_decision(
                "durable.persist", "aborted", reason=reason,
                error=type(e).__name__,
            )
            return {"outcome": "aborted", "reason": reason,
                    "error": type(e).__name__}
        wall_s = round(time.perf_counter() - t0, 6)
        artifact_bytes = sum(f["bytes"] for f in files.values())
        artifact_bytes += os.path.getsize(os.path.join(final, MANIFEST_NAME))
        with self._lock:
            self._last_epoch = epoch
            self._last_dir = final
            self._last_wall_s = wall_s
            self._last_bytes = artifact_bytes
            self._persists += 1
        _PERSIST_TOTAL.inc(1, ("persisted",))
        _PERSIST_BYTES.inc(artifact_bytes)
        _EPOCH_GAUGE.set(epoch)
        _ARTIFACT_GAUGE.set(artifact_bytes)
        _WALL_GAUGE.set(wall_s)
        _PENDING_GAUGE.set(max(0, store.current() - epoch))
        # from now on evictions of map-covered working sets can demote
        # to the mapped rung instead of discarding (priced by the
        # residency authority's readmit curve)
        _install_demotion_probe(self)
        return {
            "outcome": "persisted",
            "epoch": epoch,
            "dir": final,
            "fresh": True,
            "artifact_bytes": artifact_bytes,
            "n_bitmaps": stats["n"],
            "wall_s": wall_s,
        }

    # -- the priced verdict --------------------------------------------------

    def maybe_persist(self, store, reason: str = "flip") -> dict:
        """The persist-now-vs-skip verdict, priced by the epoch
        authority's persist curves: persist when the unpersisted
        lineage's exposure (priced at the declared durability exchange
        rate) outweighs the predicted snapshot wall. A taken persist's
        decision is joined with its measured wall; a skip is
        decision-logged but not joined (nothing executes)."""
        epoch = store.current()
        pending = self.pending_epochs(store)
        if pending <= 0:
            return {"outcome": "noop", "epoch": epoch}
        est_kb = self._estimate_kb(store)
        predicted_persist = _epoch_cost.MODEL.predict_persist_us(est_kb)
        skip_cost = _epoch_cost.MODEL.exposure_cost_us(pending)
        verdict = "persist" if skip_cost >= predicted_persist else "skip"
        seq = _decisions.record_decision(
            "durable.persist", verdict,
            outcome=(verdict == "persist" and _outcomes.enabled()),
            est_us={"persist": predicted_persist, "skip": skip_cost},
            pending=pending, artifact_kb=round(est_kb, 3), epoch=epoch,
            reason=reason,
        )
        if verdict == "skip":
            _PERSIST_TOTAL.inc(1, ("skipped",))
            _PENDING_GAUGE.set(pending)
            return {
                "outcome": "skipped", "epoch": epoch, "pending": pending,
            }
        t0 = time.perf_counter()
        record = self.persist(store, reason=reason)
        if record["outcome"] == "persisted" and seq is not None:
            _outcomes.resolve(
                seq, "durable.persist", time.perf_counter() - t0,
                engine="persist",
            )
        return record

    def on_flip(self, store, flip_record: dict) -> dict:
        """The epoch store's post-publish hook (EpochStore calls this
        after every published flip when attached): refresh the pending
        gauge and run the priced persist verdict."""
        _PENDING_GAUGE.set(self.pending_epochs(store))
        return self.maybe_persist(store, reason="flip")

    # -- views ---------------------------------------------------------------

    def pending_epochs(self, store) -> int:
        """Published epochs not yet durable (0 = fully caught up).
        Before the first persist the whole history is exposed, including
        the initial epoch-0 corpus."""
        with self._lock:
            last = self._last_epoch
        return store.current() - (last if last is not None else -1)

    def stats(self) -> dict:
        with self._lock:
            return {
                "root": self.root,
                "keep": self.keep,
                "persisted_epoch": self._last_epoch,
                "dir": self._last_dir,
                "artifact_bytes": self._last_bytes,
                "last_wall_s": self._last_wall_s,
                "persists": self._persists,
            }

    # -- internals -----------------------------------------------------------

    def _estimate_kb(self, store) -> float:
        """Predicted artifact size for the pricing input: the last
        measured artifact when one exists (the corpus drifts slowly
        between persists), else the corpus's own serialized-size sum."""
        with self._lock:
            if self._last_bytes:
                return self._last_bytes / 1024.0
        total = 0
        for bm in store.corpus:
            total += bm.serialized_size_in_bytes()
        return total / 1024.0

    def _sweep_tmp(self) -> None:
        """Remove ``.tmp-`` orphans a crashed persist left behind."""
        for name in os.listdir(self.root):
            if name.startswith(".tmp-"):
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)

    def _gc(self, keep_epoch: int) -> None:
        """Prune complete epoch dirs beyond ``keep`` newest (never the
        one just published)."""
        epochs = []
        for name in os.listdir(self.root):
            if name.startswith("epoch_"):
                try:
                    epochs.append((int(name[len("epoch_"):]), name))
                except ValueError:
                    continue
        epochs.sort(reverse=True)
        for num, name in epochs[self.keep:]:
            if num != keep_epoch:
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)


def _install_demotion_probe(dstore: "DurableStore") -> None:
    """Point the pack cache's eviction policy at this store: once an
    epoch artifact is on disk, evicting a working set demotes it to the
    mapped rung (re-admittable from the map at the readmit curve's
    price) instead of discarding it outright."""
    from ..parallel import store as _pstore

    ref = weakref.ref(dstore)

    def probe(kind: str) -> bool:
        d = ref()
        if d is None:
            return False
        with d._lock:
            return d._last_dir is not None

    _pstore.set_demotion_probe(probe)
