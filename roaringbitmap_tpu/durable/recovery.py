"""Warm restart: discover, verify, and re-serve the newest durable
epoch (ISSUE 17 tentpole, leg 3).

``recover(root)`` walks the epoch dirs newest-first, re-verifies each
manifest (schema + byte sizes + sha256 — a torn artifact is counted,
skipped, and the ``recovery-manifest-torn`` sentinel raises it), maps
the first complete corpus, and rehydrates the lineage ledger. The
returned :class:`Recovery` serves reads immediately off the map (header
parse only — no deserialize step, payloads stay OS-paged), resumes an
:class:`~..serve.epochs.EpochStore` at the persisted epoch, and
:meth:`Recovery.readmit` lazily re-warms PACK_CACHE working sets
straight from the map — each readmit is a priced ``durable.readmit``
decision joined with its measured wall, which is exactly the traffic
that teaches the residency authority's mapped-rung ``readmit_s`` curve.

The recovery contract fuzz family 31 pins: a process killed at ANY
persist/flip stage recovers to the last epoch whose persist
*published* (the ``os.rename``), bit-exactly — never a torn or
half-written state, never silently older than a completed persist.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import List, Optional

from ..observe import decisions as _decisions
from ..observe import outcomes as _outcomes
from ..observe import registry as _registry
from . import format as _format
from .store import (
    CORPUS_NAME,
    LINEAGE_NAME,
    MANIFEST_NAME,
    SCHEMA,
    _EPOCH_GAUGE,
)

_RECOVERY_TOTAL = _registry.counter(
    _registry.DURABLE_RECOVERY_TOTAL,
    "Recovery attempts by outcome (recovered | torn = a manifest failed "
    "verification and its epoch was skipped | empty = no complete "
    "artifact found)",
    ("outcome",),
)

# the last recovery's provenance (for the rb_top durable panel and the
# sidecar block): set by recover(), None until a recovery ran in this
# process
LAST: Optional[dict] = None


def verify_manifest(epoch_dir: str) -> dict:
    """Re-verify one epoch dir's manifest: schema, file presence, byte
    sizes, sha256 digests. Returns the manifest; raises ``ValueError``
    on any mismatch (the caller treats that epoch as torn)."""
    path = os.path.join(epoch_dir, MANIFEST_NAME)
    with open(path) as f:
        manifest = json.load(f)
    if manifest.get("schema") != SCHEMA:
        raise ValueError(
            f"unexpected durable schema {manifest.get('schema')!r}"
        )
    files = manifest.get("files")
    if not isinstance(files, dict) or set(files) != {
        CORPUS_NAME, LINEAGE_NAME,
    }:
        raise ValueError("manifest file index incomplete")
    for fname, meta in files.items():
        p = os.path.join(epoch_dir, fname)
        if not os.path.isfile(p):
            raise ValueError(f"durable file {fname} missing")
        if os.path.getsize(p) != meta.get("bytes"):
            raise ValueError(f"durable file {fname}: size mismatch")
        h = hashlib.sha256()
        with open(p, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        if h.hexdigest() != meta.get("sha256"):
            raise ValueError(f"durable file {fname}: sha256 mismatch")
    return manifest


def _epoch_dirs(root: str) -> List[str]:
    """Complete-looking epoch dirs, newest first (``.tmp-`` orphans are
    by construction never candidates)."""
    out = []
    for name in os.listdir(root):
        if name.startswith("epoch_") and os.path.isdir(
            os.path.join(root, name)
        ):
            try:
                out.append((int(name[len("epoch_"):]), name))
            except ValueError:
                continue
    out.sort(reverse=True)
    return [os.path.join(root, name) for _num, name in out]


class Recovery:
    """One verified durable epoch, mapped and ready to serve."""

    def __init__(self, epoch_dir: str, manifest: dict, torn_skipped: int,
                 wall_s: float):
        self.dir = epoch_dir
        self.epoch = int(manifest["epoch"])
        self.corpus = _format.MappedCorpus(
            os.path.join(epoch_dir, CORPUS_NAME)
        )
        with open(os.path.join(epoch_dir, LINEAGE_NAME)) as f:
            self.lineage: List[dict] = json.load(f).get("lineage") or []
        self.provenance = {
            "dir": epoch_dir,
            "epoch": self.epoch,
            "n_bitmaps": len(self.corpus),
            "artifact_bytes": self.corpus.artifact_bytes,
            "torn_skipped": torn_skipped,
            "wall_s": round(wall_s, 6),
            "persisted_ts": manifest.get("ts"),
        }

    def bitmap(self, i: int):
        return self.corpus.bitmap(i)

    def resume_store(self, **kwargs):
        """An EpochStore resumed at the persisted epoch: the corpus is
        deep-copied to mutable bitmaps (ingest continues mutating in
        place; the mapped originals stay frozen for the read path and
        the pack cache), and the lineage ledger is rehydrated so the
        replay oracle and the observatory see an unbroken history."""
        from ..serve.epochs import EpochStore

        store = EpochStore(
            [self.corpus.bitmap(i).to_mutable()
             for i in range(len(self.corpus))],
            **kwargs,
        )
        store.restore(self.epoch, self.lineage)
        return store

    def readmit(self, working_sets=None) -> dict:
        """Re-warm PACK_CACHE working sets straight from the map (the
        lazy half of the warm restart): each set is packed from the
        mapped bitmaps' zero-copy container views — no deserialize, no
        mutable copies. Every readmit is a priced ``durable.readmit``
        decision joined with its measured wall; those joins teach the
        residency authority's mapped-rung ``readmit_s`` curve."""
        from ..cost import residency as _residency
        from ..parallel import store as _pstore

        if working_sets is None:
            working_sets = [tuple(range(len(self.corpus)))]
        readmitted = 0
        wall_total = 0.0
        for ws in working_sets:
            bitmaps = [self.corpus.bitmap(i) for i in ws]
            est_s = _residency.MODEL.readmit_estimate("agg")
            inputs = {"kind": "agg", "bitmaps": len(ws)}
            if est_s:
                inputs["est_us"] = {"readmit": round(est_s * 1e6, 1)}
            seq = _decisions.record_decision(
                "durable.readmit", "readmit",
                outcome=_outcomes.enabled(), **inputs,
            )
            t0 = time.perf_counter()
            _pstore.packed_for(bitmaps)
            wall = time.perf_counter() - t0
            wall_total += wall
            readmitted += 1
            if seq is not None:
                _outcomes.resolve(
                    seq, "durable.readmit", wall, engine="readmit"
                )
        # fold the fresh joins into the readmit curve right away: a
        # restart is exactly when the curve should learn fastest
        _residency.MODEL.refit_from_outcomes()
        return {
            "working_sets": readmitted,
            "wall_s": round(wall_total, 6),
        }

    def close(self) -> None:
        self.corpus.close()


def recover(root: str) -> Optional[Recovery]:
    """Discover and map the newest complete epoch under ``root``.
    Returns None (outcome ``empty``) when no epoch dir verifies; torn
    candidates are counted, skipped, and surfaced through
    ``rb_tpu_durable_recovery_total{outcome="torn"}`` (the
    ``recovery-manifest-torn`` sentinel's signal)."""
    global LAST
    t0 = time.perf_counter()
    torn = 0
    if os.path.isdir(root):
        for epoch_dir in _epoch_dirs(root):
            try:
                manifest = verify_manifest(epoch_dir)
            except (OSError, ValueError, KeyError) as e:
                # torn: crashed mid-persist on a non-atomic filesystem,
                # truncated by the crash, or bit-rotted — fall back to
                # its parent epoch rather than serving corrupt bits
                torn += 1
                _RECOVERY_TOTAL.inc(1, ("torn",))
                _decisions.record_decision(
                    "durable.recover", "torn", dir=epoch_dir,
                    error=type(e).__name__,
                )
                continue
            rec = Recovery(
                epoch_dir, manifest, torn, time.perf_counter() - t0
            )
            _RECOVERY_TOTAL.inc(1, ("recovered",))
            _EPOCH_GAUGE.set(rec.epoch)
            LAST = dict(rec.provenance)
            return rec
    _RECOVERY_TOTAL.inc(1, ("empty",))
    LAST = {"dir": None, "epoch": None, "torn_skipped": torn,
            "wall_s": round(time.perf_counter() - t0, 6)}
    return None
