"""Durable epochs (ISSUE 17): the on-disk half of the epoch store.

* :mod:`.format` — the frozen mmap corpus artifact (zero-copy portable
  payloads + key directory; serves reads with no parse step).
* :mod:`.store` — atomic priced persistence of published epochs
  (tmp-dir + fsync + rename, manifest-last with sha256; fault site
  ``durable.persist``; the ``durable.persist`` decision under the epoch
  cost authority).
* :mod:`.recovery` — crash recovery and warm restart: newest complete
  manifest wins, torn artifacts are skipped and surfaced, PACK_CACHE
  working sets re-admit lazily from the map.
"""

from .format import MappedCorpus, write_corpus
from .recovery import Recovery, recover
from .store import (
    DEFAULT_KEEP,
    PERSIST_OUTCOMES,
    PERSIST_STAGES,
    SCHEMA,
    DurableStore,
    current_store,
)

__all__ = [
    "DEFAULT_KEEP",
    "DurableStore",
    "MappedCorpus",
    "PERSIST_OUTCOMES",
    "PERSIST_STAGES",
    "Recovery",
    "SCHEMA",
    "current_store",
    "recover",
    "write_corpus",
]
