"""L7' introspection: container statistics and writer recommendation
(reference ``insights/`` package: BitmapAnalyser.java:15, BitmapStatistics,
NaiveWriterRecommender.java:14)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, List

from .models.container import ArrayContainer, BitmapContainer, RunContainer
from .models.roaring import RoaringBitmap


@dataclass
class ArrayContainersStats:
    containers_count: int = 0
    cardinality_sum: int = 0

    def average_cardinality(self) -> float:
        return (
            self.cardinality_sum / self.containers_count
            if self.containers_count
            else float("nan")
        )


@dataclass
class BitmapStatistics:
    """Aggregated container statistics (insights/BitmapStatistics.java)."""

    array_stats: ArrayContainersStats = field(default_factory=ArrayContainersStats)
    bitmap_containers_count: int = 0
    run_containers_count: int = 0
    bitmaps_count: int = 0

    def container_count(self) -> int:
        return (
            self.array_stats.containers_count
            + self.bitmap_containers_count
            + self.run_containers_count
        )

    def container_fraction(self, count: int) -> float:
        total = self.container_count()
        return count / total if total else float("nan")


def analyse(bitmaps: Iterable[RoaringBitmap]) -> BitmapStatistics:
    """BitmapAnalyser.analyse (insights/BitmapAnalyser.java:15-35)."""
    stats = BitmapStatistics()
    for bm in bitmaps if not isinstance(bitmaps, RoaringBitmap) else [bitmaps]:
        stats.bitmaps_count += 1
        for c in bm.high_low_container.containers:
            if isinstance(c, RunContainer):
                stats.run_containers_count += 1
            elif isinstance(c, BitmapContainer):
                stats.bitmap_containers_count += 1
            else:
                stats.array_stats.containers_count += 1
                stats.array_stats.cardinality_sum += c.cardinality
    return stats


def dispatch_counters() -> dict:
    """Which engine/layout/backend served device aggregations so far
    (VERDICT r2 #8/#9: the reference's insights module is the analogue to
    extend with execution observability).

    Since ISSUE 1 this is a thin facade over the ``observe`` registry (the
    module counters below are registry-backed views), returning exactly the
    pre-migration shapes so no caller breaks; ``metrics_snapshot()`` exposes
    the full labeled registry for new code.

    Returns ``{"kernel": {...}, "layout": {...}, "probes": {...}}``:
      * kernel — ("wide"|"grouped", "pallas"|"xla") call counts from the
        best_* dispatchers (ops/pallas_kernels.py);
      * layout — prepare_reduce's padded vs segmented-scan choices
        (parallel/store.py);
      * probes — per-(kind, op, shape, backend) Pallas lowering probe
        outcomes (True = kernel serves this shape, False = fell back).
    """
    from .ops import pallas_kernels as pk
    from .parallel import batch, store

    return {
        "kernel": {f"{k[0]}/{k[1]}": v for k, v in pk.DISPATCH_COUNTS.items()},
        "layout": dict(store.LAYOUT_COUNTS),
        "transfer_bytes": dict(store.TRANSFER_BYTES),
        "pairwise": dict(batch.PAIRWISE_COUNTS),
        "probes": {
            f"{k[0]}/{k[1]}/{'x'.join(map(str, k[2]))}/{k[3]}": v
            for k, v in pk._PROBED.items()
        },
        "native": native_backend(),
    }


def native_backend() -> str:
    """Which host-kernel tier is serving the CPU fast path:
    'ext' (CPython C extension), 'ctypes', or 'numpy'."""
    from . import native

    return native.backend_tier()


def query_counters() -> dict:
    """Query-engine execution observability (ISSUE 2): planned steps by
    chosen engine and result-cache events, as plain str->int dicts (the
    dispatch_counters() shape convention — kept additive, not merged into
    that facade, whose key set is a frozen legacy contract).

    Returns ``{"plan": {engine: steps}, "cache": {event: count}}``; events
    are hit/miss/store/evict (query/cache.py)."""
    from . import observe

    plan = observe.REGISTRY.get(observe.QUERY_PLAN_TOTAL)
    cache = observe.REGISTRY.get(observe.QUERY_CACHE_TOTAL)
    return {
        "plan": {lv[0]: v for lv, v in plan.series().items()} if plan else {},
        "cache": {lv[0]: v for lv, v in cache.series().items()} if cache else {},
    }


def columnar_counters() -> dict:
    """Columnar pairwise-engine observability (ISSUE 5/10): batched
    container-pairs by ``op/class`` — the 9 ``(array|bitmap|run)²``
    classes for pairwise ops, the device-tier execution classes
    (``device_pair``/``device_gather``), plus ``fold_<op>/rows`` for the
    N-way CPU folds — and the cutoff-model routing verdicts by tier, as
    plain str->int dicts (the query_counters() shape convention). Backed
    by ``rb_tpu_columnar_batch_total`` / ``rb_tpu_columnar_route_total``."""
    from . import observe

    m = observe.REGISTRY.get(observe.COLUMNAR_BATCH_TOTAL)
    r = observe.REGISTRY.get(observe.COLUMNAR_ROUTE_TOTAL)
    return {
        "batch": {f"{lv[0]}/{lv[1]}": v for lv, v in m.series().items()}
        if m
        else {},
        "route": {lv[0]: v for lv, v in r.series().items()} if r else {},
    }


def columnar_costmodel() -> dict:
    """The columnar cutoff model's current state (ISSUE 10): calibration
    mode, backend, per-engine cost coefficients, and the measured fold
    gate — the inputs behind every ``columnar.cutoff`` decision entry."""
    from . import columnar

    d = columnar.MODEL.to_dict()
    d["fold_gate_rows"] = columnar.MODEL.fold_gate_rows()
    return d


def pack_cache_counters() -> dict:
    """Resident pack cache observability (ISSUE 4): per-kind hit/miss/
    delta-row/evicted-byte counters plus the resident-bytes gauge, as plain
    str->int dicts (the query_counters() shape convention). Kinds are the
    routed consumers: agg | bsi | bsi64 | andnot | threshold | colrows
    (the columnar device tier's per-bitmap flat rows, ISSUE 10)."""
    from . import observe

    def _series(name):
        m = observe.REGISTRY.get(name)
        return {lv[0]: v for lv, v in m.series().items()} if m else {}

    return {
        "hits": _series(observe.PACK_CACHE_HITS_TOTAL),
        "misses": _series(observe.PACK_CACHE_MISSES_TOTAL),
        "delta_rows": _series(observe.PACK_CACHE_DELTA_ROWS_TOTAL),
        "evicted_bytes": _series(observe.PACK_CACHE_EVICTED_BYTES_TOTAL),
        "resident_bytes": _series(observe.PACK_CACHE_RESIDENT_BYTES),
    }


def robust_counters() -> dict:
    """Fault model & degradation ladder observability (ISSUE 7):
    degradation edges (``site->from->to``), breaker transitions
    (``site/tier/state``), retry outcomes, deadline outcomes, and injected
    faults by site, as plain str->int dicts (the query_counters() shape
    convention)."""
    from . import observe

    def _joined(name):
        m = observe.REGISTRY.get(name)
        return {"/".join(lv): v for lv, v in m.series().items()} if m else {}

    return {
        "degrade": _joined(observe.DEGRADE_TOTAL),
        "breaker": _joined(observe.BREAKER_TRANSITIONS_TOTAL),
        "retry": _joined(observe.RETRY_TOTAL),
        "deadline": _joined(observe.DEADLINE_TOTAL),
        "faults": _joined(observe.FAULT_INJECTED_TOTAL),
    }


def decisions(n: int = None) -> list:
    """Decision provenance (ISSUE 9): the newest ``n`` entries of the
    bounded decision log (all retained when None), oldest first. Each
    entry names the deciding site, the decision, the inputs that drove
    it, and the query trace id it was made under — "why was this slow"
    as one artifact (planner engine choices, dispatch start tiers, ladder
    degrades/breaker flips, pack-cache admission/eviction/spill, columnar
    cutoff verdicts)."""
    from . import observe

    return observe.decisions.decisions(n)


def outcomes(n: int = None) -> list:
    """Decision-outcome joins (ISSUE 11): the newest ``n`` entries of the
    bounded ledger (all retained when None), oldest first. Each entry is
    one verdict scored against reality: the deciding site, the engine
    that actually ran, the measured wall, the prediction it was made
    under (``predicted_us`` / ``inputs.est_card``), the
    predicted/measured error ratio, and the regret seconds — wall lost
    to the wrong verdict, either priced from the not-taken alternatives'
    calibrated curves or measured outright (evict-then-repack, wasted
    ladder attempts)."""
    from . import observe

    return observe.outcomes.tail(n)


def regret_summary() -> dict:
    """Per-site regret rollup (ISSUE 11): join counts, total regret
    seconds, geometric-mean error ratio, and the worst recent decision
    with its inputs — plus the per-coefficient calibration-drift gauges
    and the cost models' provenance. ``scripts/rb_top.py`` renders this
    as the regret panel."""
    from . import columnar, observe
    # the query package re-exports plan() the function; the module itself
    # is reachable via the from-import form (sys.modules resolution, the
    # observe.histogram import-note pattern)
    from .query.plan import CARD_MODEL

    return {
        "sites": observe.outcomes.summary(),
        "drift": observe.outcomes.drift(),
        "pending": observe.outcomes.LEDGER.pending_count(),
        "provenance": {
            "columnar": columnar.MODEL.provenance if columnar.MODEL.calibrated
            else "default-gate",
            "planner_cardinality": CARD_MODEL.provenance,
            "fusion_batch": _fusion_model_provenance(),
        },
    }


def _fusion_model_provenance() -> str:
    from .cost import fusion as _fusion_cost

    return _fusion_cost.MODEL.provenance


def health() -> dict:
    """Health-sentinel snapshot (ISSUE 12): the process status
    (green/yellow/red), every rule's post-hysteresis level with its
    current value and committed thresholds, and the recent actuation log
    (auto-refits with per-authority provenance, alerts, flight bundles).
    ``scripts/rb_top.py`` renders this as the health panel."""
    from . import observe

    s = observe.sentinel.SENTINEL
    level, name = s.status()
    return {
        "status": level,
        "status_name": name,
        "rules": s.rule_states(),
        "actuations": s.actuations(8),
        "sentinel_running": observe.sentinel.running(),
    }


def fusion_counters() -> dict:
    """Cross-query fusion rollup (ISSUE 13): window volume by outcome,
    query volume, step fates (executed / merged / deduped), the derived
    window occupancy and shared-subexpression hit ratio, the in-flight
    dedup table's live stats, and the current queue depth — the rb_top
    fusion panel's data, derived from the registry plus the live
    in-flight table (batch-regret rows ride the regret panel under the
    ``fusion.batch`` site)."""
    from . import observe
    from .observe import export as _export
    from .query import inflight as _inflight

    block = _export._fusion_block(observe.REGISTRY.snapshot())
    block["inflight_live"] = _inflight.TABLE.stats()
    # the live window auto-tune state (ISSUE 19): effective vs base vs
    # floor — effective < base means the serving-p99-pressure actuation
    # has shrunk the window and not yet regrown it
    from .query import fusion as _q_fusion

    block["window_state"] = {
        "effective": _q_fusion.config.window,
        "base": _q_fusion.config.window_base,
        "min": _q_fusion.config.window_min,
        "hedge_enabled": _q_fusion.config.hedge,
    }
    return block


def serving() -> dict:
    """Serving-tier rollup (ISSUE 14): per-tenant rolling QPS, latency
    p50/p99 per phase, admission verdict volume, queue/in-flight depth,
    saturation, and PACK_CACHE byte shares — the rb_top serving panel's
    data (registry-derived, plus the live admission controller's
    stats)."""
    from . import observe
    from .observe import export as _export
    from .serve import admission as _admission

    block = _export._serving_block(
        observe.REGISTRY.snapshot(), observe.REGISTRY
    )
    block["admission_live"] = _admission.CONTROLLER.stats()
    return block


def epochs() -> dict:
    """Epoch-ledger rollup (ISSUE 15): the current epoch, live
    mutation-log depth, flip volume by outcome, per-tenant freshness
    p50/p99 (ingest->queryable lag), flip stage decomposition — all
    registry-derived — plus the live EpochStore's lineage ledger tail
    and stats (process-local, like the admission controller's live
    stats). The rb_top epoch panel renders exactly this, and a red
    episode's flight bundle carries it via :func:`observatory`."""
    from . import observe
    from .observe import export as _export
    from .serve import epochs as _epochs

    block = _export._epochs_block(
        observe.REGISTRY.snapshot(), observe.REGISTRY
    )
    store = _epochs.current_store()
    if store is not None:
        block["store_live"] = store.stats()
        block["lineage"] = store.lineage(16)
    else:
        block["store_live"] = None
        block["lineage"] = []
    return block


def structure() -> dict:
    """Structure-observatory rollup (ISSUE 16): the container-format
    census, actual/optimal serialized bytes + drift ratio, run
    fragmentation p99, epoch-delta accretion depth, maintenance-pass
    volume — all registry-derived — plus the live ledger's stats, the
    last taken pass's record, and the compaction authority's provenance
    (process-local, like the admission controller's live stats). The
    rb_top structure panel renders exactly this."""
    from . import observe
    from .cost import compaction as _compaction_cost
    from .observe import export as _export
    from .observe import structure as _structure
    from .serve import maintain as _maintain

    block = _export._structure_block(observe.REGISTRY.snapshot())
    block["ledger_live"] = (
        _structure.LEDGER.stats() if _structure.LEDGER.watched() else None
    )
    block["last_pass"] = _maintain.last_pass() or None
    block["authority"] = _compaction_cost.MODEL.provenance
    return block


def durable() -> dict:
    """Durable-epoch rollup (ISSUE 17): persisted vs serving epoch,
    artifact bytes, persist/recovery/demotion volume — all
    registry-derived — plus the live :class:`durable.DurableStore`'s
    stats and the last recovery's provenance (which directory won, how
    many torn artifacts were skipped). Process-local detail rides here
    and in flight bundles, never the registry. The rb_top durable panel
    renders exactly this."""
    from . import observe
    from .durable import recovery as _recovery
    from .durable import store as _dstore
    from .observe import export as _export

    block = _export._durable_block(observe.REGISTRY.snapshot())
    live = _dstore.current_store()
    block["store_live"] = live.stats() if live is not None else None
    block["recovery_last"] = _recovery.LAST
    return block


def cost_authorities() -> dict:
    """The unified cost facade's view (ISSUE 12): every pricing
    authority's curves, provenance, and live drift — ROADMAP item 4's
    "one self-tuning cost brain" as a read API."""
    from . import cost

    return cost.calibration_state()


def observatory() -> dict:
    """Resource-observatory snapshot (ISSUE 9): lock-wait quantiles over
    the framework locks (empty until ``observe.lockstats.install()``),
    per-fn jit compile/retrace counts, the device-memory reconciliation
    report (computed fresh), current breaker states, pack-cache stats,
    the decision-log tail, and — since ISSUE 12 — the health sentinel's
    status/rules/actuations. ``scripts/rb_top.py`` renders exactly
    this."""
    from . import observe
    from .observe import lockstats
    from .parallel import store
    from .robust import ladder

    return {
        "locks": lockstats.wait_stats(),
        "lock_timing": lockstats.timing_enabled(),
        "compile": observe.compilewatch.compile_counts(),
        "hbm": store.hbm_reconciliation(),
        "breakers": ladder.LADDER.states(),
        "pack_cache": store.PACK_CACHE.stats(),
        "decisions": decisions(32),
        "regret": regret_summary(),
        "health": health(),
        # serving tier (ISSUE 14): the per-tenant panel rides the
        # observatory view, so a red episode's flight bundle
        # (observatory.json) carries the serving state that triggered it
        "serving": serving(),
        # epoch ledger (ISSUE 15): current epoch + mutlog depth +
        # freshness + lineage tail, so a red episode's bundle carries the
        # epoch panel (which snapshot was serving, and how stale)
        "epochs": epochs(),
        # structure observatory (ISSUE 16): format census + drift +
        # maintenance-pass state, so a red episode's bundle carries the
        # corpus shape that triggered the structure-drift rule
        "structure": structure(),
        # durable epochs (ISSUE 17): persisted vs serving epoch, artifact
        # bytes, recovery provenance — so a red episode's bundle carries
        # which frozen snapshot (if any) a restart would recover to
        "durable": durable(),
    }


def metrics_snapshot() -> dict:
    """The full labeled registry snapshot (every rb_tpu_* metric incl.
    histograms) — the machine-readable superset of dispatch_counters();
    see ``observe.export`` for JSONL/Prometheus renderings."""
    from . import observe

    return observe.snapshot()


def reset_dispatch_counters() -> None:
    # NOTE: the probe ledgers (pk._PROBED and the registry probe counter)
    # deliberately survive a reset, exactly as _PROBED always has — probe
    # verdicts are compile-expensive to re-earn, and clearing only one view
    # would make dispatch_counters()["probes"] and the registry disagree.
    from .ops import pallas_kernels as pk
    from .parallel import batch, store

    pk.DISPATCH_COUNTS.clear()
    store.LAYOUT_COUNTS.clear()
    store.TRANSFER_BYTES.clear()
    batch.PAIRWISE_COUNTS.clear()


def recommend(stats: BitmapStatistics) -> str:
    """NaiveWriterRecommender.recommend (insights/NaiveWriterRecommender.java:14):
    writer-configuration advice from observed container mix."""
    lines: List[str] = []
    total = stats.container_count()
    if total == 0:
        return "No containers analysed; defaults are fine."
    run_frac = stats.container_fraction(stats.run_containers_count)
    bitmap_frac = stats.container_fraction(stats.bitmap_containers_count)
    array_frac = stats.container_fraction(stats.array_stats.containers_count)
    if run_frac > 0.5:
        lines.append(
            f"{run_frac:.0%} run containers: use writer().optimise_for_runs()"
        )
    if bitmap_frac > 0.5:
        lines.append(
            f"{bitmap_frac:.0%} bitmap containers: use writer().constant_memory() "
            "(dense chunks fill the fixed 8 KiB buffer)"
        )
    if array_frac > 0.5:
        avg = stats.array_stats.average_cardinality()
        lines.append(
            f"{array_frac:.0%} array containers (avg cardinality {avg:.0f}): use "
            f"writer().optimise_for_arrays().expected_values_per_container({int(avg) or 1})"
        )
    if stats.bitmaps_count > 64:
        lines.append(
            f"{stats.bitmaps_count} bitmaps: wide aggregations will take the "
            "batched device path (FastAggregation mode='auto')"
        )
    if not lines:
        lines.append("Mixed container profile; default writer settings are reasonable.")
    return "\n".join(lines)
