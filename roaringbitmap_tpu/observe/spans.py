"""Nested span tracer: wall-time histograms per span *path*.

A span is a named region of host execution. Spans nest: each thread keeps
a stack, and a span's histogram label is its ``/``-joined path from the
stack root ("store.reduce.padded/kernel.probe.grouped"), so after a run
the registry answers not only "how long did packing take" but "packing
under which caller". Recording happens in the registry histogram
``rb_tpu_span_seconds`` (observe/registry.py) — ``snapshot()``, the JSONL
and Prometheus exporters, and the bench sidecar all see spans with no
extra wiring.

``span(name, trace=True)`` additionally opens a
``jax.profiler.TraceAnnotation`` so the same region shows up as a named
span in XProf/TensorBoard device traces — the composition point with the
pre-existing ``tracing.annotate`` path (which now routes through here).
Only ``ImportError``/``AttributeError`` (jax missing or stripped) disable
the annotation; a real TraceAnnotation failure propagates.

Thread-local stacks mean concurrent spans never corrupt each other's
paths; the histogram itself is locked by the registry.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator, List

from . import registry as _registry
from . import timeline as _timeline

SPAN_SECONDS = _registry.histogram(
    _registry.SPAN_SECONDS,
    "Wall time of nested host spans, labeled by /-joined span path",
    ("name",),
)

_local = threading.local()


def _stack() -> List[str]:
    try:
        return _local.stack
    except AttributeError:
        _local.stack = []
        return _local.stack


def current_path() -> str:
    """The /-joined path of the innermost active span ("" outside any)."""
    return "/".join(_stack())


def depth() -> int:
    """How many spans are open on this thread."""
    return len(_stack())


@contextlib.contextmanager
def span(name: str, trace: bool = False) -> Iterator[str]:
    """Time the enclosed block under ``name`` nested below the active span.

    Yields the full span path. ``trace=True`` also opens a
    ``jax.profiler.TraceAnnotation(name)`` when jax is importable."""
    ctx = contextlib.nullcontext()
    if trace:
        try:
            import jax

            ctx = jax.profiler.TraceAnnotation(name)
        except (ImportError, AttributeError):  # jax missing or stripped build
            pass
    stack = _stack()
    stack.append(name)
    path = "/".join(stack)
    t0_ns = time.perf_counter_ns()
    try:
        with ctx:
            yield path
    finally:
        stack.pop()
        dur_ns = time.perf_counter_ns() - t0_ns
        SPAN_SECONDS.observe(dur_ns / 1e9, (path,))
        # mirror into the flight recorder (ISSUE 6) so every pre-existing
        # op_timer/span block appears on the timeline with no new wiring
        if _timeline.enabled():
            _timeline._record_complete(name, "span", t0_ns, dur_ns, None)


def span_timings() -> dict:
    """{path: {count, total_s, mean_ms}} over all recorded spans — the
    shape ``tracing.timings()`` uses, keyed by nested path."""
    out = {}
    for (path,), st in sorted(SPAN_SECONDS.series().items()):
        c, total = st["count"], st["sum"]
        out[path] = {
            "count": c,
            "total_s": round(total, 6),
            "mean_ms": round(total / c * 1e3, 3) if c else 0.0,
        }
    return out


def reset_spans() -> None:
    SPAN_SECONDS.clear()
