"""Lock-wait observatory: wait-time histograms over the framework locks
(ISSUE 9 tentpole, leg 3a).

ROADMAP item 3's serving layer will hammer the seven framework locks with
concurrent query traffic; today nothing measures what that contention
costs. This module wraps each lock in a :class:`TimedLock` proxy that
times ``acquire`` into ``rb_tpu_lock_wait_seconds{lock}`` — a latency
histogram, so the p99 wait under a thread hammer is one registry read.

Cost model, by mode:

* **not installed** (the default) — the raw locks are untouched: zero
  overhead, nothing to reason about;
* **installed, timing disabled** — one module-int compare per acquire on
  top of the proxy call (the "off-mode cost of one int compare"
  contract, pinned by tests);
* **installed + enabled** — ``perf_counter_ns`` before/after the inner
  acquire plus one histogram observe per sampled acquisition.
  ``RB_TPU_LOCK_TIMING=<n>`` samples every n-th acquisition per lock
  (default 1 = all; sampling trades quantile resolution for overhead on
  nanosecond-hot locks).

Leaf-safety (lockwitness-verified in tests/test_observatory.py): the
histogram observe runs *after* the inner lock is held, adding only
``<wrapped lock> -> observe.registry`` edges — an ordering every
instrumented module already exhibits (metrics are recorded under
framework locks throughout), so no cycle is introduced. The registry
lock itself is wrapped too; its observe re-enters through the proxy and
a thread-local guard breaks the recursion (the reentrant acquire is not
re-timed — it cannot wait, the thread already holds the lock).

``install()`` patches every live reference (module globals, the registry
plus every registered metric's captured ``_lock``, class attributes);
``uninstall()`` restores the originals. Install at a quiescent point
(startup, bench setup): swapping a lock object mid-contention is safe
only because the proxy shares the inner lock, but the wait samples
straddling the swap are lost.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from . import registry as _registry
from .histogram import latency_histogram

_LOCK_WAIT = latency_histogram(
    _registry.LOCK_WAIT_SECONDS,
    "Time spent waiting to acquire a framework lock, by lock name",
    ("lock",),
)

# 0 = timing off (int compare only); >0 = sample every n-th acquisition
_TIMING = 0

# breaks the registry-lock recursion: observing the wait histogram
# acquires the (wrapped) registry lock, which must not re-observe
_TLS = threading.local()


class TimedLock:
    """Proxy over a Lock/RLock that times (sampled) acquire waits."""

    __slots__ = ("name", "_inner", "_n")

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner
        self._n = 0  # unsynchronized sample counter: skew is harmless

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        sample = _TIMING
        if not sample:
            return self._inner.acquire(blocking, timeout)
        self._n += 1
        if self._n % sample or getattr(_TLS, "busy", False):
            return self._inner.acquire(blocking, timeout)
        t0 = time.perf_counter_ns()
        got = self._inner.acquire(blocking, timeout)
        dur = time.perf_counter_ns() - t0
        if got:
            _TLS.busy = True
            try:
                _LOCK_WAIT.observe(dur / 1e9, (self.name,))
            finally:
                _TLS.busy = False
        return got

    def release(self) -> None:
        self._inner.release()

    def __enter__(self) -> "TimedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<TimedLock {self.name} over {self._inner!r}>"


def enable(on: bool = True, sample: Optional[int] = None) -> None:
    """Turn wait timing on/off (requires :func:`install` for any effect).
    ``sample=n`` times every n-th acquisition per lock."""
    global _TIMING
    if sample is not None and sample < 1:
        raise ValueError(f"sample must be >= 1, got {sample}")
    if on:
        _TIMING = int(sample) if sample is not None else (_TIMING or 1)
    else:
        _TIMING = 0


def timing_enabled() -> bool:
    return _TIMING > 0


# ---------------------------------------------------------------------------
# the seven framework locks: (name, get, set) accessors
# ---------------------------------------------------------------------------


def _framework_locks() -> List[tuple]:
    """Late-bound accessors for the seven framework locks (ARCHITECTURE
    "Static analysis"): module globals and attributes patched in place.
    Imports are local so lockstats stays importable before the heavy
    modules (and without jax)."""
    from .. import native, tracing
    from ..parallel import aggregation
    from ..query import cache as qcache
    from ..query import exec as qexec
    from ..query import expr as qexpr

    def mod(m, attr):
        return (lambda: getattr(m, attr)), (lambda v: setattr(m, attr, v))

    return [
        ("tracing.timings", *mod(tracing, "_TIMINGS_LOCK")),
        ("observe.registry", *mod(_registry.REGISTRY, "_lock")),
        ("query.expr.intern", *mod(qexpr, "_INTERN_LOCK")),
        ("query.exec.plan_memo", *mod(qexec, "_PLAN_MEMO_LOCK")),
        ("query.cache", *mod(qcache.DEFAULT_CACHE, "_lock")),
        ("agg.pool", *mod(aggregation.ParallelAggregation, "_POOL_LOCK")),
        ("native.loader", *mod(native, "_lock")),
    ]


_INSTALLED: Dict[str, tuple] = {}  # name -> (TimedLock, restore-setter)
_INSTALL_LOCK = threading.Lock()


def install(enable_timing: bool = True, sample: Optional[int] = None) -> None:
    """Wrap the seven framework locks in :class:`TimedLock` proxies
    (idempotent). Metrics capture the registry lock at registration, so
    every already-registered metric's ``_lock`` is re-pointed at the
    wrapped registry lock; metrics registered afterwards inherit it
    through ``Registry._register``."""
    with _INSTALL_LOCK:
        for name, get, set_ in _framework_locks():
            if name in _INSTALLED:
                continue
            inner = get()
            if isinstance(inner, TimedLock):  # foreign wrap: leave it
                continue
            wrapped = TimedLock(name, inner)
            set_(wrapped)
            _INSTALLED[name] = (wrapped, set_)
        # re-point every registered metric's captured registry-lock ref
        reg_entry = _INSTALLED.get("observe.registry")
        if reg_entry is not None:
            wrapped = reg_entry[0]
            for m in _registry.REGISTRY.metrics():
                if m._lock is wrapped._inner:
                    m._lock = wrapped
    if enable_timing:
        enable(True, sample=sample)


def uninstall() -> None:
    """Restore the raw locks and stop timing (idempotent)."""
    enable(False)
    with _INSTALL_LOCK:
        reg_entry = _INSTALLED.get("observe.registry")
        if reg_entry is not None:
            wrapped = reg_entry[0]
            for m in _registry.REGISTRY.metrics():
                if m._lock is wrapped:
                    m._lock = wrapped._inner
        for _name, (wrapped, set_) in list(_INSTALLED.items()):
            set_(wrapped._inner)
        _INSTALLED.clear()


def installed() -> List[str]:
    with _INSTALL_LOCK:
        return sorted(_INSTALLED)


def wait_stats() -> Dict[str, dict]:
    """{lock: {count, sum, p50, p90, p99}} over the recorded waits."""
    out: Dict[str, dict] = {}
    for lv, st in sorted(_LOCK_WAIT.series().items()):
        out[lv[0]] = {
            "count": st["count"],
            "sum": round(st["sum"], 9),
            **{
                k: round(v, 9)
                for k, v in _LOCK_WAIT.quantiles(lv).items()
            },
        }
    return out


def _init_from_env() -> None:
    raw = os.environ.get("RB_TPU_LOCK_TIMING", "").strip().lower()
    if not raw or raw in ("0", "off", "false", "no"):
        return
    try:
        sample = max(1, int(raw))
    except ValueError:
        sample = 1
    install(enable_timing=True, sample=sample)


# NOTE: env-driven install runs on first *explicit* import of this module
# (observe/__init__ imports it lazily via attribute, not eagerly), so the
# base import path stays jax-light. bench.py and rb_top.py import it.
_init_from_env()
