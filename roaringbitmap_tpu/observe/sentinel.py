"""The health sentinel: a low-overhead supervisor evaluating the
declarative rule table and actuating closed-loop responses (ISSUE 12
tentpole, leg 2).

``observe.health`` defines WHAT healthy means (rules, bands, hysteresis,
flap suppression); this module decides WHEN to judge and WHAT TO DO
about a verdict:

* **Pacing** — three ways to drive ticks, all sharing one
  :class:`Sentinel`:

  - ``tick()`` — explicit, with an injectable ``now`` (fake-clock
    determinism for tests and the bench's seeded-drift demo);
  - ``start()``/``stop()`` — an opt-in daemon thread
    (``RB_TPU_SENTINEL=on`` at import, interval
    ``RB_TPU_SENTINEL_INTERVAL_S``, default 5 s);
  - ``maybe_tick()`` — an inline pacing hook on the dispatch path
    (``RB_TPU_SENTINEL=inline`` / ``configure(inline=True)``): a
    single-threaded serving loop gets supervision without a thread. Off
    (the default) it is ONE module-bool check — no allocation, pinned by
    tests/test_sentinel.py.

* **Judgement** — each tick builds a :class:`health.Snapshot` OUTSIDE
  the sentinel lock (gathering takes the registry/ladder/ledger leaf
  locks), runs every rule probe against it, then steps the per-rule
  state machines under the sentinel lock. The sentinel lock is a LEAF:
  nothing else is ever acquired while holding it (metrics, instants, and
  actuations all happen outside), witnessed by the test hammer.

* **Actuation** — the closed loop, per the rule table's actuation
  column:

  - ``"refit"`` (costmodel-drift): while the rule is at WARN or worse,
    ``cost.refit_all()`` re-fits every pricing authority from the live
    decision–outcome ledger — ROADMAP item 4's automatic drift-triggered
    refit. Guarded by a cooldown (``RB_TPU_SENTINEL_REFIT_COOLDOWN_S``,
    default 60 s) so a stubborn drift cannot thrash the coefficients;
    each authority's provenance ("refit-from-traffic") and moved cells
    land in the actuation log, and the columnar model persists through
    ``RB_TPU_COLUMNAR_CAL`` exactly as a manual refit would.
  - ``"maintain"`` (structure-drift / delta-accretion, ISSUE 16): while
    the rule is at WARN or worse, one priced background maintenance
    pass (``serve.maintain.run_pass``) — the pass itself still decides
    compact-vs-ride through the compaction authority, so the sentinel
    schedules work, it never forces it. Guarded by its own cooldown
    (``RB_TPU_SENTINEL_MAINTAIN_COOLDOWN_S``, default 30 s) so a
    stubborn drift cannot turn the corpus into a rewrite storm.
  - ``"autotune"`` (serving-p99-pressure, ISSUE 19): while the rule is
    at WARN or worse, re-derive the fusion executor's window bound from
    the fusion authority's refitted curves against the tightest declared
    interactive p99 budget (``query.fusion.autotune_window``) — the
    static window knob becomes a refittable policy that shrinks under
    tail pressure and regrows toward its configured base once curves or
    traffic recover. Guarded by its own cooldown
    (``RB_TPU_SENTINEL_AUTOTUNE_COOLDOWN_S``, default 30 s) so the
    window cannot thrash batch-to-batch.
  - ``"alert"``: on the fire transition, a structured
    ``sentinel.alert`` recorder instant + decision-log entry carrying
    the rule, value, and threshold — once per episode, not per tick
    (hysteresis + flap suppression upstream make that meaningful).
  - any rule reaching CRITICAL: a one-shot **flight bundle**
    (``observe.bundle``) per red episode, cooldown-guarded
    (``RB_TPU_SENTINEL_BUNDLE_COOLDOWN_S``, default 300 s).

Every tick exports ``rb_tpu_health_status`` (process rollup) and
``rb_tpu_health_rule_state{rule}``; actuations count in
``rb_tpu_health_actuation_total{rule,kind}``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import health as _health
from . import registry as _registry
from . import timeline as _timeline

DEFAULT_INTERVAL_S = 5.0
DEFAULT_REFIT_COOLDOWN_S = 60.0
DEFAULT_BUNDLE_COOLDOWN_S = 300.0
DEFAULT_MAINTAIN_COOLDOWN_S = 30.0
DEFAULT_AUTOTUNE_COOLDOWN_S = 30.0

_ACTUATION_TOTAL = _registry.counter(
    _registry.HEALTH_ACTUATION_TOTAL,
    "Sentinel closed-loop actuations by rule and kind "
    "(refit | maintain | autotune | alert | bundle)",
    ("rule", "kind"),
)


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    try:
        return float(raw) if raw else default
    except ValueError:  # malformed env must not break package import
        return default


class Sentinel:
    """Rule-table supervisor. All mutable state lives behind ``_lock``
    (a LEAF — see the module docstring); the clock is injectable so
    cooldown/hysteresis tests run on a fake timeline."""

    def __init__(
        self,
        rules: Optional[Tuple[_health.Rule, ...]] = None,
        clock=time.monotonic,
        refit_cooldown_s: Optional[float] = None,
        bundle_cooldown_s: Optional[float] = None,
        maintain_cooldown_s: Optional[float] = None,
        autotune_cooldown_s: Optional[float] = None,
    ):
        self.rules: Tuple[_health.Rule, ...] = tuple(
            _health.DEFAULT_RULES if rules is None else rules
        )
        self._clock = clock
        self.refit_cooldown_s = (
            _env_float("RB_TPU_SENTINEL_REFIT_COOLDOWN_S", DEFAULT_REFIT_COOLDOWN_S)
            if refit_cooldown_s is None else float(refit_cooldown_s)
        )
        self.bundle_cooldown_s = (
            _env_float("RB_TPU_SENTINEL_BUNDLE_COOLDOWN_S", DEFAULT_BUNDLE_COOLDOWN_S)
            if bundle_cooldown_s is None else float(bundle_cooldown_s)
        )
        self.maintain_cooldown_s = (
            _env_float(
                "RB_TPU_SENTINEL_MAINTAIN_COOLDOWN_S",
                DEFAULT_MAINTAIN_COOLDOWN_S,
            )
            if maintain_cooldown_s is None else float(maintain_cooldown_s)
        )
        self.autotune_cooldown_s = (
            _env_float(
                "RB_TPU_SENTINEL_AUTOTUNE_COOLDOWN_S",
                DEFAULT_AUTOTUNE_COOLDOWN_S,
            )
            if autotune_cooldown_s is None else float(autotune_cooldown_s)
        )
        self._lock = threading.Lock()  # leaf: guards the fields below only
        self._states: Dict[str, _health.RuleState] = {  # guarded-by: self._lock
            r.name: _health.RuleState() for r in self.rules
        }
        self._tick_no = 0  # guarded-by: self._lock
        self._status = _health.OK  # guarded-by: self._lock
        self._prev_sums: Dict[str, float] = {}  # guarded-by: self._lock
        self._actuations: "deque[dict]" = deque(maxlen=64)  # guarded-by: self._lock
        self._last_refit: Optional[float] = None  # guarded-by: self._lock
        self._last_bundle: Optional[float] = None  # guarded-by: self._lock
        self._last_maintain: Optional[float] = None  # guarded-by: self._lock
        self._last_autotune: Optional[float] = None  # guarded-by: self._lock

    # -- the tick -----------------------------------------------------------

    def tick(self, now: Optional[float] = None, snap=None) -> dict:
        """One supervision cycle: snapshot → judge → export → actuate.
        ``now`` pins the clock (tests); ``snap`` injects a pre-built
        snapshot (the hammer fabricates cheap ones)."""
        if now is None:
            now = self._clock()
        if snap is None:
            with self._lock:
                prev = dict(self._prev_sums)
            snap = _health.snapshot(prev_sums=prev, now=now)
        # probes run OUTSIDE the sentinel lock: they read other
        # subsystems' (leaf-locked) registries
        values: Dict[str, Optional[float]] = {}
        probe_errors: Dict[str, str] = {}
        for rule in self.rules:
            try:
                v = rule.probe(snap)
                values[rule.name] = float(v) if v is not None else None
            except Exception as e:  # rb-ok: exception-hygiene -- one broken probe must not kill the supervisor; the error is surfaced in the tick report and the rule judges no-data
                values[rule.name] = None
                probe_errors[rule.name] = f"{type(e).__name__}: {e}"
        alerts: List[dict] = []
        refit_due: Optional[str] = None
        maintain_due: Optional[str] = None
        autotune_due: Optional[str] = None
        bundle_due: Optional[List[str]] = None
        with self._lock:
            self._tick_no += 1
            tick_no = self._tick_no
            evals: Dict[str, dict] = {}
            status = _health.OK
            for rule in self.rules:
                st = self._states[rule.name]
                ev = st.step(rule, values[rule.name], tick_no)
                evals[rule.name] = ev
                status = max(status, st.level)
                tr = ev["transition"]
                if (
                    tr is not None and tr[1] > tr[0]
                    and rule.actuation == "alert"
                ):
                    alerts.append({
                        "rule": rule.name, "value": ev["value"],
                        "level": ev["level"], "warn": rule.warn,
                        "critical": rule.critical,
                    })
                if (
                    rule.actuation == "refit"
                    and st.level >= _health.WARN
                    and refit_due is None
                    and (
                        self._last_refit is None
                        or now - self._last_refit >= self.refit_cooldown_s
                    )
                ):
                    self._last_refit = now
                    refit_due = rule.name
                if (
                    rule.actuation == "maintain"
                    and st.level >= _health.WARN
                    and maintain_due is None
                    and (
                        self._last_maintain is None
                        or now - self._last_maintain >= self.maintain_cooldown_s
                    )
                ):
                    self._last_maintain = now
                    maintain_due = rule.name
                if (
                    rule.actuation == "autotune"
                    and st.level >= _health.WARN
                    and autotune_due is None
                    and (
                        self._last_autotune is None
                        or now - self._last_autotune >= self.autotune_cooldown_s
                    )
                ):
                    self._last_autotune = now
                    autotune_due = rule.name
            prev_status = self._status
            self._status = status
            self._prev_sums.update(snap.sums)
            if (
                status >= _health.CRITICAL
                and prev_status < _health.CRITICAL
                and (
                    self._last_bundle is None
                    or now - self._last_bundle >= self.bundle_cooldown_s
                )
            ):
                self._last_bundle = now
                bundle_due = [
                    r.name for r in self.rules
                    if self._states[r.name].level >= _health.CRITICAL
                ]
        # -- export + actuate, all OUTSIDE the sentinel lock --------------
        _health.HEALTH_STATUS.set(status)
        for rule in self.rules:
            _health.HEALTH_RULE_STATE.set(evals[rule.name]["level"], (rule.name,))
        self._emit_transitions(evals)
        actuated: List[dict] = []
        for a in alerts:
            actuated.append(self._actuate_alert(now, tick_no, a))
        if refit_due is not None:
            actuated.append(self._actuate_refit(now, tick_no, refit_due))
        if maintain_due is not None:
            actuated.append(self._actuate_maintain(now, tick_no, maintain_due))
        if autotune_due is not None:
            actuated.append(self._actuate_autotune(now, tick_no, autotune_due))
        if bundle_due is not None:
            actuated.append(self._actuate_bundle(now, tick_no, bundle_due, evals))
        if actuated:
            with self._lock:
                self._actuations.extend(actuated)
        report = {
            "tick": tick_no,
            "status": status,
            "status_name": _health.STATUS_NAMES[status],
            "rules": evals,
            "actuated": actuated,
        }
        if probe_errors:
            report["probe_errors"] = probe_errors
        return report

    def _emit_transitions(self, evals: Dict[str, dict]) -> None:
        from . import decisions as _decisions

        for name, ev in evals.items():
            tr = ev["transition"]
            if tr is None:
                continue
            frm, to = _health.LEVEL_NAMES[tr[0]], _health.LEVEL_NAMES[tr[1]]
            if _timeline.enabled():
                _timeline.instant(
                    "health.transition", "health", rule=name,
                    frm=frm, to=to, value=ev["value"],
                )
            _decisions.record_decision(
                "sentinel.rule", f"{frm}->{to}", rule=name, value=ev["value"],
            )

    # -- actuations ---------------------------------------------------------

    def _actuate_alert(self, now, tick_no, a) -> dict:
        from . import decisions as _decisions

        _ACTUATION_TOTAL.inc(1, (a["rule"], "alert"))
        _timeline.instant(
            "sentinel.alert", "health", rule=a["rule"], value=a["value"],
            level=_health.LEVEL_NAMES[a["level"]], warn=a["warn"],
            critical=a["critical"],
        )
        _decisions.record_decision(
            "sentinel.actuate", "alert", rule=a["rule"], value=a["value"],
            level=_health.LEVEL_NAMES[a["level"]],
        )
        return {"tick": tick_no, "ts": now, "kind": "alert", **a}

    def _actuate_refit(self, now, tick_no, rule_name: str) -> dict:
        from . import decisions as _decisions

        _ACTUATION_TOTAL.inc(1, (rule_name, "refit"))
        entry = {"tick": tick_no, "ts": now, "kind": "refit", "rule": rule_name}
        try:
            from .. import cost as _cost

            reports = _cost.refit_all()
            entry["authorities"] = {
                name: {
                    "moved": sorted(rep.get("moved") or {}),
                    "provenance": rep.get("provenance"),
                    "refused": rep.get("refused"),
                }
                for name, rep in reports.items()
            }
        except Exception as e:  # rb-ok: exception-hygiene -- a failed refit leaves the calibrated coefficients in place; the failure is recorded in the actuation log and the drift rule stays firing
            entry["error"] = f"{type(e).__name__}: {e}"
        _timeline.instant(
            "sentinel.refit", "health", rule=rule_name,
            moved=sum(
                len(a.get("moved") or ())
                for a in entry.get("authorities", {}).values()
            ),
        )
        _decisions.record_decision(
            "sentinel.actuate", "refit", rule=rule_name,
            error=entry.get("error"),
        )
        return entry

    def _actuate_maintain(self, now, tick_no, rule_name: str) -> dict:
        from . import decisions as _decisions

        _ACTUATION_TOTAL.inc(1, (rule_name, "maintain"))
        entry = {
            "tick": tick_no, "ts": now, "kind": "maintain", "rule": rule_name,
        }
        try:
            from ..serve import maintain as _maintain

            record = _maintain.run_pass(reason=f"sentinel:{rule_name}")
            entry["outcome"] = record.get("outcome")
            entry["reclaimed_bytes"] = record.get("reclaimed_bytes")
            entry["rewritten_keys"] = record.get("rewritten_keys")
        except Exception as e:  # rb-ok: exception-hygiene -- a failed pass leaves the uncompacted epoch in place; the failure is recorded in the actuation log and the structure rules stay firing
            entry["error"] = f"{type(e).__name__}: {e}"
        _timeline.instant(
            "sentinel.maintain", "health", rule=rule_name,
            outcome=entry.get("outcome"),
        )
        _decisions.record_decision(
            "sentinel.actuate", "maintain", rule=rule_name,
            pass_outcome=entry.get("outcome"), error=entry.get("error"),
        )
        return entry

    def _actuate_autotune(self, now, tick_no, rule_name: str) -> dict:
        from . import decisions as _decisions

        _ACTUATION_TOTAL.inc(1, (rule_name, "autotune"))
        entry = {
            "tick": tick_no, "ts": now, "kind": "autotune", "rule": rule_name,
        }
        try:
            from ..query import fusion as _fusion

            record = _fusion.autotune_window(reason=f"sentinel:{rule_name}")
            entry["verdict"] = record.get("verdict")
            entry["window_from"] = record.get("window_from")
            entry["window_to"] = record.get("window_to")
            entry["budget_ms"] = record.get("budget_ms")
        except Exception as e:  # rb-ok: exception-hygiene -- a failed auto-tune leaves the current window bounds in place; the failure is recorded in the actuation log and the pressure rule stays firing
            entry["error"] = f"{type(e).__name__}: {e}"
        _timeline.instant(
            "sentinel.autotune", "health", rule=rule_name,
            verdict=entry.get("verdict"), window_to=entry.get("window_to"),
        )
        _decisions.record_decision(
            "sentinel.actuate", "autotune", rule=rule_name,
            tune_verdict=entry.get("verdict"), error=entry.get("error"),
        )
        return entry

    def _actuate_bundle(self, now, tick_no, red_rules, evals) -> dict:
        from . import bundle as _bundle
        from . import decisions as _decisions

        for name in red_rules:
            _ACTUATION_TOTAL.inc(1, (name, "bundle"))
        reason = red_rules[0] if red_rules else "red"
        entry = {
            "tick": tick_no, "ts": now, "kind": "bundle",
            "rules": list(red_rules),
        }
        try:
            entry["path"] = _bundle.write_bundle(
                reason,
                trigger={
                    "rules": {
                        name: {
                            "value": evals[name]["value"],
                            "level": _health.LEVEL_NAMES[evals[name]["level"]],
                        }
                        for name in red_rules
                    },
                    "tick": tick_no,
                },
                health_dump=self.health_dump(),
            )
        except Exception as e:  # rb-ok: exception-hygiene -- a bundle that cannot be written (disk full at the worst moment) must not kill the supervisor; the failure is recorded in the actuation log
            entry["error"] = f"{type(e).__name__}: {e}"
        _timeline.instant(
            "sentinel.bundle", "health", rules=",".join(red_rules),
            path=entry.get("path"),
        )
        _decisions.record_decision(
            "sentinel.actuate", "bundle", rules=",".join(red_rules),
            error=entry.get("error"),
        )
        return entry

    # -- read APIs ----------------------------------------------------------

    def status(self) -> Tuple[int, str]:
        with self._lock:
            return self._status, _health.STATUS_NAMES[self._status]

    def rule_states(self) -> Dict[str, dict]:
        """{rule: state + thresholds} — the rb_top health panel's rows."""
        with self._lock:
            out = {}
            for rule in self.rules:
                st = self._states[rule.name]
                out[rule.name] = {
                    **st.as_dict(),
                    "warn": rule.warn,
                    "critical": rule.critical,
                    "actuation": rule.actuation,
                }
            return out

    def actuations(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            entries = list(self._actuations)
        if n is not None:
            entries = entries[-int(n):] if n > 0 else []
        return [dict(e) for e in entries]

    def history(self, rule: str, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            h = list(self._states[rule].history)
        return h[-int(n):] if n else h

    def health_dump(self) -> dict:
        """The bundle's health.json: status, per-rule state + evaluation
        history, and the actuation log."""
        with self._lock:
            return {
                "status": self._status,
                "status_name": _health.STATUS_NAMES[self._status],
                "tick": self._tick_no,
                "rules": {
                    rule.name: {
                        **self._states[rule.name].as_dict(),
                        "warn": rule.warn,
                        "critical": rule.critical,
                        "actuation": rule.actuation,
                        "history": list(self._states[rule.name].history),
                    }
                    for rule in self.rules
                },
                "actuations": list(self._actuations),
            }

    def reset(self) -> None:
        """Drop all evaluation state (tests, bench windows); the rule
        table and cooldown policy stay."""
        with self._lock:
            self._states = {r.name: _health.RuleState() for r in self.rules}
            self._tick_no = 0
            self._status = _health.OK
            self._prev_sums = {}
            self._actuations.clear()
            self._last_refit = None
            self._last_bundle = None
            self._last_maintain = None
            self._last_autotune = None


# The process-wide sentinel (the thread, the inline hook, rb_top, and the
# bench demo all drive this instance).
SENTINEL = Sentinel()

_THREAD_LOCK = threading.Lock()
_THREAD: Optional[threading.Thread] = None  # guarded-by: _THREAD_LOCK
_STOP = threading.Event()

# inline pacing (maybe_tick): OFF by default — the hook on the dispatch
# path is then one module-bool check, nothing allocated (pinned by test)
_INLINE = False
_INLINE_INTERVAL_NS = int(DEFAULT_INTERVAL_S * 1e9)
_NEXT_TICK_NS = 0


def maybe_tick() -> bool:
    """Inline pacing hook (called from the aggregation dispatch path):
    ticks the process sentinel at most once per interval, and only when
    inline mode is armed. The off path is one bool check."""
    if not _INLINE:
        return False
    global _NEXT_TICK_NS
    now = time.monotonic_ns()
    if now < _NEXT_TICK_NS:
        return False
    # racy window is benign: two threads can at worst tick back-to-back
    _NEXT_TICK_NS = now + _INLINE_INTERVAL_NS
    SENTINEL.tick()
    return True


def start(interval_s: Optional[float] = None) -> None:
    """Start the opt-in supervision thread (idempotent)."""
    global _THREAD
    if interval_s is None:
        interval_s = _env_float("RB_TPU_SENTINEL_INTERVAL_S", DEFAULT_INTERVAL_S)
    with _THREAD_LOCK:
        if _THREAD is not None and _THREAD.is_alive():
            return
        _STOP.clear()

        def _loop():
            while not _STOP.wait(interval_s):
                try:
                    SENTINEL.tick()
                except Exception:  # rb-ok: exception-hygiene -- the supervisor thread must survive any single bad tick; the next interval retries with fresh state
                    pass

        _THREAD = threading.Thread(
            target=_loop, name="rb-sentinel", daemon=True
        )
        _THREAD.start()


def stop() -> None:
    """Stop the supervision thread (no-op when not running)."""
    global _THREAD
    with _THREAD_LOCK:
        t = _THREAD
        _THREAD = None
        if t is not None:
            # set the stop flag INSIDE the lock: a concurrent start()
            # serializes behind us and clears the event for ITS thread —
            # setting it after releasing would race that clear and kill
            # the freshly started supervisor on its first wait
            _STOP.set()
    if t is not None:
        t.join(timeout=5.0)


def running() -> bool:
    with _THREAD_LOCK:
        return _THREAD is not None and _THREAD.is_alive()


def configure(
    inline: Optional[bool] = None,
    inline_interval_s: Optional[float] = None,
    refit_cooldown_s: Optional[float] = None,
    bundle_cooldown_s: Optional[float] = None,
    maintain_cooldown_s: Optional[float] = None,
    autotune_cooldown_s: Optional[float] = None,
) -> None:
    """Runtime overrides for the process sentinel: arm/disarm the inline
    pacing hook and adjust the actuation cooldowns."""
    global _INLINE, _INLINE_INTERVAL_NS, _NEXT_TICK_NS
    if inline is not None:
        _INLINE = bool(inline)
        _NEXT_TICK_NS = 0
    if inline_interval_s is not None:
        _INLINE_INTERVAL_NS = int(float(inline_interval_s) * 1e9)
        _NEXT_TICK_NS = 0
    if refit_cooldown_s is not None:
        SENTINEL.refit_cooldown_s = float(refit_cooldown_s)
    if bundle_cooldown_s is not None:
        SENTINEL.bundle_cooldown_s = float(bundle_cooldown_s)
    if maintain_cooldown_s is not None:
        SENTINEL.maintain_cooldown_s = float(maintain_cooldown_s)
    if autotune_cooldown_s is not None:
        SENTINEL.autotune_cooldown_s = float(autotune_cooldown_s)


def _init_from_env() -> None:
    raw = os.environ.get("RB_TPU_SENTINEL", "").strip().lower()
    if raw in ("", "0", "off", "false", "no"):
        return
    if raw == "inline":
        configure(inline=True)
    else:  # "on"/"1"/"thread"/anything truthy: the background thread
        start()


_init_from_env()
