"""Unified metrics & span subsystem (ISSUE 1).

One registry, three metric kinds, two exporters, one sidecar::

    instrumented layers                         observe/
    ────────────────────────────────            ─────────────────────────────
    kernel dispatch + probes  (ops/pallas_kernels) ─┐
    layout, transfers, cache  (parallel/store)      ├─► registry.REGISTRY ─► export:
    pairwise engines          (parallel/batch)      │   (Counter/Gauge/       Prometheus text,
    wire-format bytes         (serialization)       │    Histogram,           JSONL,
    host phases + spans       (tracing + spans)    ─┘    snapshot/reset)      metrics_sidecar(path)
                                                             │
                               legacy facades (shapes unchanged):
                               insights.dispatch_counters(), tracing.timings()

Metric naming convention: ``rb_tpu_<layer>_<name>`` (canonical names in
``registry.py``). Pure stdlib — importable before (and without) jax.
"""

from .registry import (
    ANALYSIS_FINDINGS_TOTAL,
    BATCH_PAIRWISE_TOTAL,
    BREAKER_TRANSITIONS_TOTAL,
    COLUMNAR_BATCH_TOTAL,
    COLUMNAR_CLASS_SECONDS,
    COLUMNAR_ROUTE_TOTAL,
    COMPILE_TOTAL,
    COSTMODEL_DRIFT_RATIO,
    DEADLINE_TOTAL,
    DECISION_ERROR_RATIO,
    DECISION_REGRET_SECONDS,
    DECISION_TOTAL,
    DEFAULT_TIME_BUCKETS,
    DEGRADE_TOTAL,
    DURABLE_ARTIFACT_BYTES,
    DURABLE_DEMOTE_TOTAL,
    DURABLE_EPOCH_COUNT,
    DURABLE_PENDING_COUNT,
    DURABLE_PERSIST_BYTES_TOTAL,
    DURABLE_PERSIST_STAGE_SECONDS,
    DURABLE_PERSIST_TOTAL,
    DURABLE_PERSIST_WALL_SECONDS,
    DURABLE_RECOVERY_TOTAL,
    FAULT_INJECTED_TOTAL,
    FUSION_BATCH_SECONDS,
    FUSION_BATCH_TOTAL,
    FUSION_QUERIES_TOTAL,
    FUSION_QUEUED_COUNT,
    FUSION_STEPS_TOTAL,
    HBM_ACCOUNTING_DRIFT_BYTES,
    HOST_OP_SECONDS,
    LOCK_WAIT_SECONDS,
    KERNEL_DISPATCH_TOTAL,
    KERNEL_PROBE_TOTAL,
    HEALTH_ACTUATION_TOTAL,
    HEALTH_RULE_STATE,
    HEALTH_STATUS,
    OUTCOME_ANOMALY_TOTAL,
    OUTCOME_JOIN_TOTAL,
    OUTCOME_ORPHANS_TOTAL,
    PACK_CACHE_DELTA_ROWS_TOTAL,
    PACK_CACHE_EVICTED_BYTES_TOTAL,
    PACK_CACHE_HITS_TOTAL,
    PACK_CACHE_MISSES_TOTAL,
    PACK_CACHE_RESIDENT_BYTES,
    QUERY_CACHE_TOTAL,
    QUERY_INFLIGHT_TOTAL,
    QUERY_LATENCY_SECONDS,
    QUERY_PLAN_TOTAL,
    REGISTRY,
    RETRY_TOTAL,
    SERIAL_BYTES_TOTAL,
    SERVE_ADMIT_TOTAL,
    SERVE_EPOCH_COUNT,
    SERVE_EPOCH_FLIP_TOTAL,
    SERVE_FLIP_STAGE_SECONDS,
    SERVE_FRESHNESS_SECONDS,
    SERVE_INFLIGHT_COUNT,
    SERVE_INGEST_TOTAL,
    SERVE_LATENCY_SECONDS,
    SERVE_MAINTAIN_KEYS_TOTAL,
    SERVE_MAINTAIN_RECLAIMED_BYTES_TOTAL,
    SERVE_MAINTAIN_SECONDS,
    SERVE_MAINTAIN_TOTAL,
    SERVE_MUTLOG_COUNT,
    SERVE_QPS,
    SERVE_QUEUE_COUNT,
    SERVE_REQUESTS_TOTAL,
    SERVE_SATURATION_RATIO,
    SERVE_TENANT_BYTES,
    SPAN_SECONDS,
    STORE_DELTA_STAGE_SECONDS,
    STORE_LAYOUT_TOTAL,
    STORE_OVERLAP_RATIO,
    STORE_PACK_STAGE_SECONDS,
    STORE_RESIDENT_BYTES,
    STORE_TRANSFER_BYTES_TOTAL,
    STRUCTURE_ACCRETION_COUNT,
    STRUCTURE_BYTES,
    STRUCTURE_CONTAINERS,
    STRUCTURE_DRIFT_RATIO,
    STRUCTURE_FRAGMENTATION_COUNT,
    TIMELINE_ANOMALY_TOTAL,
    TIMELINE_SPAN_SECONDS,
    Counter,
    Gauge,
    Histogram,
    MetricError,
    Registry,
    counter,
    gauge,
    histogram,
    reset,
    snapshot,
)
from .compat import CounterMap
from .histogram import (
    DEFAULT_LATENCY_BUCKETS,
    SNAPSHOT_QUANTILES,
    LatencyHistogram,
    latency_histogram,
    log_time_buckets,
)
from . import timeline
from .timeline import FlightRecorder, TimelineEvent
# query-scoped trace context + decision provenance (ISSUE 9); the lock
# observatory (observe.lockstats) is import-on-demand — it patches locks
# across the whole framework and must never load mid-import-cycle
from . import context
from . import decisions
from . import compilewatch
# the decision-outcome ledger (ISSUE 11): joins decisions to measured
# executions; imported after decisions (it is decisions' lazy dependency)
from . import outcomes
# the health sentinel tier (ISSUE 12): unified artifact sink, declarative
# health rules, the supervisor (opt-in thread via RB_TPU_SENTINEL), and
# flight bundles; imported last — sentinel reads every registry above
from . import artifacts
from . import health
from . import sentinel
from . import bundle
from .context import adopt, current_trace, new_trace_id, trace_scope
from .decisions import DecisionLog, record_decision
from .outcomes import OutcomeLedger
from .sentinel import SENTINEL, Sentinel
from .health import Rule, RuleState
from .spans import current_path, depth, reset_spans, span, span_timings

# the .histogram submodule import above shadows the registration helper on
# the package namespace; re-bind the helper (the submodule stays reachable
# as roaringbitmap_tpu.observe.histogram via sys.modules)
from .registry import histogram
from .export import (
    SIDECAR_SCHEMA,
    jsonl_lines,
    metrics_sidecar,
    prometheus_text,
    sidecar_snapshot,
    to_jsonl,
    write_jsonl,
    write_prometheus,
)

__all__ = [
    "REGISTRY",
    "Registry",
    "Counter",
    "Gauge",
    "Histogram",
    "LatencyHistogram",
    "FlightRecorder",
    "TimelineEvent",
    "MetricError",
    "CounterMap",
    "counter",
    "gauge",
    "histogram",
    "latency_histogram",
    "log_time_buckets",
    "timeline",
    "snapshot",
    "reset",
    "span",
    "span_timings",
    "current_path",
    "depth",
    "reset_spans",
    "metrics_sidecar",
    "sidecar_snapshot",
    "prometheus_text",
    "to_jsonl",
    "jsonl_lines",
    "write_jsonl",
    "write_prometheus",
    "SIDECAR_SCHEMA",
    "DEFAULT_TIME_BUCKETS",
    "DEFAULT_LATENCY_BUCKETS",
    "SNAPSHOT_QUANTILES",
    "KERNEL_DISPATCH_TOTAL",
    "KERNEL_PROBE_TOTAL",
    "STORE_LAYOUT_TOTAL",
    "STORE_TRANSFER_BYTES_TOTAL",
    "STORE_RESIDENT_BYTES",
    "PACK_CACHE_HITS_TOTAL",
    "PACK_CACHE_MISSES_TOTAL",
    "PACK_CACHE_DELTA_ROWS_TOTAL",
    "PACK_CACHE_EVICTED_BYTES_TOTAL",
    "PACK_CACHE_RESIDENT_BYTES",
    "BATCH_PAIRWISE_TOTAL",
    "COLUMNAR_BATCH_TOTAL",
    "COLUMNAR_ROUTE_TOTAL",
    "SERIAL_BYTES_TOTAL",
    "HOST_OP_SECONDS",
    "SPAN_SECONDS",
    "QUERY_CACHE_TOTAL",
    "QUERY_PLAN_TOTAL",
    "ANALYSIS_FINDINGS_TOTAL",
    "TIMELINE_SPAN_SECONDS",
    "TIMELINE_ANOMALY_TOTAL",
    "STORE_PACK_STAGE_SECONDS",
    "STORE_DELTA_STAGE_SECONDS",
    "QUERY_LATENCY_SECONDS",
    "COLUMNAR_CLASS_SECONDS",
    "DEGRADE_TOTAL",
    "DURABLE_ARTIFACT_BYTES",
    "DURABLE_DEMOTE_TOTAL",
    "DURABLE_EPOCH_COUNT",
    "DURABLE_PENDING_COUNT",
    "DURABLE_PERSIST_BYTES_TOTAL",
    "DURABLE_PERSIST_STAGE_SECONDS",
    "DURABLE_PERSIST_TOTAL",
    "DURABLE_PERSIST_WALL_SECONDS",
    "DURABLE_RECOVERY_TOTAL",
    "BREAKER_TRANSITIONS_TOTAL",
    "RETRY_TOTAL",
    "FAULT_INJECTED_TOTAL",
    "DEADLINE_TOTAL",
    "LOCK_WAIT_SECONDS",
    "COMPILE_TOTAL",
    "HBM_ACCOUNTING_DRIFT_BYTES",
    "DECISION_TOTAL",
    "DECISION_REGRET_SECONDS",
    "DECISION_ERROR_RATIO",
    "OUTCOME_JOIN_TOTAL",
    "OUTCOME_ORPHANS_TOTAL",
    "OUTCOME_ANOMALY_TOTAL",
    "COSTMODEL_DRIFT_RATIO",
    "HEALTH_STATUS",
    "HEALTH_RULE_STATE",
    "HEALTH_ACTUATION_TOTAL",
    "SERVE_LATENCY_SECONDS",
    "SERVE_QPS",
    "SERVE_ADMIT_TOTAL",
    "SERVE_REQUESTS_TOTAL",
    "SERVE_QUEUE_COUNT",
    "SERVE_INFLIGHT_COUNT",
    "SERVE_SATURATION_RATIO",
    "SERVE_TENANT_BYTES",
    "FUSION_BATCH_TOTAL",
    "FUSION_QUERIES_TOTAL",
    "FUSION_STEPS_TOTAL",
    "FUSION_BATCH_SECONDS",
    "FUSION_QUEUED_COUNT",
    "QUERY_INFLIGHT_TOTAL",
    "context",
    "decisions",
    "outcomes",
    "compilewatch",
    "artifacts",
    "health",
    "sentinel",
    "bundle",
    "Rule",
    "RuleState",
    "Sentinel",
    "SENTINEL",
    "trace_scope",
    "adopt",
    "current_trace",
    "new_trace_id",
    "record_decision",
    "DecisionLog",
    "OutcomeLedger",
]
