"""Pipeline timeline tracer: a flight recorder for the marshal path
(ISSUE 6 tentpole).

The metrics registry answers "how much, how many" in aggregate; it cannot
answer *where inside* a 3.4 s pack or an 8.3 s delta repack the time went,
in what order, or on which thread — the question ROADMAP item 1 (the
delta-vs-full-repack inversion) needs answered before anything can be
fixed. This module keeps a thread-safe, bounded ring buffer of structured
trace events (name, category, start/duration in monotonic ns, thread id,
free-form attrs like rows/bytes/cache kind) and exports it as Chrome
trace-event JSON, loadable directly in Perfetto / chrome://tracing.

Three recording modes, chosen by ``RB_TPU_TIMELINE`` (read once at import;
``configure()`` overrides at runtime, e.g. bench.py's traced twin rows):

* **unset / "off"** — recording fully disabled. The instrumented call
  sites reduce to one module-int comparison; no span objects, no events,
  no attrs dicts retained (the <2 % overhead contract, pinned by
  tests/test_timeline.py's zero-overhead check).
* **"on"** — spans and instants record into the ring buffer and feed the
  ``rb_tpu_timeline_span_seconds{cat}`` latency histogram. Device work is
  timed as *dispatched* (async backends may under-attribute).
* **"fenced"** — additionally, ``fence(x)`` calls ``block_until_ready`` on
  device values inside their producing span, so a span's duration is the
  truthful device-inclusive wall time. This perturbs pipelining — it is a
  diagnosis mode, not a production default.

Spans opened with ``trace=True`` also open a
``jax.profiler.TraceAnnotation`` so the same region appears in XProf /
TensorBoard device traces — host flight-recorder spans and device traces
correlate by name (the composition ``observe.spans`` already uses).

``observe.spans.span`` (and therefore every ``tracing.op_timer`` block)
mirrors into the recorder when a mode is active, so pre-existing
instrumentation appears on the timeline for free.

**Dump-on-anomaly**: when a span exceeds the configured budget
(``RB_TPU_TIMELINE_BUDGET_MS`` / ``configure(budget_ms=...)``), the whole
flight recorder flushes to a JSONL artifact (``RB_TPU_TIMELINE_DUMP``,
default ``rb_tpu_timeline_anomaly.jsonl`` inside the unified artifact
sink ``RB_TPU_ARTIFACT_DIR`` — see ``observe.artifacts``; an explicit
path with a directory component is honoured verbatim) — the "what led up
to this" context a post-hoc aggregate can never reconstruct. Dumps are throttled to
one per second so a pathological run cannot turn into an I/O storm;
``rb_tpu_timeline_anomaly_total{cat}`` counts every trigger regardless.

Lock discipline: the recorder lock is a leaf — record() never acquires any
other lock, so call sites holding the pack-cache or registry lock nest
safely over it (witnessed by the tests/test_timeline.py hammer).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from . import context as _context
from . import registry as _registry
from .histogram import latency_histogram

OFF, ON, FENCED = 0, 1, 2
_MODE_NAMES = {"off": OFF, "on": ON, "fenced": FENCED}

DEFAULT_CAPACITY = 65536
DUMP_SCHEMA = "rb_tpu_timeline/1"

_SPAN_SECONDS = latency_histogram(
    _registry.TIMELINE_SPAN_SECONDS,
    "Wall time of flight-recorder timeline spans by category",
    ("cat",),
)
_ANOMALY_TOTAL = _registry.counter(
    _registry.TIMELINE_ANOMALY_TOTAL,
    "Spans that exceeded the timeline anomaly budget and triggered a "
    "flight-recorder dump",
    ("cat",),
)


class TimelineEvent:
    """One recorded event. ``ph`` follows the trace-event format: ``"X"``
    (complete span, has ``dur_ns``), ``"i"`` (instant), or ``"s"``/``"t"``/
    ``"f"`` (flow start/step/finish — producer/consumer links across
    threads; the flow id lives in ``attrs["flow"]``). ``trace`` is the
    query-scoped trace id active when the event was recorded (ISSUE 9) —
    None outside any trace scope."""

    __slots__ = ("name", "cat", "ph", "ts_ns", "dur_ns", "tid", "attrs", "trace")

    def __init__(self, name, cat, ph, ts_ns, dur_ns, tid, attrs, trace=None):
        self.name = name
        self.cat = cat
        self.ph = ph
        self.ts_ns = ts_ns
        self.dur_ns = dur_ns
        self.tid = tid
        self.attrs = attrs
        self.trace = trace

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "cat": self.cat,
            "ph": self.ph,
            "ts_us": self.ts_ns / 1e3,
            "tid": self.tid,
        }
        if self.ph == "X":
            d["dur_us"] = self.dur_ns / 1e3
        if self.trace is not None:
            d["trace"] = self.trace
        if self.attrs:
            d["args"] = dict(self.attrs)
        return d


class FlightRecorder:
    """Bounded ring buffer of :class:`TimelineEvent`. O(1) record under one
    leaf lock; when full, the oldest events are overwritten and counted as
    ``dropped()`` — a flight recorder keeps the *latest* window, which is
    the window that explains an anomaly."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._lock = threading.Lock()
        self._buf: List[Optional[TimelineEvent]] = [None] * int(capacity)  # guarded-by: self._lock
        self._total = 0  # guarded-by: self._lock

    @property
    def capacity(self) -> int:
        return len(self._buf)

    def record(self, ev: TimelineEvent) -> None:
        with self._lock:
            self._buf[self._total % len(self._buf)] = ev
            self._total += 1

    def events(self) -> List[TimelineEvent]:
        """Point-in-time copy in recording (≈ end-time) order."""
        with self._lock:
            n, cap = self._total, len(self._buf)
            if n <= cap:
                return list(self._buf[:n])
            i = n % cap
            return self._buf[i:] + self._buf[:i]

    def __len__(self) -> int:
        with self._lock:
            return min(self._total, len(self._buf))

    def total(self) -> int:
        """Events ever recorded (retained + overwritten)."""
        with self._lock:
            return self._total

    def dropped(self) -> int:
        with self._lock:
            return max(0, self._total - len(self._buf))

    def clear(self) -> None:
        with self._lock:
            self._buf = [None] * len(self._buf)
            self._total = 0

    def resize(self, capacity: int) -> None:
        """Re-bound the buffer, keeping the newest events that fit."""
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        kept = self.events()[-capacity:]
        with self._lock:
            self._buf = kept + [None] * (capacity - len(kept))
            self._total = len(kept)


# The process-wide recorder every instrumented module records into.
RECORDER = FlightRecorder()

# thread-id -> name, refreshed on every record so the Chrome trace carries
# thread_name metadata without growing each event
_THREAD_NAMES: Dict[int, str] = {}  # guarded-by: _STATE_LOCK

_STATE_LOCK = threading.Lock()
_MODE = OFF  # guarded-by: _STATE_LOCK (reads are lock-free int loads)
_BUDGET_NS: Optional[int] = None  # guarded-by: _STATE_LOCK
_DUMP_PATH = "rb_tpu_timeline_anomaly.jsonl"  # guarded-by: _STATE_LOCK
_LAST_DUMP_NS = 0  # guarded-by: _STATE_LOCK
_DUMP_MIN_INTERVAL_NS = 1_000_000_000


def _init_from_env() -> None:
    raw = os.environ.get("RB_TPU_TIMELINE", "").strip().lower()
    if raw in _MODE_NAMES:
        mode = raw
    elif raw in ("", "0", "false", "no"):
        mode = "off"
    else:  # any other truthy value: plain recording
        mode = "on"
    budget = os.environ.get("RB_TPU_TIMELINE_BUDGET_MS")
    cap = os.environ.get("RB_TPU_TIMELINE_CAPACITY")
    configure(
        mode=mode,
        budget_ms=float(budget) if budget else None,
        dump_path=os.environ.get("RB_TPU_TIMELINE_DUMP") or None,
        capacity=int(cap) if cap else None,
    )


def configure(
    mode=None,
    budget_ms: Optional[float] = None,
    dump_path: Optional[str] = None,
    capacity: Optional[int] = None,
) -> None:
    """Runtime override of the env-derived config. ``mode`` accepts
    "off"/"on"/"fenced" or the module constants; ``budget_ms`` <= 0
    disables the anomaly hook; others keep their current value when None."""
    global _MODE, _BUDGET_NS, _DUMP_PATH
    with _STATE_LOCK:
        if mode is not None:
            if isinstance(mode, str):
                if mode not in _MODE_NAMES:
                    raise ValueError(f"unknown timeline mode {mode!r}")
                mode = _MODE_NAMES[mode]
            if mode not in (OFF, ON, FENCED):
                raise ValueError(f"unknown timeline mode {mode!r}")
            _MODE = mode
        if budget_ms is not None:
            _BUDGET_NS = int(budget_ms * 1e6) if budget_ms > 0 else None
        if dump_path is not None:
            _DUMP_PATH = dump_path
    if capacity is not None:
        RECORDER.resize(capacity)


def enabled() -> bool:
    """Is the flight recorder recording at all?"""
    return _MODE != OFF


def fenced() -> bool:
    """Are instrumented sites fencing device values (RB_TPU_TIMELINE=fenced)?"""
    return _MODE == FENCED


def mode_name() -> str:
    return {OFF: "off", ON: "on", FENCED: "fenced"}[_MODE]


def fence(x):
    """``block_until_ready`` on ``x`` when fencing is active — call inside
    the producing span so its duration includes the device work it
    dispatched. No-op (one int compare) in every other mode; returns ``x``
    either way so call sites stay expression-shaped."""
    if _MODE == FENCED and x is not None:
        try:
            x.block_until_ready()
        except AttributeError:  # host value: nothing to fence
            pass
    return x


def register_thread(name: Optional[str] = None) -> None:
    """Eagerly register this thread's display name for the Chrome-trace
    ``thread_name`` metadata (mode-independent). Recording registers names
    lazily as a backstop, but a dedicated worker (the ShipLane pool) must
    register at thread start so it is named from its very first event —
    a bare tid in Perfetto is an attribution dead end (ISSUE 9
    satellite)."""
    tid = threading.get_ident()
    with _STATE_LOCK:
        _THREAD_NAMES[tid] = name or threading.current_thread().name


def thread_names() -> Dict[int, str]:
    """Point-in-time copy of the tid -> display-name registry."""
    with _STATE_LOCK:
        return dict(_THREAD_NAMES)


def _record_complete(name, cat, t0_ns, dur_ns, attrs) -> None:
    tid = threading.get_ident()
    with _STATE_LOCK:
        _THREAD_NAMES[tid] = threading.current_thread().name
        budget = _BUDGET_NS
    RECORDER.record(
        TimelineEvent(
            name, cat, "X", t0_ns, dur_ns, tid, attrs,
            trace=_context.current_trace(),
        )
    )
    _SPAN_SECONDS.observe(dur_ns / 1e9, (cat,))
    if budget is not None and dur_ns > budget:
        _anomaly(name, cat, dur_ns, budget)


def instant(name: str, cat: str = "event", /, **attrs) -> None:
    """Record a zero-duration marker (cache hit/miss/evict, epoch flip).
    ``name``/``cat`` are positional-only so attrs may carry those keys
    (decision inputs are arbitrary key/value pairs)."""
    if _MODE == OFF:
        return
    tid = threading.get_ident()
    with _STATE_LOCK:
        _THREAD_NAMES[tid] = threading.current_thread().name
    RECORDER.record(
        TimelineEvent(
            name, cat, "i", time.perf_counter_ns(), 0, tid, attrs or None,
            trace=_context.current_trace(),
        )
    )


def flow_point(name: str, phase: str, flow_id: int, cat: str = "flow") -> None:
    """Record one flow event: ``phase`` is ``"s"`` (start, at the
    producer), ``"t"`` (step), or ``"f"`` (finish, at the consumer).
    Events sharing a ``flow_id`` render as one arrow chain in Perfetto —
    the cross-thread producer/consumer link (e.g. a query's prefetch
    handoff to the ShipLane and back to its ``overlap_wait``) that
    same-thread nesting cannot express. No-op when recording is off."""
    if _MODE == OFF:
        return
    if phase not in ("s", "t", "f"):
        raise ValueError(f"flow phase must be 's'/'t'/'f', got {phase!r}")
    tid = threading.get_ident()
    with _STATE_LOCK:
        _THREAD_NAMES[tid] = threading.current_thread().name
    RECORDER.record(
        TimelineEvent(
            name, cat, phase, time.perf_counter_ns(), 0, tid,
            {"flow": int(flow_id)}, trace=_context.current_trace(),
        )
    )


def flow_id(*parts) -> int:
    """A stable 32-bit flow id from hashable parts (trace id + handoff
    key): producer and consumer compute the same id independently."""
    import zlib

    return zlib.crc32(repr(parts).encode()) & 0x7FFFFFFF


class _Span:
    """A recording span (only ever constructed while a mode is active)."""

    __slots__ = ("name", "cat", "attrs", "_trace", "_ann", "_t0")

    def __init__(self, name, cat, trace, attrs):
        self.name = name
        self.cat = cat
        self.attrs = attrs
        self._trace = trace
        self._ann = None

    def attr(self, **kw) -> None:
        """Attach attrs discovered mid-span (e.g. the serving epoch a
        request was pinned to, known only after admission) — recorded at
        exit with the rest. Callers must guard for off-mode, where tspan
        returns a span-less null context."""
        if self.attrs:
            self.attrs.update(kw)
        else:
            self.attrs = dict(kw)

    def __enter__(self) -> "_Span":
        if self._trace:
            try:
                import jax

                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except (ImportError, AttributeError):  # jax missing or stripped
                self._ann = None
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter_ns() - self._t0
        if self._ann is not None:
            self._ann.__exit__(*exc)
        _record_complete(self.name, self.cat, self._t0, dur, self.attrs or None)
        return False


_NULL = contextlib.nullcontext()


def tspan(name: str, cat: str = "host", trace: bool = False, **attrs):
    """Context manager timing the enclosed block into the flight recorder.
    Disabled mode returns a shared null context — no span object exists.
    ``trace=True`` additionally opens a ``jax.profiler.TraceAnnotation`` so
    the region correlates with device traces."""
    if _MODE == OFF:
        return _NULL
    return _Span(name, cat, trace, attrs)


class stage:
    """Time one pipeline stage into BOTH a latency histogram (always — an
    ``observe()`` is two dict ops under the registry lock, invisible next
    to millisecond stages) and, when a mode is active, the flight
    recorder. This is the instrumentation primitive the marshal pipeline
    uses: the histogram gives p50/p99 over the run, the recorder gives the
    one-run decomposition."""

    __slots__ = ("_hist", "_labels", "_name", "_cat", "_attrs", "_t0")

    def __init__(self, hist, label, name: Optional[str] = None,
                 cat: str = "stage", **attrs):
        self._hist = hist
        self._labels = (label,) if isinstance(label, str) else tuple(label)
        self._name = name or "/".join(self._labels)
        self._cat = cat
        self._attrs = attrs
        self._t0 = 0

    def __enter__(self) -> "stage":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter_ns() - self._t0
        self._hist.observe(dur / 1e9, self._labels)
        if _MODE != OFF:
            _record_complete(
                self._name, self._cat, self._t0, dur, self._attrs or None
            )
        return False


# ---------------------------------------------------------------------------
# anomaly dump
# ---------------------------------------------------------------------------


def _anomaly(name: str, cat: str, dur_ns: int, budget_ns: int) -> None:
    global _LAST_DUMP_NS
    _ANOMALY_TOTAL.inc(1, (cat,))
    instant(
        "timeline.anomaly", "anomaly",
        span=name, span_cat=cat,
        dur_ms=round(dur_ns / 1e6, 3), budget_ms=round(budget_ns / 1e6, 3),
    )
    now = time.perf_counter_ns()
    with _STATE_LOCK:
        if now - _LAST_DUMP_NS < _DUMP_MIN_INTERVAL_NS and _LAST_DUMP_NS:
            return
        _LAST_DUMP_NS = now
        path = _DUMP_PATH
    trigger = {
        "span": name, "cat": cat,
        "dur_ms": round(dur_ns / 1e6, 3),
        "budget_ms": round(budget_ns / 1e6, 3),
    }
    # snapshot NOW (cheap list copy under the leaf recorder lock), write on
    # a daemon thread: anomalous spans routinely fire while the caller
    # holds a framework lock (the delta stages run under the process-wide
    # PACK_CACHE lock), and blocking file I/O there would turn one slow
    # entry into a process-wide stall
    events = RECORDER.events()
    dropped = RECORDER.dropped()

    def _write():
        try:
            _dump_events(path, events, RECORDER.capacity, dropped, trigger)
        except OSError:  # rb-ok: exception-hygiene -- diagnostics must never kill the instrumented pipeline; the anomaly counter above still recorded the trigger
            pass

    threading.Thread(
        target=_write, name="rb-timeline-dump", daemon=True
    ).start()


def dump_jsonl(
    path: str,
    recorder: Optional[FlightRecorder] = None,
    trigger: Optional[dict] = None,
) -> None:
    """Flush the flight recorder to a JSONL artifact: a header line
    (schema, capacity, dropped count, optional anomaly trigger) followed by
    one event per line in recording order. Atomic write."""
    rec = RECORDER if recorder is None else recorder
    _dump_events(path, rec.events(), rec.capacity, rec.dropped(), trigger)


def _dump_events(path, events, capacity, dropped, trigger) -> None:
    from . import artifacts as _artifacts
    from .export import _atomic_write

    # unified artifact sink (ISSUE 12): a bare-filename dump path (the
    # default) lands in RB_TPU_ARTIFACT_DIR, never loose in the CWD
    path = _artifacts.resolve(path)
    header = {
        "schema": DUMP_SCHEMA,
        "generated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "capacity": capacity,
        "dropped": dropped,
        "events": len(events),
    }
    if trigger is not None:
        header["trigger"] = trigger
    lines = [json.dumps(header, sort_keys=True)]
    lines.extend(json.dumps(e.to_dict(), sort_keys=True) for e in events)
    _atomic_write(path, "\n".join(lines) + "\n")


# ---------------------------------------------------------------------------
# Chrome trace-event / Perfetto export
# ---------------------------------------------------------------------------


def chrome_trace(
    events: Optional[Iterable[TimelineEvent]] = None,
    meta: Optional[dict] = None,
) -> dict:
    """The trace-event-format object (JSON Object Format): ``traceEvents``
    with ``ph: "X"`` complete spans and ``ph: "i"`` instants, ``ts``/``dur``
    in microseconds, plus thread_name metadata — loadable in Perfetto and
    chrome://tracing as-is. ``meta`` lands under ``otherData`` (the format's
    designated extra-info key; bench.py puts its stage-attribution summary
    there)."""
    evs = RECORDER.events() if events is None else list(events)
    pid = os.getpid()
    out: List[dict] = []
    tids = set()
    for e in evs:
        tids.add(e.tid)
        rec = {
            "name": e.name,
            "cat": e.cat,
            "ph": e.ph,
            "pid": pid,
            "tid": e.tid,
            "ts": e.ts_ns / 1e3,
        }
        if e.ph == "X":
            rec["dur"] = e.dur_ns / 1e3
        elif e.ph in ("s", "t", "f"):
            # flow events: the id binds start/step/finish into one arrow;
            # "bp": "e" binds the finish to its enclosing slice
            rec["id"] = (e.attrs or {}).get("flow", 0)
            if e.ph == "f":
                rec["bp"] = "e"
        else:
            rec["s"] = "t"
        args = dict(e.attrs) if e.attrs else {}
        if e.trace is not None:
            args["trace"] = e.trace
        if args:
            rec["args"] = args
        out.append(rec)
    with _STATE_LOCK:
        names = {tid: _THREAD_NAMES.get(tid) for tid in tids}
    for tid in sorted(tids):
        if names.get(tid):
            out.append(
                {
                    "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                    "args": {"name": names[tid]},
                }
            )
    trace = {"displayTimeUnit": "ms", "traceEvents": out}
    if meta is not None:
        trace["otherData"] = meta
    return trace


def write_chrome_trace(
    path: str,
    events: Optional[Iterable[TimelineEvent]] = None,
    meta: Optional[dict] = None,
) -> None:
    from .export import _atomic_write

    _atomic_write(path, json.dumps(chrome_trace(events, meta), indent=1) + "\n")


def stage_totals(
    events: Iterable[TimelineEvent],
    names: Iterable[str],
    per_trace: bool = False,
):
    """Sum complete-span durations (seconds) per stage name, restricted to
    ``names`` — the attribution primitive bench.py uses to check that named
    stages account for >= 90 % of a measured wall clock. The caller picks a
    non-overlapping stage set; nested helper spans are simply not named.

    ``per_trace=True`` keys the sums by the events' query trace ids
    (ISSUE 9): ``{trace_id_or_"": {stage: seconds}}`` — a multi-query run
    decomposes per query (events recorded outside any trace scope land
    under ``""``)."""
    wanted = set(names)
    if not per_trace:
        out: Dict[str, float] = {n: 0.0 for n in wanted}
        for e in events:
            if e.ph == "X" and e.name in wanted:
                out[e.name] += e.dur_ns / 1e9
        return out
    by_trace: Dict[str, Dict[str, float]] = {}
    for e in events:
        if e.ph == "X" and e.name in wanted:
            tr = by_trace.setdefault(e.trace or "", {})
            tr[e.name] = tr.get(e.name, 0.0) + e.dur_ns / 1e9
    return by_trace


_init_from_env()
