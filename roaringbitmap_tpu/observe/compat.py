"""Legacy-facade adapters: the pre-registry module globals
(``pallas_kernels.DISPATCH_COUNTS``, ``store.LAYOUT_COUNTS``,
``store.TRANSFER_BYTES``, ``batch.PAIRWISE_COUNTS``) were
``collections.Counter`` objects that tests and tooling read directly.
``CounterMap`` keeps that mapping interface while storing every value in a
labeled registry ``Counter`` — so ``insights.dispatch_counters()`` and
direct readers see exactly the pre-migration shapes, and the registry
exporters see the same numbers under their canonical metric names.

Writers inside this package go through ``Counter.inc`` (atomic under the
registry lock); ``CounterMap.__setitem__`` exists only so external code
that still does ``COUNTS[key] += 1`` keeps working (that read-modify-write
is exactly as racy as the ``collections.Counter`` it replaces — no worse,
and migrating to ``inc`` fixes it)."""

from __future__ import annotations

from collections.abc import MutableMapping
from typing import Iterator, Tuple, Union

from .registry import Counter

Key = Union[str, Tuple[str, ...]]


class CounterMap(MutableMapping):
    """``collections.Counter``-compatible view over one labeled registry
    Counter. ``scalar=True`` maps bare-string keys onto a single-label
    metric; otherwise keys are tuples aligned with the metric's
    labelnames."""

    def __init__(self, metric: Counter, scalar: bool = False):
        if scalar and len(metric.labelnames) != 1:
            raise ValueError(
                f"scalar CounterMap needs a 1-label metric, "
                f"{metric.name} has {metric.labelnames}"
            )
        self._metric = metric
        self._scalar = scalar

    @property
    def metric(self) -> Counter:
        return self._metric

    def _lv(self, key: Key) -> Tuple[str, ...]:
        return (str(key),) if self._scalar else tuple(str(k) for k in key)

    def _key(self, lv: Tuple[str, ...]) -> Key:
        return lv[0] if self._scalar else lv

    def __getitem__(self, key: Key):
        # Counter semantics: a missing key reads as 0 and is not created
        return self._metric.get(self._lv(key))

    def __setitem__(self, key: Key, value) -> None:
        self._metric.set(value, self._lv(key))

    def __delitem__(self, key: Key) -> None:
        self._metric.remove(self._lv(key))

    def __contains__(self, key) -> bool:
        try:
            lv = self._lv(key)
        except TypeError:
            return False
        return lv in self._metric.series()

    def __iter__(self) -> Iterator[Key]:
        return iter([self._key(lv) for lv in self._metric.series()])

    def __len__(self) -> int:
        return len(self._metric.series())

    def items(self):
        return [(self._key(lv), v) for lv, v in self._metric.series().items()]

    def clear(self) -> None:
        self._metric.clear()

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"CounterMap({self._metric.name}, {dict(self.items())!r})"
