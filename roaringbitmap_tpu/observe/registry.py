"""Thread-safe labeled metrics registry — the single substrate behind every
counter, gauge, and histogram in the framework (ISSUE 1 tentpole).

The reference library externalizes introspection to its ``insights/``
package; the TPU port previously scattered five unrelated module-level
``collections.Counter`` globals across the dispatch layers, with no labels,
no thread safety, and no machine-readable export. This module replaces
that substrate:

* ``Registry`` — named metrics, each a family of label-tuple-keyed series
  guarded by one registry-wide lock (all hot-path mutations are a dict
  update; contention is nanoseconds against dispatch costs of
  microseconds).
* ``Counter`` / ``Gauge`` / ``Histogram`` — the three metric kinds.
  Histograms use fixed upper-bound buckets chosen at registration
  (``DEFAULT_TIME_BUCKETS`` spans 100 µs .. 10 s, the host-phase range).
* ``snapshot()`` / ``reset()`` — a point-in-time plain-dict view of every
  series (what ``observe.export`` serializes) and a values-only clear that
  keeps metric definitions registered.

Naming convention: ``rb_tpu_<layer>_<name>`` (canonical names below) so a
Prometheus scrape of a fleet is groupable by layer. The legacy module
globals (``pallas_kernels.DISPATCH_COUNTS`` etc.) remain importable as
``observe.compat.CounterMap`` views over these metrics — see ``compat.py``.

Pure stdlib: importable before (and without) jax.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple, Union

Number = Union[int, float]
LabelsArg = Union[Sequence[str], Mapping[str, str]]

# canonical metric names, one per instrumented layer (rb_tpu_<layer>_<name>)
KERNEL_DISPATCH_TOTAL = "rb_tpu_kernel_dispatch_total"
KERNEL_PROBE_TOTAL = "rb_tpu_kernel_probe_total"
STORE_LAYOUT_TOTAL = "rb_tpu_store_layout_total"
STORE_TRANSFER_BYTES_TOTAL = "rb_tpu_store_transfer_bytes_total"
STORE_RESIDENT_BYTES = "rb_tpu_store_resident_bytes"
# overlap shipping lane (ISSUE 8): fraction of staged marshal wall hidden
# behind the previous query's compute (0 = fully serial, 1 = fully hidden)
STORE_OVERLAP_RATIO = "rb_tpu_store_overlap_ratio"
PACK_CACHE_HITS_TOTAL = "rb_tpu_pack_cache_hits_total"
PACK_CACHE_MISSES_TOTAL = "rb_tpu_pack_cache_misses_total"
PACK_CACHE_DELTA_ROWS_TOTAL = "rb_tpu_pack_cache_delta_rows_total"
PACK_CACHE_EVICTED_BYTES_TOTAL = "rb_tpu_pack_cache_evicted_bytes_total"
PACK_CACHE_RESIDENT_BYTES = "rb_tpu_pack_cache_resident_bytes"
BATCH_PAIRWISE_TOTAL = "rb_tpu_batch_pairwise_total"
COLUMNAR_BATCH_TOTAL = "rb_tpu_columnar_batch_total"
# columnar cutoff-model verdicts by chosen engine tier (ISSUE 10)
COLUMNAR_ROUTE_TOTAL = "rb_tpu_columnar_route_total"
SERIAL_BYTES_TOTAL = "rb_tpu_serial_bytes_total"
HOST_OP_SECONDS = "rb_tpu_host_op_seconds"
SPAN_SECONDS = "rb_tpu_span_seconds"
QUERY_CACHE_TOTAL = "rb_tpu_query_cache_total"
QUERY_PLAN_TOTAL = "rb_tpu_query_plan_total"
ANALYSIS_FINDINGS_TOTAL = "rb_tpu_analysis_findings_total"
ANALYSIS_CONTRACT_FINDINGS_TOTAL = "rb_tpu_analysis_contract_findings_total"
# timeline / latency instrumentation (ISSUE 6): the flight recorder's span
# feed plus the per-stage latency histograms over the marshal pipeline
TIMELINE_SPAN_SECONDS = "rb_tpu_timeline_span_seconds"
TIMELINE_ANOMALY_TOTAL = "rb_tpu_timeline_anomaly_total"
STORE_PACK_STAGE_SECONDS = "rb_tpu_store_pack_stage_seconds"
STORE_DELTA_STAGE_SECONDS = "rb_tpu_store_delta_stage_seconds"
QUERY_LATENCY_SECONDS = "rb_tpu_query_latency_seconds"
COLUMNAR_CLASS_SECONDS = "rb_tpu_columnar_class_seconds"
# fault model & degradation ladder (ISSUE 7): every degradation, breaker
# transition, retry, injected fault, and deadline outcome is a counter
DEGRADE_TOTAL = "rb_tpu_degrade_total"
BREAKER_TRANSITIONS_TOTAL = "rb_tpu_breaker_transitions_total"
RETRY_TOTAL = "rb_tpu_retry_total"
FAULT_INJECTED_TOTAL = "rb_tpu_fault_injected_total"
DEADLINE_TOTAL = "rb_tpu_deadline_total"
# resource observatory + decision provenance (ISSUE 9): lock-wait
# histograms over the framework locks, jit compile/retrace counts per
# tracked entry point, device-memory accounting drift (gauge vs reality),
# and the decision-log volume per deciding site
LOCK_WAIT_SECONDS = "rb_tpu_lock_wait_seconds"
COMPILE_TOTAL = "rb_tpu_compile_total"
HBM_ACCOUNTING_DRIFT_BYTES = "rb_tpu_hbm_accounting_drift_bytes"
DECISION_TOTAL = "rb_tpu_decision_total"
# decision-outcome ledger (ISSUE 11): per-site routing regret and
# predicted-vs-measured error, join/orphan/anomaly volume, and the
# per-coefficient-cell calibration-drift gauge over the cost model
DECISION_REGRET_SECONDS = "rb_tpu_decision_regret_seconds"
DECISION_ERROR_RATIO = "rb_tpu_decision_error_ratio"
OUTCOME_JOIN_TOTAL = "rb_tpu_outcome_join_total"
OUTCOME_ORPHANS_TOTAL = "rb_tpu_outcome_orphans_total"
OUTCOME_ANOMALY_TOTAL = "rb_tpu_outcome_anomaly_total"
COSTMODEL_DRIFT_RATIO = "rb_tpu_costmodel_drift_ratio"
# health sentinel (ISSUE 12): enum gauges — _status is the process rollup
# (0 green / 1 yellow / 2 red), _state the per-rule level (same encoding);
# the _state/_status suffix marks an enum gauge by convention (the
# metric-naming rule validates it like the _total/_seconds unit suffixes)
HEALTH_STATUS = "rb_tpu_health_status"
HEALTH_RULE_STATE = "rb_tpu_health_rule_state"
# sentinel actuations (auto-refit, alert instants, flight bundles) by
# rule and action kind
HEALTH_ACTUATION_TOTAL = "rb_tpu_health_actuation_total"
# cross-query fusion (ISSUE 13): micro-batch window volume by outcome
# (fused | per-query | degraded), query volume through windows, step fate
# (executed | merged | deduped), batch wall + per-query queue wait
# latency, the live window queue depth, and the in-flight dedup table's
# event volume (lead | join | stale | fail)
FUSION_BATCH_TOTAL = "rb_tpu_fusion_batch_total"
FUSION_QUERIES_TOTAL = "rb_tpu_fusion_queries_total"
FUSION_STEPS_TOTAL = "rb_tpu_fusion_steps_total"
FUSION_BATCH_SECONDS = "rb_tpu_fusion_batch_seconds"
FUSION_QUEUED_COUNT = "rb_tpu_fusion_queued_count"
QUERY_INFLIGHT_TOTAL = "rb_tpu_query_inflight_total"
# tail-latency engineering (ISSUE 19): per-request joint priced
# batch-vs-solo verdicts against the tenant's declared p99 budget
# (window = rode the forming window, solo = hedged solo dispatch through
# the in-flight dedup table), and the live effective window bound the
# serving-p99-pressure actuation auto-tunes from the fusion authority's
# refitted curves
FUSION_HEDGE_TOTAL = "rb_tpu_fusion_hedge_total"
FUSION_WINDOW_COUNT = "rb_tpu_fusion_window_count"
# serving tier (ISSUE 14): per-tenant request latency by phase
# (queue = admission wall incl. any backpressure wait, execute = query
# execution), rolling per-tenant QPS, admission verdicts, live queue
# depth / in-flight gauges, per-tenant token-bucket saturation, and the
# per-tenant byte share of the resident PACK_CACHE working sets. Tenant
# label VALUES come from the bounded declared tenant registry
# (serve/slo.py TENANTS — the metric-naming rule enforces it)
SERVE_LATENCY_SECONDS = "rb_tpu_serve_latency_seconds"
SERVE_QPS = "rb_tpu_serve_qps"
SERVE_ADMIT_TOTAL = "rb_tpu_serve_admit_total"
SERVE_REQUESTS_TOTAL = "rb_tpu_serve_requests_total"
SERVE_QUEUE_COUNT = "rb_tpu_serve_queue_count"
SERVE_INFLIGHT_COUNT = "rb_tpu_serve_inflight_count"
SERVE_SATURATION_RATIO = "rb_tpu_serve_saturation_ratio"
SERVE_TENANT_BYTES = "rb_tpu_serve_tenant_bytes"
# per-tenant declared latency SLO (ISSUE 19): the p99 budget each tenant
# declared with its latency class — exported so the serving-p99-pressure
# rule and the rb_top latency panel judge measured p99 against DECLARED
# budget instead of a blanket threshold
SERVE_SLO_BUDGET_SECONDS = "rb_tpu_serve_slo_budget_seconds"
# epoch ledger / streaming ingestion (ISSUE 15): ingest->queryable lag per
# tenant (observed at epoch publish, per drained mutation batch), flip
# stage decomposition (the declared FLIP_STAGES set in serve/epochs.py:
# drain | repack | publish | reclaim), mutation-batch volume by tenant,
# flip volume by outcome (flipped | aborted | noop), the live mutation-log
# depth gauge (pending batches), and the current epoch id as a gauge
# VALUE. Epoch ids are unbounded and must NEVER be metric label values —
# lineage lives in the epoch ledger and trace/decision attrs (the
# metric-naming rule enforces it, like trace ids and tenant names)
SERVE_FRESHNESS_SECONDS = "rb_tpu_serve_freshness_seconds"
SERVE_FLIP_STAGE_SECONDS = "rb_tpu_serve_flip_stage_seconds"
SERVE_INGEST_TOTAL = "rb_tpu_serve_ingest_total"
SERVE_EPOCH_FLIP_TOTAL = "rb_tpu_serve_epoch_flip_total"
SERVE_MUTLOG_COUNT = "rb_tpu_serve_mutlog_count"
SERVE_EPOCH_COUNT = "rb_tpu_serve_epoch_count"
# structure observatory (ISSUE 16): corpus-shape telemetry maintained
# incrementally at the mutators (observe/structure.py). The census gauge
# counts live containers by format — label VALUES come from the declared
# frozen format set (structure.FORMATS, the Chambi et al. container
# model: array | bitmap | run; the metric-naming rule enforces the
# declared-collection spelling like tenant names). Drift is the ratio of
# actual serialized bytes to the size-rule-optimal bytes (1.0 = every
# container in its cheapest format); fragmentation is the p99
# runs-per-run-container; accretion is the epoch-delta depth (batches
# accreted since the last maintenance pass). The maintenance tier
# (serve/maintain.py) prices every pass (compacted | rode | aborted |
# noop), measures the pass wall, and accounts reclaimed serialized bytes
# plus rewritten chunk keys
STRUCTURE_CONTAINERS = "rb_tpu_structure_containers"
STRUCTURE_DRIFT_RATIO = "rb_tpu_structure_drift_ratio"
STRUCTURE_FRAGMENTATION_COUNT = "rb_tpu_structure_fragmentation_count"
STRUCTURE_ACCRETION_COUNT = "rb_tpu_structure_accretion_count"
STRUCTURE_BYTES = "rb_tpu_structure_bytes"
SERVE_MAINTAIN_TOTAL = "rb_tpu_serve_maintain_total"
SERVE_MAINTAIN_SECONDS = "rb_tpu_serve_maintain_seconds"
SERVE_MAINTAIN_RECLAIMED_BYTES_TOTAL = "rb_tpu_serve_maintain_reclaimed_bytes_total"
SERVE_MAINTAIN_KEYS_TOTAL = "rb_tpu_serve_maintain_keys_total"
# durable epochs (ISSUE 17): the on-disk half of the epoch store
# (durable/). Persist volume by outcome (persisted | skipped = priced
# skip verdict | aborted = fault, epoch stays memory-only), the persist
# stage latency decomposition (the declared durable/store.py
# PERSIST_STAGES set), persisted-artifact bytes written, the newest
# persisted epoch id and artifact size as gauge VALUES (epoch ids are
# unbounded and never label values — the epoch-ledger discipline), the
# persist backlog gauge (published epochs not yet durable — the
# epoch-persist-stall rule's signal), the last completed persist wall,
# recovery volume by outcome (recovered | torn = manifest failed
# verification and was skipped | empty = no complete artifact), and
# eviction demotions by residency rung (mapped = the working set stays
# re-admittable from the persisted map | discard = cold repack on
# return)
DURABLE_PERSIST_TOTAL = "rb_tpu_durable_persist_total"
DURABLE_PERSIST_STAGE_SECONDS = "rb_tpu_durable_persist_stage_seconds"
DURABLE_PERSIST_BYTES_TOTAL = "rb_tpu_durable_persist_bytes_total"
DURABLE_EPOCH_COUNT = "rb_tpu_durable_epoch_count"
DURABLE_ARTIFACT_BYTES = "rb_tpu_durable_artifact_bytes"
DURABLE_PENDING_COUNT = "rb_tpu_durable_pending_count"
DURABLE_PERSIST_WALL_SECONDS = "rb_tpu_durable_persist_wall_seconds"
DURABLE_RECOVERY_TOTAL = "rb_tpu_durable_recovery_total"
DURABLE_DEMOTE_TOTAL = "rb_tpu_durable_demote_total"

# upper bucket bounds (seconds) for wall-time histograms: host phases span
# ~100 µs packing steps to multi-second CPU folds; +Inf is implicit
DEFAULT_TIME_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class MetricError(ValueError):
    """Registration conflict or label mismatch (always a caller bug)."""


class _Metric:
    """Base: a named family of label-tuple-keyed series."""

    kind = "untyped"

    def __init__(self, registry: "Registry", name: str, help: str, labelnames):
        self._lock = registry._lock
        self.name = name
        self.help = help
        self.labelnames: Tuple[str, ...] = tuple(labelnames)
        self._series: Dict[Tuple[str, ...], object] = {}  # guarded-by: self._lock

    def _labels_tuple(self, labels: LabelsArg) -> Tuple[str, ...]:
        if isinstance(labels, Mapping):
            if set(labels) != set(self.labelnames):
                raise MetricError(
                    f"{self.name}: expected labels {self.labelnames}, "
                    f"got {sorted(labels)}"
                )
            labels = [labels[n] for n in self.labelnames]
        vals = tuple(str(v) for v in labels)
        if len(vals) != len(self.labelnames):
            raise MetricError(
                f"{self.name}: expected {len(self.labelnames)} label value(s) "
                f"{self.labelnames}, got {vals!r}"
            )
        return vals

    def clear(self) -> None:
        """Drop every series (values AND label sets); the metric definition
        stays registered."""
        with self._lock:
            self._series.clear()

    def series(self) -> Dict[Tuple[str, ...], object]:
        """Point-in-time copy: {labelvalues: value-or-state-dict}."""
        with self._lock:
            return {
                k: (dict(v) if isinstance(v, dict) else v)
                for k, v in self._series.items()
            }

    def _same_definition(self, other: "_Metric") -> bool:
        return type(self) is type(other) and self.labelnames == other.labelnames


class Counter(_Metric):
    """Monotonic labeled counter. ``set``/``remove`` exist only for the
    legacy Counter-dict facade (observe/compat.py) — new code uses ``inc``,
    which is atomic under the registry lock."""

    kind = "counter"

    def inc(self, amount: Number = 1, labels: LabelsArg = ()) -> None:
        if amount < 0:
            raise MetricError(f"{self.name}: counters only go up (inc {amount})")
        lv = self._labels_tuple(labels)
        with self._lock:
            self._series[lv] = self._series.get(lv, 0) + amount

    def get(self, labels: LabelsArg = ()) -> Number:
        lv = self._labels_tuple(labels)
        with self._lock:
            return self._series.get(lv, 0)

    def set(self, value: Number, labels: LabelsArg = ()) -> None:
        lv = self._labels_tuple(labels)
        with self._lock:
            self._series[lv] = value

    def remove(self, labels: LabelsArg) -> None:
        lv = self._labels_tuple(labels)
        with self._lock:
            self._series.pop(lv, None)


class Gauge(_Metric):
    """Labeled gauge: goes up and down (resident-bytes accounting)."""

    kind = "gauge"

    def set(self, value: Number, labels: LabelsArg = ()) -> None:
        lv = self._labels_tuple(labels)
        with self._lock:
            self._series[lv] = value

    def inc(self, amount: Number = 1, labels: LabelsArg = ()) -> None:
        lv = self._labels_tuple(labels)
        with self._lock:
            self._series[lv] = self._series.get(lv, 0) + amount

    def dec(self, amount: Number = 1, labels: LabelsArg = ()) -> None:
        self.inc(-amount, labels)

    def get(self, labels: LabelsArg = ()) -> Number:
        lv = self._labels_tuple(labels)
        with self._lock:
            return self._series.get(lv, 0)


class Histogram(_Metric):
    """Fixed-bucket labeled histogram. Per series: observation count, sum,
    and one slot per upper bound plus the implicit +Inf overflow slot
    (slots are per-bucket internally; exporters emit the cumulative
    Prometheus ``le`` form)."""

    kind = "histogram"

    def __init__(self, registry, name, help, labelnames, buckets=DEFAULT_TIME_BUCKETS):
        super().__init__(registry, name, help, labelnames)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise MetricError(f"{name}: histogram needs at least one bucket bound")
        if len(set(bs)) != len(bs):
            raise MetricError(f"{name}: duplicate bucket bounds {bs}")
        self.buckets: Tuple[float, ...] = bs

    def observe(self, value: Number, labels: LabelsArg = ()) -> None:
        lv = self._labels_tuple(labels)
        v = float(value)
        with self._lock:
            st = self._series.get(lv)
            if st is None:
                st = self._series[lv] = {
                    "count": 0,
                    "sum": 0.0,
                    "slots": [0] * (len(self.buckets) + 1),
                }
            st["count"] += 1
            st["sum"] += v
            st["slots"][bisect.bisect_left(self.buckets, v)] += 1

    def get(self, labels: LabelsArg = ()) -> Optional[dict]:
        lv = self._labels_tuple(labels)
        with self._lock:
            st = self._series.get(lv)
            return None if st is None else {**st, "slots": list(st["slots"])}

    def series(self) -> Dict[Tuple[str, ...], dict]:
        with self._lock:
            return {
                k: {**st, "slots": list(st["slots"])}
                for k, st in self._series.items()
            }

    def _sample_dict(self, st: dict) -> dict:
        """The snapshot sample for one series state: count/sum plus the
        cumulative Prometheus ``le`` bucket map. Subclasses (the latency
        histogram) extend this — snapshot() delegates here so every
        exporter sees their extra keys with no exporter changes."""
        cum, buckets = 0, {}
        for le, n in zip(self.buckets, st["slots"]):
            cum += n
            buckets[format_le(le)] = cum
        buckets["+Inf"] = st["count"]
        return {"count": st["count"], "sum": st["sum"], "buckets": buckets}

    def _same_definition(self, other) -> bool:
        return super()._same_definition(other) and self.buckets == other.buckets


class Registry:
    """Named metric registry. Registration is idempotent for an identical
    definition and loud (MetricError) for a conflicting one — a silent
    re-type would corrupt every exporter downstream."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}  # guarded-by: self._lock

    def _register(self, cls, name: str, help: str, labelnames, **kw) -> _Metric:
        if not name.replace("_", "").replace(":", "").isalnum() or name[0].isdigit():
            raise MetricError(f"invalid metric name {name!r}")
        candidate = cls(self, name, help, labelnames, **kw)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not existing._same_definition(candidate):
                    raise MetricError(
                        f"metric {name!r} already registered as {existing.kind} "
                        f"with labels {existing.labelnames}"
                    )
                return existing
            self._metrics[name] = candidate
            return candidate

    def counter(self, name: str, help: str = "", labelnames=()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(
        self, name: str, help: str = "", labelnames=(), buckets=DEFAULT_TIME_BUCKETS
    ) -> Histogram:
        return self._register(Histogram, name, help, labelnames, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def snapshot(self) -> dict:
        """{name: {type, help, labelnames, samples: [...]}} — plain dicts
        only, directly json.dump-able. Counter/gauge samples carry
        ``value``; histogram samples carry ``count``/``sum`` and the
        cumulative ``buckets`` {le: count} map (Prometheus semantics)."""
        out: dict = {}
        for m in self.metrics():
            samples = []
            for lv, st in sorted(m.series().items()):
                labels = dict(zip(m.labelnames, lv))
                if isinstance(m, Histogram):
                    samples.append({"labels": labels, **m._sample_dict(st)})
                else:
                    samples.append({"labels": labels, "value": st})
            out[m.name] = {
                "type": m.kind,
                "help": m.help,
                "labelnames": list(m.labelnames),
                "samples": samples,
            }
        return out

    def reset(self) -> None:
        """Clear every series; metric definitions stay registered."""
        for m in self.metrics():
            m.clear()


def format_le(bound: float) -> str:
    """Prometheus bucket-bound formatting: integral bounds render without a
    trailing .0 ("1" not "1.0"), matching client_python."""
    if bound == int(bound):
        return str(int(bound))
    return repr(bound)


# The process-wide default registry every instrumented module registers on.
REGISTRY = Registry()


def counter(name: str, help: str = "", labelnames=()) -> Counter:
    return REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "", labelnames=()) -> Gauge:
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name: str, help: str = "", labelnames=(), buckets=DEFAULT_TIME_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, labelnames, buckets=buckets)


def snapshot() -> dict:
    return REGISTRY.snapshot()


def reset() -> None:
    REGISTRY.reset()
