"""One-call exporters for the metrics registry: JSONL, Prometheus text
exposition, and the bench/CI metrics sidecar.

* ``jsonl_lines()`` / ``write_jsonl(path)`` — one JSON object per line per
  series (counters/gauges carry ``value``; histograms carry ``count``,
  ``sum``, and cumulative ``buckets``). The shape log shippers ingest
  without a schema.
* ``prometheus_text()`` / ``write_prometheus(path)`` — the Prometheus
  text exposition format (``# HELP``/``# TYPE`` + samples; histograms as
  ``_bucket{le=...}``/``_sum``/``_count``), scrapeable by a node exporter
  textfile collector or pushgateway.
* ``sidecar_snapshot()`` / ``metrics_sidecar(path)`` — the structured
  summary bench.py drops next to its result line (BENCH_METRICS.json):
  top-level ``kernel``/``layout``/``transfer_bytes``/``spans`` keys (the
  contract scripts/ci.sh validates) plus the full registry snapshot. The
  context manager writes atomically (tmp file + os.replace) on exit, even
  when the enclosed block raises — a crashed bench still leaves its
  telemetry behind.

Everything is a pure function of a ``Registry`` (default: the process
registry), so golden-format tests run against a private registry.
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
import time
from typing import Iterator, List, Optional

from . import registry as _registry
from .histogram import SNAPSHOT_QUANTILES, LatencyHistogram
from .registry import Histogram, Registry, format_le

SIDECAR_SCHEMA = "rb_tpu_metrics/1"


def _reg(registry: Optional[Registry]) -> Registry:
    return _registry.REGISTRY if registry is None else registry


# ---------------------------------------------------------------------------
# JSONL
# ---------------------------------------------------------------------------


def jsonl_lines(registry: Optional[Registry] = None) -> List[str]:
    """One compact JSON object per metric series, in sorted name order."""
    lines = []
    for name, m in sorted(_reg(registry).snapshot().items()):
        for s in m["samples"]:
            rec = {"name": name, "type": m["type"], "labels": s["labels"]}
            if m["type"] == "histogram":
                rec.update(count=s["count"], sum=s["sum"], buckets=s["buckets"])
                if "quantiles" in s:  # latency histograms publish p50/p90/p99
                    rec["quantiles"] = s["quantiles"]
            else:
                rec["value"] = s["value"]
            lines.append(json.dumps(rec, sort_keys=True))
    return lines


def to_jsonl(registry: Optional[Registry] = None) -> str:
    lines = jsonl_lines(registry)
    return "\n".join(lines) + ("\n" if lines else "")


def write_jsonl(path: str, registry: Optional[Registry] = None) -> None:
    _atomic_write(path, to_jsonl(registry))


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(labels: dict, extra: Optional[str] = None) -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in labels.items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text(registry: Optional[Registry] = None) -> str:
    """The text exposition format, empty-series metrics included (HELP/TYPE
    only) so a scrape always shows what *could* be reported."""
    out: List[str] = []
    for m in _reg(registry).metrics():
        out.append(f"# HELP {m.name} {_escape_help(m.help)}")
        out.append(f"# TYPE {m.name} {m.kind}")
        if isinstance(m, Histogram):
            for lv, st in sorted(m.series().items()):
                labels = dict(zip(m.labelnames, lv))
                cum = 0
                for le, n in zip(m.buckets, st["slots"]):
                    cum += n
                    le_attr = 'le="%s"' % format_le(le)
                    out.append(f"{m.name}_bucket{_label_str(labels, le_attr)} {cum}")
                inf_attr = 'le="+Inf"'
                out.append(
                    f"{m.name}_bucket{_label_str(labels, inf_attr)} {st['count']}"
                )
                out.append(f"{m.name}_sum{_label_str(labels)} {st['sum']}")
                out.append(f"{m.name}_count{_label_str(labels)} {st['count']}")
                if isinstance(m, LatencyHistogram):
                    # summary-style quantile convenience samples next to the
                    # buckets (our own exporter's extension; scrapers that
                    # only understand TYPE histogram ignore them)
                    for q in SNAPSHOT_QUANTILES:
                        q_attr = 'quantile="%g"' % q
                        out.append(
                            f"{m.name}{_label_str(labels, q_attr)} "
                            f"{m._quantile_of_state(st, q)}"
                        )
        else:
            for lv, v in sorted(m.series().items()):
                labels = dict(zip(m.labelnames, lv))
                out.append(f"{m.name}{_label_str(labels)} {v}")
    return "\n".join(out) + ("\n" if out else "")


def write_prometheus(path: str, registry: Optional[Registry] = None) -> None:
    _atomic_write(path, prometheus_text(registry))


# ---------------------------------------------------------------------------
# bench/CI sidecar
# ---------------------------------------------------------------------------


def _counter_map(snap: dict, name: str, joined: bool = False) -> dict:
    """Flatten one counter's samples to {key: value}; multi-label keys are
    /-joined (the legacy ``insights.dispatch_counters()`` rendering)."""
    m = snap.get(name)
    if m is None:
        return {}
    out = {}
    for s in m["samples"]:
        vals = [s["labels"][n] for n in m["labelnames"]]
        key = "/".join(vals) if (joined or len(vals) != 1) else vals[0]
        out[key] = s["value"]
    return out


def _histogram_timings(snap: dict, name: str) -> dict:
    m = snap.get(name)
    if m is None:
        return {}
    out = {}
    for s in m["samples"]:
        c, total = s["count"], s["sum"]
        key = "/".join(s["labels"][n] for n in m["labelnames"])
        out[key] = {
            "count": c,
            "total_s": round(total, 6),
            "mean_ms": round(total / c * 1e3, 3) if c else 0.0,
        }
    return out


def _latency_summaries(registry: Registry) -> dict:
    """{metric: {label-values (/-joined): {count, sum, p50, p90, p99}}} for
    every latency histogram — the sidecar's quantile table (the schema gate
    in scripts/ci.sh checks the pack/delta stage rows here)."""
    out: dict = {}
    for m in registry.metrics():
        if not isinstance(m, LatencyHistogram):
            continue
        series = {}
        for lv, st in sorted(m.series().items()):
            series["/".join(lv)] = {
                "count": st["count"],
                "sum": round(st["sum"], 6),
                **{
                    "p%g" % (q * 100): round(m._quantile_of_state(st, q), 6)
                    for q in SNAPSHOT_QUANTILES
                },
            }
        out[m.name] = series
    return out


def _regret_block(snap: dict, registry: Registry) -> dict:
    """The decision-outcome ledger's sidecar block (ISSUE 11): per-site
    regret totals + error-ratio quantiles derived from the registry
    histograms, join/orphan/anomaly volume, and the per-coefficient-cell
    drift gauges — a pure function of the registry (like everything in
    the sidecar), so a ``--from`` rendering needs no live process."""
    sites: dict = {}
    regret = registry.get(_registry.DECISION_REGRET_SECONDS)
    if isinstance(regret, LatencyHistogram):
        for lv, st in sorted(regret.series().items()):
            sites.setdefault("/".join(lv), {}).update(
                regret_events=st["count"],
                regret_s=round(st["sum"], 6),
            )
    err = snap.get(_registry.DECISION_ERROR_RATIO)
    if err is not None:
        err_m = registry.get(_registry.DECISION_ERROR_RATIO)
        for lv, st in sorted(err_m.series().items()):
            c = st["count"]
            sites.setdefault("/".join(lv), {}).update(
                error_samples=c,
                error_ratio_mean=round(st["sum"] / c, 4) if c else None,
            )
    return {
        "sites": sites,
        "joins": _counter_map(snap, _registry.OUTCOME_JOIN_TOTAL),
        "orphans": _counter_map(snap, _registry.OUTCOME_ORPHANS_TOTAL),
        "anomalies": _counter_map(snap, _registry.OUTCOME_ANOMALY_TOTAL),
        "drift": _counter_map(snap, _registry.COSTMODEL_DRIFT_RATIO, joined=True),
    }


def _fusion_block(snap: dict) -> dict:
    """The cross-query fusion sidecar block (ISSUE 13), derived purely
    from the registry like the regret/health blocks: window volume by
    outcome, queries through windows, step fates, the derived window
    occupancy (queries per drained window) and shared-subexpression hit
    ratio (deduped / planned), in-flight dedup joins, and the live queue
    depth — the rb_top fusion panel's ``--from`` data."""
    batches = _counter_map(snap, _registry.FUSION_BATCH_TOTAL)
    steps = _counter_map(snap, _registry.FUSION_STEPS_TOTAL)
    queries = 0.0
    m = snap.get(_registry.FUSION_QUERIES_TOTAL)
    if m is not None:
        queries = float(sum(s.get("value", 0) for s in m["samples"]))
    depth = None
    g = snap.get(_registry.FUSION_QUEUED_COUNT)
    if g is not None:
        for s in g["samples"]:
            if not s["labels"]:
                depth = s["value"]
    window = None
    g = snap.get(_registry.FUSION_WINDOW_COUNT)
    if g is not None:
        for s in g["samples"]:
            if not s["labels"]:
                window = s["value"]
    n_batches = float(sum(batches.values()))
    executed = float(steps.get("executed", 0))
    deduped = float(steps.get("deduped", 0))
    planned = executed + deduped
    # hedge verdict volume (ISSUE 19): solo = hedged solo dispatches,
    # window = priced window verdicts; the rate is solo over all verdicts
    hedges = _counter_map(snap, _registry.FUSION_HEDGE_TOTAL)
    verdicts = float(sum(hedges.values()))
    return {
        "batches": batches,
        "queries": queries,
        "steps": steps,
        "occupancy": round(queries / n_batches, 3) if n_batches else None,
        "dedup_hit_ratio": round(deduped / planned, 4) if planned else None,
        "inflight": _counter_map(snap, _registry.QUERY_INFLIGHT_TOTAL),
        "queue_depth": depth,
        "hedges": hedges,
        "hedge_rate": round(float(hedges.get("solo", 0)) / verdicts, 4)
        if verdicts else None,
        "window": window,
    }


def _serving_block(snap: dict, registry: Registry) -> dict:
    """The serving tier's sidecar block (ISSUE 14), derived PURELY from
    the registry like the regret/health/fusion blocks so a ``--from``
    rendering needs no live process: per-tenant rolling QPS gauges,
    latency p50/p99 per (tenant, phase), admission verdict volume, the
    live queue/in-flight depth gauges, per-tenant saturation, and the
    per-tenant PACK_CACHE byte shares."""
    tenants: dict = {}
    lat = registry.get(_registry.SERVE_LATENCY_SECONDS)
    if isinstance(lat, LatencyHistogram):
        for lv, st in sorted(lat.series().items()):
            tenant, phase = lv
            tenants.setdefault(tenant, {}).setdefault("latency", {})[phase] = {
                "count": st["count"],
                **{
                    "p%g" % (q * 100): round(lat._quantile_of_state(st, q), 6)
                    for q in SNAPSHOT_QUANTILES
                },
            }
    for name, key in (
        (_registry.SERVE_QPS, "qps"),
        (_registry.SERVE_SATURATION_RATIO, "saturation"),
        (_registry.SERVE_TENANT_BYTES, "bytes"),
        # declared p99 budget (ISSUE 19): the latency-class contract the
        # pressure rule and the rb_top latency panel judge p99 against
        (_registry.SERVE_SLO_BUDGET_SECONDS, "slo_budget_s"),
    ):
        m = snap.get(name)
        if m is None:
            continue
        for s in m["samples"]:
            tenant = s["labels"].get("tenant")
            if tenant is not None:
                tenants.setdefault(tenant, {})[key] = s["value"]
    def _gauge(name):
        m = snap.get(name)
        if m is not None:
            for s in m["samples"]:
                if not s["labels"]:
                    return s["value"]
        return None
    return {
        "tenants": tenants,
        "admit": _counter_map(snap, _registry.SERVE_ADMIT_TOTAL, joined=True),
        "requests": _counter_map(snap, _registry.SERVE_REQUESTS_TOTAL, joined=True),
        "queue_depth": _gauge(_registry.SERVE_QUEUE_COUNT),
        "inflight": _gauge(_registry.SERVE_INFLIGHT_COUNT),
    }


def _epochs_block(snap: dict, registry: Registry) -> dict:
    """The epoch ledger's sidecar block (ISSUE 15), derived PURELY from
    the registry like the serving/fusion blocks so a ``--from`` rendering
    needs no live process: the current epoch gauge, live mutation-log
    depth, flip volume by outcome, per-tenant freshness p50/p99
    (ingest->queryable lag), ingest batch volume by tenant, and the flip
    stage latency decomposition. Epoch LINEAGE is process-local (the
    EpochStore's bounded ledger) and rides ``insights.epochs()`` /
    flight bundles, never the registry — epoch ids are unbounded and
    must not mint series."""
    freshness: dict = {}
    fr = registry.get(_registry.SERVE_FRESHNESS_SECONDS)
    if isinstance(fr, LatencyHistogram):
        for lv, st in sorted(fr.series().items()):
            freshness[lv[0]] = {
                "count": st["count"],
                **{
                    "p%g" % (q * 100): round(fr._quantile_of_state(st, q), 6)
                    for q in SNAPSHOT_QUANTILES
                },
            }
    stages: dict = {}
    fs = registry.get(_registry.SERVE_FLIP_STAGE_SECONDS)
    if isinstance(fs, LatencyHistogram):
        for lv, st in sorted(fs.series().items()):
            stages[lv[0]] = {
                "count": st["count"],
                "sum": round(st["sum"], 6),
                "p99": round(fs._quantile_of_state(st, 0.99), 6),
            }
    def _gauge(name):
        m = snap.get(name)
        if m is not None:
            for s in m["samples"]:
                if not s["labels"]:
                    return s["value"]
        return None
    return {
        "epoch": _gauge(_registry.SERVE_EPOCH_COUNT),
        "mutlog_depth": _gauge(_registry.SERVE_MUTLOG_COUNT),
        "flips": _counter_map(snap, _registry.SERVE_EPOCH_FLIP_TOTAL),
        "ingest": _counter_map(snap, _registry.SERVE_INGEST_TOTAL),
        "freshness": freshness,
        "flip_stages": stages,
    }


def _structure_block(snap: dict) -> dict:
    """The structure observatory's sidecar block (ISSUE 16), derived
    PURELY from the registry like every block here: the container-format
    census, actual/optimal serialized bytes + drift ratio, the run
    fragmentation p99 and epoch-delta accretion depth gauges, and the
    maintenance tier's volume (passes by outcome, reclaimed bytes,
    rewritten keys, pass wall time) — the rb_top structure panel's
    ``--from`` data."""
    def _gauge(name):
        m = snap.get(name)
        if m is not None:
            for s in m["samples"]:
                if not s["labels"]:
                    return s["value"]
        return None
    bytes_by_kind = _counter_map(snap, _registry.STRUCTURE_BYTES)
    wall = None
    m = snap.get(_registry.SERVE_MAINTAIN_SECONDS)
    if m is not None:
        for s in m["samples"]:
            if not s["labels"]:
                wall = {"count": s["count"], "sum": round(s["sum"], 6)}
    return {
        "containers": _counter_map(snap, _registry.STRUCTURE_CONTAINERS),
        "bytes": bytes_by_kind,
        "drift_ratio": _gauge(_registry.STRUCTURE_DRIFT_RATIO),
        "fragmentation_p99": _gauge(_registry.STRUCTURE_FRAGMENTATION_COUNT),
        "accretion_depth": _gauge(_registry.STRUCTURE_ACCRETION_COUNT),
        "passes": _counter_map(snap, _registry.SERVE_MAINTAIN_TOTAL),
        "reclaimed_bytes": _gauge(_registry.SERVE_MAINTAIN_RECLAIMED_BYTES_TOTAL),
        "rewritten_keys": _gauge(_registry.SERVE_MAINTAIN_KEYS_TOTAL),
        "pass_wall": wall,
    }


def _durable_block(snap: dict) -> dict:
    """The durable-epoch sidecar block (ISSUE 17), derived PURELY from
    the registry like every block here: the last persisted vs serving
    epoch, the frozen artifact's bytes, persist volume by outcome +
    cumulative bytes, the persist stage latency decomposition, pending
    (unpersisted) epoch depth, last persist wall seconds, recovery
    outcome volume, and residency demotions by rung. Recovery
    PROVENANCE (which directory, torn-skip list) is process-local and
    rides ``insights.durable()`` / flight bundles, never the registry —
    paths are unbounded label values and must not mint series."""
    def _gauge(name):
        m = snap.get(name)
        if m is not None:
            for s in m["samples"]:
                if not s["labels"]:
                    return s["value"]
        return None
    stages: dict = {}
    m = snap.get(_registry.DURABLE_PERSIST_STAGE_SECONDS)
    if m is not None:
        for s in m["samples"]:
            if s["labels"]:
                stages[s["labels"]["stage"]] = {
                    "count": s["count"],
                    "sum": round(s["sum"], 6),
                }
    return {
        "epoch": _gauge(_registry.DURABLE_EPOCH_COUNT),
        "serving_epoch": _gauge(_registry.SERVE_EPOCH_COUNT),
        "pending_epochs": _gauge(_registry.DURABLE_PENDING_COUNT),
        "artifact_bytes": _gauge(_registry.DURABLE_ARTIFACT_BYTES),
        "persist_wall_s": _gauge(_registry.DURABLE_PERSIST_WALL_SECONDS),
        "persists": _counter_map(snap, _registry.DURABLE_PERSIST_TOTAL),
        "persist_bytes": _gauge(_registry.DURABLE_PERSIST_BYTES_TOTAL),
        "persist_stages": stages,
        "recoveries": _counter_map(snap, _registry.DURABLE_RECOVERY_TOTAL),
        "demotions": _counter_map(snap, _registry.DURABLE_DEMOTE_TOTAL),
    }


def _analysis_block(snap: dict) -> dict:
    """The static-analysis sidecar block (ISSUE 18), derived PURELY from
    the registry like every block here: per-rule finding counts from the
    two analyzer tiers — ``rb_tpu_analysis_findings_total{rule}`` (the
    lexical per-file rules) and
    ``rb_tpu_analysis_contract_findings_total{rule}`` (the whole-program
    contract tier). scripts/analyze.py materializes a zero series for
    every rule it ran, so an empty map means "analyzer never ran in this
    process" while an explicit ``{rule: 0}`` means "ran and found
    nothing" — rb_top's analysis panel leans on that distinction."""
    lexical = _counter_map(snap, _registry.ANALYSIS_FINDINGS_TOTAL)
    contracts = _counter_map(snap, _registry.ANALYSIS_CONTRACT_FINDINGS_TOTAL)
    return {
        "lexical": lexical,
        "contracts": contracts,
        "total": int(sum(lexical.values()) + sum(contracts.values())),
    }


def _health_block(snap: dict) -> dict:
    """The health sentinel's sidecar block (ISSUE 12), derived PURELY
    from the registry gauges (like the regret block) so a ``--from``
    rendering needs no live sentinel: the process status enum, per-rule
    state enums, and the actuation counters. ``status`` is None when no
    sentinel tick ever exported (the gauge has no unlabeled series)."""
    status = None
    m = snap.get(_registry.HEALTH_STATUS)
    if m is not None:
        for s in m["samples"]:
            if not s["labels"]:
                status = s["value"]
    names = {0: "green", 1: "yellow", 2: "red"}
    return {
        "status": status,
        "status_name": names.get(status),
        "rules": _counter_map(snap, _registry.HEALTH_RULE_STATE),
        "actuations": _counter_map(
            snap, _registry.HEALTH_ACTUATION_TOTAL, joined=True
        ),
    }


def sidecar_snapshot(registry: Optional[Registry] = None) -> dict:
    """The structured summary the bench sidecar persists. Top-level keys
    ``kernel``/``layout``/``transfer_bytes``/``spans`` are the contract
    scripts/ci.sh enforces; the full registry snapshot rides along under
    ``registry`` for anything the summary flattens away."""
    snap = _reg(registry).snapshot()
    return {
        "schema": SIDECAR_SCHEMA,
        "generated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "kernel": _counter_map(snap, _registry.KERNEL_DISPATCH_TOTAL, joined=True),
        "layout": _counter_map(snap, _registry.STORE_LAYOUT_TOTAL),
        "transfer_bytes": _counter_map(snap, _registry.STORE_TRANSFER_BYTES_TOTAL),
        "pairwise": _counter_map(snap, _registry.BATCH_PAIRWISE_TOTAL),
        "serial_bytes": _counter_map(snap, _registry.SERIAL_BYTES_TOTAL),
        "probes": _counter_map(snap, _registry.KERNEL_PROBE_TOTAL, joined=True),
        "timings": _histogram_timings(snap, _registry.HOST_OP_SECONDS),
        "spans": _histogram_timings(snap, _registry.SPAN_SECONDS),
        "latency": _latency_summaries(_reg(registry)),
        # resource observatory (ISSUE 9): lock-wait totals (quantiles ride
        # in the latency block above), per-fn compile/retrace counts, the
        # device-memory accounting drift gauges, and decision volume —
        # the blocks scripts/ci.sh gates next to the pack/delta rows
        "lock_wait": _histogram_timings(snap, _registry.LOCK_WAIT_SECONDS),
        "compile": _counter_map(snap, _registry.COMPILE_TOTAL),
        "hbm_drift": _counter_map(snap, _registry.HBM_ACCOUNTING_DRIFT_BYTES),
        "decisions": _counter_map(snap, _registry.DECISION_TOTAL),
        # decision-outcome ledger (ISSUE 11): per-site regret + error
        # ratios, join/orphan/anomaly volume, coefficient drift
        "regret": _regret_block(snap, _reg(registry)),
        # health sentinel (ISSUE 12): the status/rule-state enum gauges
        # and actuation counters, registry-derived like everything here
        "health": _health_block(snap),
        # cross-query fusion (ISSUE 13): window/step volume, occupancy,
        # shared-subexpression hit ratio, in-flight dedup joins
        "fusion": _fusion_block(snap),
        # serving tier (ISSUE 14): per-tenant QPS/p50/p99, admission
        # verdicts, queue/in-flight depth, saturation, byte shares
        "serving": _serving_block(snap, _reg(registry)),
        # epoch ledger (ISSUE 15): current epoch, mutation-log depth,
        # flip volume + stage decomposition, per-tenant freshness
        "epochs": _epochs_block(snap, _reg(registry)),
        # structure observatory (ISSUE 16): container-format census,
        # bytes-vs-optimal drift, fragmentation/accretion gauges, and
        # the maintenance tier's pass volume + reclaimed bytes
        "structure": _structure_block(snap),
        # durable epochs (ISSUE 17): persisted vs serving epoch, artifact
        # bytes, persist outcome/stage volume, recovery + demotion volume
        "durable": _durable_block(snap),
        # static analysis (ISSUE 18): per-rule finding counts from the
        # lexical and whole-program contract tiers of scripts/analyze.py
        "analysis": _analysis_block(snap),
        "registry": snap,
    }


@contextlib.contextmanager
def metrics_sidecar(path: str, registry: Optional[Registry] = None) -> Iterator[str]:
    """Atomically write ``sidecar_snapshot()`` to ``path`` when the block
    exits — success OR failure, so crashed runs keep their telemetry."""
    try:
        yield path
    finally:
        _atomic_write(path, json.dumps(sidecar_snapshot(registry), indent=1) + "\n")


def _atomic_write(path: str, content: str) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".metrics.", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(content)
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise
