"""Query-scoped trace context (ISSUE 9 tentpole, leg 1).

Every top-level pipeline entry — a facade fold, ``query.execute``, a
pipelined batch — opens a **trace scope**: a process-unique trace id
carried in a :mod:`contextvars` variable for the dynamic extent of the
query. Everything recorded underneath (flight-recorder spans and
instants, decision-log entries) picks the id up automatically, so a
multi-query run decomposes per query instead of smearing into one
aggregate — the attribution ROADMAP item 3's concurrent serving traffic
needs *before* it exists, because it cannot be retrofitted onto
interleaved telemetry.

Rules:

* a ``trace_scope()`` opened while another is active **reuses** the
  ambient id (a query's internal engine calls are the same query);
  passing an explicit id pins it (the pipelined drivers pre-assign ids so
  query i+1's prefetch work is attributed to query i+1, not to the query
  that happened to drive the prefetch);
* contextvars do NOT cross thread boundaries — worker threads (the
  overlap lane, thread pools) receive the id by **explicit handoff**:
  the submitter captures ``current_trace()`` into the job, the worker
  wraps its work in ``adopt(trace_id)``. Implicit inheritance would be a
  lie on a pooled thread (the pool predates the query);
* ids are process-unique monotonic tokens (``q<serial hex>``), not
  UUIDs: cheap to mint, fine to correlate within one process/artifact,
  and deliberately **never** used as a metric label (the metric-naming
  rule rejects unbounded-cardinality label values — trace ids live on
  events and decisions, which are bounded rings).

Off-mode cost: ``current_trace()`` is one module-bool check plus a C
``ContextVar.get``; ``configure(enabled=False)`` (the bench's
everything-off twin row) short-circuits to ``None`` before the get.
"""

from __future__ import annotations

import contextvars
import itertools
from typing import Optional

_TRACE: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "rb_tpu_trace", default=None
)

# itertools.count.__next__ is atomic under the GIL: no lock needed
_SERIAL = itertools.count(1)

_ENABLED = True


def configure(enabled: Optional[bool] = None) -> None:
    """Kill switch for the bench's observability-off twin row: disabled,
    ``current_trace()`` returns None and ``trace_scope`` is a no-op."""
    global _ENABLED
    if enabled is not None:
        _ENABLED = bool(enabled)


def enabled() -> bool:
    return _ENABLED


def new_trace_id() -> str:
    """Mint a process-unique trace id (monotonic serial, hex)."""
    return "q%06x" % next(_SERIAL)


def current_trace() -> Optional[str]:
    """The active trace id on this thread/context, or None."""
    if not _ENABLED:
        return None
    return _TRACE.get()


class trace_scope:
    """Ensure a trace id is active for the enclosed block.

    With no argument: reuse the ambient id if one is active (nested entry
    points belong to the enclosing query), else mint a fresh one. With an
    explicit ``trace_id``: pin it for the block regardless (the pipelined
    drivers' pre-assigned per-query ids). Re-entrant and exception-safe;
    ``self.trace_id`` is the id in effect inside the block."""

    __slots__ = ("_explicit", "_token", "trace_id")

    def __init__(self, trace_id: Optional[str] = None):
        self._explicit = trace_id
        self._token = None
        self.trace_id = None

    def __enter__(self) -> "trace_scope":
        if not _ENABLED:
            return self
        if self._explicit is None:
            cur = _TRACE.get()
            if cur is not None:
                self.trace_id = cur  # nested: same query, no token to reset
                return self
            self.trace_id = new_trace_id()
        else:
            self.trace_id = self._explicit
        self._token = _TRACE.set(self.trace_id)
        return self

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _TRACE.reset(self._token)
            self._token = None


class adopt:
    """Explicit cross-thread handoff: run a worker-thread block under the
    submitting query's trace id (captured by the submitter with
    ``current_trace()`` and carried in the job). ``adopt(None)`` is a
    no-op, so call sites need no conditional."""

    __slots__ = ("_trace_id", "_token")

    def __init__(self, trace_id: Optional[str]):
        self._trace_id = trace_id
        self._token = None

    def __enter__(self) -> "adopt":
        if _ENABLED and self._trace_id is not None:
            self._token = _TRACE.set(self._trace_id)
        return self

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _TRACE.reset(self._token)
            self._token = None
