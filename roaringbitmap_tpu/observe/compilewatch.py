"""jit compile/retrace observatory (ISSUE 9 tentpole, leg 3b).

PR 8's marshal rebuild claims its pow2-padded kernel arguments *bound*
retraces — but nothing counted them, so a shape-leak regression would
surface only as mysteriously slow steady state. This module counts every
XLA trace of the pipeline's jitted entry points into
``rb_tpu_compile_total{fn}``.

Mechanism: ``tracked(name)`` wraps the *pre-jit* Python callable. Under
``jax.jit`` the Python body runs exactly once per compilation (tracing
executes it; cache hits do not), so a counter bump inside the wrapper
counts compiles/retraces precisely — no polling, no jax internals. The
wrapper preserves the signature (``functools.wraps``), so
``static_argnames``/``donate_argnums`` resolve unchanged::

    @functools.partial(jax.jit, static_argnames=("op",))
    @compilewatch.tracked("wide_reduce")
    def wide_reduce(words, op="or"): ...

Per-call steady-state cost: zero — the wrapper body only runs while XLA
is already spending milliseconds-to-seconds compiling.

**Anomaly hook**: when any fn's trace count passes the budget
(``RB_TPU_COMPILE_BUDGET``, default 32; ``configure(budget=...)``;
``<= 0`` disables), the flight recorder flushes to a JSONL artifact
(``RB_TPU_COMPILE_DUMP``, default ``rb_tpu_compile_anomaly.jsonl`` inside
the unified ``RB_TPU_ARTIFACT_DIR`` sink — see ``observe.artifacts``) with
the offending fn in the trigger header — the "what shapes led up to
this" context a post-hoc counter cannot reconstruct. Dumps are throttled
to one per second; ``rb_tpu_timeline_anomaly_total{cat="compile"}``
counts every overrun regardless.

``compile_counts()`` is the read API; bench.py snapshots it around the
timed reduction reps to *prove* the north-star pipeline reaches steady
state with zero retraces after warmup (the acceptance row).
"""

from __future__ import annotations

import functools
import os
import threading
import time
from typing import Callable, Dict, Optional

from . import registry as _registry
from . import timeline as _timeline

_COMPILE_TOTAL = _registry.counter(
    _registry.COMPILE_TOTAL,
    "XLA traces (compiles + retraces) of tracked jitted entry points",
    ("fn",),
)

DEFAULT_BUDGET = 32


def _init_budget() -> int:
    raw = os.environ.get("RB_TPU_COMPILE_BUDGET")
    try:
        return int(raw) if raw else DEFAULT_BUDGET
    except ValueError:  # malformed env must not break package import
        return DEFAULT_BUDGET


_BUDGET = _init_budget()
_DUMP_PATH = os.environ.get("RB_TPU_COMPILE_DUMP") or "rb_tpu_compile_anomaly.jsonl"

_THROTTLE_LOCK = threading.Lock()
_LAST_DUMP_NS = 0  # guarded-by: _THROTTLE_LOCK
_DUMP_MIN_INTERVAL_NS = 1_000_000_000


def configure(
    budget: Optional[int] = None, dump_path: Optional[str] = None
) -> None:
    """Runtime overrides: ``budget <= 0`` disables the anomaly hook."""
    global _BUDGET, _DUMP_PATH
    if budget is not None:
        _BUDGET = int(budget)
    if dump_path is not None:
        _DUMP_PATH = dump_path


def tracked(name: str) -> Callable:
    """Decorator (applied UNDER ``jax.jit``) counting each trace of the
    wrapped callable as one compile of ``name``."""

    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            _note_trace(name)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def _note_trace(name: str) -> None:
    _COMPILE_TOTAL.inc(1, (name,))
    total = _COMPILE_TOTAL.get((name,))
    if _timeline.enabled():
        _timeline.instant("compile.trace", "compile", fn=name, total=total)
    if _BUDGET > 0 and total > _BUDGET:
        _anomaly(name, total)


def _anomaly(name: str, total: int) -> None:
    global _LAST_DUMP_NS
    _timeline._ANOMALY_TOTAL.inc(1, ("compile",))
    _timeline.instant(
        "compile.anomaly", "anomaly", fn=name, total=total, budget=_BUDGET
    )
    now = time.perf_counter_ns()
    with _THROTTLE_LOCK:
        if _LAST_DUMP_NS and now - _LAST_DUMP_NS < _DUMP_MIN_INTERVAL_NS:
            return
        _LAST_DUMP_NS = now
        path = _DUMP_PATH
    try:
        _timeline.dump_jsonl(
            path,
            trigger={"compile_fn": name, "traces": total, "budget": _BUDGET},
        )
    except OSError:  # rb-ok: exception-hygiene -- diagnostics must never fail a compile; the anomaly counter above already recorded the overrun
        pass


def compile_counts() -> Dict[str, int]:
    """{fn: traces-so-far} for every tracked entry point."""
    return {lv[0]: int(v) for lv, v in _COMPILE_TOTAL.series().items()}


def reset_counts() -> None:
    """Clear the per-fn series (tests; the metric stays registered)."""
    _COMPILE_TOTAL.clear()
