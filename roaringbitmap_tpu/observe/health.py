"""Declarative health rules over the telemetry surface (ISSUE 12
tentpole, leg 1).

Every prior observability PR added *signals* — drift gauges, breaker
states, regret rollups, anomaly counters, accounting reconciliation — but
"is this process healthy?" still required a human reading rb_top. This
module is the judgement layer: a **rule table** evaluated over point-in-
time snapshots of those registries, folding into one process status.

* :class:`Rule` — a named probe over a :class:`Snapshot` returning a
  scalar "badness" (bigger is worse), with **warn/critical bands**
  (``value >= warn`` → WARN, ``>= critical`` → CRITICAL), **hysteresis**
  (``fire_after`` consecutive out-of-band ticks to raise the level,
  ``clear_after`` consecutive in-band ticks to lower it — a single noisy
  sample never flips the status), and **flap suppression** (a rule whose
  raw band changed ``flap_limit`` times within the last ``flap_window``
  ticks is *flapping*: it holds its fired level and suppresses downward
  transitions until the signal stabilises — an oscillating input produces
  one alert, not an alert storm).
* :class:`RuleState` — the per-rule evaluation state machine. Pure data +
  arithmetic: no clocks, no locks, no I/O — the sentinel owns locking and
  pacing, which is what makes the fake-clock tests deterministic.
* :class:`Snapshot` — what probes see: the metrics-registry snapshot,
  breaker open-ages, the cost-model drift cells, and the outcome ledger's
  per-site rollup, plus ``counter_delta`` (per-tick counter movement
  against the previous tick's totals — rate rules without a clock).

Levels are the Prometheus-style enum-gauge encoding the new metrics
export: per-rule ``rb_tpu_health_rule_state{rule}`` ∈ {0 ok, 1 warn,
2 critical} and the process rollup ``rb_tpu_health_status`` ∈ {0 green,
1 yellow, 2 red} = max over rules.

The **default rule table** below is the committed production judgement
(thresholds in-repo, gated by scripts/ci.sh — the bench must end green):

====================== ======================================== ===== =====
rule                   badness value                            warn  crit
====================== ======================================== ===== =====
costmodel-drift        max over drift cells of max(r, 1/r)      2.0   4.0
routing-regret         cumulative regret_s / measured_s         0.05  0.20
breaker-stuck-open     max seconds any breaker has been open    30    300
outcome-anomaly-burst  out-of-band joins since last tick        1     16
hbm-accounting-drift   max |accounting drift| bytes             1     2^20
compile-storm          jit traces since last tick               8     32
fusion-queue-stall     fusion queue depth with no drained batch 1     64
serving-p99-breach     worst per-tenant windowed serving p99 s  0.5   2.0
tenant-saturation      worst per-tenant shed fraction per tick  0.25  0.75
freshness-lag-breach   worst windowed ingest->queryable p99 s   2.0   10.0
epoch-flip-stall       mutation-log depth with no epoch flip    4     64
structure-drift        actual/optimal serialized-bytes ratio    1.3   2.0
delta-accretion        epoch-delta batches since maintenance    8     64
epoch-persist-stall    persist backlog with no completed persist 4    64
recovery-manifest-torn torn artifacts skipped by recovery       0.5   1
serving-p99-pressure   worst tenant p99 / declared p99 budget   1.0   2.0
====================== ======================================== ===== =====

Actuations (the sentinel's closed-loop half — see ``observe.sentinel``):
``costmodel-drift`` actuates ``"refit"`` (the ``cost/`` facade's
``refit_all``, ROADMAP item 4's auto-trigger); ``structure-drift`` and
``delta-accretion`` actuate ``"maintain"`` (a priced background
compaction pass under its own cooldown — serve/maintain.py, ISSUE 16);
``serving-p99-pressure`` actuates ``"autotune"`` (the fusion executor's
window bounds re-derived from the fusion authority's refitted curves
under its own cooldown — query/fusion.py ``autotune_window``, ISSUE 19);
the rest actuate ``"alert"`` (a structured instant + decision entry on
the fire transition); any rule reaching CRITICAL additionally triggers
a one-shot flight bundle (``observe.bundle``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from collections import deque
from typing import Callable, Dict, Optional, Tuple

from . import registry as _registry

OK, WARN, CRITICAL = 0, 1, 2
LEVEL_NAMES = {OK: "ok", WARN: "warn", CRITICAL: "critical"}
STATUS_NAMES = {OK: "green", WARN: "yellow", CRITICAL: "red"}

# enum gauges (see module docstring for the encoding); registered here so
# the series exist for the export/health-block derivation even before the
# first sentinel tick
HEALTH_STATUS = _registry.gauge(
    _registry.HEALTH_STATUS,
    "Process health rollup from the sentinel rule table "
    "(0 green / 1 yellow / 2 red = max over rule states)",
)
HEALTH_RULE_STATE = _registry.gauge(
    _registry.HEALTH_RULE_STATE,
    "Per-rule health level after hysteresis/flap suppression "
    "(0 ok / 1 warn / 2 critical)",
    ("rule",),
)


@dataclass(frozen=True)
class Rule:
    """One declarative health judgement. ``probe(snapshot)`` returns the
    scalar badness (bigger is worse; None = no data, treated as OK);
    ``actuation`` names the closed-loop response the sentinel runs when
    the rule fires (``"refit"`` / ``"alert"`` / None)."""

    name: str
    help: str
    probe: Callable[["Snapshot"], Optional[float]]
    warn: float
    critical: float
    fire_after: int = 2
    clear_after: int = 2
    flap_window: int = 16
    flap_limit: int = 4
    actuation: Optional[str] = None

    def band(self, value: Optional[float]) -> int:
        """The raw (pre-hysteresis) level of one sample."""
        if value is None:
            return OK
        if value >= self.critical:
            return CRITICAL
        if value >= self.warn:
            return WARN
        return OK


class RuleState:
    """The per-rule hysteresis + flap-suppression state machine. Owned
    and locked by the sentinel; this class itself is pure bookkeeping so
    tests drive it tick-by-tick with no clock at all."""

    __slots__ = (
        "level", "streak_worse", "streak_better", "last_raw", "last_value",
        "flapping", "_band_changes", "history",
    )

    def __init__(self, history: int = 64):
        self.level = OK
        self.streak_worse = 0
        self.streak_better = 0
        self.last_raw: Optional[int] = None
        self.last_value: Optional[float] = None
        self.flapping = False
        # tick numbers at which the RAW band changed (the flap signal —
        # counting applied transitions would self-sustain: a suppressed
        # clear would count as instability and pin the rule flapping)
        self._band_changes: "deque[int]" = deque()
        self.history: "deque[dict]" = deque(maxlen=history)

    def step(self, rule: Rule, value: Optional[float], tick_no: int) -> dict:
        """Advance one tick; returns the evaluation record (also appended
        to ``history``): value, raw band, applied level, the transition
        (``(from, to)`` or None), and whether flap suppression held a
        would-be clear."""
        raw = rule.band(value)
        # flap bookkeeping first: raw band movement within the window
        if self.last_raw is not None and raw != self.last_raw:
            self._band_changes.append(tick_no)
        self.last_raw = raw
        self.last_value = value
        floor = tick_no - rule.flap_window
        while self._band_changes and self._band_changes[0] <= floor:
            self._band_changes.popleft()
        self.flapping = len(self._band_changes) >= rule.flap_limit
        transition: Optional[Tuple[int, int]] = None
        suppressed = False
        if raw > self.level:
            self.streak_worse += 1
            self.streak_better = 0
            if self.streak_worse >= rule.fire_after:
                transition = (self.level, raw)
                self.level = raw
                self.streak_worse = 0
        elif raw < self.level:
            self.streak_better += 1
            self.streak_worse = 0
            if self.streak_better >= rule.clear_after:
                if self.flapping:
                    # hold the fired level: an oscillating signal must not
                    # clear-and-refire its way into an alert storm
                    suppressed = True
                else:
                    transition = (self.level, raw)
                    self.level = raw
                self.streak_better = 0
        else:
            self.streak_worse = 0
            self.streak_better = 0
        rec = {
            "tick": tick_no,
            "value": value,
            "raw": raw,
            "level": self.level,
            "transition": transition,
            "flapping": self.flapping,
            "suppressed": suppressed,
        }
        self.history.append(rec)
        return rec

    def as_dict(self) -> dict:
        return {
            "level": self.level,
            "level_name": LEVEL_NAMES[self.level],
            "value": self.last_value,
            "flapping": self.flapping,
        }


# ---------------------------------------------------------------------------
# snapshot: what rule probes see
# ---------------------------------------------------------------------------


class Snapshot:
    """Point-in-time view of every registry a rule may judge. Built by
    ``snapshot()`` OUTSIDE the sentinel lock (gathering takes the
    registry/ladder/ledger leaf locks); probes then run against plain
    data. ``counter_delta`` compares against the previous tick's totals
    (``prev_sums``) — the first tick reports 0 so pre-existing totals
    never fire a rate rule."""

    def __init__(
        self,
        metrics: dict,
        breaker_open_ages: Dict[str, float],
        drift: Dict[Tuple[str, str, str], float],
        outcome_sites: Dict[str, dict],
        now: float,
        prev_sums: Optional[Dict[str, float]] = None,
    ):
        self.metrics = metrics
        self.breaker_open_ages = breaker_open_ages
        self.drift = drift
        self.outcome_sites = outcome_sites
        self.now = now
        self._prev = prev_sums or {}
        self.sums: Dict[str, float] = {}  # totals touched this tick

    def counter_sum(self, name: str) -> float:
        m = self.metrics.get(name)
        if m is None:
            return 0.0
        return float(sum(s.get("value", 0) for s in m.get("samples", ())))

    def counter_delta(self, name: str) -> float:
        cur = self.counter_sum(name)
        self.sums[name] = cur
        prev = self._prev.get(name)
        if prev is None:
            return 0.0
        return max(0.0, cur - prev)

    def labeled_counter_delta(self, name: str) -> Dict[Tuple[str, ...], float]:
        """Per-series counter movement since the previous tick (compound
        ``name|labelvalues`` keys ride the same prev-sums channel as
        :meth:`counter_delta`; a series first seen this tick reports 0 so
        pre-existing totals never fire a rate rule)."""
        m = self.metrics.get(name)
        out: Dict[Tuple[str, ...], float] = {}
        if m is None:
            return out
        labelnames = m.get("labelnames", [])
        for s in m.get("samples", ()):
            lv = tuple(s["labels"].get(n, "") for n in labelnames)
            key = name + "|" + "|".join(lv)
            cur = float(s.get("value", 0))
            self.sums[key] = cur
            prev = self._prev.get(key)
            out[lv] = 0.0 if prev is None else max(0.0, cur - prev)
        return out

    def histogram_delta_quantile(self, name: str, q: float) -> Optional[float]:
        """Windowed quantile over a histogram's per-tick movement —
        the max over series, or None when no series moved (first tick,
        idle window). See :meth:`histogram_delta_quantiles`."""
        per = self.histogram_delta_quantiles(name, q)
        return max(per.values()) if per else None

    def histogram_delta_quantiles(
        self, name: str, q: float
    ) -> Dict[Tuple[str, ...], float]:
        """Per-series windowed quantile over a histogram's per-tick
        movement: for each labeled series, rebuild the bucket counts
        observed SINCE the previous tick (cumulative-``le`` diffs against
        the prev-sums channel) and estimate the ``q``-quantile by the
        same cumulative-walk + in-bucket interpolation as
        LatencyHistogram; a series that did not move this tick (first
        tick, idle window) is omitted — cumulative histograms would
        otherwise pin a breach forever after one bad burst. The sums
        writes are idempotent, so probes may call this and
        :meth:`histogram_delta_quantile` on the same name in one tick."""
        out: Dict[Tuple[str, ...], float] = {}
        m = self.metrics.get(name)
        if m is None:
            return out
        for s in m.get("samples", ()):
            lv = [s["labels"][n] for n in m.get("labelnames", [])]
            skey = name + "|" + "|".join(lv)
            buckets = s.get("buckets") or {}
            count = float(s.get("count", 0))
            cur = {le: float(c) for le, c in buckets.items()}
            first = (skey + "|count") not in self._prev
            prev_count = self._prev.get(skey + "|count", 0.0)
            self.sums[skey + "|count"] = count
            for le, c in cur.items():
                self.sums[skey + "|" + le] = c
            if first:
                continue
            total = count - prev_count
            if total <= 0:
                continue
            keyed = sorted(
                ((le, float(le)) for le in cur if le != "+Inf"),
                key=lambda kv: kv[1],
            )
            bounds = [b for _le, b in keyed]
            slots = []
            prev_cum = 0.0
            for le, _b in keyed:
                cum = cur[le] - self._prev.get(skey + "|" + le, 0.0)
                slots.append(max(0.0, cum - prev_cum))
                prev_cum = max(prev_cum, cum)
            slots.append(max(0.0, total - prev_cum))  # +Inf overflow
            rank = max(1.0, q * total)
            cum = 0.0
            est = bounds[-1] if bounds else 0.0
            for i, n in enumerate(slots):
                if n <= 0:
                    continue
                below = cum
                cum += n
                if cum >= rank:
                    if i >= len(bounds):
                        est = bounds[-1]  # overflow: clamp
                    else:
                        hi = bounds[i]
                        lo = bounds[i - 1] if i > 0 else 0.0
                        est = lo + (hi - lo) * ((rank - below) / n)
                    break
            out[tuple(lv)] = est
        return out

    def gauge_max_abs(self, name: str) -> float:
        m = self.metrics.get(name)
        if m is None:
            return 0.0
        vals = [abs(s.get("value", 0)) for s in m.get("samples", ())]
        return float(max(vals)) if vals else 0.0


def snapshot(
    prev_sums: Optional[Dict[str, float]] = None,
    now: Optional[float] = None,
    refresh_hbm: bool = True,
) -> Snapshot:
    """Gather the rule-probe view. ``refresh_hbm`` additionally runs the
    device-memory reconciliation so the drift gauges judge CURRENT
    reality, not the last time someone happened to reconcile; any failure
    there leaves the stale gauges in place (judging stale telemetry beats
    killing the supervisor)."""
    import time as _time

    from . import outcomes as _outcomes

    if refresh_hbm:
        try:
            from ..parallel import store as _store

            _store.hbm_reconciliation()
        except Exception:  # rb-ok: exception-hygiene -- the supervisor must keep judging on stale gauges when a refresh fails (e.g. a backend probe raising mid-teardown); the stale values are still real telemetry
            pass
    ages: Dict[str, float] = {}
    try:
        from ..robust import ladder as _ladder

        ages = _ladder.LADDER.open_ages()
    except Exception:  # rb-ok: exception-hygiene -- same stale-beats-dead contract as the hbm refresh above
        pass
    return Snapshot(
        metrics=_registry.REGISTRY.snapshot(),
        breaker_open_ages=ages,
        drift=_outcomes.LEDGER.drift(),
        outcome_sites=_outcomes.LEDGER.summary(),
        now=_time.monotonic() if now is None else now,
        prev_sums=prev_sums,
    )


# ---------------------------------------------------------------------------
# the committed default rule table
# ---------------------------------------------------------------------------


def _drift_badness(s: Snapshot) -> float:
    """Worst coefficient-cell drift, symmetric: max(r, 1/r) over the
    geometric-EWMA cells (1.0 = every calibrated curve still truthful)."""
    worst = 1.0
    for r in s.drift.values():
        if r > 0:
            worst = max(worst, r, 1.0 / r)
    return worst


def _regret_fraction(s: Snapshot) -> float:
    """Cumulative wall lost to wrong verdicts as a fraction of the joined
    measured wall (the ROADMAP item 4 gate, judged continuously)."""
    regret = sum(a.get("regret_s", 0.0) for a in s.outcome_sites.values())
    measured = sum(a.get("measured_s", 0.0) for a in s.outcome_sites.values())
    if measured <= 0:
        return 0.0
    return regret / measured


def _max_open_age(s: Snapshot) -> float:
    return max(s.breaker_open_ages.values(), default=0.0)


def _serving_p99_breach(s: Snapshot) -> Optional[float]:
    """Worst windowed p99 (seconds) over the serving tier's per-tenant
    latency series since the last tick (ISSUE 14 — one of the two
    serving-shaped rules the ISSUE-12/13 closure notes promised). The
    window is the per-tick histogram movement, so a single bad burst
    clears once traffic recovers instead of pinning the cumulative p99
    red forever; queue-phase series count too — a breach driven by
    backpressure wait is exactly what an operator needs to see."""
    return s.histogram_delta_quantile(_registry.SERVE_LATENCY_SECONDS, 0.99)


# a tenant must offer at least this many requests in a tick window before
# its shed fraction is judged — one shed of one request is not saturation
_SATURATION_MIN_REQUESTS = 8.0


def _tenant_saturation(s: Snapshot) -> Optional[float]:
    """Worst per-tenant shed fraction since the last tick: sheds over
    offered admission verdicts, judged only for tenants with enough
    window volume (ISSUE 14 — the per-tenant saturation rule). A tenant
    over quota sheds a sustained fraction of its traffic; transient
    single-request noise stays below the volume floor."""
    deltas = s.labeled_counter_delta(_registry.SERVE_ADMIT_TOTAL)
    per_tenant: Dict[str, Dict[str, float]] = {}
    for (tenant, verdict), d in deltas.items():
        per_tenant.setdefault(tenant, {})[verdict] = d
    worst: Optional[float] = None
    for tenant, by_verdict in per_tenant.items():
        offered = sum(by_verdict.values())
        if offered < _SATURATION_MIN_REQUESTS:
            continue
        frac = by_verdict.get("shed", 0.0) / offered
        worst = frac if worst is None else max(worst, frac)
    return worst


def _freshness_lag_breach(s: Snapshot) -> Optional[float]:
    """Worst windowed ingest->queryable lag p99 (seconds) over the epoch
    ledger's per-tenant freshness series since the last tick (ISSUE 15 —
    the freshness half of the serving SLO story). Same per-tick windowing
    as the serving-p99 rule: a stale flip fires while stale batches keep
    publishing and clears once fresh flips resume — a cumulative p99
    would pin one bad backlog red forever."""
    return s.histogram_delta_quantile(_registry.SERVE_FRESHNESS_SECONDS, 0.99)


def _epoch_flip_stall(s: Snapshot) -> float:
    """Mutation batches parked in the ingest log while NO epoch flip
    published since the last tick (ISSUE 15 — the write-path twin of
    fusion-queue-stall): badness is the mutlog depth gauge, judged
    against the flip counter's per-tick movement. A draining log —
    however deep — is healthy accumulation; a deep log with a wedged
    flip loop is data that will never become queryable."""
    depth = s.gauge_max_abs(_registry.SERVE_MUTLOG_COUNT)
    if depth <= 0:
        return 0.0
    flips = s.labeled_counter_delta(_registry.SERVE_EPOCH_FLIP_TOTAL)
    drained = sum(
        d for (outcome,), d in flips.items() if outcome == "flipped"
    )
    return depth if drained == 0 else 0.0


def _epoch_persist_stall(s: Snapshot) -> float:
    """Published epochs pending durability while NO persist completed
    since the last tick (ISSUE 17 — the durability twin of
    epoch-flip-stall): badness is the persist-backlog gauge, judged
    against the persist counter's per-tick movement. A backlog the
    priced skip verdict is deliberately carrying is healthy patience; a
    growing backlog with a wedged (or perpetually aborting) persist
    loop is warm state a crash will erase."""
    depth = s.gauge_max_abs(_registry.DURABLE_PENDING_COUNT)
    if depth <= 0:
        return 0.0
    persists = s.labeled_counter_delta(_registry.DURABLE_PERSIST_TOTAL)
    completed = sum(
        d for (outcome,), d in persists.items() if outcome == "persisted"
    )
    return depth if completed == 0 else 0.0


def _recovery_manifest_torn(s: Snapshot) -> float:
    """Torn durable artifacts skipped by recovery since the last tick
    (ISSUE 17): a torn manifest means a crash landed mid-persist on a
    non-atomic filesystem — or worse, bit rot — and the restart silently
    fell back to an OLDER epoch. Any occurrence goes straight to red
    (one tick, critical), and the critical transition's flight bundle
    carries the durable panel with the recovery provenance."""
    torn = s.labeled_counter_delta(_registry.DURABLE_RECOVERY_TOTAL)
    return float(sum(
        d for (outcome,), d in torn.items() if outcome == "torn"
    ))


def _fusion_queue_stall(s: Snapshot) -> float:
    """Queries parked in the fusion window queue while NO batch drained
    since the last tick (ISSUE 13 — the ~5-line serving-shaped rule the
    ISSUE-12 note promised): badness is the queue depth gauge, judged
    against the batch counter's per-tick movement; the batch-latency
    histogram (``rb_tpu_fusion_batch_seconds``) carries the drill-down.
    A draining queue — however deep — is healthy backpressure."""
    depth = s.gauge_max_abs(_registry.FUSION_QUEUED_COUNT)
    if depth <= 0:
        return 0.0
    return depth if s.counter_delta(_registry.FUSION_BATCH_TOTAL) == 0 else 0.0


def _serving_p99_pressure(s: Snapshot) -> Optional[float]:
    """Worst per-tenant ratio of windowed serving p99 over that tenant's
    DECLARED p99 budget (ISSUE 19): 1.0 means some tenant's tail just
    consumed its whole SLO. Unlike ``serving-p99-breach`` (one absolute
    band for everyone), this judges each tenant against its own declared
    latency class, so an interactive tenant at 30 ms fires while a batch
    tenant at 300 ms stays green — and it actuates the fusion-window
    auto-tune instead of an alert, because the batching window is the
    knob that trades this exact tail for throughput. Tenants without a
    declared budget (no ``rb_tpu_serve_slo_budget_seconds`` series) are
    not judged."""
    m = s.metrics.get(_registry.SERVE_SLO_BUDGET_SECONDS)
    if m is None:
        return None
    budgets: Dict[str, float] = {}
    for smp in m.get("samples", ()):
        tenant = smp.get("labels", {}).get("tenant", "")
        v = float(smp.get("value", 0))
        if tenant and v > 0:
            budgets[tenant] = v
    if not budgets:
        return None
    per = s.histogram_delta_quantiles(_registry.SERVE_LATENCY_SECONDS, 0.99)
    worst: Optional[float] = None
    for (tenant, _phase), p99 in per.items():
        budget = budgets.get(tenant)
        if budget is None:
            continue
        ratio = p99 / budget
        worst = ratio if worst is None else max(worst, ratio)
    return worst


DEFAULT_RULES: Tuple[Rule, ...] = (
    Rule(
        "costmodel-drift",
        "a pricing authority's coefficient cell no longer describes live "
        "traffic (geometric-EWMA drift left its band)",
        _drift_badness,
        warn=2.0, critical=4.0, fire_after=2, clear_after=2,
        actuation="refit",
    ),
    Rule(
        "routing-regret",
        "wall-clock lost to wrong routing verdicts exceeds the regret "
        "budget (fraction of joined measured wall)",
        _regret_fraction,
        warn=0.05, critical=0.20, fire_after=3, clear_after=3,
        actuation="alert",
    ),
    Rule(
        "breaker-stuck-open",
        "a circuit breaker has been continuously open past recovery "
        "expectations (seconds)",
        _max_open_age,
        warn=30.0, critical=300.0, fire_after=1, clear_after=1,
        actuation="alert",
    ),
    Rule(
        "outcome-anomaly-burst",
        "out-of-band predicted-vs-measured joins since the last tick",
        lambda s: s.counter_delta(_registry.OUTCOME_ANOMALY_TOTAL),
        warn=1.0, critical=16.0, fire_after=1, clear_after=2,
        actuation="alert",
    ),
    Rule(
        "hbm-accounting-drift",
        "device-memory accounting drift (resident gauge vs cache "
        "ledgers), max |bytes| over sources",
        lambda s: s.gauge_max_abs(_registry.HBM_ACCOUNTING_DRIFT_BYTES),
        warn=1.0, critical=float(1 << 20), fire_after=1, clear_after=1,
        actuation="alert",
    ),
    Rule(
        "compile-storm",
        "XLA traces (compiles + retraces) since the last tick — steady "
        "state must not retrace",
        lambda s: s.counter_delta(_registry.COMPILE_TOTAL),
        warn=8.0, critical=32.0, fire_after=1, clear_after=2,
        actuation="alert",
    ),
    Rule(
        "fusion-queue-stall",
        "queries waiting in the fusion window queue while no batch "
        "drained since the last tick (stalled drain loop, not healthy "
        "backpressure)",
        _fusion_queue_stall,
        warn=1.0, critical=64.0, fire_after=2, clear_after=2,
        actuation="alert",
    ),
    # the two serving-shaped rules ISSUE 12's closure note promised,
    # judging the serve tier's per-tenant histograms/counters (ISSUE 14);
    # appended so the earlier rules keep their table positions
    Rule(
        "serving-p99-breach",
        "worst per-tenant serving p99 (seconds, windowed per tick over "
        "queue+execute phases) breached the latency SLO",
        _serving_p99_breach,
        warn=0.5, critical=2.0, fire_after=2, clear_after=2,
        actuation="alert",
    ),
    Rule(
        "tenant-saturation",
        "a tenant's shed fraction of offered requests since the last "
        "tick (sustained quota breach, judged above a per-tick volume "
        "floor)",
        _tenant_saturation,
        warn=0.25, critical=0.75, fire_after=2, clear_after=2,
        actuation="alert",
    ),
    # the two epoch-ledger rules (ISSUE 15): data freshness joins the
    # latency SLOs as a judged signal, and a wedged flip loop is loud
    # before the backlog becomes an outage; appended so every earlier
    # rule keeps its table position
    Rule(
        "freshness-lag-breach",
        "worst ingest->queryable lag p99 (seconds, windowed per tick "
        "over the per-tenant freshness series) breached the freshness "
        "SLO",
        _freshness_lag_breach,
        warn=2.0, critical=10.0, fire_after=2, clear_after=2,
        actuation="alert",
    ),
    Rule(
        "epoch-flip-stall",
        "mutation batches pending in the ingest log while no epoch flip "
        "published since the last tick (wedged flip loop, not healthy "
        "accumulation)",
        _epoch_flip_stall,
        warn=4.0, critical=64.0, fire_after=2, clear_after=2,
        actuation="alert",
    ),
    # the two structure-observatory rules (ISSUE 16): corpus shape joins
    # the judged signals — both actuate a priced maintenance pass
    # (serve/maintain.py) under the sentinel's maintain cooldown;
    # appended so every earlier rule keeps its table position
    Rule(
        "structure-drift",
        "watched working sets' actual serialized bytes over the "
        "size-rule optimum (1.0 = every container in its cheapest "
        "format; sustained ingest without maintenance drifts it up)",
        lambda s: s.gauge_max_abs(_registry.STRUCTURE_DRIFT_RATIO),
        warn=1.3, critical=2.0, fire_after=2, clear_after=2,
        actuation="maintain",
    ),
    Rule(
        "delta-accretion",
        "epoch-delta batches folded into the corpus since the last "
        "maintenance pass settled them (unbounded accretion = unbounded "
        "rewrite debt)",
        lambda s: s.gauge_max_abs(_registry.STRUCTURE_ACCRETION_COUNT),
        warn=8.0, critical=64.0, fire_after=2, clear_after=2,
        actuation="maintain",
    ),
    # the two durable-epoch rules (ISSUE 17): crash exposure and
    # recovery integrity join the judged signals; appended so every
    # earlier rule keeps its table position
    Rule(
        "epoch-persist-stall",
        "published epochs pending durability while no persist completed "
        "since the last tick (wedged or perpetually aborting persist "
        "loop — warm state a crash will erase; a priced skip backlog "
        "that is still draining is healthy patience)",
        _epoch_persist_stall,
        warn=4.0, critical=64.0, fire_after=2, clear_after=2,
        actuation="alert",
    ),
    Rule(
        "recovery-manifest-torn",
        "torn durable artifacts skipped during recovery since the last "
        "tick (restart silently fell back to an older epoch) — any "
        "occurrence is red, and the flight bundle carries the durable "
        "panel's recovery provenance",
        _recovery_manifest_torn,
        warn=0.5, critical=1.0, fire_after=1, clear_after=1,
        actuation="alert",
    ),
    # the SLO-pressure rule (ISSUE 19): each tenant judged against its
    # OWN declared p99 budget, actuating the fusion-window auto-tune —
    # the knob that trades exactly this tail for throughput; appended so
    # every earlier rule keeps its table position
    Rule(
        "serving-p99-pressure",
        "worst per-tenant windowed serving p99 over that tenant's "
        "declared p99 budget (1.0 = the tail consumed the whole SLO) — "
        "actuates the fusion-window auto-tune under cooldown",
        _serving_p99_pressure,
        warn=1.0, critical=2.0, fire_after=2, clear_after=2,
        actuation="autotune",
    ),
)
