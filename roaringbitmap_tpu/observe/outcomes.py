"""Decision–outcome ledger: predicted-vs-measured joins, routing regret,
and the calibration-drift watch (ISSUE 11 tentpole).

Since ISSUE 9 every routing verdict lands in the decision log *with the
cost-model inputs that drove it*, and the flight recorder measures what
each stage actually took — but nothing joined the two, so a mispriced
verdict was invisible until a human read twin benchmark rows. This module
closes the loop:

* **Join.** A decision site that wants its verdict scored registers the
  decision as *pending* (``decisions.record_decision(..., outcome=True)``
  returns the decision's process-unique serial) and, after the chosen
  engine ran, resolves it with the measured wall clock
  (:func:`resolve` / the :class:`measure` context manager). The same
  serial is threaded into the flight-recorder span attrs at every site
  (``decision=<seq>`` on the ladder-attempt, query-step, and columnar
  spans), so the recorder-side join (:func:`join_recorder`) can rebuild
  the ledger offline from a trace artifact — trace id + decision serial
  is the join key in both directions.

* **Regret.** When the decision carried per-engine cost estimates
  (``est_us``, the cutoff model's argmin inputs), the join prices the
  not-taken alternatives from the same calibrated curves: regret is the
  wall-clock lost to a wrong verdict — ``measured(chosen) −
  min(predicted(alternatives))``, counted only when some alternative was
  predicted to beat what actually happened. Sites with a *measured*
  counterfactual (a pack-cache eviction whose key is re-packed while the
  eviction is still remembered, a ladder tier that burned wall clock and
  then failed) resolve with an explicit ``regret_s``. Per-site regret
  accumulates in ``rb_tpu_decision_regret_seconds{site}``.

* **Calibration drift.** Every join with a prediction observes
  ``predicted/measured`` into the log-bucketed
  ``rb_tpu_decision_error_ratio{site}``, and ``columnar.cutoff`` joins
  additionally feed a per-coefficient-cell drift gauge
  ``rb_tpu_costmodel_drift_ratio{group,engine,shape}`` (geometric EWMA of
  measured/predicted — 1.0 means the calibrated curve still prices this
  cell truthfully). A join whose error ratio leaves the calibrated band
  dumps the ledger tail to a JSONL artifact (throttled to one per
  second, the timeline module's discipline) and bumps
  ``rb_tpu_outcome_anomaly_total{site}``.

* **Refit feed.** Joined ``columnar.cutoff`` samples carry the features
  the cost model fits on (op group, engine, shape, pair count, measured
  µs) — ``columnar.costmodel.refit_from_outcomes()`` and the planner's
  cardinality-model refit consume :func:`samples` directly, which is what
  makes the pricing authorities self-tuning instead of
  calibrated-once-per-host (ROADMAP item 4).

Bounds & cost: pending decisions live in a bounded map (default 2048) and
joined entries in a bounded ring (default 512); an outcome that arrives
after its decision was evicted is counted as
``rb_tpu_outcome_orphans_total{site}`` and dropped — never an error. Off
mode (``RB_TPU_OUTCOMES=off`` / ``configure(enabled=False)``) reduces
every hook to one module-bool check; the bench's interleaved off-mode
twin bounds the on-path cost under the same <1 % budget as the trace
context and decision log (ISSUE 9 discipline).

Lock discipline: the ledger lock is a LEAF — it guards only the pending
map, the ring, and the per-site aggregate dicts; metric bumps, recorder
instants, and the anomaly dump all happen outside it, so decision sites
that resolve while holding other framework locks nest safely
(tests/test_outcomes.py hammers this under the lock witness).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from . import registry as _registry
from .histogram import latency_histogram

DEFAULT_CAPACITY = 512
DEFAULT_PENDING = 2048
# calibrated band for predicted/measured: a join outside [lo, hi] is a
# pricing anomaly (the curves are two-point fits — 4x either way is far
# beyond fit noise and means the coefficient no longer describes traffic)
DEFAULT_BAND = (0.25, 4.0)
DUMP_SCHEMA = "rb_tpu_outcomes/1"
_DUMP_MIN_INTERVAL_NS = 1_000_000_000
# drift EWMA weight: ~20-sample memory, enough to ride out one weird pair
# without hiding a real drift for long
_DRIFT_ALPHA = 0.1

_REGRET_SECONDS = latency_histogram(
    _registry.DECISION_REGRET_SECONDS,
    "Wall-clock lost to wrong routing verdicts, by deciding site "
    "(measured chosen-engine cost minus the best not-taken alternative's "
    "predicted cost, when that alternative was predicted to win)",
    ("site",),
)
# log-bucketed predicted/measured ratio: symmetric decades around 1.0 so
# systematic over- and under-pricing resolve equally
_ERROR_RATIO_BUCKETS = (
    0.0625, 0.125, 0.25, 0.5, 0.75, 0.9, 1.111, 1.333, 2.0, 4.0, 8.0, 16.0,
)
_ERROR_RATIO = _registry.histogram(
    _registry.DECISION_ERROR_RATIO,
    "Predicted/measured cost ratio per joined decision, by site "
    "(1.0 = the model priced this verdict truthfully)",
    ("site",),
    buckets=_ERROR_RATIO_BUCKETS,
)
_JOIN_TOTAL = _registry.counter(
    _registry.OUTCOME_JOIN_TOTAL,
    "Decision outcomes joined to their measured execution, by site",
    ("site",),
)
_ORPHANS_TOTAL = _registry.counter(
    _registry.OUTCOME_ORPHANS_TOTAL,
    "Outcomes that arrived after their decision left the pending ring "
    "(joined lazily impossible — counted, never an error), by site",
    ("site",),
)
_ANOMALY_TOTAL = _registry.counter(
    _registry.OUTCOME_ANOMALY_TOTAL,
    "Joins whose predicted/measured ratio left the calibrated band and "
    "triggered a (throttled) ledger dump, by site",
    ("site",),
)
_DRIFT_RATIO = _registry.gauge(
    _registry.COSTMODEL_DRIFT_RATIO,
    "Geometric EWMA of measured/predicted cost per columnar cost-model "
    "coefficient cell (1.0 = calibration still truthful)",
    ("group", "engine", "shape"),
)


def _init_enabled() -> bool:
    raw = os.environ.get("RB_TPU_OUTCOMES", "").strip().lower()
    return raw not in ("0", "off", "false", "no")


_ENABLED = _init_enabled()


class OutcomeLedger:
    """Thread-safe bounded pending map + joined ring + per-site rollups.

    All state lives behind one LEAF lock; every method returns plain data
    and leaves metric emission to the module-level wrappers (which bump
    outside the lock)."""

    def __init__(
        self, capacity: int = DEFAULT_CAPACITY, pending: int = DEFAULT_PENDING
    ):
        if capacity < 1 or pending < 1:
            raise ValueError(
                f"capacity/pending must be >= 1, got {capacity}/{pending}"
            )
        self._lock = threading.Lock()  # leaf: guards the three dicts only
        self._pending: "OrderedDict[int, dict]" = OrderedDict()  # guarded-by: self._lock
        self._pending_cap = int(pending)  # guarded-by: self._lock
        self._ring: "deque[dict]" = deque(maxlen=int(capacity))  # guarded-by: self._lock
        # site -> {count, regret_s, log_err_sum, log_err_n, worst (entry)}
        self._sites: Dict[str, dict] = {}  # guarded-by: self._lock
        # (group, engine, shape) -> geometric EWMA of measured/predicted
        self._drift: Dict[Tuple[str, str, str], float] = {}  # guarded-by: self._lock

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    # -- pending ------------------------------------------------------------

    def register(self, seq: int, entry: dict) -> None:
        """Park a decision for a later measured join. Over capacity the
        OLDEST pending decision ages out silently — an unresolved verdict
        is not an error, it simply never produced a sample."""
        with self._lock:
            self._pending[seq] = entry
            while len(self._pending) > self._pending_cap:
                self._pending.popitem(last=False)

    def pop_pending(self, seq: int) -> Optional[dict]:
        with self._lock:
            return self._pending.pop(seq, None)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- joined entries ------------------------------------------------------

    def append(self, joined: dict) -> None:
        site = joined["site"]
        regret = joined.get("regret_s") or 0.0
        err = joined.get("error_ratio")
        measured = joined.get("measured_s") or 0.0
        with self._lock:
            self._ring.append(joined)
            agg = self._sites.get(site)
            if agg is None:
                agg = self._sites[site] = {
                    "count": 0, "regret_s": 0.0, "measured_s": 0.0,
                    "log_err_sum": 0.0, "log_err_n": 0, "worst": None,
                }
            agg["count"] += 1
            agg["regret_s"] += regret
            # cumulative measured wall: the denominator of the health
            # sentinel's routing-regret fraction (ISSUE 12)
            agg["measured_s"] += measured
            if err is not None and err > 0:
                import math

                agg["log_err_sum"] += math.log(err)
                agg["log_err_n"] += 1
            worst = agg["worst"]
            if regret > 0 and (worst is None or regret > worst.get("regret_s", 0.0)):
                agg["worst"] = joined

    def note_drift(self, cell: Tuple[str, str, str], ratio: float) -> float:
        """Fold one measured/predicted sample into the cell's geometric
        EWMA; returns the updated drift value (emitted by the caller)."""
        import math

        with self._lock:
            prev = self._drift.get(cell)
            if prev is None or prev <= 0:
                cur = ratio
            else:
                cur = math.exp(
                    (1 - _DRIFT_ALPHA) * math.log(prev)
                    + _DRIFT_ALPHA * math.log(ratio)
                )
            self._drift[cell] = cur
            return cur

    def drift(self) -> Dict[Tuple[str, str, str], float]:
        with self._lock:
            return dict(self._drift)

    def rebase_drift(self, cells) -> None:
        """Re-base the given cells' EWMAs to 1.0 — called after a refit
        replaced their coefficients (ISSUE 12): the accumulated drift
        measured the OLD curve's error; leaving it would re-trigger the
        sentinel's drift rule against coefficients that already moved."""
        with self._lock:
            for cell in cells:
                if cell in self._drift:
                    self._drift[cell] = 1.0

    def tail(self, n: Optional[int] = None) -> List[dict]:
        """The newest ``n`` joined entries (all retained when None),
        oldest first — point-in-time copies, safe to mutate."""
        with self._lock:
            entries = list(self._ring)
        if n is not None:
            entries = entries[-int(n):] if n > 0 else []
        return [dict(e) for e in entries]

    def summary(self) -> Dict[str, dict]:
        """Per-site rollup: join count, total regret seconds, geometric
        mean error ratio, and the worst (highest-regret) recent decision
        with its inputs — the rb_top regret panel's data."""
        import math

        with self._lock:
            out = {}
            for site, agg in sorted(self._sites.items()):
                n = agg["log_err_n"]
                out[site] = {
                    "count": agg["count"],
                    "regret_s": round(agg["regret_s"], 6),
                    "measured_s": round(agg["measured_s"], 6),
                    "error_ratio_geomean": (
                        round(math.exp(agg["log_err_sum"] / n), 4) if n else None
                    ),
                    "worst": dict(agg["worst"]) if agg["worst"] else None,
                }
            return out

    def clear(self) -> None:
        with self._lock:
            self._pending.clear()
            self._ring.clear()
            self._sites.clear()
            self._drift.clear()

    def resize(self, capacity: Optional[int] = None, pending: Optional[int] = None) -> None:
        with self._lock:
            if capacity is not None:
                if capacity < 1:
                    raise ValueError(f"capacity must be >= 1, got {capacity}")
                self._ring = deque(self._ring, maxlen=int(capacity))
            if pending is not None:
                if pending < 1:
                    raise ValueError(f"pending must be >= 1, got {pending}")
                self._pending_cap = int(pending)
                while len(self._pending) > self._pending_cap:
                    self._pending.popitem(last=False)


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name) or default))
    except ValueError:
        return default


LEDGER = OutcomeLedger(
    capacity=_env_int("RB_TPU_OUTCOMES_CAPACITY", DEFAULT_CAPACITY),
    pending=_env_int("RB_TPU_OUTCOMES_PENDING", DEFAULT_PENDING),
)

_STATE_LOCK = threading.Lock()
_BAND = DEFAULT_BAND  # guarded-by: _STATE_LOCK
_DUMP_PATH = os.environ.get(  # guarded-by: _STATE_LOCK
    "RB_TPU_OUTCOMES_DUMP", "rb_tpu_outcomes_anomaly.jsonl"
)
_LAST_DUMP_NS = 0  # guarded-by: _STATE_LOCK


def configure(
    enabled: Optional[bool] = None,
    capacity: Optional[int] = None,
    pending: Optional[int] = None,
    band: Optional[Tuple[float, float]] = None,
    dump_path: Optional[str] = None,
) -> None:
    """Runtime overrides: ``enabled=False`` is the bench twin's kill
    switch (every hook reduces to one bool check); ``band`` re-arms the
    anomaly watch ((lo, hi) predicted/measured limits)."""
    global _ENABLED, _BAND, _DUMP_PATH
    if enabled is not None:
        _ENABLED = bool(enabled)
    if capacity is not None or pending is not None:
        LEDGER.resize(capacity=capacity, pending=pending)
    with _STATE_LOCK:
        if band is not None:
            lo, hi = float(band[0]), float(band[1])
            if not 0 < lo < hi:
                raise ValueError(f"band needs 0 < lo < hi, got {band}")
            _BAND = (lo, hi)
        if dump_path is not None:
            _DUMP_PATH = dump_path


def enabled() -> bool:
    return _ENABLED


def register(seq: int, site: str, inputs: Optional[dict], trace) -> None:
    """Park a recorded decision for its measured join (called by
    ``decisions.record_decision`` when the site asked for an outcome)."""
    if not _ENABLED:
        return
    LEDGER.register(seq, {
        "seq": seq, "site": site, "trace": trace,
        "ts_ns": time.perf_counter_ns(),
        "inputs": dict(inputs) if inputs else {},
    })


def resolve(
    seq: Optional[int],
    site: str,
    measured_s: float,
    engine: Optional[str] = None,
    regret_s: Optional[float] = None,
    actual: Optional[float] = None,
) -> Optional[dict]:
    """Join one measured execution to its pending decision.

    ``engine`` names what actually ran (for regret/drift it is looked up
    in the decision's ``est_us``); ``regret_s`` is the explicit
    measured-counterfactual form (evict-then-repack, wasted ladder
    attempt) and overrides the priced estimate; ``actual`` is the
    measured prediction target for non-time predictions (the planner's
    cardinality). ``site`` labels the orphan counter when the pending
    entry is gone (the joined entry itself always carries the decision's
    own site). A ``seq`` that is no longer pending counts as an orphan
    and returns None — never an error (the decision ring is bounded; the
    outcome simply outlived it)."""
    if not _ENABLED or seq is None:
        return None
    entry = LEDGER.pop_pending(seq)
    if entry is None:
        _ORPHANS_TOTAL.inc(1, (site or "unknown",))
        return None
    site = entry.get("site") or site or "unknown"
    inputs = entry.get("inputs") or {}
    measured_us = measured_s * 1e6
    est_us = inputs.get("est_us")
    predicted_us = None
    error_ratio = None
    if isinstance(est_us, dict) and engine is not None:
        predicted_us = est_us.get(engine)
    if predicted_us is not None and measured_us > 0:
        error_ratio = predicted_us / measured_us
    elif (
        actual is not None and actual > 0
        and (inputs.get("est_card") or 0) > 0
    ):
        # non-time prediction (planner cardinality): predicted/measured in
        # the prediction's own unit — the same drift semantics
        error_ratio = float(inputs["est_card"]) / float(actual)
    if regret_s is None and isinstance(est_us, dict) and engine is not None:
        alts = [v for k, v in est_us.items() if k != engine and v is not None]
        if alts:
            best_alt_us = min(alts)
            if best_alt_us < measured_us:
                regret_s = (measured_us - best_alt_us) / 1e6
    joined = {
        "seq": seq,
        "site": site,
        "trace": entry.get("trace"),
        "engine": engine,
        "measured_s": round(measured_s, 9),
        "predicted_us": predicted_us,
        "error_ratio": round(error_ratio, 6) if error_ratio is not None else None,
        "regret_s": round(regret_s, 9) if regret_s else 0.0,
        "inputs": inputs,
    }
    if actual is not None:
        joined["actual"] = actual
    LEDGER.append(joined)
    # metrics OUTSIDE the ledger lock (leaf discipline)
    _JOIN_TOTAL.inc(1, (site,))
    if joined["regret_s"]:
        _REGRET_SECONDS.observe(joined["regret_s"], (site,))
    if error_ratio is not None:
        _ERROR_RATIO.observe(error_ratio, (site,))
        if site == "columnar.cutoff" and predicted_us is not None:
            _note_cell_drift(inputs, engine, measured_us, predicted_us)
        # the calibrated band judges PRICED joins only (predicted_us from
        # measured cost curves — 4x off a two-point fit is an anomaly);
        # cardinality-style ratios (the planner's structural bounds) are
        # EXPECTED to miss by orders of magnitude until a refit learns
        # the traffic's bias — banding them would dump once per second on
        # perfectly healthy query traffic and drown the real alerts
        if predicted_us is not None:
            with _STATE_LOCK:
                lo, hi = _BAND
            if not lo <= error_ratio <= hi:
                _anomaly(site, joined)
    return joined


def _note_cell_drift(inputs, engine, measured_us, predicted_us) -> None:
    """Fold a columnar.cutoff join into its coefficient cell's drift
    gauge; the cell is the exact (op-group, engine, shape) the cost model
    fits — drift 1.0 means the two-point calibration still prices live
    traffic truthfully."""
    from ..columnar import costmodel as _costmodel

    op = inputs.get("op")
    shape = inputs.get("shape")
    if op is None or shape not in _costmodel.SHAPES or engine not in _costmodel.ENGINES:
        return
    group = _costmodel.op_group(op)
    cell = (group, engine, shape)
    ratio = measured_us / predicted_us if predicted_us > 0 else None
    if ratio is None or ratio <= 0:
        return
    drift = LEDGER.note_drift(cell, ratio)
    _DRIFT_RATIO.set(round(drift, 4), cell)


class measure:
    """Context manager resolving a pending decision with the wall clock of
    the enclosed block::

        with outcomes.measure(seq, "columnar.cutoff", engine=tier):
            result = run_the_engine()

    ``seq=None`` (site below its record gate, outcomes off) is a no-op —
    call sites need no conditional. The engine may be (re)assigned via
    ``.engine`` before the block exits (ladder sites learn which tier
    absorbed the traffic mid-block)."""

    __slots__ = ("seq", "site", "engine", "_t0")

    def __init__(self, seq: Optional[int], site: str, engine: Optional[str] = None):
        self.seq = seq if _ENABLED else None
        self.site = site
        self.engine = engine
        self._t0 = 0

    def __enter__(self) -> "measure":
        if self.seq is not None:
            self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, *exc) -> None:
        if self.seq is None:
            return
        if exc_type is not None:
            # the engine raised: the ladder/degrade path owns the regret
            # accounting for failures; drop the pending entry silently
            LEDGER.pop_pending(self.seq)
            return
        t1 = time.perf_counter_ns()
        resolve(self.seq, self.site, (t1 - self._t0) / 1e9, engine=self.engine)
        # thread the serial into the flight recorder (ISSUE 11 join key):
        # the measured window lands as a span whose attrs carry the
        # decision serial, so join_recorder() can rebuild this join from
        # a dumped trace artifact
        from . import timeline as _timeline

        if _timeline.enabled():
            _timeline._record_complete(
                "outcome." + self.site, "outcome", self._t0, t1 - self._t0,
                {"decision": self.seq, "engine": self.engine},
            )


# ---------------------------------------------------------------------------
# refit feed + offline recorder join
# ---------------------------------------------------------------------------


def samples(site: str = "columnar.cutoff", n: Optional[int] = None) -> List[dict]:
    """Joined samples for ``site`` in refit-ready shape. For the columnar
    cutoff each sample carries ``{op, engine, shape, n, measured_us}`` —
    exactly the features ``costmodel.refit_from_outcomes`` fits on; other
    sites get their joined entries as-is."""
    out = []
    for e in LEDGER.tail(n):
        if e["site"] != site:
            continue
        if site == "columnar.cutoff":
            inputs = e.get("inputs") or {}
            na, nb = inputs.get("na"), inputs.get("nb")
            if na is None or nb is None or e.get("engine") is None:
                continue
            out.append({
                "op": inputs.get("op", "and"),
                "engine": e["engine"],
                "shape": inputs.get("shape"),
                "n": min(int(na), int(nb)),
                "measured_us": e["measured_s"] * 1e6,
            })
        else:
            out.append(dict(e))
    return out


def join_recorder(events, decisions_tail: Optional[List[dict]] = None) -> List[dict]:
    """Offline join over a flight-recorder window: complete spans whose
    attrs carry a ``decision`` serial are matched to the decision entries
    (by serial, cross-checked by trace id when both sides carry one) —
    the artifact-side view of the same ledger, usable on a dumped trace
    long after the live pending ring moved on."""
    from . import decisions as _decisions

    if decisions_tail is None:
        decisions_tail = _decisions.decisions()
    by_seq = {d.get("seq"): d for d in decisions_tail if d.get("seq") is not None}
    joined = []
    for e in events:
        if getattr(e, "ph", None) != "X" or not getattr(e, "attrs", None):
            continue
        seq = e.attrs.get("decision")
        if seq is None:
            continue
        d = by_seq.get(seq)
        if d is None:
            continue
        if d.get("trace") and e.trace and d["trace"] != e.trace:
            continue  # serial reuse across traces cannot happen, but be strict
        joined.append({
            "seq": seq,
            "site": d["site"],
            "decision": d["decision"],
            "trace": e.trace,
            "span": e.name,
            "measured_s": e.dur_ns / 1e9,
            "inputs": d.get("inputs", {}),
        })
    return joined


def summary() -> Dict[str, dict]:
    """Per-site regret/error rollup (the rb_top panel + bench row feed)."""
    return LEDGER.summary()


def tail(n: Optional[int] = None) -> List[dict]:
    return LEDGER.tail(n)


def drift() -> Dict[str, float]:
    """Current per-coefficient-cell drift as ``{"group/engine/shape": r}``."""
    return {"/".join(cell): round(v, 4) for cell, v in sorted(LEDGER.drift().items())}


def rebase_drift(cells=None) -> None:
    """Re-base drift EWMAs (and their gauge series) to 1.0 after a refit
    replaced the underlying coefficients; ``cells`` is an iterable of
    ``(group, engine, shape)`` tuples or ``"group/engine/shape"`` strings
    (None = every tracked cell). The cost facade calls this with exactly
    the cells a refit moved (ISSUE 12)."""
    tracked = LEDGER.drift()
    if cells is None:
        chosen = list(tracked)
    else:
        chosen = []
        for c in cells:
            cell = tuple(c.split("/")) if isinstance(c, str) else tuple(c)
            if cell in tracked:
                chosen.append(cell)
    LEDGER.rebase_drift(chosen)
    for cell in chosen:
        _DRIFT_RATIO.set(1.0, cell)


def reset() -> None:
    """Drop all ledger state (tests, bench windows); metrics keep their
    registry series (reset those via observe.reset like everything else)."""
    LEDGER.clear()


# ---------------------------------------------------------------------------
# anomaly dump (throttled, off the caller's critical path)
# ---------------------------------------------------------------------------


def _anomaly(site: str, joined: dict) -> None:
    global _LAST_DUMP_NS
    _ANOMALY_TOTAL.inc(1, (site,))
    now = time.perf_counter_ns()
    with _STATE_LOCK:
        if _LAST_DUMP_NS and now - _LAST_DUMP_NS < _DUMP_MIN_INTERVAL_NS:
            return
        _LAST_DUMP_NS = now
        path = _DUMP_PATH
        band = _BAND
    entries = LEDGER.tail()
    header = {
        "schema": DUMP_SCHEMA,
        "generated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "trigger": {k: joined.get(k) for k in
                    ("seq", "site", "engine", "error_ratio", "regret_s")},
        "band": list(band),
        "entries": len(entries),
    }

    def _write():
        from . import artifacts as _artifacts
        from .export import _atomic_write

        try:
            lines = [json.dumps(header, sort_keys=True)]
            lines.extend(json.dumps(e, sort_keys=True, default=str) for e in entries)
            # unified artifact sink (ISSUE 12): bare filenames land in
            # RB_TPU_ARTIFACT_DIR, never loose in the CWD
            _atomic_write(_artifacts.resolve(path), "\n".join(lines) + "\n")
        except OSError:  # rb-ok: exception-hygiene -- diagnostics must never kill the instrumented pipeline; the anomaly counter above still recorded the trigger
            pass

    threading.Thread(target=_write, name="rb-outcomes-dump", daemon=True).start()
