"""Decision provenance: a bounded in-memory log of every choice the
pipeline makes (ISSUE 9 tentpole, leg 2).

The system decides constantly — the planner picks an engine per node, the
dispatch prelude picks a start tier, the ladder degrades and trips
breakers, the pack cache admits/evicts/spills, the columnar router
accepts or rejects a cutoff — and before this module each decision left
at best a counter bump: "why was this query slow" required reverse-
engineering aggregate metrics. Now every decision site calls
:func:`record_decision` with the decision **and the inputs that drove
it**, landing in one bounded ring (``insights.decisions()`` is the read
API, ``scripts/rb_top.py`` renders the tail) and — when a timeline mode
is active — mirroring onto the flight recorder as a ``decision.<site>``
instant, so Perfetto shows the choice at the moment it was made, on the
thread and under the trace id that made it.

Entry shape (plain dicts, json-dumpable)::

    {"ts_ns": ..., "site": "query.plan", "decision": "device-or",
     "trace": "q00002a", "inputs": {"op": "or", "est_rows": 308211}}

Bounds & cost: the ring holds ``RB_TPU_DECISIONS_CAPACITY`` entries
(default 512) under a leaf lock — recording is a deque append plus one
labeled counter bump (``rb_tpu_decision_total{site}``), nanoseconds
against the microsecond-to-second decisions it records. Hot per-pair
sites (the columnar cutoff) record fully above the count gate, where the
op itself costs tens of microseconds; below it the 2 µs per-container
floor pays one int compare and a 1-in-N :class:`SampledSite` record
keeps the zone visible to the cost model's calibration data (ISSUE 10
satellite — see columnar/engine.py). ``configure(enabled=False)`` is
the bench twin's kill switch.

Trace ids, fingerprints, and other unbounded values belong in the entry
payload — never in metric labels (the metric-naming analysis rule now
rejects that).

Lock discipline: the log lock is a leaf — record() takes it only around
the deque append, so decision sites inside other framework locks (the
pack-cache evictor) nest safely.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from . import context as _context
from . import registry as _registry
from . import timeline as _timeline

DEFAULT_CAPACITY = 512

_DECISION_TOTAL = _registry.counter(
    _registry.DECISION_TOTAL,
    "Decisions recorded into the provenance log by deciding site",
    ("site",),
)

_ENABLED = True


class DecisionLog:
    """Thread-safe bounded ring of decision entries (newest last)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._lock = threading.Lock()  # leaf: guards the deque only
        self._ring: "deque[dict]" = deque(maxlen=int(capacity))  # guarded-by: self._lock
        self._total = 0  # guarded-by: self._lock

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    def record(self, entry: dict) -> None:
        with self._lock:
            self._ring.append(entry)
            self._total += 1

    def tail(self, n: Optional[int] = None) -> List[dict]:
        """The newest ``n`` entries (all retained when None), oldest
        first — point-in-time copies, safe to mutate."""
        with self._lock:
            entries = list(self._ring)
        if n is not None:
            entries = entries[-int(n):] if n > 0 else []
        return [dict(e) for e in entries]

    def total(self) -> int:
        """Decisions ever recorded (retained + overwritten)."""
        with self._lock:
            return self._total

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._total = 0

    def resize(self, capacity: int) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        with self._lock:
            self._ring = deque(self._ring, maxlen=int(capacity))


def _init_capacity() -> int:
    raw = os.environ.get("RB_TPU_DECISIONS_CAPACITY")
    try:
        return max(1, int(raw)) if raw else DEFAULT_CAPACITY
    except ValueError:
        return DEFAULT_CAPACITY


# The process-wide log every decision site records into.
LOG = DecisionLog(_init_capacity())


def configure(
    enabled: Optional[bool] = None, capacity: Optional[int] = None
) -> None:
    """Runtime overrides: ``enabled=False`` is the bench twin's kill
    switch (recording reduces to one bool check); ``capacity`` re-bounds
    the ring keeping the newest entries."""
    global _ENABLED
    if enabled is not None:
        _ENABLED = bool(enabled)
    if capacity is not None:
        LOG.resize(capacity)


def enabled() -> bool:
    return _ENABLED


# process-unique decision serials (itertools.count.__next__ is atomic
# under the GIL): the outcome join key (ISSUE 11) — a serial + the trace
# id identifies one verdict across the decision log, the pending outcome
# ledger, and the flight-recorder span attrs it gets threaded into
_SEQ = itertools.count(1)


def record_decision(
    site: str, decision: str, /, outcome: bool = False, **inputs
) -> Optional[int]:
    """Record one decision: what was chosen at ``site`` and the inputs
    that drove the choice. Also bumps ``rb_tpu_decision_total{site}`` and
    mirrors a ``decision.<site>`` flight-recorder instant when a timeline
    mode is active (the instant carries the ambient trace id).

    Returns the decision's process-unique serial (``entry["seq"]``).
    ``outcome=True`` additionally parks the decision in the outcome
    ledger's pending ring (ISSUE 11) — the site promises to resolve it
    with the measured execution (``outcomes.resolve``/``measure``), and
    the returned serial is the join key to thread into the measured
    span's attrs. Sites whose verdicts have no measurable execution
    (breaker flips, admits) record as before and stay out of the pending
    ring. Returns None when recording is disabled."""
    if not _ENABLED:
        return None
    seq = next(_SEQ)
    trace = _context.current_trace()
    entry: Dict = {
        "ts_ns": time.perf_counter_ns(),
        "seq": seq,
        "site": site,
        "decision": decision,
        "trace": trace,
    }
    if inputs:
        entry["inputs"] = inputs
    LOG.record(entry)
    _DECISION_TOTAL.inc(1, (site,))
    if outcome:
        from . import outcomes as _outcomes

        _outcomes.register(seq, site, inputs, trace)
    if _timeline.enabled():
        _timeline.instant(
            "decision." + site, "decision", decision=decision, seq=seq,
            **inputs
        )
    return seq


def decisions(n: Optional[int] = None) -> List[dict]:
    """The decision-log tail (newest ``n``, oldest first)."""
    return LOG.tail(n)


class SampledSite:
    """1-in-N sampling gate for decision sites too hot to record every
    verdict (ISSUE 10 satellite: the columnar cutoff's below-gate branch
    sits at the ~2 µs per-container C floor, yet the cost model's
    calibration data under-sampled exactly that regression zone because
    sub-gate verdicts were never recorded at all). ``tick()`` costs one
    int increment + mask compare off-path; every ``every``-th call returns
    True and the caller records one representative entry (tagged with the
    sampling factor so consumers can re-weight).

    The counter is deliberately lock-free: a racing increment can at
    worst skip or double one sample — sampling noise, not data loss —
    and a lock here would cost more than the branch it meters."""

    __slots__ = ("every", "_mask", "_n")

    def __init__(self, every: int = 64):
        every = max(1, int(every))
        if every & (every - 1):
            raise ValueError(f"sampling factor must be a power of two, got {every}")
        self.every = every
        self._mask = every - 1
        self._n = 0

    def tick(self) -> bool:
        n = self._n + 1
        self._n = n
        return not (n & self._mask)
