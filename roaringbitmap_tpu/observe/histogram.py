"""Log-bucketed latency histograms with quantile snapshots (ISSUE 6).

The fixed linear-ish buckets of :class:`~.registry.Histogram` were chosen
for coarse host-phase accounting; serving-style latency questions ("what is
the p99 of a delta repack?") need *relative* resolution across six orders
of magnitude — a 100 µs phase and a 20 s bucket build must both land in a
bucket whose width is a constant *ratio* of the value, or the quantile
estimate for one of them is garbage. :class:`LatencyHistogram` therefore
buckets on a log grid (default 8 buckets per decade, 1 µs .. 100 s, ratio
10^(1/8) ≈ 1.33 between bounds) and answers ``quantile(q)`` by cumulative
walk + linear interpolation inside the landing bucket — the estimate is
always within one bucket ratio of the true order statistic, which
tests/test_timeline.py pins against a numpy percentile oracle.

Registered alongside Counter/Gauge/Histogram on the same registry
(``latency_histogram(name, ...)``), it inherits the Prometheus ``histogram``
exposition (cumulative ``le`` buckets) and additionally publishes p50/p90/
p99 snapshots: ``snapshot()``/JSONL samples carry a ``quantiles`` map, and
the Prometheus text exporter emits summary-style ``name{quantile="0.5"}``
convenience samples next to the buckets (observe/export.py).

Naming contract (enforced by the metric-naming analysis rule): latency
histograms measure seconds, so their names end in ``_seconds``.

Pure stdlib, like the rest of the registry substrate.

Import note: the package attribute ``observe.histogram`` is the plain
registry-histogram *registration helper* (pre-existing API, kept); this
module is reached as ``from roaringbitmap_tpu.observe.histogram import
...`` — the ``import ... as`` spelling resolves the package attribute and
hands back the helper function instead.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence, Tuple

from . import registry as _registry
from .registry import Histogram, MetricError, Registry

# the quantiles every snapshot/export publishes (p50/p90/p99)
SNAPSHOT_QUANTILES: Tuple[float, ...] = (0.5, 0.9, 0.99)


def log_time_buckets(
    lo: float = 1e-6, hi: float = 100.0, per_decade: int = 8
) -> Tuple[float, ...]:
    """Upper bucket bounds on a log grid: ``lo * 10^(k/per_decade)`` until
    ``hi`` is covered, rounded to 4 significant digits so the Prometheus
    ``le`` labels stay readable. Defaults span 1 µs .. 100 s — sub-pack
    stages to the worst cold bucket build — at ratio ~1.33 per bucket."""
    if not (0 < lo < hi):
        raise MetricError(f"log_time_buckets: need 0 < lo < hi, got {lo}, {hi}")
    if per_decade < 1:
        raise MetricError(f"log_time_buckets: per_decade must be >= 1, got {per_decade}")
    out = []
    k = 0
    while True:
        b = float(f"{lo * 10.0 ** (k / per_decade):.4g}")
        out.append(b)
        if b >= hi:
            return tuple(out)
        k += 1


DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = log_time_buckets()


class LatencyHistogram(Histogram):
    """Log-bucketed histogram with quantile snapshots.

    Exposition ``kind`` stays ``"histogram"`` (the cumulative-``le`` form is
    what scrapers understand); the subclass adds the quantile estimator and
    folds p50/p90/p99 into every snapshot sample.
    """

    def __init__(
        self, registry, name, help, labelnames, buckets=DEFAULT_LATENCY_BUCKETS
    ):
        super().__init__(registry, name, help, labelnames, buckets=buckets)

    def _quantile_of_state(self, st: Mapping, q: float) -> float:
        """Estimate the ``q``-quantile from a series state dict: cumulative
        walk to the landing bucket, then linear interpolation between its
        edges. Values beyond the last bound clamp to it (the overflow
        bucket has no upper edge — a clamped answer beats a fabricated
        one). Caller holds the registry lock or owns a copied state."""
        count = st["count"]
        if count <= 0:
            return 0.0
        if not 0.0 <= q <= 1.0:
            raise MetricError(f"{self.name}: quantile {q} outside [0, 1]")
        rank = max(1.0, q * count)
        cum = 0
        for i, n in enumerate(st["slots"]):
            if n == 0:
                continue
            prev = cum
            cum += n
            if cum >= rank:
                if i >= len(self.buckets):  # overflow slot: clamp
                    return self.buckets[-1]
                hi = self.buckets[i]
                lo = self.buckets[i - 1] if i > 0 else 0.0
                return lo + (hi - lo) * ((rank - prev) / n)
        return self.buckets[-1]  # pragma: no cover - count>0 lands above

    def quantile(self, q: float, labels=()) -> float:
        """Point estimate of the ``q``-quantile for one labeled series
        (0.0 when the series has recorded nothing)."""
        st = self.get(labels)
        return 0.0 if st is None else self._quantile_of_state(st, q)

    def quantiles(
        self, labels=(), qs: Sequence[float] = SNAPSHOT_QUANTILES
    ) -> dict:
        """``{"p50": ..., "p90": ..., "p99": ...}`` for one series."""
        st = self.get(labels)
        return {
            _q_key(q): (0.0 if st is None else self._quantile_of_state(st, q))
            for q in qs
        }

    def _sample_dict(self, st: Mapping) -> dict:
        base = super()._sample_dict(st)
        base["quantiles"] = {
            _q_key(q): round(self._quantile_of_state(st, q), 9)
            for q in SNAPSHOT_QUANTILES
        }
        return base


def _q_key(q: float) -> str:
    """0.5 -> "p50", 0.99 -> "p99" (the sidecar/JSONL key form)."""
    return "p" + format(q * 100, "g")


def latency_histogram(
    name: str,
    help: str = "",
    labelnames=(),
    buckets=DEFAULT_LATENCY_BUCKETS,
    registry: Optional[Registry] = None,
) -> LatencyHistogram:
    """Register (idempotently) a :class:`LatencyHistogram` on ``registry``
    (default: the process registry). Same conflict-loudness as the other
    registration helpers; latency metric names must end in ``_seconds``."""
    if not name.endswith("_seconds"):
        raise MetricError(
            f"latency histogram {name!r} must end in '_seconds' "
            "(latency histograms measure seconds)"
        )
    reg = _registry.REGISTRY if registry is None else registry
    return reg._register(
        LatencyHistogram, name, help, labelnames, buckets=buckets
    )
