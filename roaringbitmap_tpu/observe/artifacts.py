"""Unified diagnostic-artifact sink (ISSUE 12 satellite).

Before this module every dump-on-anomaly hook scattered loose JSONL into
the process CWD (``rb_tpu_timeline_anomaly.jsonl``,
``rb_tpu_compile_anomaly.jsonl``, ``rb_tpu_outcomes_anomaly.jsonl``) —
which in a repo checkout means uncommitted noise next to the sources, and
in a fleet means diagnostics sprayed wherever the process happened to
start. Now every anomaly dump AND every flight bundle
(``observe.bundle``) routes through ONE directory:

* ``RB_TPU_ARTIFACT_DIR`` (default ``./rb_tpu_artifacts/``, gitignored)
  names the sink; ``configure(dir=...)`` overrides at runtime.
* :func:`resolve` is the write-side hook the dump sinks call: a bare
  filename lands inside the artifact dir; an explicit path (anything
  with a directory component, e.g. a test's ``tmp_path`` or an operator's
  absolute ``RB_TPU_TIMELINE_DUMP``) is honoured verbatim — the sink
  unifies defaults, it does not fight explicit routing.
* The directory is created lazily at first write — a healthy process
  never creates it at all.

Pure stdlib, importable before (and without) jax, like the rest of
``observe``.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

DEFAULT_DIR = "rb_tpu_artifacts"

_LOCK = threading.Lock()
_DIR = os.environ.get("RB_TPU_ARTIFACT_DIR") or DEFAULT_DIR  # guarded-by: _LOCK


def configure(dir: Optional[str] = None) -> None:
    """Runtime override of the artifact directory (tests point it at a
    tmp path; None keeps the current value)."""
    global _DIR
    if dir is not None:
        with _LOCK:
            _DIR = dir


def artifact_dir() -> str:
    """The sink directory as an absolute path (NOT created — creation is
    the writer's job, via :func:`resolve` / the bundle writer)."""
    with _LOCK:
        d = _DIR
    return os.path.abspath(d)


def resolve(name: str, mkdir: bool = True) -> str:
    """Where a diagnostic artifact named ``name`` should be written: a
    bare filename joins the artifact dir (created on demand when
    ``mkdir``); a path with any directory component is returned as-is —
    explicit routing always wins over the unified default."""
    if os.path.dirname(name):
        return name
    base = artifact_dir()
    if mkdir:
        os.makedirs(base, exist_ok=True)
    return os.path.join(base, name)
