"""Structure observatory: corpus-shape telemetry over the live working
sets (ISSUE 16 tentpole, leg 1).

PR 15 made streaming ingest *correct*; nothing kept it *optimal*. The
warm delta path patches containers in place and never revisits format
choice, so sustained writes drift arrays past the 4096 threshold,
fragment runs, and accrete epoch deltas — and until now nothing could
*see* it happening. This module is the seeing half: a cheap incremental
ledger over watched ``RoaringArray`` working sets exporting four
corpus-shape gauges:

* ``rb_tpu_structure_containers{format}`` — live container census by
  declared format (``FORMATS``: the Chambi et al. container model —
  array | bitmap | run);
* ``rb_tpu_structure_drift_ratio`` — actual serialized bytes over the
  size-rule-optimal bytes (what ``run_optimize`` would pick per
  container, Container.java:882); 1.0 = every container already in its
  cheapest format;
* ``rb_tpu_structure_fragmentation_count`` — p99 runs-per-run-container
  (run fragmentation: adversarial interleaved writes shatter runs);
* ``rb_tpu_structure_accretion_count`` — epoch-delta accretion depth:
  flip batches folded into the corpus since the last maintenance pass.

**Cost discipline**: the ledger piggybacks on the per-key dirty
tracking the mutators already pay for (``RoaringArray.touch_key`` /
``dirty_keys_since`` — ISSUE 4's pack-cache substrate), so the hot path
stays O(1): no mutator hook, no per-write scan. :meth:`refresh` (the
sentinel-tick / maintenance cadence) re-measures only the keys dirtied
since its last baseline; the per-format census, byte totals, and the
runs-per-run-container histogram are maintained as incremental deltas
against the per-key cache, so even refresh never walks clean keys. The
one full-corpus walk lives in :meth:`census` under a
``structure.census`` timeline span — the slow audit bench/ci run to
reconcile the incremental books (it rebuilds them from scratch, so any
bookkeeping drift heals there).

The maintenance tier (serve/maintain.py) consumes the same books:
:meth:`drift_targets` lists exactly the keys whose actual serialized
size exceeds the size-rule optimum — the pass rewrites those and
nothing else.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from . import registry as _registry
from . import timeline as _timeline

# the declared frozen container-format set (Chambi et al.; the
# metric-naming rule requires census label values to resolve through
# this mapping — FORMATS[fmt] — so a future or typo'd Container.TYPE
# can never mint a series)
FORMATS = {"array": "array", "bitmap": "bitmap", "run": "run"}

_CONTAINERS = _registry.gauge(
    _registry.STRUCTURE_CONTAINERS,
    "Live containers across watched working sets by declared format",
    ("format",),
)
_DRIFT_RATIO = _registry.gauge(
    _registry.STRUCTURE_DRIFT_RATIO,
    "Actual serialized bytes over size-rule-optimal bytes across watched "
    "working sets (1.0 = every container in its cheapest format)",
)
_FRAGMENTATION_COUNT = _registry.gauge(
    _registry.STRUCTURE_FRAGMENTATION_COUNT,
    "p99 runs per run container across watched working sets",
)
_ACCRETION_COUNT = _registry.gauge(
    _registry.STRUCTURE_ACCRETION_COUNT,
    "Epoch-delta accretion depth: flip batches folded into the corpus "
    "since the last maintenance pass",
)
_BYTES = _registry.gauge(
    _registry.STRUCTURE_BYTES,
    "Serialized bytes across watched working sets (actual vs size-rule "
    "optimal)",
    ("kind",),
)


def _measure(container) -> Tuple[str, int, int, int]:
    """(format, actual_bytes, optimal_bytes, nruns) for one container —
    the size rule run_optimize applies (Container.java:882): optimal is
    the cheaper of the run form and the efficient non-run form."""
    card = container.cardinality
    nruns = container.num_runs()
    run_size = 2 + 4 * nruns
    flat_size = 8192 if card > 4096 else 2 + 2 * card
    return (
        container.TYPE,
        int(container.serialized_size()),
        int(min(run_size, flat_size)),
        int(nruns),
    )


class _Row:
    """Per-bitmap incremental books: a dirty-tracking baseline plus the
    per-key measurements the aggregates are deltas of. ``gen`` pins the
    baseline to ONE RoaringArray identity — wholesale operators (|=, &=)
    rebind ``bm.high_low_container`` to a fresh array whose version
    counter restarts, so a generation change means the baseline is
    meaningless and the row rescans (the fingerprint contract)."""

    __slots__ = ("bm", "baseline", "gen", "per_key")

    def __init__(self, bm):
        self.bm = bm
        self.baseline = -1  # everything dirty on first refresh
        self.gen = -1
        self.per_key: Dict[int, Tuple[str, int, int, int]] = {}


class StructureLedger:
    """Thread-safe incremental structure books over named working sets.
    The lock is a leaf: refresh measures containers outside any other
    framework lock, and gauge exports go through the registry's own."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sets: Dict[str, List[_Row]] = {}  # guarded-by: self._lock
        # incremental aggregates (guarded-by: self._lock)
        self._counts: Dict[str, int] = {f: 0 for f in FORMATS}
        self._actual_bytes = 0
        self._optimal_bytes = 0
        self._run_hist: Dict[int, int] = {}  # nruns -> run-container count
        self._accretion = 0

    # -- registration --------------------------------------------------------

    def watch(self, name: str, bitmaps) -> None:
        """(Re)register a named working set (a list of RoaringBitmap).
        The initial measurement lands on the next :meth:`refresh` —
        watch itself is O(set size) bookkeeping, no container walk."""
        rows = [_Row(bm) for bm in bitmaps]
        with self._lock:
            old = self._sets.pop(name, None)
            if old is not None:
                for row in old:
                    self._retire_row(row)
            self._sets[name] = rows

    def forget(self, name: str) -> None:
        with self._lock:
            rows = self._sets.pop(name, None)
            if rows is not None:
                for row in rows:
                    self._retire_row(row)
        self._export()

    def watched(self) -> List[str]:
        with self._lock:
            return sorted(self._sets)

    # -- accretion depth (epoch ledger hook) ---------------------------------

    def accrete(self, batches: int = 1) -> None:
        """An epoch flip folded ``batches`` delta batches into the
        corpus — called from the publish stage (serve/epochs.py).
        Accretion depth is defined over WATCHED working sets (the docs
        above: batches folded since the last maintenance pass); with
        nothing watched there is no maintenance tier to settle it, so
        unwatched stores must not pump the delta-accretion rule."""
        with self._lock:
            if not self._sets:
                return
            self._accretion += max(0, int(batches))
            depth = self._accretion
        _ACCRETION_COUNT.set(depth)

    def settle_accretion(self) -> None:
        """A maintenance pass merged the accumulated deltas — depth
        back to zero (serve/maintain.py)."""
        with self._lock:
            self._accretion = 0
        _ACCRETION_COUNT.set(0)

    # -- incremental refresh -------------------------------------------------

    def refresh(self) -> dict:
        """Re-measure only the keys dirtied since the last refresh
        (O(dirty), the sentinel-tick cadence), fold the deltas into the
        aggregate books, export the gauges, and return the stats view."""
        refreshed = 0
        with self._lock:
            for rows in self._sets.values():
                for row in rows:
                    refreshed += self._refresh_row(row)
        self._export()
        return self.stats(dirty_refreshed=refreshed)

    def _refresh_row(self, row: _Row) -> int:
        hlc = row.bm.high_low_container
        version = hlc._version
        gen = hlc._gen
        dirty = (
            hlc.dirty_keys_since(row.baseline)
            if row.baseline >= 0 and gen == row.gen else None
        )
        if dirty is None:
            # wholesale mutation (or first sight): re-measure every key
            for key in list(row.per_key):
                self._drop_key(row, key)
            dirty = set(hlc.keys)
        row.baseline = version
        row.gen = gen
        n = 0
        for key in dirty:
            self._drop_key(row, key)
            c = hlc.get_container(key)
            if c is None:
                continue  # key removed since baseline
            m = _measure(c)
            row.per_key[key] = m
            self._credit(m, +1)
            n += 1
        return n

    def _drop_key(self, row: _Row, key: int) -> None:
        m = row.per_key.pop(key, None)
        if m is not None:
            self._credit(m, -1)

    def _retire_row(self, row: _Row) -> None:
        for m in row.per_key.values():
            self._credit(m, -1)
        row.per_key.clear()

    def _credit(self, m: Tuple[str, int, int, int], sign: int) -> None:
        fmt, actual, optimal, nruns = m
        if fmt in self._counts:
            self._counts[fmt] += sign
        self._actual_bytes += sign * actual
        self._optimal_bytes += sign * optimal
        if fmt == "run":
            new = self._run_hist.get(nruns, 0) + sign
            if new > 0:
                self._run_hist[nruns] = new
            else:
                self._run_hist.pop(nruns, None)

    # -- the slow full audit (bench/ci only) ---------------------------------

    def census(self) -> dict:
        """Full-corpus audit: rebuild every row's books from scratch
        under a ``structure.census`` timeline span, healing any
        incremental bookkeeping drift, then export and return stats."""
        with self._lock:
            sets = {name: list(rows) for name, rows in self._sets.items()}
        total = sum(len(rows) for rows in sets.values())
        with _timeline.tspan("structure.census", "structure", bitmaps=total):
            with self._lock:
                for rows in self._sets.values():
                    for row in rows:
                        self._retire_row(row)
                        row.baseline = -1
                refreshed = sum(
                    self._refresh_row(row)
                    for rows in self._sets.values()
                    for row in rows
                )
        self._export()
        return self.stats(dirty_refreshed=refreshed)

    # -- maintenance feed ----------------------------------------------------

    def drift_targets(self) -> List[Tuple[object, int, int]]:
        """[(bitmap, key, excess_bytes)] for every watched key whose
        actual serialized size exceeds the size-rule optimum — exactly
        the rewrite set a maintenance pass should touch (as of the last
        refresh; the pass re-checks under its own epoch brackets)."""
        out: List[Tuple[object, int, int]] = []
        with self._lock:
            for rows in self._sets.values():
                for row in rows:
                    for key, (fmt, actual, optimal, _n) in row.per_key.items():
                        if actual > optimal:
                            out.append((row.bm, key, actual - optimal))
        return out

    # -- views ---------------------------------------------------------------

    def stats(self, dirty_refreshed: Optional[int] = None) -> dict:
        with self._lock:
            counts = dict(self._counts)
            actual = self._actual_bytes
            optimal = self._optimal_bytes
            p99 = _hist_quantile(self._run_hist, 0.99)
            depth = self._accretion
            nsets = len(self._sets)
        out = {
            "working_sets": nsets,
            "containers": counts,
            "actual_bytes": actual,
            "optimal_bytes": optimal,
            "drift_ratio": round(actual / optimal, 4) if optimal else 1.0,
            "fragmentation_p99": p99,
            "accretion_depth": depth,
        }
        if dirty_refreshed is not None:
            out["dirty_refreshed"] = dirty_refreshed
        return out

    def _export(self) -> None:
        with self._lock:
            counts = dict(self._counts)
            actual = self._actual_bytes
            optimal = self._optimal_bytes
            p99 = _hist_quantile(self._run_hist, 0.99)
        for fmt in FORMATS:
            _CONTAINERS.set(counts.get(fmt, 0), (FORMATS[fmt],))
        _DRIFT_RATIO.set(round(actual / optimal, 4) if optimal else 1.0)
        _FRAGMENTATION_COUNT.set(p99)
        _BYTES.set(actual, ("actual",))
        _BYTES.set(optimal, ("optimal",))

    def reset(self) -> None:
        with self._lock:
            self._sets.clear()
            self._counts = {f: 0 for f in FORMATS}
            self._actual_bytes = 0
            self._optimal_bytes = 0
            self._run_hist.clear()
            self._accretion = 0
        self._export()
        _ACCRETION_COUNT.set(0)


def _hist_quantile(hist: Dict[int, int], q: float) -> int:
    """Quantile over a {value: count} histogram (nearest-rank)."""
    total = sum(hist.values())
    if total == 0:
        return 0
    rank = max(1, int(q * total + 0.5))
    seen = 0
    for value in sorted(hist):
        seen += hist[value]
        if seen >= rank:
            return int(value)
    return int(max(hist))


LEDGER = StructureLedger()
