"""One-shot diagnostic flight bundles (ISSUE 12 tentpole, leg 3).

When a health rule goes red, the individual anomaly dumps (timeline
tail, ledger tail) each capture one subsystem — but diagnosing a
production incident needs all of them from the SAME moment: the
timeline, the decisions and their measured outcomes, the metric totals,
every pricing authority's calibration, and the rule-evaluation history
that explains why the sentinel judged the process red. A **flight
bundle** is that cross-section as one manifest-indexed artifact
directory:

    <RB_TPU_ARTIFACT_DIR>/bundle_<utc>_<pid>_<seq>/
        MANIFEST.json       schema, trigger, file index (bytes + sha256)
        timeline.jsonl      flight-recorder dump (events + header)
        decisions.json      decision-log tail
        outcomes.json       ledger tail + per-site rollup + drift cells
        metrics.jsonl       full registry export (one series per line)
        calibration.json    cost facade: every authority's curves,
                            provenance, drift
        observatory.json    lock-wait stats, compile counts, breaker
                            states + open ages, pack-cache stats, hbm
                            reconciliation
        health.json         sentinel status, rule states, evaluation
                            history, actuation log

**Atomicity**: everything is written into a hidden ``.tmp-…`` sibling
and the directory is renamed into place as the last step — a crash
mid-write leaves a temp directory, never a half-bundle that tooling
would trust. The manifest is written last inside the temp dir, so a
bundle that HAS a manifest has every file the manifest indexes
(:func:`read_manifest` re-verifies sizes and digests).

Collection never raises past :func:`write_bundle`'s per-section guards:
a section whose collector fails records the error string in its place —
a diagnostic artifact with one broken panel beats no artifact at the
exact moment something is wrong.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import time
from typing import Callable, Dict, Optional

from . import artifacts as _artifacts

SCHEMA = "rb_tpu_bundle/1"
MANIFEST_NAME = "MANIFEST.json"

# process-unique bundle serials (itertools.count.__next__ is atomic under
# the GIL): two rules going red in the same second must not collide
_SEQ = itertools.count(1)


def _json_or_error(collect: Callable[[], object]) -> str:
    """One section's content: the collector's JSON, or a JSON error
    record when it failed — a broken panel must not sink the bundle."""
    try:
        return json.dumps(collect(), indent=1, sort_keys=True, default=str) + "\n"
    except Exception as e:  # rb-ok: exception-hygiene -- bundle sections degrade to an error record; diagnostics must never fail AT the moment of failure
        return json.dumps(
            {"error": f"{type(e).__name__}: {e}"}, sort_keys=True
        ) + "\n"


def _collect_sections(health_dump: Optional[dict]) -> Dict[str, str]:
    """{filename: content} for every bundle section except the manifest."""
    from . import decisions as _decisions
    from . import outcomes as _outcomes
    from . import timeline as _timeline
    from .export import to_jsonl as _to_jsonl

    sections: Dict[str, str] = {}

    def _timeline_jsonl() -> str:
        rec = _timeline.RECORDER
        header = {
            "schema": _timeline.DUMP_SCHEMA,
            "generated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "capacity": rec.capacity,
            "dropped": rec.dropped(),
        }
        lines = [json.dumps(header, sort_keys=True)]
        lines.extend(
            json.dumps(e.to_dict(), sort_keys=True) for e in rec.events()
        )
        return "\n".join(lines) + "\n"

    try:
        sections["timeline.jsonl"] = _timeline_jsonl()
    except Exception as e:  # rb-ok: exception-hygiene -- same degrade-to-error-record contract as _json_or_error
        sections["timeline.jsonl"] = json.dumps(
            {"error": f"{type(e).__name__}: {e}"}
        ) + "\n"
    sections["decisions.json"] = _json_or_error(_decisions.decisions)
    sections["outcomes.json"] = _json_or_error(
        lambda: {
            "tail": _outcomes.tail(),
            "summary": _outcomes.summary(),
            "drift": _outcomes.drift(),
        }
    )
    try:
        sections["metrics.jsonl"] = _to_jsonl()
    except Exception as e:  # rb-ok: exception-hygiene -- same degrade-to-error-record contract as _json_or_error
        sections["metrics.jsonl"] = json.dumps(
            {"error": f"{type(e).__name__}: {e}"}
        ) + "\n"

    def _calibration():
        from .. import cost as _cost

        return _cost.calibration_state()

    sections["calibration.json"] = _json_or_error(_calibration)

    def _observatory():
        from .. import insights as _insights
        from ..parallel import store as _store
        from ..robust import ladder as _ladder
        from . import compilewatch as _compilewatch
        from . import lockstats as _lockstats

        return {
            "locks": _lockstats.wait_stats(),
            "lock_timing": _lockstats.timing_enabled(),
            "compile": _compilewatch.compile_counts(),
            "breakers": _ladder.LADDER.states(),
            "breaker_open_ages": _ladder.LADDER.open_ages(),
            "pack_cache": _store.PACK_CACHE.stats(),
            "hbm": _store.hbm_reconciliation(),
            # serving panel (ISSUE 14): a red episode triggered by the
            # serving rules must ship the per-tenant state that fired it
            "serving": _insights.serving(),
            # epoch panel (ISSUE 15): which snapshot was serving, how
            # stale the log is, and the lineage that led here — the
            # freshness-lag-breach / epoch-flip-stall episodes' context
            "epochs": _insights.epochs(),
            # structure panel (ISSUE 16): format census + drift ratio +
            # maintenance-pass state — the structure-drift /
            # delta-accretion episodes' context
            "structure": _insights.structure(),
            # durable panel (ISSUE 17): which frozen epoch (if any) a
            # restart would recover to, plus torn-skip provenance — the
            # epoch-persist-stall / recovery-manifest-torn episodes'
            # context
            "durable": _insights.durable(),
        }

    sections["observatory.json"] = _json_or_error(_observatory)
    sections["health.json"] = _json_or_error(lambda: health_dump or {})
    return sections


def write_bundle(
    reason: str,
    trigger: Optional[dict] = None,
    dir: Optional[str] = None,
    health_dump: Optional[dict] = None,
) -> str:
    """Write one flight bundle; returns the final bundle directory path.
    ``reason`` is a short slug for the trigger (e.g. the red rule's
    name); ``trigger`` rides in the manifest verbatim; ``dir`` overrides
    the artifact sink (tests); ``health_dump`` is the sentinel's rule/
    actuation state at the moment of triggering."""
    base = _artifacts.artifact_dir() if dir is None else os.path.abspath(dir)
    os.makedirs(base, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    name = f"bundle_{stamp}_{os.getpid()}_{next(_SEQ):04d}"
    tmp = os.path.join(base, f".tmp-{name}")
    final = os.path.join(base, name)
    os.makedirs(tmp)
    sections = _collect_sections(health_dump)
    files = {}
    for fname, content in sorted(sections.items()):
        data = content.encode()
        with open(os.path.join(tmp, fname), "wb") as f:
            f.write(data)
        files[fname] = {
            "bytes": len(data),
            "sha256": hashlib.sha256(data).hexdigest(),
        }
    manifest = {
        "schema": SCHEMA,
        "generated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "reason": reason,
        "trigger": trigger or {},
        "pid": os.getpid(),
        "files": files,
    }
    with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.write("\n")
    os.rename(tmp, final)
    return final


def read_manifest(bundle_dir: str, verify: bool = True) -> dict:
    """Load and validate a bundle's manifest: schema, file presence, and
    (``verify=True``) byte sizes + sha256 digests. Raises ``ValueError``
    on any mismatch — a bundle that fails this was torn or tampered."""
    path = os.path.join(bundle_dir, MANIFEST_NAME)
    with open(path) as f:
        manifest = json.load(f)
    if manifest.get("schema") != SCHEMA:
        raise ValueError(
            f"bundle manifest schema {manifest.get('schema')!r} != {SCHEMA!r}"
        )
    files = manifest.get("files")
    if not isinstance(files, dict) or not files:
        raise ValueError("bundle manifest indexes no files")
    for fname, meta in files.items():
        fpath = os.path.join(bundle_dir, fname)
        if not os.path.isfile(fpath):
            raise ValueError(f"bundle file missing: {fname}")
        if not verify:
            continue
        with open(fpath, "rb") as f:
            data = f.read()
        if len(data) != meta.get("bytes"):
            raise ValueError(
                f"bundle file {fname}: {len(data)} bytes != manifest "
                f"{meta.get('bytes')}"
            )
        digest = hashlib.sha256(data).hexdigest()
        if digest != meta.get("sha256"):
            raise ValueError(f"bundle file {fname}: sha256 mismatch")
    return manifest
