"""Bit-sliced index queries: range predicates, filtered aggregates, top-k
(reference bsi/ module: RoaringBitmapSliceIndex setValue/compare/sum/topK;
the O'Neil compare chain is the framework's device north-star workload)."""

import numpy as np

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.models.bsi import Operation, RoaringBitmapSliceIndex


def main():
    rng = np.random.default_rng(0)
    n = 500_000
    user_ids = np.arange(n, dtype=np.uint32)
    scores = rng.integers(0, 1_000_000, size=n).astype(np.int64)

    index = RoaringBitmapSliceIndex()
    index.set_values((user_ids, scores))  # vectorized bulk load
    print("rows:", index.get_cardinality(), "slices:", index.bit_count())

    # range predicate over every row (device-fused O'Neil past the
    # dispatch threshold; mode='cpu'/'device' force an engine)
    high = index.compare(Operation.GE, 900_000, 0, None)
    print("scores >= 900k:", high.get_cardinality())

    # filtered: only the found-set columns participate
    cohort = RoaringBitmap(np.arange(0, n, 10, dtype=np.uint32))
    mid = index.compare(Operation.RANGE, 250_000, 750_000, cohort)
    print("cohort rows in [250k, 750k]:", mid.get_cardinality())

    # count-only query: on the device path only per-chunk popcounts come
    # back to host — for "how many?" questions this skips the result
    # stream-back and container rebuild entirely
    n_high = index.compare_cardinality(Operation.GE, 900_000, 0, None)
    assert n_high == high.get_cardinality()
    print("scores >= 900k (count-only):", n_high)

    # aggregates ride the same packed tensor
    total, count = index.sum(cohort)
    print(f"cohort sum={total} over {count} rows (mean {total // count})")

    top = index.top_k(cohort, 5)
    print("top-5 cohort scores:", sorted((int(scores[c]) for c in top), reverse=True))

    # distinct values over a found set (transpose; the buffer twin's
    # parallel_transpose_with_count yields value -> multiplicity)
    small = RoaringBitmapSliceIndex()
    small.set_values((np.arange(6, dtype=np.uint32), np.array([3, 1, 3, 2, 3, 1])))
    print("distinct values:", sorted(small.transpose().to_array().tolist()))

    # bulk point reads: one vectorized membership pass per slice answers a
    # whole batch of columns (vs one get_value walk per column)
    probe = np.arange(0, 1000, 7, dtype=np.uint32)
    values, exists = index.get_values(probe)
    assert (values[exists] == scores[probe[exists]]).all()
    print(f"bulk-read {probe.size} columns, {int(exists.sum())} present")

    # batched predicates: a whole array of thresholds in ONE device
    # dispatch (per-tenant cutoffs, histogram buckets, percentile scans —
    # all Q walks share a single HBM pass over the packed slice tensor)
    cutoffs = np.quantile(scores, [0.5, 0.9, 0.99]).astype(np.int64)
    counts = index.compare_cardinality_many(Operation.GE, cutoffs, found_set=cohort)
    for c, k in zip(cutoffs, counts):
        print(f"cohort rows with score >= {int(c)}: {int(k)}")


if __name__ == "__main__":
    main()
