"""Serialize to a byte array / buffer (reference
examples/src/main/java/SerializeToByteArrayExample.java +
SerializeToByteBufferExample.java): the portable RoaringFormatSpec bytes
round-trip and interoperate with the C/Go/Java implementations."""

from roaringbitmap_tpu import RoaringBitmap


def main():
    mrb = RoaringBitmap.bitmap_of(*range(100000, 200000, 3))
    print("cardinality:", mrb.get_cardinality())

    blob = mrb.serialize()
    bound = RoaringBitmap.maximum_serialized_size(mrb.get_cardinality(), 200001)
    print(f"serialized: {len(blob)} bytes (bound {bound})")
    assert len(blob) <= bound

    back = RoaringBitmap.deserialize(blob)
    assert back == mrb
    # memoryview works too — no copy on the way in
    assert RoaringBitmap.deserialize(memoryview(blob)) == mrb
    print("round-trip ok")


if __name__ == "__main__":
    main()
