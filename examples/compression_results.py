"""Compression behavior across value distributions (reference
examples/src/main/java/CompressionResults.java): bytes per value for
dense ranges, periodic values, and random scatter — showing where the
run/array/bitmap container choices win."""

import numpy as np

from roaringbitmap_tpu import RoaringBitmap


def report(name, values):
    bm = RoaringBitmap(values)
    bm.run_optimize()
    n = bm.get_cardinality()
    size = bm.serialized_size_in_bytes() if hasattr(bm, "serialized_size_in_bytes") else len(
        bm.serialize()
    )
    print(f"{name:24s} {n:9d} values  {size:9d} bytes  {size / n:6.3f} bytes/value")


def main():
    report("consecutive [0, 1M)", np.arange(1_000_000, dtype=np.uint32))
    report("every 2nd", np.arange(0, 2_000_000, 2, dtype=np.uint32))
    report("every 10th", np.arange(0, 10_000_000, 10, dtype=np.uint32))
    rng = np.random.default_rng(0)
    report(
        "random 1% of 100M",
        np.unique(rng.integers(0, 100_000_000, size=1_000_000)).astype(np.uint32),
    )


if __name__ == "__main__":
    main()
