"""Execution observability: which engine, layout, and backend served each
aggregation, how many bytes moved host->device, and where host time went
(insights.dispatch_counters + tracing; the reference's introspection-only
story extended to the device runtime).

Since ISSUE 1 everything records into the unified ``observe`` registry —
the legacy facades below still work unchanged, and the same numbers export
as Prometheus text, JSONL, or an atomic JSON sidecar for scrapers and CI.
"""

import json

import numpy as np

from roaringbitmap_tpu import FastAggregation, RoaringBitmap, insights, observe, tracing


def main():
    tracing.reset_timings()
    insights.reset_dispatch_counters()

    rng = np.random.default_rng(0)
    bms = [
        RoaringBitmap(rng.choice(1 << 21, size=20_000, replace=False).astype(np.uint32))
        for _ in range(64)
    ]
    with observe.span("examples.observability"):  # nested under this span
        union = FastAggregation.or_(*bms, mode="device")
    print("union cardinality:", union.get_cardinality())

    # the legacy facades: unchanged shapes, now registry-backed
    counters = insights.dispatch_counters()
    print("kernel dispatch:", counters["kernel"])  # pallas vs xla per shape class
    print("layout chosen:", counters["layout"])  # padded vs segmented-scan
    print("bytes shipped:", counters["transfer_bytes"])
    print("host phases:", json.dumps(tracing.timings(), indent=2))

    # the registry itself: nested span paths and machine-readable exports
    print("span paths:", sorted(observe.span_timings()))
    prom = observe.prometheus_text()
    print("prometheus exposition:", len(prom.splitlines()), "lines, e.g.")
    print("\n".join(l for l in prom.splitlines() if l.startswith("rb_tpu_store_layout")))
    observe.write_jsonl("/tmp/rb_tpu_metrics.jsonl")
    with observe.metrics_sidecar("/tmp/rb_tpu_metrics_sidecar.json"):
        pass  # snapshot written atomically on exit — bench.py wraps its whole run
    print("wrote /tmp/rb_tpu_metrics.jsonl and /tmp/rb_tpu_metrics_sidecar.json")


if __name__ == "__main__":
    main()
