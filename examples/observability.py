"""Execution observability: which engine, layout, and backend served each
aggregation, how many bytes moved host->device, and where host time went
(insights.dispatch_counters + tracing; the reference's introspection-only
story extended to the device runtime)."""

import json

import numpy as np

from roaringbitmap_tpu import FastAggregation, RoaringBitmap, insights, tracing


def main():
    tracing.reset_timings()
    insights.reset_dispatch_counters()

    rng = np.random.default_rng(0)
    bms = [
        RoaringBitmap(rng.choice(1 << 21, size=20_000, replace=False).astype(np.uint32))
        for _ in range(64)
    ]
    union = FastAggregation.or_(*bms, mode="device")
    print("union cardinality:", union.get_cardinality())

    counters = insights.dispatch_counters()
    print("kernel dispatch:", counters["kernel"])  # pallas vs xla per shape class
    print("layout chosen:", counters["layout"])  # padded vs segmented-scan
    print("bytes shipped:", counters["transfer_bytes"])
    print("host phases:", json.dumps(tracing.timings(), indent=2))


if __name__ == "__main__":
    main()
