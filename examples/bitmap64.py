"""64-bit bitmaps (reference examples/src/main/java/Bitmap64.java):
both 64-bit designs — the ART-backed Roaring64Bitmap and the
NavigableMap-of-32-bit-bitmaps Roaring64NavigableMap."""

from roaringbitmap_tpu import Roaring64Bitmap, Roaring64NavigableMap


def main():
    for cls in (Roaring64Bitmap, Roaring64NavigableMap):
        bm = cls()
        bm.add_long(1)
        bm.add_long(2)
        bm.add_long(1 << 40)  # far beyond the 32-bit universe
        bm.add_long((1 << 63) - 1)
        print(cls.__name__, "cardinality:", bm.get_long_cardinality())
        assert bm.contains(1 << 40)
        blob = bm.serialize()
        back = cls.deserialize(blob)
        assert back.get_long_cardinality() == bm.get_long_cardinality()
        print(cls.__name__, "serialized bytes:", len(blob))


if __name__ == "__main__":
    main()
