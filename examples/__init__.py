"""Runnable documentation — twin of the reference ``examples/`` module
(13 files, run via ``./gradlew :examples:runAll``, README.md:190).

Each module here is a self-contained script with a ``main()`` covering one
workflow; ``python -m examples.run_all`` executes every one (the runAll
analogue) and is smoke-tested by tests/test_examples.py.  The
``device_aggregation`` example is new — it shows the TPU batch path that
has no reference counterpart.
"""

EXAMPLES = [
    "basic",
    "bitmap64",
    "compression_results",
    "for_each",
    "immutable_example",
    "interval_check",
    "range_index",
    "bsi_queries",
    "similarity_matrix",
    "observability",
    "query_engine",
    "memory_mapping",
    "paged_iterator",
    "serialize_to_bytes",
    "serialize_to_disk",
    "serialize_to_string",
    "very_large_bitmap",
    "device_aggregation",
    "multi_chip",
]
