"""Range membership queries (reference
examples/src/main/java/IntervalCheck.java): contains_range /
intersects_range answer "is [start, end) fully / partly covered?"
without materializing the range."""

from roaringbitmap_tpu import RoaringBitmap


def main():
    bm = RoaringBitmap()
    bm.add_range(100, 200)
    bm.add(500)

    print("contains [100,200):", bm.contains_range(100, 200))  # True
    print("contains [100,201):", bm.contains_range(100, 201))  # False
    print("intersects [150,600):", bm.intersects_range(150, 600))  # True
    print("intersects [300,400):", bm.intersects_range(300, 400))  # False
    print("cardinality in [0,512):", bm.range_cardinality(0, 512))


if __name__ == "__main__":
    main()
