"""Paged iteration (reference examples/src/main/java/PagedIterator.java):
consume a large bitmap page by page with the batch iterator — constant
memory regardless of cardinality."""

import numpy as np

from roaringbitmap_tpu import RoaringBitmap

PAGE = 4096


def main():
    bm = RoaringBitmap(np.arange(0, 1_000_000, 3, dtype=np.uint32))
    pages = 0
    seen = 0
    for page in bm.batch_iterator(PAGE):
        pages += 1
        seen += len(page)
    assert seen == bm.get_cardinality()
    print(f"walked {seen} values in {pages} pages of <= {PAGE}")


if __name__ == "__main__":
    main()
