"""Visiting every value (reference examples/src/main/java/ForEachExample.java):
python iteration, the flyweight int-iterator, and the batch iterator —
the bulk path that should be preferred for large extractions."""

from roaringbitmap_tpu import RoaringBitmap


def main():
    bm = RoaringBitmap.bitmap_of(1, 2, 3, 100, 1000)

    total = sum(bm)  # python protocol
    it = bm.get_int_iterator()  # flyweight
    total2 = 0
    while it.has_next():
        total2 += it.next()
    total3 = sum(int(batch.sum()) for batch in bm.batch_iterator(256))  # batch
    assert total == total2 == total3
    print("sum of values:", total)


if __name__ == "__main__":
    main()
