"""Run every example — the ``./gradlew :examples:runAll`` analogue
(README.md:190).  ``python -m examples.run_all``."""

import importlib
import sys

from . import EXAMPLES


def main() -> int:
    failed = []
    for name in EXAMPLES:
        print(f"=== {name} " + "=" * max(1, 60 - len(name)))
        try:
            importlib.import_module(f"examples.{name}").main()
        except Exception as e:  # keep going; report at the end
            failed.append((name, e))
            print(f"FAILED: {e!r}")
    print("=" * 66)
    print(f"{len(EXAMPLES) - len(failed)}/{len(EXAMPLES)} examples ok")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
