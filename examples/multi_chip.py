"""Multi-chip scaling: wide aggregations and BSI range queries sharded
over a ``jax.sharding.Mesh`` (parallel/sharding.py — the distributed
story SURVEY.md §5 maps from the reference's single-JVM fork-join).

Setting ``config.mesh`` on the aggregation / BSI config routes every
device dispatch through ``shard_map`` over a 2D (containers, words)
mesh: container chunks split across chips, the word axis across the
second mesh axis, with XLA placing the collectives (one containers-axis
all-gather + words-axis all-reduce per reduce; the compiled placement is
recorded in MULTICHIP_HLO_r04.json). On a single chip the mesh
degenerates gracefully; under the test harness this runs on 8 virtual
CPU devices.
"""

import jax
import numpy as np

from roaringbitmap_tpu import (
    FastAggregation,
    Operation,
    RoaringBitmap,
    RoaringBitmapSliceIndex,
    insights,
)
from roaringbitmap_tpu.models.bsi import config as bsi_config
from roaringbitmap_tpu.parallel import sharding
from roaringbitmap_tpu.parallel.aggregation import config as agg_config

N_BITMAPS = 64


def main():
    import bench

    # a registered-but-unreachable TPU plugin would block jax.devices()
    # forever; probe in a subprocess and pin CPU on failure, like
    # device_aggregation (run_all's try/except cannot catch a hang)
    if not bench._probe_backend_once(timeout_s=60):
        print("(TPU backend unreachable; running the same path on CPU)")
        jax.config.update("jax_platforms", "cpu")

    n_dev = len(jax.devices())
    mesh = sharding.make_mesh(n_dev, words_axis=2)
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))} over {n_dev} device(s)")

    rng = np.random.default_rng(42)
    bms = [
        RoaringBitmap(np.unique(rng.integers(0, 1 << 20, 4000)).astype(np.uint32))
        for _ in range(N_BITMAPS)
    ]
    want = FastAggregation.naive_or(*bms)

    insights.reset_dispatch_counters()
    agg_config.mesh = bsi_config.mesh = mesh
    try:
        # wide OR: containers sharded across chips, OR-combine over ICI
        union = FastAggregation.or_(*bms, mode="device")
        assert union == want
        # count-only twin fetches just the popcounts (no result words)
        n_union = FastAggregation.or_cardinality(*bms, mode="device")
        assert n_union == want.get_cardinality()
        print(f"wide OR over the mesh: {n_union} distinct values")

        # BSI: a whole batch of thresholds in ONE sharded dispatch — all
        # Q O'Neil walks share the sharded [S, K, 2048] pack
        cols = np.arange(200_000, dtype=np.uint32)
        vals = (cols.astype(np.int64) * 48271) % (1 << 20)
        index = RoaringBitmapSliceIndex()
        index.set_values((cols, vals))
        cutoffs = np.quantile(vals, [0.5, 0.9, 0.99]).astype(np.int64)
        counts = index.compare_cardinality_many(Operation.GE, cutoffs, mode="device")
        assert counts.tolist() == [int((vals >= c).sum()) for c in cutoffs]
        for c, k in zip(cutoffs, counts):
            print(f"rows with value >= {int(c)}: {int(k)}")
    finally:
        agg_config.mesh = bsi_config.mesh = None

    print("mesh dispatches:", insights.dispatch_counters()["kernel"])


if __name__ == "__main__":
    main()
