"""The lazy query expression engine (ISSUE 2): build a boolean query as a
DAG, inspect the plan the cost-based planner chose (rewrites, operand
ordering, engine per node), execute it through the memoizing result cache,
and watch repeated queries short-circuit — the serving-system hot path
``(users_in_A & users_in_B) - opted_out | ...`` as a first-class object.
"""

import numpy as np

from roaringbitmap_tpu import Q, RoaringBitmap, insights
from roaringbitmap_tpu.query import ResultCache, evaluate_naive, execute, plan


def main():
    rng = np.random.default_rng(7)

    def segment(n):
        return RoaringBitmap(
            rng.choice(1 << 20, size=n, replace=False).astype(np.uint32)
        )

    users_in_a = segment(50_000)
    users_in_b = segment(60_000)
    users_in_c = segment(40_000)
    premium = segment(30_000)
    trial = segment(30_000)
    opted_out = segment(20_000)
    everyone = evaluate_naive(
        Q.or_(*[Q.leaf(b) for b in (users_in_a, users_in_b, users_in_c, premium, trial)])
    )

    # build lazily: operators on Q.leaf(...) nodes allocate DAG nodes only
    q = (
        (Q.leaf(users_in_a) & Q.leaf(users_in_b) | Q.leaf(users_in_c))
        - Q.leaf(opted_out)
        # "in at least 2 of these 3 programs" — the bit-sliced threshold
        | Q.threshold(2, Q.leaf(premium), Q.leaf(trial), Q.leaf(users_in_a))
        # complement against an explicit universe, De-Morgan'd by the planner
        & Q.not_(Q.leaf(opted_out), Q.leaf(everyone))
    )

    p = plan(q)
    print(p.explain())

    cache = ResultCache(max_entries=64)
    cold = execute(p, cache=cache)
    print("result cardinality:", cold.get_cardinality())
    assert cold == evaluate_naive(q), "planned execution must match naive algebra"

    warm = execute(q, cache=cache)  # same DAG, unchanged leaves: all hits
    assert warm == cold
    stats = cache.stats()
    print(f"cache after repeat: {stats['hits']} hits, {stats['misses']} misses")
    assert stats["hits"] > 0

    opted_out.add_many(np.arange(0, 2048, dtype=np.uint32))  # fingerprint bump
    fresh = execute(q, cache=cache)
    assert fresh == evaluate_naive(q), "mutated leaf must invalidate by key"
    print("after opt-out mutation:", fresh.get_cardinality())
    print("registry counters:", insights.query_counters()["cache"])


if __name__ == "__main__":
    main()
