"""RangeBitmap: a sealed range index over a value column
(reference RangeBitmap.java appender/map; queries lt/lte/gt/gte/eq/neq/
between with optional context pre-filters that skip untouched 2^16-row
chunks)."""

import numpy as np

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.models.range_bitmap import RangeBitmap


def main():
    rng = np.random.default_rng(0)
    prices = rng.integers(0, 10_000, size=300_000, dtype=np.uint64)

    # append-then-seal: the appender holds at most one 2^16-row chunk of
    # raw values; chunks flush to compressed per-slice containers
    app = RangeBitmap.appender(9_999)
    app.add_many(prices)
    index = app.build()
    print("rows:", index.row_count)

    cheap = index.lt(100)
    print("rows with price < 100:", cheap.get_cardinality())
    mid = index.between(2_500, 7_500)
    print("rows in [2500, 7500]:", mid.get_cardinality())

    # context pre-filter: only chunks present in the context are evaluated
    ctx = RoaringBitmap(np.arange(0, 300_000, 2, dtype=np.uint32))
    before = index.chunks_evaluated
    filtered = index.between(2_500, 7_500, context=ctx)
    print(
        "filtered rows:", filtered.get_cardinality(),
        "(chunks evaluated:", index.chunks_evaluated - before,
        "of", (index.row_count + 65535) // 65536, ")",
    )

    # serialize -> map: zero-copy reopen; payloads decode on first touch.
    # The sealed bytes are the REFERENCE wire format (RangeBitmap.java
    # Appender.serialize), so a buffer sealed by the Java library maps here
    # directly and vice versa; the round-3 native form stays readable via
    # serialize(form="native").
    data = index.serialize()
    mapped = RangeBitmap.map(data)
    assert mapped.lt(100) == cheap
    print("sealed bytes (reference format):", len(data))
    native = index.serialize(form="native")
    assert RangeBitmap.map(native).lt(100) == cheap
    print("native form bytes:", len(native), "(both forms map lazily)")


if __name__ == "__main__":
    main()
