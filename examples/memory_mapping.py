"""Memory-mapped bitmaps from disk (reference
examples/src/main/java/MemoryMappingExample.java + TestMemoryMapping):
write several serialized bitmaps into one file, np.memmap it, and map
ImmutableRoaringBitmaps over slices — no copy, no parse of payloads."""

import os
import tempfile

import numpy as np

from roaringbitmap_tpu import ImmutableRoaringBitmap, RoaringBitmap


def main():
    bitmaps = [
        RoaringBitmap(np.arange(i * 1000, i * 1000 + 500, dtype=np.uint32))
        for i in range(4)
    ]
    blobs = [b.serialize() for b in bitmaps]

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "bitmaps.bin")
        offsets = []
        with open(path, "wb") as f:
            for blob in blobs:
                offsets.append(f.tell())
                f.write(blob)
        size = os.path.getsize(path)

        mm = np.memmap(path, dtype=np.uint8, mode="r")
        mapped = []
        for i, off in enumerate(offsets):
            end = offsets[i + 1] if i + 1 < len(offsets) else size
            mapped.append(ImmutableRoaringBitmap(memoryview(mm)[off:end]))

        for orig, m in zip(bitmaps, mapped):
            assert m.get_cardinality() == orig.get_cardinality()
        union = ImmutableRoaringBitmap.or_(mapped[0], mapped[1])
        print("mapped", len(mapped), "bitmaps from", size, "bytes on disk")
        print("union of first two:", union.get_cardinality())


if __name__ == "__main__":
    main()
