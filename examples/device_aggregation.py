"""TPU batch aggregation — the path with no reference counterpart.

Thousands of bitmaps are packed once into a dense [rows, 2048]-uint32
device array (parallel/store.py), then a wide OR + cardinality runs as a
single fused XLA reduction with a Pallas popcount; the result streams
back through the append writer as a normal RoaringBitmap.  This is the
north-star configuration (BASELINE.md) in ~20 lines."""

import time

import numpy as np

from roaringbitmap_tpu import FastAggregation, RoaringBitmap

N_BITMAPS = 2000
VALUES_PER_BITMAP = 5000


def main():
    import bench

    # single short probe: an example should fall back within a minute,
    # not sit through bench.py's multi-probe retry window
    if not bench._probe_backend_once(timeout_s=60):
        import jax

        print("(TPU backend unreachable; running the same path on CPU)")
        jax.config.update("jax_platforms", "cpu")

    rng = np.random.default_rng(0)
    bitmaps = [
        RoaringBitmap(
            np.unique(rng.integers(0, 1 << 20, size=VALUES_PER_BITMAP)).astype(np.uint32)
        )
        for _ in range(N_BITMAPS)
    ]

    t0 = time.perf_counter()
    cpu = FastAggregation.or_(*bitmaps, mode="cpu")
    t_cpu = time.perf_counter() - t0

    t0 = time.perf_counter()
    dev = FastAggregation.or_(*bitmaps, mode="device")
    t_dev = time.perf_counter() - t0
    assert dev == cpu

    print(f"wide-OR of {len(bitmaps)} bitmaps -> cardinality {cpu.get_cardinality()}")
    print(f"cpu fold: {t_cpu * 1e3:.1f} ms   device batch: {t_dev * 1e3:.1f} ms")
    print("(device time includes one-time packing + compile on first call)")


if __name__ == "__main__":
    main()
