"""Serialize to disk (reference
examples/src/main/java/SerializeToDiskExample.java): file round-trip of
the portable format."""

import os
import tempfile

from roaringbitmap_tpu import RoaringBitmap


def main():
    rb = RoaringBitmap.bitmap_of(1, 2, 3, 1000)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "bitmap.bin")
        with open(path, "wb") as f:
            f.write(rb.serialize())
        with open(path, "rb") as f:
            back = RoaringBitmap.deserialize(f.read())
        assert back == rb
        print("disk round-trip ok:", os.path.getsize(path), "bytes")


if __name__ == "__main__":
    main()
