"""A bitmap beyond the 32-bit universe (reference
examples/src/main/java/VeryLargeBitmap.java): ranges over billions of
values stay tiny thanks to run containers; 64-bit types extend the
universe past 2^32."""

from roaringbitmap_tpu import Roaring64Bitmap, RoaringBitmap


def main():
    rb = RoaringBitmap()
    rb.add_range(0, 1 << 31)  # two billion values
    print("32-bit: cardinality", rb.get_cardinality())
    rb.run_optimize()
    print("32-bit: serialized", len(rb.serialize()), "bytes after run_optimize")

    big = Roaring64Bitmap()
    big.add_range(1 << 40, (1 << 40) + 1_000_000)
    print("64-bit: cardinality", big.get_long_cardinality(), "starting at 2^40")
    assert big.contains_long(1 << 40)


if __name__ == "__main__":
    main()
