"""All-pairs overlap and Jaccard similarity matrices over bitmap sets —
the similarity-join workload. The reference library can only assemble
this with n*m pairwise andCardinality calls; here the whole matrix is one
batched computation, and on TPU the counts are literally matmuls on the
systolic array (popcount(a AND b) == bits(a)·bits(b) over 0/1 vectors)."""

import numpy as np

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.parallel.batch import (
    pairwise_and_cardinality,
    pairwise_jaccard,
)


def main():
    rng = np.random.default_rng(0)
    # users-per-tag bitmaps: heavy overlap inside topic clusters
    n_users = 200_000
    clusters = [rng.choice(n_users, size=30_000, replace=False) for _ in range(3)]
    tags = []
    for t in range(12):
        base = clusters[t % 3]
        take = rng.random(base.size) < 0.6
        extra = rng.choice(n_users, size=2_000, replace=False)
        tags.append(
            RoaringBitmap(np.unique(np.concatenate([base[take], extra])).astype(np.uint32))
        )

    overlap = pairwise_and_cardinality(tags, tags)
    sim = pairwise_jaccard(tags, tags)
    print("overlap diagonal == cardinalities:",
          bool(np.all(overlap.diagonal() == [t.get_cardinality() for t in tags])))

    # most similar distinct pair
    np.fill_diagonal(sim, 0.0)
    i, j = np.unravel_index(np.argmax(sim), sim.shape)
    print(f"most similar tags: {i} ~ {j} (jaccard {sim[i, j]:.3f}, "
          f"same cluster: {i % 3 == j % 3})")

    # sanity vs a pairwise loop on one row
    want = [RoaringBitmap.and_cardinality(tags[0], t) for t in tags]
    assert overlap[0].tolist() == want
    print("row 0 matches pairwise loop:", overlap[0, :4].tolist(), "...")


if __name__ == "__main__":
    main()
