"""Basic set algebra (reference examples/src/main/java/Basic.java)."""

from roaringbitmap_tpu import RoaringBitmap


def main():
    rr = RoaringBitmap.bitmap_of(1, 2, 3, 1000)
    rr2 = RoaringBitmap()
    rr2.add_range(500, 1100)  # add a half-open range [500, 1100)

    print("cardinality:", rr.get_cardinality())
    print("contains 3:", rr.contains(3))

    rror = RoaringBitmap.or_(rr, rr2)  # new bitmap
    rr.ior(rr2)  # in-place union
    assert rror == rr
    print("union cardinality:", rr.get_cardinality())

    # iteration: python iterator protocol and explicit int-iterator
    first_five = [v for _, v in zip(range(5), rr)]
    print("first five:", first_five)
    it = rr.get_int_iterator()
    assert it.has_next() and it.next() == 1


if __name__ == "__main__":
    main()
