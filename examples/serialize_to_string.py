"""Serialize to a printable string (reference
examples/src/main/java/SerializeToStringExample.java): base64 text
round-trip — handy for JSON payloads and the fuzz Reporter's repro dumps."""

import base64

from roaringbitmap_tpu import RoaringBitmap


def main():
    mrb = RoaringBitmap.bitmap_of(*range(100000, 200000, 3))
    text = base64.b64encode(mrb.serialize()).decode("ascii")
    print("base64 length:", len(text), "prefix:", text[:32], "...")
    back = RoaringBitmap.deserialize(base64.b64decode(text))
    assert back == mrb
    print("string round-trip ok")


if __name__ == "__main__":
    main()
