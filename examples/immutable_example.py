"""Zero-copy immutable bitmaps (reference
examples/src/main/java/ImmutableRoaringBitmapExample.java): serialize a
mutable bitmap, map an ImmutableRoaringBitmap over the bytes without
deserialization, operate on it, and cast back to mutable."""

from roaringbitmap_tpu import ImmutableRoaringBitmap, MutableRoaringBitmap


def main():
    rr1 = MutableRoaringBitmap.bitmap_of(1, 2, 3, 1000)
    rr2 = MutableRoaringBitmap.bitmap_of(2, 3, 1010)
    blob1, blob2 = rr1.serialize(), rr2.serialize()

    # map: metadata parsed, containers stay views over the bytes
    irb1 = ImmutableRoaringBitmap(blob1)
    irb2 = ImmutableRoaringBitmap(blob2)
    print("mapped cardinalities:", irb1.get_cardinality(), irb2.get_cardinality())

    both = ImmutableRoaringBitmap.and_(irb1, irb2)
    print("intersection:", sorted(both))

    # O(1)-spirit cast immutable -> mutable (MutableRoaringBitmap.java toMutable)
    mutable = MutableRoaringBitmap.of(irb1)
    mutable.add(7)
    assert mutable.contains(7) and not irb1.contains(7)
    print("mutable copy diverged:", mutable.get_cardinality(), irb1.get_cardinality())


if __name__ == "__main__":
    main()
