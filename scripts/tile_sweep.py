"""Sweep Pallas tile configurations on the real chip and emit a GB/s table.

The wide/grouped reduces are memory-bound; the winner is whichever tiling
sustains the highest achieved HBM bandwidth (v5e-1 peak ~800 GB/s). Results
justify the ROW_TILE / G_TILE / G_ROW_TILE / GROUPED_PREFER_XLA defaults in
ops/pallas_kernels.py and are committed as a JSON artifact (VERDICT r3 #1/#2).

Round-4 additions over the round-3 sweep:
  * the flagship [66, 1450, 2048] shape (the bench.py working set) — the
    shape where XLA beat the Pallas grid 423 vs 137 GB/s in round 3;
  * the staged variants attacking that gap: fold="linear" (no halving
    temporaries), w_tile (word-axis grid split -> smaller double-buffered
    blocks), dimsem (Mosaic parallel/arbitrary dimension semantics).

Timing is steady-state: K reductions inside one jitted scan
(benchmarks.common.steady_state_reduce), because per-dispatch timing through
the axon tunnel is RPC-bound (~25-75 ms floor) and cannot distinguish
tilings — the first sweep measured every config at an identical ~1-2 GB/s.

Configs whose double-buffered input blocks exceed the ~16 MiB/core VMEM are
skipped up front: each remote-compile failure costs minutes through the
tunnel.

Run:  PYTHONPATH=/root/repo:$PYTHONPATH timeout 2400 python -u scripts/tile_sweep.py --json chip_artifacts/<ts>/tile_sweep.json
"""

import argparse
import json
import os
import sys
import time
import traceback

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

K = 32
REPS = 3
VMEM_BUDGET = 12 * 2**20  # leave headroom under the ~16 MiB/core VMEM

from benchmarks.common import fetch_device as _fetch  # noqa: E402
from benchmarks.common import steady_state_reduce  # noqa: E402

RECORDS = []


def _run(kind, shape, config, params, with_seed, arr, nbytes, k=K):
    # k recorded per row: the flagship shape runs a shorter scan (k=16)
    # than the top-level default, and the artifact must say so
    rec = {"kind": kind, "shape": list(shape), "config": config, "params": params, "k": k}
    try:
        t0 = time.time()
        s, _total = steady_state_reduce(arr, with_seed, k=k, reps=REPS)
        rec.update(
            ms=round(s * 1e3, 3),
            gbps=round(nbytes / s / 1e9, 1),
            wall_s=round(time.time() - t0, 1),
        )
        print(f"  {config:<34} {s*1e3:8.3f} ms  {rec['gbps']:7.1f} GB/s", flush=True)
    except Exception as e:
        rec["error"] = repr(e)[:300]
        rec["traceback"] = traceback.format_exc()[-800:]
        print(f"  {config:<34} ERROR {rec['error'][:120]}", flush=True)
    RECORDS.append(rec)
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", help="write the sweep table to this path")
    ap.add_argument("--skip-flagship", action="store_true", help="skip the 784 MB shape")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import device as dev
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    backend = jax.default_backend()
    print("backend:", backend, flush=True)
    print(f"steady-state timing: best of {REPS} x (scan of K={K} reductions)", flush=True)
    rng = np.random.default_rng(0)

    # ---- wide: [N, 2048] ----
    # two sizes: the historical 128 MiB shape (comparable to r3) and a
    # 512 MiB shape, because the 2026-07-31 scaling probe
    # (chip_artifacts/20260731T013545Z/wide_scaling_probe.json) showed the
    # 128 MiB rate is dominated by fixed per-iteration cost (28-59 GB/s
    # regardless of engine) while at >= 512 MiB the engines separate
    # (xla 228-318 vs pallas 109-186 GB/s). The digest crowns the LARGEST
    # wide shape, so the dispatch verdict now rides on the scale-relevant one.
    for n, wide_cfgs in (
        (
            16_384,
            [
                {"row_tile": 128},
                {"row_tile": 256},
                {"row_tile": 512},
                {"row_tile": 256, "fold": "linear"},
                {"row_tile": 256, "w_tile": 512},
                {"row_tile": 256, "w_tile": 512, "fold": "linear"},
                {"row_tile": 512, "w_tile": 1024, "dimsem": True},
                {"row_tile": 256, "w_tile": 512, "fold": "linear", "dimsem": True},
            ],
        ),
        (
            65_536,
            [
                {"row_tile": 256},
                {"row_tile": 512},
                {"row_tile": 256, "w_tile": 512},
                {"row_tile": 512, "w_tile": 1024, "dimsem": True},
            ],
        ),
    ):
        host = rng.integers(0, 1 << 32, size=(n, 2048), dtype=np.uint64).astype(np.uint32)
        arr = jnp.asarray(host)
        _fetch(arr.sum())  # flush the transfer before timing anything
        nbytes = arr.size * 4
        shape = (n, 2048)
        k = 16 if n > 30_000 else K  # bound the 512 MiB shape's wall clock
        print(f"\nwide [N={n}, 2048] ({nbytes/2**20:.0f} MiB) K={k}", flush=True)
        _run("wide", shape, "xla", {}, lambda w, s: dev.wide_reduce_with_cardinality(w ^ s, op="or"), arr, nbytes, k=k)
        for g in (32, 128, 512):
            _run(
                "wide", shape, f"xla 2stage g={g}", {"stage_groups": g},
                lambda w, s, g=g: dev.wide_reduce_two_stage(w ^ s, op="or", stage_groups=g),
                arr, nbytes, k=k,
            )
        for kw in wide_cfgs:
            label = "pallas " + " ".join(f"{k_}={v}" for k_, v in kw.items())
            _run(
                "wide", shape, label, kw,
                lambda w, s, kw=kw: pk.wide_reduce_cardinality_pallas(w, op="or", seed=s, **kw),
                arr, nbytes, k=k,
            )
        del arr, host

    # ---- grouped: [G, M, 2048] ----
    # census-like, skewed-wide, and (unless skipped) the flagship bench shape
    shapes = [(66, 512), (512, 64)]
    if not args.skip_flagship:
        shapes.append((66, 1450))
    for g, m in shapes:
        host3 = rng.integers(0, 1 << 32, size=(g, m, 2048), dtype=np.uint64).astype(np.uint32)
        arr3 = jnp.asarray(host3)
        _fetch(arr3.sum())
        nbytes = arr3.size * 4
        shape = (g, m, 2048)
        flagship = (g, m) == (66, 1450)
        k = 16 if flagship else K  # bound the 784 MB shape's wall clock
        print(f"\ngrouped [G={g}, M={m}, 2048] ({nbytes/2**20:.0f} MiB) K={k}", flush=True)
        _run(
            "grouped", shape, "xla", {},
            lambda w, s: dev.grouped_reduce_with_cardinality(w ^ s, op="or"),
            arr3, nbytes, k=k,
        )
        if flagship:
            cfgs = [
                {"g_tile": 8, "row_tile": 64},  # round-3 default: the 137 GB/s row
                {"g_tile": 8, "row_tile": 64, "fold": "linear"},
                {"g_tile": 8, "row_tile": 64, "dimsem": True},
                {"g_tile": 8, "row_tile": 64, "w_tile": 512},
                {"g_tile": 8, "row_tile": 128, "w_tile": 512},
                {"g_tile": 8, "row_tile": 128, "w_tile": 512, "fold": "linear"},
                {"g_tile": 8, "row_tile": 128, "w_tile": 512, "dimsem": True},
                {"g_tile": 8, "row_tile": 256, "w_tile": 256, "fold": "linear"},
                {"g_tile": 8, "row_tile": 128, "w_tile": 1024, "dimsem": True},
                {"g_tile": 16, "row_tile": 64, "w_tile": 512, "dimsem": True},
            ]
        else:
            cfgs = [
                {"g_tile": 8, "row_tile": 32},
                {"g_tile": 8, "row_tile": 64},
                {"g_tile": 16, "row_tile": 32},
                {"g_tile": 16, "row_tile": 64},
                {"g_tile": 8, "row_tile": 64, "fold": "linear"},
                {"g_tile": 8, "row_tile": 64, "w_tile": 512},
            ]
        for kw in cfgs:
            block = 4 * kw["g_tile"] * kw["row_tile"] * kw.get("w_tile", 2048)
            label = "pallas " + " ".join(f"{k_}={v}" for k_, v in kw.items())
            if 2 * block > VMEM_BUDGET:
                RECORDS.append(
                    {"kind": "grouped", "shape": list(shape), "config": label,
                     "params": kw, "skipped": "VMEM"}
                )
                print(f"  {label:<34} skipped (VMEM)", flush=True)
                continue
            _run(
                "grouped", shape, label, kw,
                lambda w, s, kw=kw: pk.grouped_reduce_cardinality_pallas(w, op="or", seed=s, **kw),
                arr3, nbytes, k=k,
            )

    # ---- O'Neil walk: [S, K, 2048] (the BSI engine's kernel) ----
    # the 100M-row shape scaled to bound the sweep's wall clock; crowned
    # (16, 512) on 2026-07-31 (oneil_tiling_probe.json) — re-crown each window
    from roaringbitmap_tpu.models.bsi import o_neil_math

    s_cnt, k_chunks = 32, 512  # 134 MB
    slices = rng.integers(0, 1 << 32, size=(s_cnt, k_chunks, 2048), dtype=np.uint64).astype(np.uint32)
    ebm = np.bitwise_or.reduce(slices, axis=0)
    bits = np.array([(0xA5A5A5A5 >> i) & 1 for i in range(s_cnt - 1, -1, -1)], dtype=bool)
    sl, bv, eb = jnp.asarray(slices), jnp.asarray(bits), jnp.asarray(ebm)
    _fetch(sl.sum())
    nbytes = sl.size * 4
    shape = (s_cnt, k_chunks, 2048)
    print(f"\noneil [S={s_cnt}, K={k_chunks}, 2048] ({nbytes/2**20:.0f} MiB) K={K}", flush=True)
    _run(
        "oneil", shape, "xla", {},
        lambda w, s: o_neil_math(w, bv, eb ^ s, eb, "GE"), sl, nbytes,
    )
    for kt, wt in ((8, 0), (16, 512), (8, 1024), (64, 512)):
        label = f"pallas k_tile={kt} w_tile={wt}"
        block = 2 * 4 * s_cnt * kt * (wt or 2048)  # double-buffered slices block
        if block > VMEM_BUDGET:
            RECORDS.append(
                {"kind": "oneil", "shape": list(shape), "config": label,
                 "params": {"k_tile": kt, "w_tile": wt}, "skipped": "VMEM"}
            )
            print(f"  {label:<34} skipped (VMEM)", flush=True)
            continue
        _run(
            "oneil", shape, label, {"k_tile": kt, "w_tile": wt},
            lambda w, s, kt=kt, wt=wt: pk.oneil_compare_pallas(
                w, bv, eb, eb, op="GE", k_tile=kt, w_tile=wt, seed=s
            ),
            sl, nbytes,
        )
    del sl, slices

    result = {
        "generated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "backend": backend,
        "devices": [str(d) for d in jax.devices()],
        "jax_version": jax.__version__,
        "steady_state_k": K,
        "reps": REPS,
        "records": RECORDS,
    }
    if args.json:
        os.makedirs(os.path.dirname(args.json) or ".", exist_ok=True)
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1)
        print("wrote", args.json, flush=True)


if __name__ == "__main__":
    main()
