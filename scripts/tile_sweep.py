"""Sweep Pallas tile sizes on the real chip and print a GB/s table.

The wide/grouped reduces are memory-bound; the winner is whichever tiling
sustains the highest achieved HBM bandwidth (v5e-1 peak ~800 GB/s). Results
are recorded in BENCH_NOTES.md and justify the ROW_TILE / G_TILE /
G_ROW_TILE defaults in ops/pallas_kernels.py (VERDICT r2 #3).

Timing is steady-state: K reductions inside one jitted scan
(benchmarks.common.steady_state_reduce), because per-dispatch timing through
the axon tunnel is RPC-bound (~25-75 ms floor) and cannot distinguish
tilings — the first sweep measured every config at an identical ~1-2 GB/s.

Configs whose double-buffered input blocks exceed the ~16 MiB/core VMEM are
skipped up front: a first sweep showed every such config (e.g. g_tile=8
row_tile=128 -> 2x8 MiB) fails remote compile with tpu_compile_helper
errors, and each failure costs minutes of retry through the tunnel.

Run:  PYTHONPATH=/root/repo:$PYTHONPATH timeout 900 python -u scripts/tile_sweep.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

K = 32
REPS = 3
VMEM_BUDGET = 12 * 2**20  # leave headroom under the ~16 MiB/core VMEM


from benchmarks.common import fetch_device as _fetch  # noqa: E402
from benchmarks.common import steady_state_reduce  # noqa: E402


def _time(with_seed, arr):
    s, _total = steady_state_reduce(arr, with_seed, k=K, reps=REPS)
    return s


def main():
    import jax
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import device as dev
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    print("backend:", jax.default_backend(), flush=True)
    print(f"steady-state timing: best of {REPS} x (scan of K={K} reductions)", flush=True)
    rng = np.random.default_rng(0)

    # ---- wide: [N, 2048] ----
    n = 16_384
    host = rng.integers(0, 1 << 32, size=(n, 2048), dtype=np.uint64).astype(np.uint32)
    arr = jnp.asarray(host)
    _fetch(arr.sum())  # flush the transfer before timing anything
    nbytes = arr.size * 4
    print(f"\nwide [N={n}, 2048] ({nbytes/2**20:.0f} MiB)", flush=True)
    t = _time(lambda w, s: dev.wide_reduce_with_cardinality(w ^ s, op="or"), arr)
    print(f"  xla            {t*1e3:8.3f} ms  {nbytes/t/1e9:7.1f} GB/s", flush=True)
    for g in (32, 128, 512):
        t = _time(
            lambda w, s, g=g: dev.wide_reduce_two_stage(w ^ s, op="or", stage_groups=g),
            arr,
        )
        print(
            f"  xla 2stage g={g:<4} {t*1e3:7.3f} ms  {nbytes/t/1e9:7.1f} GB/s", flush=True
        )
    for row_tile in (128, 256, 512):
        t = _time(
            lambda w, s, rt=row_tile: pk.wide_reduce_cardinality_pallas(
                w, op="or", row_tile=rt, seed=s
            ),
            arr,
        )
        print(
            f"  pallas rt={row_tile:<5} {t*1e3:8.3f} ms  {nbytes/t/1e9:7.1f} GB/s",
            flush=True,
        )

    # ---- grouped: [G, M, 2048]: census-like and skewed-wide shapes ----
    for g, m in ((66, 512), (512, 64)):
        host3 = rng.integers(0, 1 << 32, size=(g, m, 2048), dtype=np.uint64).astype(
            np.uint32
        )
        arr3 = jnp.asarray(host3)
        _fetch(arr3.sum())
        nbytes = arr3.size * 4
        print(f"\ngrouped [G={g}, M={m}, 2048] ({nbytes/2**20:.0f} MiB)", flush=True)
        t = _time(lambda w, s: dev.grouped_reduce_with_cardinality(w ^ s, op="or"), arr3)
        print(f"  xla                    {t*1e3:8.3f} ms  {nbytes/t/1e9:7.1f} GB/s", flush=True)
        for g_tile in (8, 16):
            for row_tile in (32, 64):
                block = 4 * g_tile * row_tile * 2048
                if 2 * block > VMEM_BUDGET:
                    print(f"  pallas gt={g_tile:<3} rt={row_tile:<5} skipped (VMEM)", flush=True)
                    continue
                for fold in ("log", "linear"):
                    t = _time(
                        lambda w, s, gt=g_tile, rt=row_tile, f=fold: pk.grouped_reduce_cardinality_pallas(
                            w, op="or", g_tile=gt, row_tile=rt, seed=s, fold=f
                        ),
                        arr3,
                    )
                    print(
                        f"  pallas gt={g_tile:<3} rt={row_tile:<3} {fold:<6} {t*1e3:8.3f} ms  {nbytes/t/1e9:7.1f} GB/s",
                        flush=True,
                    )


if __name__ == "__main__":
    main()
