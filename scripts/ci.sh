#!/bin/bash
# One-command CI gate (VERDICT r3 #8) — the analogue of the reference's
# per-push workflow (.github/workflows/java-all-versions.yml: tests x 4
# JDKs + analysis). Everything runs on the CPU backend (tests/conftest.py
# forces an 8-virtual-device CPU mesh; the chip-only suite lives in
# scripts/chip_suite.sh), exits nonzero on the first failure, and finishes
# in well under 10 minutes.
#
#   bash scripts/ci.sh            # full gate
#   bash scripts/ci.sh --fast     # skip the pytest suite (pre-push sanity)
set -euo pipefail
cd "$(dirname "$0")/.."
export PYTHONPATH=$PWD:${PYTHONPATH:-}

t0=$SECONDS
step() { echo; echo "=== ci: $1 (t+$((SECONDS - t0))s)"; }

step "static analysis (lexical + whole-program contract tiers, ISSUE 18)"
# the analysis half of the reference's per-push gate: zero non-baselined
# findings across BOTH tiers (per-file lexical rules + the ProjectContext
# contract/dataflow rules) or the push fails (runs in --fast mode too —
# it's seconds). scripts/analyze.py also reports the two per-rule finding
# counters (rb_tpu_analysis[_contract]_findings_total) in-process.
JAX_PLATFORMS=cpu python scripts/analyze.py --check --contracts

step "knob table drift (KNOBS.md vs the tree's RB_TPU_* reads)"
JAX_PLATFORMS=cpu python scripts/analyze.py --check-knobs

if [[ "${1:-}" == "--fast" ]]; then
  step "analyze --diff wall-time budget (incremental pre-push path)"
  # the --diff mode is the editor-loop entry point: lexical tier over the
  # files changed vs HEAD only (contracts stay whole-tree). Assert it
  # stays interactive — a full ProjectContext build + a scoped lexical
  # pass in well under 10 s on this tree (~seconds of margin: the budget
  # catches an accidental O(files^2) extractor, not scheduler jitter)
  JAX_PLATFORMS=cpu python - <<'EOF'
import subprocess, sys, time
t0 = time.monotonic()
p = subprocess.run(
    [sys.executable, "scripts/analyze.py", "--check", "--contracts",
     "--diff", "HEAD"], capture_output=True, text=True)
wall = time.monotonic() - t0
sys.stdout.write(p.stdout)
sys.stderr.write(p.stderr)
if p.returncode != 0:
    raise SystemExit(f"analyze --diff failed (exit {p.returncode})")
if wall > 10.0:
    raise SystemExit(f"analyze --diff took {wall:.1f}s (budget 10s)")
print(f"analyze --diff ok in {wall:.2f}s (budget 10s)")
EOF
fi

if [[ "${1:-}" != "--fast" ]]; then
  step "pytest (full suite incl. Mosaic block-rule checks)"
  python -m pytest tests/ -q
fi

step "fuzz smoke (500 iterations x 31 invariant families)"
python -m roaringbitmap_tpu.fuzz 500 > /tmp/ci_fuzz.log 2>&1 \
  || { tail -20 /tmp/ci_fuzz.log; exit 1; }
tail -1 /tmp/ci_fuzz.log

step "query engine (differential fuzz + benchmark contract)"
# planner+executor vs naive set algebra on sampled DAGs (both regimes),
# then the query benchmark's four-way contract with sane positive timings
JAX_PLATFORMS=cpu python - <<'EOF'
from roaringbitmap_tpu import fuzz
fuzz.verify_query_invariance("ci-query-differential", iterations=40, seed=51)
fuzz.verify_query_invariance(
    "ci-query-differential(device)", iterations=15, seed=52, mode="device")
print("query differential ok (55 DAGs, cpu + forced-device engines)")
from benchmarks import query
rs = {r.benchmark: r.value for r in query.run(reps=1, datasets=["census1881"], limit=32)}
need = {"queryNaive", "queryPlanned", "queryPlannedColdCache", "queryPlannedWarmCache",
        "queryPlannedColdPack", "queryPlannedWarmPack"}
missing = need - set(rs)
if missing:
    raise SystemExit("query bench contract: missing %s" % sorted(missing))
if not all(v > 0 for v in rs.values()):
    raise SystemExit("query bench contract: non-positive timing %r" % rs)
print("query bench ok (planned %.1fx vs naive, warm cache %.1fx, warm pack %.1fx vs cold)"
      % (rs["queryNaive"] / rs["queryPlanned"],
         rs["queryNaive"] / rs["queryPlannedWarmCache"],
         rs["queryPlannedColdPack"] / rs["queryPlannedWarmPack"]))
EOF

step "columnar engine parity (census1881 sample vs per-container, ISSUE 5)"
# the batched pairwise engine must agree with the per-container engine on
# every op over a real-corpus sample, and must actually have engaged (the
# counter proves the router didn't silently fall back)
JAX_PLATFORMS=cpu python - <<'EOF'
from benchmarks import common
from roaringbitmap_tpu import columnar, insights
from roaringbitmap_tpu.models.roaring import RoaringBitmap as RB

bms = common.corpus_bitmaps("census1881", limit=64)
pairs = list(zip(bms[:-1], bms[1:]))
ops = {"and": RB.and_, "or": RB.or_, "xor": RB.xor, "andnot": RB.andnot}
checked = 0
for a, b in pairs:
    for name, op in ops.items():
        got = op(a, b)
        with columnar.disabled():
            want = op(a, b)
        if got != want:
            raise SystemExit("columnar parity broke: %s" % name)
        checked += 1
    with columnar.disabled():
        wc, wi = RB.and_cardinality(a, b), RB.intersects(a, b)
    if RB.and_cardinality(a, b) != wc or RB.intersects(a, b) != wi:
        raise SystemExit("columnar cardinality/intersects parity broke")
counts = insights.columnar_counters()["batch"]
if not sum(counts.values()):
    raise SystemExit("columnar engine never engaged on the census sample")
print("columnar parity ok (%d op pairs; %d batched container-pairs)"
      % (checked, sum(counts.values())))
EOF

step "chaos gate (ISSUE 7): tier-1 subset + differential under RB_TPU_FAULTS"
# a tier-1 subset runs once under the fixed seeded fault schedule: every
# injected fault must be absorbed by the degradation ladder (zero escaped
# exceptions) and every asserted result must stay bit-exact (zero
# divergence — the tests assert values, so a stale/partial degrade fails)
JAX_PLATFORMS=cpu RB_TPU_FAULTS=ci-chaos-seed \
  python -m pytest tests/test_aggregation.py tests/test_query.py \
  tests/test_query_fusion.py -q
# then the explicit differential: randomized op/query sequences under
# seeded schedules vs the mid-schedule no-fault oracle, plus the fixed
# ci-chaos-seed schedule exercised end-to-end with the new rb_tpu_*
# robustness metric names validated against the naming convention
JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
from roaringbitmap_tpu import fuzz, insights, observe
from roaringbitmap_tpu.models.roaring import RoaringBitmap as RB
from roaringbitmap_tpu.parallel.aggregation import FastAggregation as FA
from roaringbitmap_tpu.robust import faults, ladder

fuzz.verify_fault_schedule_invariance("ci-fault-differential", iterations=150, seed=56)
print("fault-schedule differential ok (150 randomized schedules)")

# the fired/degraded assertions below must gate THIS loop, not counts the
# differential above already accumulated in the same interpreter: snapshot
# first, assert on the delta
before = insights.robust_counters()
faults.install("ci-chaos-seed:0.3")
rng = np.random.default_rng(0)
bms = [RB(np.sort(rng.choice(1 << 20, 3000, replace=False)).astype(np.uint32))
       for _ in range(4)]
with faults.suspended():
    want = FA.or_(*bms, mode="cpu")
for _ in range(30):
    ladder.LADDER.reset()  # keep the device tier attempting every round
    if FA.or_(*bms, mode="device") != want:
        raise SystemExit("chaos gate: result diverged under ci-chaos-seed")
faults.clear()
rc = insights.robust_counters()
fired = sum(rc["faults"].values()) - sum(before["faults"].values())
degraded = sum(rc["degrade"].values()) - sum(before["degrade"].values())
if fired <= 0:
    raise SystemExit("chaos gate: the ci-chaos-seed schedule never fired")
if degraded <= 0:
    raise SystemExit("chaos gate: no ladder degradations recorded under chaos")
for name in (observe.DEGRADE_TOTAL, observe.BREAKER_TRANSITIONS_TOTAL,
             observe.RETRY_TOTAL, observe.FAULT_INJECTED_TOTAL,
             observe.DEADLINE_TOTAL):
    if not (name.startswith("rb_tpu_") and name.endswith("_total")):
        raise SystemExit("robustness metric violates naming convention: %r" % name)
print("chaos gate ok (faults fired at %d sites; degrades %s)"
      % (len(rc["faults"]), sorted(rc["degrade"])))
EOF

step "bench.py --smoke (end-to-end north-star path, CPU)"
# validate the driver contract, not just the exit code: exactly the keys
# BENCH_r*.json records, with a sane positive speedup
rm -f /tmp/ci_bench_metrics.json /tmp/ci_bench.json /tmp/ci_bench_timeline.json
rm -rf /tmp/ci_artifacts
JAX_PLATFORMS=cpu BENCH_METRICS_OUT=/tmp/ci_bench_metrics.json \
  BENCH_JSON_OUT=/tmp/ci_bench.json \
  BENCH_TIMELINE_OUT=/tmp/ci_bench_timeline.json \
  RB_TPU_ARTIFACT_DIR=/tmp/ci_artifacts \
  python bench.py --smoke | python -c '
import json, sys
line = sys.stdin.readlines()[-1]
r = json.loads(line)
if set(r) != {"metric", "value", "unit", "vs_baseline"}:
    raise SystemExit("bench contract: wrong keys %s" % sorted(r))
if not (r["value"] > 0 and r["vs_baseline"] > 0):
    raise SystemExit("bench contract: non-positive %s" % r)
print("bench contract ok (vs_baseline %s)" % r["vs_baseline"])'

step "pack-cache rows in the bench artifact (ISSUE 4 contract)"
# cold/warm/delta schema: the warm lookup must be cheaper than the cold
# pack, and the delta repack must ship exactly the mutated containers
# (O(k) rows, not O(N)) — asserted on the committed-artifact meta block
python -c '
import json
m = json.load(open("/tmp/ci_bench.json"))["meta"]
need = {"pack_cache_hit_ratio", "delta_repack_s", "pack_warm_s",
        "pack_delta_rows", "pack_mutated_containers"}
missing = need - set(m)
if missing:
    raise SystemExit("bench pack-cache contract: missing %s" % sorted(missing))
if not (0.0 <= m["pack_cache_hit_ratio"] <= 1.0):
    raise SystemExit("bench pack-cache contract: bad hit ratio %r" % m)
if not (0 < m["pack_warm_s"] < m["pack_s"]):
    raise SystemExit("bench pack-cache contract: warm lookup not cheaper than cold pack %r" % m)
if m["pack_delta_rows"] != m["pack_mutated_containers"]:
    raise SystemExit("bench pack-cache contract: delta shipped %s rows for %s mutations"
                     % (m["pack_delta_rows"], m["pack_mutated_containers"]))
if not m["delta_repack_s"] > 0:
    raise SystemExit("bench pack-cache contract: non-positive delta_repack_s %r" % m)
if not m.get("degraded_fold_s", 0) > 0:
    raise SystemExit("bench robustness contract: missing/non-positive degraded_fold_s %r"
                     % m.get("degraded_fold_s"))
print("pack-cache rows ok (hit ratio %s, delta %s rows in %ss; degraded_fold_s %s)"
      % (m["pack_cache_hit_ratio"], m["pack_delta_rows"], m["delta_repack_s"],
         m["degraded_fold_s"]))'

step "columnar dispatch floor in the bench artifact (ISSUE 5 contract)"
# the bench must have run its in-bench parity gate and recorded the
# per-container dispatch floor before/after (the smoke numbers gate
# presence and sanity; the >=2x claim lives in the full-run BENCH_r*.json)
python -c '
import json
m = json.load(open("/tmp/ci_bench.json"))["meta"]
col = m.get("columnar")
if not isinstance(col, dict):
    raise SystemExit("bench columnar contract: missing meta.columnar block")
need = {"parity_ok", "n_pairs", "and2by2_percontainer_ns", "and2by2_columnar_ns",
        "and2by2_speedup", "andcard_percontainer_ns", "andcard_columnar_ns",
        "andcard_speedup", "cpu_fold_percontainer_s", "fold_speedup"}
missing = need - set(col)
if missing:
    raise SystemExit("bench columnar contract: missing %s" % sorted(missing))
if col["parity_ok"] is not True:
    raise SystemExit("bench columnar contract: parity gate did not pass")
if not all(col[k] > 0 for k in need - {"parity_ok"}):
    raise SystemExit("bench columnar contract: non-positive floor %r" % col)
print("columnar floor ok (and2by2 %.2fx, andCardinality %.2fx, cpu fold %.2fx)"
      % (col["and2by2_speedup"], col["andcard_speedup"], col["fold_speedup"]))'

step "columnar device tier + cutoff model (ISSUE 10 contract)"
# the bench must have run the in-bench device≡CPU parity sweep and
# recorded the three-way twin rows + the cost-model accuracy row; on the
# CPU backend the mid-size routed verdict must NOT be the device tier
# (r11-identical routing — the >=1.5x-vs-columnar-CPU dense claim gates
# accelerator artifacts, not the CPU smoke)
python -c '
import json
m = json.load(open("/tmp/ci_bench.json"))["meta"]
cd = m.get("columnar_device")
if not isinstance(cd, dict):
    raise SystemExit("columnar device contract: missing meta.columnar_device")
need = {"parity_ok", "n_pairs", "backend", "and2by2_device_ns",
        "and2by2_device_vs_cpu", "or2by2_device_ns", "or2by2_device_vs_cpu",
        "routed_tier_midsize", "cost_model"}
missing = need - set(cd)
if missing:
    raise SystemExit("columnar device contract: missing %s" % sorted(missing))
if cd["parity_ok"] is not True:
    raise SystemExit("columnar device contract: device parity sweep did not pass")
if not (cd["and2by2_device_ns"] > 0 and cd["or2by2_device_ns"] > 0):
    raise SystemExit("columnar device contract: non-positive twin rows %r" % cd)
if cd["backend"] == "cpu" and cd["routed_tier_midsize"] == "columnar-device":
    raise SystemExit("columnar device contract: CPU host routed the device tier")
cm = cd["cost_model"]
if not cm.get("calibrated"):
    raise SystemExit("columnar device contract: cost model never calibrated")
if not (cm["cells"] >= 6 and 0.0 <= cm["accuracy"] <= 1.0):
    raise SystemExit("columnar device contract: bad accuracy row %r" % cm)
if cm["accuracy"] < 0.5:
    raise SystemExit("columnar device contract: model accuracy %s below 0.5"
                     % cm["accuracy"])
print("columnar device ok (and2by2 dev %.2fx vs cpu, or2by2 %.2fx; "
      "midsize routes %s on %s; model accuracy %s over %d cells)"
      % (cd["and2by2_device_vs_cpu"], cd["or2by2_device_vs_cpu"],
         cd["routed_tier_midsize"], cd["backend"], cm["accuracy"], cm["cells"]))'

step "routed small-operand floor (ISSUE 10: no case below 0.9x vs per-container)"
# the jmh-grid shape (single-value containers) through the DEFAULT routed
# path vs the pinned per-container walk: the router must keep these
# per-container, so the routed wall prices within noise of the floor
JAX_PLATFORMS=cpu python - <<'EOF'
import time
import numpy as np
from roaringbitmap_tpu import columnar
from roaringbitmap_tpu.models.roaring import RoaringBitmap as RB

K = 1 << 16
ident = np.arange(10_000, dtype=np.uint64) * K
b1 = RB(ident.astype(np.uint32))
b2 = b1.clone()
tier = columnar.route(b1.high_low_container, b2.high_low_container, record=False)
if tier != "per-container":
    raise SystemExit("routed floor: jmh identical-case routed %r" % tier)

def best(fn, reps=5):
    t = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        t = min(t, time.perf_counter() - t0)
    return t

routed = best(lambda: RB.and_(b1, b2))
with columnar.disabled():
    floor = best(lambda: RB.and_(b1, b2))
ratio = floor / routed
if ratio < 0.85:  # 0.9 contract with host-noise slack
    raise SystemExit("routed floor: routed path at %.2fx of per-container" % ratio)
print("routed floor ok (identical:and routed %.2fx of the per-container floor)" % ratio)
EOF

step "bench metrics sidecar (observe/ registry snapshot contract)"
# same SystemExit discipline as the driver-contract check above: the smoke
# run must leave a schema-valid registry snapshot behind
python -c '
import json, os, sys
path = "/tmp/ci_bench_metrics.json"
if not os.path.isfile(path):
    raise SystemExit("metrics sidecar missing: %s" % path)
try:
    with open(path) as f:
        m = json.load(f)
except ValueError as e:
    raise SystemExit("metrics sidecar is not valid JSON: %s" % e)
required = {"kernel", "layout", "transfer_bytes", "spans"}
missing = required - set(m)
if missing:
    raise SystemExit("metrics sidecar lacks keys %s (has %s)" % (sorted(missing), sorted(m)))
for key in ("kernel", "layout", "transfer_bytes"):
    if not (isinstance(m[key], dict) and all(isinstance(v, int) for v in m[key].values())):
        raise SystemExit("metrics sidecar %s must map str->int: %r" % (key, m[key]))
if not (m["layout"] and m["spans"]):
    raise SystemExit("metrics sidecar recorded no layouts/spans: %r" % sorted(m))
pack = m.get("registry", {}).get("rb_tpu_pack_cache_hits_total", {}).get("samples", [])
if not pack:
    raise SystemExit("metrics sidecar recorded no pack-cache hits (ISSUE 4)")
col = m.get("registry", {}).get("rb_tpu_columnar_batch_total", {}).get("samples", [])
if not col:
    raise SystemExit("metrics sidecar recorded no columnar batches (ISSUE 5)")
deg = m.get("registry", {}).get("rb_tpu_degrade_total", {}).get("samples", [])
if not deg:
    raise SystemExit("metrics sidecar recorded no ladder degradations (ISSUE 7: "
                     "the degraded_fold_s row must ride the ladder)")
print("metrics sidecar ok (layouts %s, %d span paths, pack-cache hits %s, columnar pairs %s, degrades %s)"
      % (m["layout"], len(m["spans"]), sum(s["value"] for s in pack),
         sum(s["value"] for s in col), sum(s["value"] for s in deg)))'

step "timeline artifact (BENCH_TIMELINE.json schema + stage attribution, ISSUE 6)"
# the flight-recorder artifact must be Perfetto-loadable trace-event JSON
# and its named stages must attribute >=90% of the traced pack and delta
# walls — the decomposition ROADMAP item 1 consumes
python -c '
import json
path = "/tmp/ci_bench_timeline.json"
t = json.load(open(path))
evs = t.get("traceEvents")
if not (isinstance(evs, list) and evs):
    raise SystemExit("timeline: traceEvents missing/empty")
for e in evs:
    need = {"name", "ph", "pid", "tid"}
    if e.get("ph") == "X":
        need = need | {"ts", "dur", "cat"}
    elif e.get("ph") == "i":
        need = need | {"ts"}
    # ph "M" metadata (thread_name) legitimately has no timestamp
    missing = need - set(e)
    if missing:
        raise SystemExit("timeline event lacks %s: %r" % (sorted(missing), e))
od = t.get("otherData", {})
if od.get("schema") != "rb_tpu_bench_timeline/1":
    raise SystemExit("timeline: bad otherData.schema %r" % od.get("schema"))
for part in ("pack", "delta"):
    blk = od.get(part)
    if not (isinstance(blk, dict) and blk.get("stage_s") and blk.get("wall_s", 0) > 0):
        raise SystemExit("timeline: missing %s attribution block: %r" % (part, blk))
    if blk["coverage"] < 0.9:
        raise SystemExit("timeline: %s stages cover only %.1f%% of the wall"
                         % (part, blk["coverage"] * 100))
if not od["delta"].get("dominant_stage"):
    raise SystemExit("timeline: delta block names no dominant stage")
spans = sum(1 for e in evs if e.get("ph") == "X")
print("timeline ok (%d events, %d spans; pack %.1f%%, delta %.1f%% attributed; delta dominated by %s)"
      % (len(evs), spans, od["pack"]["coverage"] * 100,
         od["delta"]["coverage"] * 100, od["delta"]["dominant_stage"]))'

step "marshal-wall contract (ISSUE 8): delta < pack, expand attribution, overlap twin"
# the rebuilt marshal path's invariants, asserted on the smoke artifact:
# the donated O(k) delta must be strictly cheaper than the payload pack,
# the device-expansion window must exist and attribute >=90% of its wall,
# and the overlap twin rows (serial pre-ISSUE-8 pipeline vs the lane) must
# be present with sane walls (the >=30% reduction claim gates the
# full-scale committed BENCH_r*.json, not the smoke scale)
python -c '
import json
m = json.load(open("/tmp/ci_bench.json"))["meta"]
if not (0 < m["delta_repack_s"] < m["pack_s"]):
    raise SystemExit("marshal-wall: delta_repack_s %s not strictly below pack_s %s"
                     % (m["delta_repack_s"], m["pack_s"]))
if not m.get("pack_expand_s", 0) > 0:
    raise SystemExit("marshal-wall: missing/non-positive pack_expand_s %r"
                     % m.get("pack_expand_s"))
ov = m.get("overlap")
need = {"queries", "bitmaps_per_query", "serial_wall_s", "overlapped_wall_s",
        "wall_reduction_pct", "lane_staged_s", "lane_hidden_s"}
if not (isinstance(ov, dict) and need <= set(ov)):
    raise SystemExit("marshal-wall: overlap twin rows missing/incomplete: %r" % ov)
if not (ov["serial_wall_s"] > 0 and ov["overlapped_wall_s"] > 0):
    raise SystemExit("marshal-wall: non-positive overlap walls %r" % ov)
tl = json.load(open("/tmp/ci_bench_timeline.json"))["otherData"]
ex = tl.get("expand")
if not (isinstance(ex, dict) and ex.get("wall_s", 0) > 0
        and ex.get("coverage", 0) >= 0.9):
    raise SystemExit("marshal-wall: expand window missing/unattributed: %r" % ex)
print("marshal-wall ok (pack %ss + expand %ss, delta %ss, overlap %s%% over %s queries)"
      % (m["pack_s"], m["pack_expand_s"], m["delta_repack_s"],
         ov["wall_reduction_pct"], ov["queries"]))'

step "latency histogram rows in the metrics sidecar (p50/p99, ISSUE 6)"
# the log-bucketed latency histograms must surface quantile snapshots in
# the sidecar (and therefore the JSONL/Prometheus exports they mirror)
python -c '
import json
m = json.load(open("/tmp/ci_bench_metrics.json"))
lat = m.get("latency")
if not isinstance(lat, dict):
    raise SystemExit("metrics sidecar lacks the latency block")
need = {"rb_tpu_store_pack_stage_seconds", "rb_tpu_store_delta_stage_seconds",
        "rb_tpu_timeline_span_seconds"}
missing = need - set(lat)
if missing:
    raise SystemExit("latency block lacks %s (has %s)" % (sorted(missing), sorted(lat)))
for name in need:
    series = lat[name]
    if not series:
        raise SystemExit("latency metric %s recorded no series" % name)
    for key, st in series.items():
        if not ({"count", "sum", "p50", "p90", "p99"} <= set(st)):
            raise SystemExit("latency series %s{%s} lacks quantiles: %r" % (name, key, st))
        if st["count"] <= 0 or st["p99"] < st["p50"]:
            raise SystemExit("latency series %s{%s} is inconsistent: %r" % (name, key, st))
reg = m.get("registry", {}).get("rb_tpu_store_pack_stage_seconds", {})
if reg.get("type") != "histogram" or not reg.get("samples"):
    raise SystemExit("registry snapshot lacks the pack-stage histogram")
if "quantiles" not in reg["samples"][0]:
    raise SystemExit("pack-stage histogram sample carries no quantiles")
stages = sorted(lat["rb_tpu_store_pack_stage_seconds"])
print("latency rows ok (%d pack stages %s; delta stages %s)"
      % (len(stages), stages, sorted(lat["rb_tpu_store_delta_stage_seconds"])))'

step "resource observatory blocks in the sidecar (lock-wait/compile/drift, ISSUE 9)"
# the sidecar must carry the observatory's new blocks: lock-wait rows for
# the framework locks (bench installs the timed wrappers), per-fn compile
# counts, the device-memory drift gauges (ledger drift must be exactly 0
# — nonzero means the resident gauge and the cache ledger disagree), and
# decision-log volume per site
python -c '
import json
m = json.load(open("/tmp/ci_bench_metrics.json"))
for key in ("lock_wait", "compile", "hbm_drift", "decisions"):
    if key not in m:
        raise SystemExit("metrics sidecar lacks the %s block" % key)
if not m["lock_wait"]:
    raise SystemExit("no lock-wait rows: lockstats did not run in bench")
if "observe.registry" not in m["lock_wait"]:
    raise SystemExit("lock-wait rows lack the registry lock: %s" % sorted(m["lock_wait"]))
if not m["compile"] or not all(v > 0 for v in m["compile"].values()):
    raise SystemExit("compile block empty/non-positive: %r" % m["compile"])
if m["hbm_drift"].get("ledger") != 0:
    raise SystemExit("pack-cache accounting drift: %r" % m["hbm_drift"])
need_dec = {"agg.dispatch", "pack_cache.admit", "columnar.cutoff"}
missing = need_dec - set(m["decisions"])
if missing:
    raise SystemExit("decision log missing sites %s (has %s)"
                     % (sorted(missing), sorted(m["decisions"])))
lat = m.get("latency", {})
lw = lat.get("rb_tpu_lock_wait_seconds")
if not lw or not all({"p50", "p99"} <= set(v) for v in lw.values()):
    raise SystemExit("lock-wait latency quantiles missing: %r" % lw)
print("observatory blocks ok (locks %s; compiles %s; ledger drift 0; decisions %s)"
      % (sorted(m["lock_wait"]), sum(m["compile"].values()),
         sum(m["decisions"].values())))'

step "decision-outcome ledger: regret rows + sidecar block (ISSUE 11)"
# the bench must commit the routing_regret row (fraction of measured wall
# lost to wrong verdicts over the routed window — gated <= 5%), the
# predicted-vs-measured error-ratio row, the per-site decomposition, the
# seeded-mispricing refit demonstration (coefficient moved toward
# measured truth, provenance flipped), and the host-noise bands the
# variance-aware trend gate consumes; the sidecar must carry the regret
# block (pure registry derivation) with live joins recorded
python -c '
import json
m = json.load(open("/tmp/ci_bench.json"))["meta"]
reg = m.get("regret")
if not isinstance(reg, dict):
    raise SystemExit("bench meta lacks the regret block")
need = {"window_wall_s", "regret_s", "routing_regret", "error_ratio_p50",
        "per_site", "refit"}
missing = need - set(reg)
if missing:
    raise SystemExit("regret block lacks %s" % sorted(missing))
if not (0.0 <= reg["routing_regret"] <= 0.05):
    raise SystemExit("routing_regret %s blew the 5%% budget" % reg["routing_regret"])
if not reg["per_site"].get("columnar.cutoff", {}).get("count", 0) > 0:
    raise SystemExit("regret window joined no columnar.cutoff outcomes: %r"
                     % reg["per_site"])
rf = reg["refit"]
if rf.get("moved_toward_truth") is not True:
    raise SystemExit("refit did not move the seeded mispriced cell: %r" % rf)
if rf.get("provenance") != "refit-from-traffic":
    raise SystemExit("refit provenance missing: %r" % rf)
noise = m.get("host_noise")
if not (isinstance(noise, dict)
        and {"delta_repack_s", "pack_warm_s"} <= set(noise)):
    raise SystemExit("host_noise bands missing: %r" % noise)
for row, rec in noise.items():
    if not ({"reps", "min", "median", "max", "spread_pct"} <= set(rec)
            and rec["reps"] >= 2 and 0 < rec["min"] <= rec["max"]):
        raise SystemExit("host_noise band for %s malformed: %r" % (row, rec))
side = json.load(open("/tmp/ci_bench_metrics.json"))
sreg = side.get("regret")
if not isinstance(sreg, dict):
    raise SystemExit("metrics sidecar lacks the regret block")
smissing = {"sites", "joins", "orphans", "anomalies", "drift"} - set(sreg)
if smissing:
    raise SystemExit("sidecar regret block lacks %s" % sorted(smissing))
if not sreg["joins"].get("columnar.cutoff", 0) > 0:
    raise SystemExit("sidecar records no columnar.cutoff joins: %r" % sreg["joins"])
if not sreg["drift"]:
    raise SystemExit("sidecar records no coefficient drift gauges")
print("regret rows ok (routing_regret %s over %ss window, err p50 %s; "
      "refit %s -> %s; %d joined sites; noise bands %s)"
      % (reg["routing_regret"], reg["window_wall_s"], reg["error_ratio_p50"],
         rf["poisoned"], rf["refit"], len(sreg["joins"]),
         {k: v["spread_pct"] for k, v in noise.items()}))'
# the new metric names must pass the naming convention (declared label
# sets are enforced by analyze --check; this pins the unit suffixes)
JAX_PLATFORMS=cpu python -c '
from roaringbitmap_tpu import observe
for name, suffix in ((observe.DECISION_REGRET_SECONDS, "_seconds"),
                     (observe.DECISION_ERROR_RATIO, "_ratio"),
                     (observe.COSTMODEL_DRIFT_RATIO, "_ratio"),
                     (observe.OUTCOME_JOIN_TOTAL, "_total"),
                     (observe.OUTCOME_ORPHANS_TOTAL, "_total"),
                     (observe.OUTCOME_ANOMALY_TOTAL, "_total")):
    if not (name.startswith("rb_tpu_") and name.endswith(suffix)):
        raise SystemExit("outcome metric violates naming convention: %r" % name)
m = observe.REGISTRY.get(observe.DECISION_REGRET_SECONDS)
if m is None or m.labelnames != ("site",):
    raise SystemExit("regret histogram label set is not the declared (site,)")
d = observe.REGISTRY.get(observe.COSTMODEL_DRIFT_RATIO)
if d is None or d.labelnames != ("group", "engine", "shape"):
    raise SystemExit("drift gauge label set is not the declared cell tuple")
print("outcome metric names ok (suffixes + declared label sets)")'

step "health sentinel: green end state, auto-refit demo, flight bundle (ISSUE 12)"
# the bench must commit the closed-loop demo (seeded drift -> red ->
# cost.refit_all within the cooldown -> coefficients toward truth ->
# provenance persisted through RB_TPU_COLUMNAR_CAL -> exactly one
# manifest-indexed bundle in the artifact sink -> green), the end-of-run
# judgement must be green over the committed in-repo rule table, the
# sidecar must carry the registry-derived health block, and NO diagnostic
# artifact may sit loose in the repo CWD (the unified sink contract)
python -c '
import json, os
m = json.load(open("/tmp/ci_bench.json"))["meta"]
sent = m.get("sentinel")
if not isinstance(sent, dict):
    raise SystemExit("bench meta lacks the sentinel demo block")
need = {"rule", "cell", "drift_seeded", "ticks_to_refit", "poisoned", "refit",
        "moved_toward_truth", "provenance_live", "provenance_persisted",
        "refit_authorities", "bundle", "status_end"}
missing = need - set(sent)
if missing:
    raise SystemExit("sentinel block lacks %s" % sorted(missing))
if sent["rule"] != "costmodel-drift":
    raise SystemExit("auto-refit actuated by the wrong rule: %r" % sent["rule"])
if 0.25 <= sent["drift_seeded"] <= 4.0:
    raise SystemExit("seeded drift %s never left the band" % sent["drift_seeded"])
if sent["moved_toward_truth"] is not True:
    raise SystemExit("auto-refit did not move the poisoned cell: %r" % sent)
if sent["provenance_live"] != "refit-from-traffic" \
        or sent["provenance_persisted"] != "refit-from-traffic":
    raise SystemExit("auto-refit provenance missing/unpersisted: %r"
                     % {k: sent[k] for k in ("provenance_live", "provenance_persisted")})
if sent["refit_authorities"].get("columnar-cutoff") != "refit-from-traffic":
    raise SystemExit("actuation log lacks the columnar authority provenance: %r"
                     % sent["refit_authorities"])
bun = sent["bundle"]
if not (bun.get("manifest_ok") is True and bun.get("files", 0) >= 7):
    raise SystemExit("red episode bundle missing/incomplete: %r" % bun)
if sent["status_end"] != "green":
    raise SystemExit("demo did not return green: %r" % sent["status_end"])
h = m.get("health")
if not (isinstance(h, dict) and h.get("status_end") == "green"):
    raise SystemExit("end-of-bench health is not green: %r" % h)
if h.get("cwd_clean") is not True or any(h.get("rules", {}).values()):
    raise SystemExit("end-of-bench rules firing / CWD dirty: %r" % h)
need_rules = {"costmodel-drift", "routing-regret", "breaker-stuck-open",
              "outcome-anomaly-burst", "hbm-accounting-drift", "compile-storm",
              "fusion-queue-stall", "serving-p99-breach", "tenant-saturation",
              "freshness-lag-breach", "epoch-flip-stall", "structure-drift",
              "delta-accretion", "epoch-persist-stall",
              "recovery-manifest-torn", "serving-p99-pressure"}
if set(h.get("rules", {})) != need_rules:
    raise SystemExit("committed rule table changed: %r" % sorted(h.get("rules", {})))
side = json.load(open("/tmp/ci_bench_metrics.json"))
sh = side.get("health")
if not isinstance(sh, dict):
    raise SystemExit("metrics sidecar lacks the health block")
if sh.get("status") != 0 or sh.get("status_name") != "green":
    raise SystemExit("sidecar health status not green: %r" % sh)
if set(sh.get("rules", {})) != need_rules or any(sh["rules"].values()):
    raise SystemExit("sidecar rule states wrong/firing: %r" % sh.get("rules"))
strays = sorted(f for f in os.listdir(".")
                if (f.startswith("rb_tpu_") and f.endswith(".jsonl"))
                or f.startswith("bundle_"))
if strays:
    raise SystemExit("diagnostic artifacts loose in the repo CWD: %r" % strays)
if not os.path.isdir("/tmp/ci_artifacts"):
    raise SystemExit("artifact sink dir never materialized")
print("health sentinel ok (drift %s -> refit %s in %s ticks, bundle %s files, "
      "end %s; sink %s)"
      % (sent["drift_seeded"], sent["refit"], sent["ticks_to_refit"],
         bun["files"], h["status_end"], sorted(os.listdir("/tmp/ci_artifacts"))[:3]))'
# bundle schema validated end-to-end by forcing one red tick in a FRESH
# subprocess (not the bench state): a synthetic critical rule goes red on
# its first evaluation, the bundle must land manifest-indexed in the
# artifact sink (never the CWD), and the manifest must re-verify
JAX_PLATFORMS=cpu RB_TPU_ARTIFACT_DIR=/tmp/ci_artifacts python - <<'EOF'
import json, os
from roaringbitmap_tpu.observe import artifacts, bundle, health, sentinel

cwd_before = set(os.listdir("."))
rule = health.Rule("ci-forced-red", "forced", lambda s: 1e9,
                   warn=1.0, critical=2.0, fire_after=1, clear_after=1)
s = sentinel.Sentinel(rules=(rule,), clock=lambda: 0.0)
rep = s.tick(now=0.0)
if rep["status_name"] != "red":
    raise SystemExit("forced red tick judged %r" % rep["status_name"])
bundles = [a for a in rep["actuated"] if a["kind"] == "bundle"]
if len(bundles) != 1 or "path" not in bundles[0]:
    raise SystemExit("forced red tick wrote %d bundle(s)" % len(bundles))
path = bundles[0]["path"]
if os.path.dirname(path) != artifacts.artifact_dir():
    raise SystemExit("bundle escaped the sink: %r" % path)
manifest = bundle.read_manifest(path)  # schema + sizes + sha256
need = {"timeline.jsonl", "decisions.json", "outcomes.json", "metrics.jsonl",
        "calibration.json", "observatory.json", "health.json"}
if set(manifest["files"]) != need:
    raise SystemExit("bundle file set wrong: %r" % sorted(manifest["files"]))
hd = json.load(open(os.path.join(path, "health.json")))
if hd["rules"]["ci-forced-red"]["level"] != 2 or not hd["rules"]["ci-forced-red"]["history"]:
    raise SystemExit("bundle health.json lacks the red rule state/history")
cal = json.load(open(os.path.join(path, "calibration.json")))
if set(cal.get("authorities", {})) != {"columnar-cutoff", "compaction",
                                       "device-breakeven",
                                       "epoch-flip", "fusion-batch",
                                       "pack-residency",
                                       "planner-cardinality", "serve-admission"}:
    raise SystemExit("bundle calibration.json lacks the eight authorities: %r"
                     % sorted(cal.get("authorities", {})))
obs = json.load(open(os.path.join(path, "observatory.json")))
if "serving" not in obs:
    raise SystemExit("bundle observatory.json lacks the serving panel")
if "epochs" not in obs:
    raise SystemExit("bundle observatory.json lacks the epoch panel")
if "structure" not in obs:
    raise SystemExit("bundle observatory.json lacks the structure panel")
new_cwd = sorted(set(os.listdir(".")) - cwd_before)
if new_cwd:
    raise SystemExit("forced red tick wrote into the CWD: %r" % new_cwd)
print("bundle schema ok (%s, %d files, manifest verified)"
      % (os.path.basename(path), len(manifest["files"])))
EOF
# the health metric names must pass the naming convention (enum-gauge
# _state/_status suffixes + declared label sets)
JAX_PLATFORMS=cpu python -c '
from roaringbitmap_tpu import observe
for name, suffix in ((observe.HEALTH_STATUS, "_status"),
                     (observe.HEALTH_RULE_STATE, "_state"),
                     (observe.HEALTH_ACTUATION_TOTAL, "_total")):
    if not (name.startswith("rb_tpu_") and name.endswith(suffix)):
        raise SystemExit("health metric violates naming convention: %r" % name)
g = observe.REGISTRY.get(observe.HEALTH_RULE_STATE)
if g is None or g.labelnames != ("rule",):
    raise SystemExit("rule-state gauge label set is not the declared (rule,)")
a = observe.REGISTRY.get(observe.HEALTH_ACTUATION_TOTAL)
if a is None or a.labelnames != ("rule", "kind"):
    raise SystemExit("actuation counter label set is not the declared (rule, kind)")
print("health metric names ok (enum-gauge suffixes + declared label sets)"
)'

step "query-scoped tracing + off-mode twin rows (ISSUE 9 acceptance)"
# 100% of lane-emitted events must carry the originating query trace id
# (explicit handoff across the lane thread), per-trace stage attribution
# must cover every query, the observability off-mode overhead twin must
# stay under 1% (with the bench's 5 ms absolute noise slack), and the
# north-star reduce must reach steady state with zero retraces
python -c '
import json
m = json.load(open("/tmp/ci_bench.json"))["meta"]
tr = m.get("tracing")
if not isinstance(tr, dict):
    raise SystemExit("bench meta lacks the tracing block")
if tr["lane_traced_pct"] != 100.0:
    raise SystemExit("lane trace attribution only %s%%" % tr["lane_traced_pct"])
if tr["traces_attributed"] < tr["queries"]:
    raise SystemExit("per-trace attribution covers %s of %s queries"
                     % (tr["traces_attributed"], tr["queries"]))
if not tr["per_trace_stage_s"]:
    raise SystemExit("tracing block carries no per-trace stage sums")
obs = m.get("observability")
if not isinstance(obs, dict):
    raise SystemExit("bench meta lacks the observability twin rows")
if not (obs["off_overhead_pct"] < 1.0 or obs["off_delta_s"] < 0.005):
    raise SystemExit("observability off-mode overhead %s%% (%ss) over the 1%% budget"
                     % (obs["off_overhead_pct"], obs["off_delta_s"]))
comp = m.get("compile", {})
if comp.get("steady_state_retraces") != 0:
    raise SystemExit("north-star reduce retraced in steady state: %r" % comp)
print("tracing ok (lane %s events 100%% attributed over %s queries; off-mode %s%%; 0 retraces)"
      % (tr["lane_events"], tr["queries"], obs["off_overhead_pct"]))'

step "cross-query fusion: fused-vs-serial twin rows + sidecar block (ISSUE 13)"
# the bench must commit the fused/serial twin (aggregate QPS on the
# overlapping-predicate workload, p50/p99 per-query latency, dedup hit
# ratio): fused must not lose to serial dispatch, results must have been
# asserted bit-exact, the off-mode twin must be in budget, the window
# scaling slice must show the shared-subexpression speedup GROWING with
# window size (the superlinear claim), and the fusion.batch decision
# site must have joined outcomes with regret inside the 5% budget
python -c '
import json
m = json.load(open("/tmp/ci_bench.json"))["meta"]
fu = m.get("fusion")
if not isinstance(fu, dict):
    raise SystemExit("bench meta lacks the fusion block")
need = {"queries", "window", "serial_qps", "fused_qps", "qps_speedup",
        "bitexact", "dedup_hit_ratio", "serial_p50_ms", "serial_p99_ms",
        "fused_p50_ms", "fused_p99_ms", "off_overhead_pct", "off_delta_s",
        "scaling", "batch_regret", "batch_joins"}
missing = need - set(fu)
if missing:
    raise SystemExit("fusion block lacks %s" % sorted(missing))
if fu["bitexact"] is not True:
    raise SystemExit("fused results were not asserted bit-exact")
if not (fu["fused_qps"] >= fu["serial_qps"]):
    raise SystemExit("fused QPS %s lost to serial %s"
                     % (fu["fused_qps"], fu["serial_qps"]))
if not (0 < fu["dedup_hit_ratio"] < 1):
    raise SystemExit("overlapping workload never deduped: %r"
                     % fu["dedup_hit_ratio"])
if not (fu["off_overhead_pct"] < 1.0 or fu["off_delta_s"] < 0.005):
    raise SystemExit("fusion off-mode twin overhead %s%% (%ss) over budget"
                     % (fu["off_overhead_pct"], fu["off_delta_s"]))
sc = fu["scaling"]
if len(sc) < 2:
    raise SystemExit("fusion scaling slice too small: %r" % sc)
ws = sorted(int(k) for k in sc)
if not (sc[str(ws[-1])]["speedup"] > sc[str(ws[0])]["speedup"] * 0.95
        and sc[str(ws[-1])]["speedup"] >= 1.0):
    raise SystemExit("shared-subexpression speedup does not scale: %r" % sc)
if not fu["batch_joins"] > 0:
    raise SystemExit("no fusion.batch outcomes joined")
# regret gates on max(5%, the recorded fused-window host-noise band) —
# the first-use refit calibrates against one rep, so rep spread lands
# directly in the ratio (ISSUE 19 satellite: the variance-aware gate
# bench_trend already applies, not a bare 5% on a noisy host)
budget = max(0.05, fu.get("batch_regret_budget", 0.05))
if not (0.0 <= fu["batch_regret"] <= budget):
    raise SystemExit("fusion.batch regret %s blew the %s budget"
                     % (fu["batch_regret"], budget))
side = json.load(open("/tmp/ci_bench_metrics.json"))
sf = side.get("fusion")
if not isinstance(sf, dict):
    raise SystemExit("metrics sidecar lacks the fusion block")
smissing = {"batches", "queries", "steps", "occupancy", "dedup_hit_ratio",
            "inflight"} - set(sf)
if smissing:
    raise SystemExit("sidecar fusion block lacks %s" % sorted(smissing))
if not sf["batches"].get("fused"):
    raise SystemExit("sidecar records no fused windows: %r" % sf["batches"])
print("fusion rows ok (fused %s vs serial %s q/s, speedup %sx, dedup %s, "
      "scaling %s, regret %s over %d joins)"
      % (fu["fused_qps"], fu["serial_qps"], fu["qps_speedup"],
         fu["dedup_hit_ratio"],
         {k: v["speedup"] for k, v in sorted(sc.items(), key=lambda kv: int(kv[0]))},
         fu["batch_regret"], fu["batch_joins"]))'
# the new fusion metric names must pass the naming convention, with the
# declared label sets, and the query.fusion fault site must be registered
JAX_PLATFORMS=cpu python -c '
from roaringbitmap_tpu import observe
from roaringbitmap_tpu.robust import faults
for name, suffix in ((observe.FUSION_BATCH_TOTAL, "_total"),
                     (observe.FUSION_QUERIES_TOTAL, "_total"),
                     (observe.FUSION_STEPS_TOTAL, "_total"),
                     (observe.FUSION_BATCH_SECONDS, "_seconds"),
                     (observe.FUSION_QUEUED_COUNT, "_count"),
                     (observe.QUERY_INFLIGHT_TOTAL, "_total")):
    if not (name.startswith("rb_tpu_") and name.endswith(suffix)):
        raise SystemExit("fusion metric violates naming convention: %r" % name)
import roaringbitmap_tpu.query  # registers the fusion metrics
b = observe.REGISTRY.get(observe.FUSION_BATCH_TOTAL)
if b is None or b.labelnames != ("outcome",):
    raise SystemExit("fusion batch counter label set is not the declared (outcome,)")
s = observe.REGISTRY.get(observe.FUSION_STEPS_TOTAL)
if s is None or s.labelnames != ("kind",):
    raise SystemExit("fusion steps counter label set is not the declared (kind,)")
if "query.fusion" not in faults.SITES:
    raise SystemExit("query.fusion fault site not registered")
print("fusion metric names ok (suffixes + declared label sets; fault site registered)")'

step "serving tier: SLO rows, overload demo, admission curve, trace attribution (ISSUE 14)"
# the bench must commit meta.serving: per-tenant p50/p99 + aggregate QPS
# at >=2 concurrency levels over >=2 tenants (bit-exact vs the serial
# oracle), 100% per-trace attribution under contention, the serve.admit
# site joined with regret <=5%, per-tenant PACK_CACHE byte shares, the
# off-mode twin in budget, the seeded-overload sentinel demo
# (tenant-saturation fires red -> bundle carries the serving panel ->
# clears green), and the fairness row; the metrics sidecar must carry
# the registry-derived serving block
python -c '
import json
m = json.load(open("/tmp/ci_bench.json"))["meta"]
sv = m.get("serving")
if not isinstance(sv, dict):
    raise SystemExit("bench meta lacks the serving block")
need = {"host", "tenants", "levels", "bitexact", "trace_attribution_pct",
        "admission", "byte_share", "off_overhead_pct", "off_delta_s",
        "overload", "fairness"}
missing = need - set(sv)
if missing:
    raise SystemExit("serving block lacks %s" % sorted(missing))
if len(sv["tenants"]) < 2:
    raise SystemExit("serving rows cover fewer than 2 tenants: %r" % sv["tenants"])
if len(sv["levels"]) < 2:
    raise SystemExit("serving rows cover fewer than 2 concurrency levels")
for name, lvl in sv["levels"].items():
    if not lvl.get("aggregate_qps", 0) > 0:
        raise SystemExit("serving level %s has no aggregate QPS: %r" % (name, lvl))
    active = [t for t, r in lvl["per_tenant"].items() if r["served"] > 0]
    if len(active) < 2:
        raise SystemExit("serving level %s served fewer than 2 tenants" % name)
    for t in active:
        r = lvl["per_tenant"][t]
        if not (r.get("execute_p50_ms", 0) > 0 and r.get("execute_p99_ms", 0) > 0
                and r["execute_p99_ms"] >= r["execute_p50_ms"]):
            raise SystemExit("serving level %s tenant %s p50/p99 malformed: %r"
                             % (name, t, r))
if sv["bitexact"] is not True:
    raise SystemExit("serving results were not asserted bit-exact vs serial")
if sv["trace_attribution_pct"] != 100.0:
    raise SystemExit("serving trace attribution only %s%%" % sv["trace_attribution_pct"])
adm = sv["admission"]
if not adm.get("joins", 0) > 0:
    raise SystemExit("no serve.admit outcomes joined: %r" % adm)
if not (0.0 <= adm.get("regret", 1) <= 0.05):
    raise SystemExit("serve.admit regret %s blew the 5%% budget" % adm.get("regret"))
if adm.get("refit", {}).get("provenance") != "refit-from-traffic":
    raise SystemExit("admission curve never refit from traffic: %r" % adm)
if not all(v > 0 for v in sv["byte_share"].values()):
    raise SystemExit("tenant byte shares missing: %r" % sv["byte_share"])
if not (sv["off_overhead_pct"] < 1.0 or sv["off_delta_s"] < 0.005):
    raise SystemExit("serving off-mode twin %s%% (%ss) over budget"
                     % (sv["off_overhead_pct"], sv["off_delta_s"]))
ov = sv["overload"]
if ov.get("rule") != "tenant-saturation" or not ov.get("shed", 0) > 0:
    raise SystemExit("overload demo did not shed via tenant-saturation: %r" % ov)
if ov.get("status_end") != "green":
    raise SystemExit("overload demo did not clear green: %r" % ov.get("status_end"))
if not (ov.get("bundle", {}).get("serving_panel") is True
        and ov["bundle"].get("files", 0) >= 7):
    raise SystemExit("overload red bundle missing the serving panel: %r" % ov.get("bundle"))
fair = sv["fairness"]
if fair.get("starved") is not False or not fair.get("shed", 0) > 0:
    raise SystemExit("fairness row vacuous/starved: %r" % fair)
if not (1.2 <= fair.get("served_ratio", 0) <= 3.4):
    raise SystemExit("served ratio %s strayed from the quota ratio" % fair.get("served_ratio"))
side = json.load(open("/tmp/ci_bench_metrics.json"))
ssv = side.get("serving")
if not isinstance(ssv, dict):
    raise SystemExit("metrics sidecar lacks the serving block")
smissing = {"tenants", "admit", "requests", "queue_depth", "inflight"} - set(ssv)
if smissing:
    raise SystemExit("sidecar serving block lacks %s" % sorted(smissing))
if not ssv["tenants"]:
    raise SystemExit("sidecar serving block records no tenants")
for t, row in ssv["tenants"].items():
    lat = row.get("latency") or {}
    if "execute" in lat and not lat["execute"].get("p99", 0) > 0:
        raise SystemExit("sidecar serving tenant %s lacks execute p99: %r" % (t, row))
print("serving rows ok (%d tenants x %d levels, agg qps %s; admission joins %d "
      "regret %s err %s; overload shed %d -> red tick %s -> green tick %s; "
      "fairness %s vs quota 2.0)"
      % (len(sv["tenants"]), len(sv["levels"]),
         {k: v["aggregate_qps"] for k, v in sorted(sv["levels"].items())},
         adm["joins"], adm["regret"], adm.get("error_ratio_geomean"),
         ov["shed"], ov.get("ticks_to_red"), ov.get("ticks_to_green"),
         fair["served_ratio"]))'
# the new serving metric names must pass the naming convention with the
# declared label sets, the serve.admit fault site must be registered,
# and host provenance must be stamped into the twin blocks
JAX_PLATFORMS=cpu python -c '
import json
from roaringbitmap_tpu import observe
from roaringbitmap_tpu.robust import faults
for name, suffix in ((observe.registry.SERVE_LATENCY_SECONDS, "_seconds"),
                     (observe.registry.SERVE_QPS, "_qps"),
                     (observe.registry.SERVE_ADMIT_TOTAL, "_total"),
                     (observe.registry.SERVE_REQUESTS_TOTAL, "_total"),
                     (observe.registry.SERVE_QUEUE_COUNT, "_count"),
                     (observe.registry.SERVE_INFLIGHT_COUNT, "_count"),
                     (observe.registry.SERVE_SATURATION_RATIO, "_ratio"),
                     (observe.registry.SERVE_TENANT_BYTES, "_bytes")):
    if not (name.startswith("rb_tpu_") and name.endswith(suffix)):
        raise SystemExit("serving metric violates naming convention: %r" % name)
import roaringbitmap_tpu.serve  # registers the serving metrics
lat = observe.REGISTRY.get(observe.registry.SERVE_LATENCY_SECONDS)
if lat is None or lat.labelnames != ("tenant", "phase"):
    raise SystemExit("serve latency label set is not the declared (tenant, phase)")
adm = observe.REGISTRY.get(observe.registry.SERVE_ADMIT_TOTAL)
if adm is None or adm.labelnames != ("tenant", "verdict"):
    raise SystemExit("serve admit label set is not the declared (tenant, verdict)")
if "serve.admit" not in faults.SITES:
    raise SystemExit("serve.admit fault site not registered")
m = json.load(open("/tmp/ci_bench.json"))["meta"]
host = m.get("host")
need_host = {"cpu_count", "backend", "device_kind", "device_count"}
if not (isinstance(host, dict) and need_host <= set(host)):
    raise SystemExit("bench meta lacks host provenance: %r" % host)
for block in ("columnar", "columnar_device", "overlap", "fusion", "serving",
              "epochs", "observability"):
    if m.get(block, {}).get("host") != host:
        raise SystemExit("twin block %s lacks the host provenance stamp" % block)
print("serving metric names ok (suffixes + declared label sets; fault site "
      "registered; host provenance stamped into %d twin blocks)" % 7)'

step "SLO frontier: mixed-class QPS-vs-p99 gate on smoke + committed row (ISSUE 19)"
# the tail-latency tentpole's standing claim, gated twice: the smoke
# artifact AND the newest committed BENCH_r*.json carrying meta.frontier
# must both show the mixed interactive+batch window (a) beating the
# serial baseline on aggregate QPS, (b) holding EVERY tenant's declared
# p99 budget, (c) keeping the interactive tenant's p99 within 2x its
# solo-dispatch twin, and (d) actually exercising hedged solo dispatch
python -c '
import glob, json

def gate(path, m):
    fr = m.get("frontier")
    if not isinstance(fr, dict):
        raise SystemExit("%s lacks the frontier block" % path)
    need = {"requests", "threads", "bitexact", "aggregate_qps", "serial_qps",
            "hedges", "hedge_rate", "interactive_p99_ms",
            "interactive_solo_p99_ms", "per_tenant", "classes", "window"}
    missing = need - set(fr)
    if missing:
        raise SystemExit("%s frontier block lacks %s" % (path, sorted(missing)))
    if fr["bitexact"] is not True:
        raise SystemExit("%s: frontier window was not asserted bit-exact" % path)
    if not fr["aggregate_qps"] >= fr["serial_qps"]:
        raise SystemExit("%s: mixed-class QPS %s lost to serial %s"
                         % (path, fr["aggregate_qps"], fr["serial_qps"]))
    classes = {r.get("latency_class") for r in fr["per_tenant"].values()}
    if not {"interactive", "batch"} <= classes:
        raise SystemExit("%s: frontier workload is not mixed-class: %r"
                         % (path, sorted(classes)))
    for t, r in fr["per_tenant"].items():
        if r.get("slo_ok") is not True:
            raise SystemExit("%s: tenant %s blew its declared p99 budget: %r"
                             % (path, t, r))
        if not (0 < r["total_p99_ms"] <= r["p99_budget_ms"]):
            raise SystemExit("%s: tenant %s p99 %s vs budget %s malformed"
                             % (path, t, r["total_p99_ms"], r["p99_budget_ms"]))
    if not fr["hedges"] > 0:
        raise SystemExit("%s: no request hedged solo under the mixed window" % path)
    if not (fr["interactive_p99_ms"]
            <= 2.0 * max(fr["interactive_solo_p99_ms"], 0.001)):
        raise SystemExit("%s: interactive p99 %s blew 2x its solo twin %s"
                         % (path, fr["interactive_p99_ms"],
                            fr["interactive_solo_p99_ms"]))
    return fr

smoke = gate("/tmp/ci_bench.json",
             json.load(open("/tmp/ci_bench.json"))["meta"])
committed = [p for p in sorted(glob.glob("BENCH_r*.json"))
             if isinstance(json.load(open(p)).get("meta", {})
                           .get("frontier"), dict)]
if not committed:
    raise SystemExit("no committed BENCH_r*.json carries the frontier row")
row = gate(committed[-1], json.load(open(committed[-1]))["meta"])
print("frontier ok (smoke %s vs serial %s q/s, hedge rate %s; committed %s: "
      "%s vs %s q/s, interactive p99 %s/%s ms vs solo %s ms)"
      % (smoke["aggregate_qps"], smoke["serial_qps"], smoke["hedge_rate"],
         committed[-1], row["aggregate_qps"], row["serial_qps"],
         row["interactive_p99_ms"],
         row["per_tenant"][[t for t, r in row["per_tenant"].items()
                            if r["latency_class"] == "interactive"][0]]
         ["p99_budget_ms"], row["interactive_solo_p99_ms"]))'
# latency-class machinery: the pressure rule must be registered with the
# autotune actuation, the hedge metrics must pass the naming convention,
# and the query.hedge fault site must be registered
JAX_PLATFORMS=cpu python -c '
from roaringbitmap_tpu import observe
from roaringbitmap_tpu.observe import health
from roaringbitmap_tpu.robust import faults
for name, suffix in ((observe.FUSION_HEDGE_TOTAL, "_total"),
                     (observe.FUSION_WINDOW_COUNT, "_count"),
                     (observe.SERVE_SLO_BUDGET_SECONDS, "_seconds")):
    if not (name.startswith("rb_tpu_") and name.endswith(suffix)):
        raise SystemExit("latency metric violates naming convention: %r" % name)
rule = next((r for r in health.DEFAULT_RULES
             if r.name == "serving-p99-pressure"), None)
if rule is None or rule.actuation != "autotune":
    raise SystemExit("serving-p99-pressure rule missing/unactuated: %r" % rule)
if "query.hedge" not in faults.SITES:
    raise SystemExit("query.hedge fault site not registered")
from roaringbitmap_tpu.serve import slo
if set(slo.LATENCY_CLASSES) != {"interactive", "balanced", "batch"}:
    raise SystemExit("latency class table changed: %r" % sorted(slo.LATENCY_CLASSES))
print("latency-class machinery ok (pressure rule -> autotune, hedge metrics, "
      "query.hedge site, %d classes)" % len(slo.LATENCY_CLASSES))'

step "epoch ledger: freshness rows, torn reads, flip attribution, staleness demo (ISSUE 15)"
# the bench must commit meta.epochs: read-write rows at 2 ingest rates
# (each bit-exact vs the epoch-replay oracle — zero torn reads),
# freshness p50/p99 per rate, ZERO full repacks on the warm flip path
# (the O(k) delta contract), aggregate QPS at the low rate within 10%
# of the read-only twin, flip-stage timeline attribution >=90%, the
# epoch.flip site joined with regret <=5% + refit provenance, and the
# seeded staleness demo (stale publishes -> freshness-lag-breach red ->
# bundle carries the epoch panel with lineage -> green); the metrics
# sidecar must carry the registry-derived epochs block
python -c '
import json
m = json.load(open("/tmp/ci_bench.json"))["meta"]
ep = m.get("epochs")
if not isinstance(ep, dict):
    raise SystemExit("bench meta lacks the epochs block")
need = {"host", "rates", "read_only_qps", "low_rate_qps_ratio", "torn_reads",
        "bitexact", "flip_attribution_pct", "flip_decision", "staleness_demo",
        "lineage_tail"}
missing = need - set(ep)
if missing:
    raise SystemExit("epochs block lacks %s" % sorted(missing))
rates = ep["rates"]
if set(rates) != {"low", "high"}:
    raise SystemExit("epochs rows do not cover 2 ingest rates: %r" % sorted(rates))
for name, row in rates.items():
    if not row.get("writes", 0) > 0:
        raise SystemExit("epoch rate %s ingested no batches: %r" % (name, row))
    if not row.get("flips", 0) > 0:
        raise SystemExit("epoch rate %s never flipped: %r" % (name, row))
    fr = row.get("freshness_ms", {})
    if not (fr.get("p50", 0) > 0 and fr.get("p99", 0) >= fr.get("p50", 0)):
        raise SystemExit("epoch rate %s freshness p50/p99 malformed: %r" % (name, fr))
    if row.get("torn_reads") != 0:
        raise SystemExit("epoch rate %s saw torn reads: %r" % (name, row))
    d = row.get("delta", {})
    if d.get("full_repacks") != 0 or not d.get("delta_rows", 0) > 0:
        raise SystemExit("epoch rate %s flips left the O(k) delta path: %r" % (name, d))
    if not row.get("aggregate_qps", 0) > 0:
        raise SystemExit("epoch rate %s has no aggregate QPS" % name)
if ep["torn_reads"] != 0 or ep["bitexact"] is not True:
    raise SystemExit("epoch windows were not torn-free bit-exact: %r"
                     % {"torn": ep["torn_reads"], "bitexact": ep["bitexact"]})
if not ep["low_rate_qps_ratio"] >= 0.9:
    raise SystemExit("low-rate ingest taxed read-only QPS past 10%%: %s"
                     % ep["low_rate_qps_ratio"])
if not ep["flip_attribution_pct"] >= 90.0:
    raise SystemExit("flip stages attribute only %s%% of the flip wall"
                     % ep["flip_attribution_pct"])
fd = ep["flip_decision"]
if not fd.get("joins", 0) > 0:
    raise SystemExit("no epoch.flip outcomes joined: %r" % fd)
if not (0.0 <= fd.get("regret", 1) <= 0.05):
    raise SystemExit("epoch.flip regret %s blew the 5%% budget" % fd.get("regret"))
if fd.get("refit", {}).get("provenance") != "refit-from-traffic":
    raise SystemExit("epoch-flip curve never refit from traffic: %r" % fd)
sd = ep["staleness_demo"]
if sd.get("rule") != "freshness-lag-breach" or sd.get("ticks_to_red") is None:
    raise SystemExit("staleness demo did not fire freshness-lag-breach: %r" % sd)
if sd.get("status_end") != "green":
    raise SystemExit("staleness demo did not clear green: %r" % sd.get("status_end"))
bun = sd.get("bundle", {})
if not (bun.get("epoch_panel") is True and bun.get("files", 0) >= 7
        and bun.get("lineage_epochs")):
    raise SystemExit("staleness red bundle lacks the epoch panel/lineage: %r" % bun)
side = json.load(open("/tmp/ci_bench_metrics.json"))
sep = side.get("epochs")
if not isinstance(sep, dict):
    raise SystemExit("metrics sidecar lacks the epochs block")
smissing = {"epoch", "mutlog_depth", "flips", "ingest", "freshness",
            "flip_stages"} - set(sep)
if smissing:
    raise SystemExit("sidecar epochs block lacks %s" % sorted(smissing))
if not sep.get("flips", {}).get("flipped"):
    raise SystemExit("sidecar epochs block records no flips: %r" % sep.get("flips"))
for stage in ("drain", "repack", "publish", "reclaim"):
    if stage not in sep.get("flip_stages", {}):
        raise SystemExit("sidecar epochs block lacks flip stage %r" % stage)
print("epoch rows ok (freshness p99 low %sms / high %sms; qps ratio %s; "
      "flips low %d / high %d all-delta; attribution %s%%; flip joins %d "
      "regret %s err %s; staleness red tick %s -> green tick %s, bundle "
      "lineage %s)"
      % (rates["low"]["freshness_ms"]["p99"], rates["high"]["freshness_ms"]["p99"],
         ep["low_rate_qps_ratio"], rates["low"]["flips"], rates["high"]["flips"],
         ep["flip_attribution_pct"], fd["joins"], fd["regret"],
         fd.get("error_ratio_geomean"), sd.get("ticks_to_red"),
         sd.get("ticks_to_green"), bun.get("lineage_epochs")))'
# the epoch metric names must pass the naming convention with declared
# label sets, the epoch.flip fault site and seventh authority must be
# registered, and epoch ids must never be metric label values (the rule
# clause rides analyze --check; pinned here against the live registry)
JAX_PLATFORMS=cpu python -c '
from roaringbitmap_tpu import cost, observe
from roaringbitmap_tpu.robust import faults
for name, suffix in ((observe.SERVE_FRESHNESS_SECONDS, "_seconds"),
                     (observe.SERVE_FLIP_STAGE_SECONDS, "_seconds"),
                     (observe.SERVE_INGEST_TOTAL, "_total"),
                     (observe.SERVE_EPOCH_FLIP_TOTAL, "_total"),
                     (observe.SERVE_MUTLOG_COUNT, "_count"),
                     (observe.SERVE_EPOCH_COUNT, "_count")):
    if not (name.startswith("rb_tpu_") and name.endswith(suffix)):
        raise SystemExit("epoch metric violates naming convention: %r" % name)
import roaringbitmap_tpu.serve  # registers the epoch metrics
fr = observe.REGISTRY.get(observe.SERVE_FRESHNESS_SECONDS)
if fr is None or fr.labelnames != ("tenant",):
    raise SystemExit("freshness label set is not the declared (tenant,)")
fs = observe.REGISTRY.get(observe.SERVE_FLIP_STAGE_SECONDS)
if fs is None or fs.labelnames != ("stage",):
    raise SystemExit("flip-stage label set is not the declared (stage,)")
eg = observe.REGISTRY.get(observe.SERVE_EPOCH_COUNT)
if eg is None or eg.labelnames != ():
    raise SystemExit("epoch gauge must be unlabeled (epoch ids are VALUES)")
if "epoch.flip" not in faults.SITES:
    raise SystemExit("epoch.flip fault site not registered")
if "epoch-flip" not in cost.names():
    raise SystemExit("epoch-flip authority not registered in the cost facade")
from roaringbitmap_tpu.analysis.rules.metrics import _EPOCH_VALUE
if not (_EPOCH_VALUE.search("epoch") and _EPOCH_VALUE.search("epoch_id")):
    raise SystemExit("metric-naming rule lost the epoch label-value clause")
print("epoch metric names ok (suffixes + declared label sets; fault site + "
      "seventh authority registered; epoch-id label clause armed)"
)'

step "structure soak: maintained vs unmaintained twin, priced compaction, drift demo (ISSUE 16)"
# the bench must commit meta.soak: the sustained-ingest soak ran a
# maintained corpus and an unmaintained twin through identical drift
# windows; the maintained side must hold drift <=1.1x while the twin
# degrades past 1.5x, every round's serving window must be bit-exact vs
# the epoch-replay oracle with zero torn reads (including the final
# round, whose pass runs CONCURRENT with serving), the ledger's
# incremental books must reconcile against a from-scratch census, the
# serve.maintain site must join priced (unforced) outcomes with regret
# <=5% and a traffic-refit compaction curve, and the seeded drift demo
# must fire structure-drift -> actuate one pass under cooldown -> green
python -c '
import json
m = json.load(open("/tmp/ci_bench.json"))["meta"]
sk = m.get("soak")
if not isinstance(sk, dict):
    raise SystemExit("bench meta lacks the soak block")
need = {"host", "rounds", "requests_per_round", "drift_spans_per_round",
        "maintained", "twin", "torn_reads", "bitexact",
        "ledger_census_reconciled", "compaction_decision", "drift_demo"}
missing = need - set(sk)
if missing:
    raise SystemExit("soak block lacks %s" % sorted(missing))
rounds = sk["rounds"]
if not len(rounds) >= 3:
    raise SystemExit("soak ran only %d rounds" % len(rounds))
for row in rounds:
    mt = row["maintained"]
    if mt.get("torn_reads") != 0:
        raise SystemExit("soak round %s saw torn reads: %r" % (row.get("round"), mt))
    if mt.get("pass", {}).get("outcome") != "compacted":
        raise SystemExit("soak round %s pass did not compact: %r"
                         % (row.get("round"), mt.get("pass")))
    if not row["twin"].get("drift_ratio", 0) > mt.get("drift_ratio", 0):
        raise SystemExit("soak round %s twin did not drift past maintained: %r"
                         % (row.get("round"), row))
if not any(r["maintained"]["pass"].get("concurrent") for r in rounds):
    raise SystemExit("no soak pass ran concurrent with the serving window")
mend = sk["maintained"]["drift_ratio_end"]
tend = sk["twin"]["drift_ratio_end"]
if not mend <= 1.1:
    raise SystemExit("maintained corpus drifted to %sx (budget 1.1x)" % mend)
if not tend >= 1.5:
    raise SystemExit("unmaintained twin failed to degrade (%sx) — drift "
                     "injection is not exercising the maintainer" % tend)
if sk["torn_reads"] != 0 or sk["bitexact"] is not True:
    raise SystemExit("soak was not torn-free bit-exact: %r"
                     % {"torn": sk["torn_reads"], "bitexact": sk["bitexact"]})
if sk["ledger_census_reconciled"] is not True:
    raise SystemExit("structure ledger books diverged from the census")
cd = sk["compaction_decision"]
if not cd.get("joins", 0) > 0:
    raise SystemExit("no priced serve.maintain outcomes joined: %r" % cd)
if not (0.0 <= cd.get("regret", 1) <= 0.05):
    raise SystemExit("compaction regret %s blew the 5%% budget" % cd.get("regret"))
if cd.get("refit", {}).get("provenance") != "refit-from-traffic":
    raise SystemExit("compaction curve never refit from traffic: %r" % cd)
dd = sk["drift_demo"]
if dd.get("rule") != "structure-drift" or dd.get("ticks_to_actuate") is None:
    raise SystemExit("drift demo did not fire structure-drift: %r" % dd)
if dd.get("pass_outcome") != "compacted" or not dd.get("reclaimed_bytes", 0) > 0:
    raise SystemExit("drift demo actuation did not compact: %r" % dd)
if dd.get("passes_under_cooldown") != 1:
    raise SystemExit("maintain cooldown did not hold to one pass: %r" % dd)
if dd.get("status_end") != "green":
    raise SystemExit("drift demo did not clear green: %r" % dd.get("status_end"))
side = json.load(open("/tmp/ci_bench_metrics.json"))
sst = side.get("structure")
if not isinstance(sst, dict):
    raise SystemExit("metrics sidecar lacks the structure block")
smissing = {"containers", "bytes", "drift_ratio", "accretion_depth",
            "passes"} - set(sst)
if smissing:
    raise SystemExit("sidecar structure block lacks %s" % sorted(smissing))
print("soak ok (%d rounds; maintained %sx vs twin %sx; torn 0 bit-exact; "
      "books reconciled; %d priced joins regret %s err %s; drift demo "
      "%sx -> %s in %s ticks, %d pass under cooldown -> %s)"
      % (len(rounds), mend, tend, cd["joins"], cd["regret"],
         cd.get("error_ratio_geomean"), dd.get("drift_ratio_seeded"),
         dd.get("pass_outcome"), dd.get("ticks_to_actuate"),
         dd.get("passes_under_cooldown"), dd.get("status_end")))'
# the structure metric names must pass the naming convention with the
# CONTAINERS suffix clause, the serve.maintain fault site and eighth
# authority must be registered, and the two sentinel rules must carry
# the maintain actuation
JAX_PLATFORMS=cpu python -c '
from roaringbitmap_tpu import cost, observe
from roaringbitmap_tpu.robust import faults
for name, suffix in ((observe.STRUCTURE_CONTAINERS, "_containers"),
                     (observe.STRUCTURE_BYTES, "_bytes"),
                     (observe.STRUCTURE_DRIFT_RATIO, "_ratio"),
                     (observe.STRUCTURE_FRAGMENTATION_COUNT, "_count"),
                     (observe.STRUCTURE_ACCRETION_COUNT, "_count"),
                     (observe.SERVE_MAINTAIN_TOTAL, "_total"),
                     (observe.SERVE_MAINTAIN_SECONDS, "_seconds"),
                     (observe.SERVE_MAINTAIN_RECLAIMED_BYTES_TOTAL, "_total"),
                     (observe.SERVE_MAINTAIN_KEYS_TOTAL, "_total")):
    if not (name.startswith("rb_tpu_") and name.endswith(suffix)):
        raise SystemExit("structure metric violates naming convention: %r" % name)
import roaringbitmap_tpu.serve  # registers the maintenance metrics
import roaringbitmap_tpu.observe.structure as structure_mod
cn = observe.REGISTRY.get(observe.STRUCTURE_CONTAINERS)
if cn is None or cn.labelnames != ("format",):
    raise SystemExit("container census label set is not the declared (format,)")
by = observe.REGISTRY.get(observe.STRUCTURE_BYTES)
if by is None or by.labelnames != ("kind",):
    raise SystemExit("structure bytes label set is not the declared (kind,)")
if set(structure_mod.FORMATS) != {"array", "bitmap", "run"}:
    raise SystemExit("declared container-format set drifted: %r"
                     % sorted(structure_mod.FORMATS))
if "serve.maintain" not in faults.SITES:
    raise SystemExit("serve.maintain fault site not registered")
if "compaction" not in cost.names():
    raise SystemExit("compaction authority not registered in the cost facade")
from roaringbitmap_tpu.observe import health
rules = {r.name: r for r in health.DEFAULT_RULES}
for rn in ("structure-drift", "delta-accretion"):
    if rn not in rules:
        raise SystemExit("rule table lacks %s" % rn)
    if rules[rn].actuation != "maintain":
        raise SystemExit("rule %s does not actuate maintain: %r"
                         % (rn, rules[rn].actuation))
from roaringbitmap_tpu.analysis.rules.metrics import _FORMAT_VALUE
if not (_FORMAT_VALUE.search("format") and _FORMAT_VALUE.search("fmt")
        and _FORMAT_VALUE.search("container_format")):
    raise SystemExit("metric-naming rule lost the container-format clause")
print("structure metric names ok (suffixes + declared label sets; fault site + "
      "eighth authority registered; maintain actuation wired; format clause armed)"
)'

step "durable epochs: restart twin rows, kill-walk recovery, sha256 re-verify (ISSUE 17)"
# the bench must commit meta.durable: persist walls attributed to the
# four named stages (>=90%), and the restart twin — warm (recover:
# manifest discovery + sha256 re-verify + mmap + hot-set readmit off
# zero-copy views) must beat cold (full deserialize copy=True before
# the identical hot-set pack) on the SAME artifact, bit-exact
python -c '
import json
m = json.load(open("/tmp/ci_bench.json"))["meta"]
du = m.get("durable")
if not isinstance(du, dict):
    raise SystemExit("bench meta lacks the durable block")
need = {"corpus_bitmaps", "hot_set_bitmaps", "flips_persisted",
        "artifact_bytes", "persist_wall_s", "persist_stage_attr_pct",
        "persist_stages_s", "warm_restart_s", "cold_restart_s",
        "warm_vs_cold", "bitexact", "recovery", "readmit"}
missing = need - set(du)
if missing:
    raise SystemExit("durable block lacks %s" % sorted(missing))
if not du["persist_stage_attr_pct"] >= 90.0:
    raise SystemExit("persist stages attribute only %s%% of the persist wall"
                     % du["persist_stage_attr_pct"])
if set(du["persist_stages_s"]) != {"snapshot", "lineage", "manifest",
                                   "publish"}:
    raise SystemExit("persist stage set drifted: %r"
                     % sorted(du["persist_stages_s"]))
if not du["warm_restart_s"] < du["cold_restart_s"]:
    raise SystemExit("warm restart %ss did not beat cold deserialize+pack %ss"
                     % (du["warm_restart_s"], du["cold_restart_s"]))
if du["bitexact"] is not True:
    raise SystemExit("restart twin was not bit-exact")
rec = du["recovery"]
if rec.get("torn_skipped") != 0 or not rec.get("epoch", 0) > 0:
    raise SystemExit("bench recovery row is not clean: %r" % rec)
if not du["readmit"].get("joins", 0) > 0:
    raise SystemExit("no priced durable.readmit outcomes joined: %r"
                     % du["readmit"])
if not du["artifact_bytes"] > 0 or not du["persist_wall_s"] > 0:
    raise SystemExit("durable artifact rows are empty: %r"
                     % {k: du[k] for k in ("artifact_bytes",
                                           "persist_wall_s")})
side = json.load(open("/tmp/ci_bench_metrics.json"))
sdu = side.get("durable")
if not isinstance(sdu, dict):
    raise SystemExit("metrics sidecar lacks the durable block")
smissing = {"epoch", "serving_epoch", "pending_epochs", "artifact_bytes",
            "persists", "persist_stages", "recoveries",
            "demotions"} - set(sdu)
if smissing:
    raise SystemExit("sidecar durable block lacks %s" % sorted(smissing))
print("durable rows ok (%d bitmaps -> %d B artifact; persist %ss, %s%% "
      "attributed; warm %ss vs cold %ss = %sx; %d readmit joins)"
      % (du["corpus_bitmaps"], du["artifact_bytes"], du["persist_wall_s"],
         du["persist_stage_attr_pct"], du["warm_restart_s"],
         du["cold_restart_s"], du["warm_vs_cold"],
         du["readmit"]["joins"]))'
# the deterministic kill-walk: one seeded plan, a child process killed
# WITHOUT UNWINDING (os._exit mid-stage) at each of the five
# durable.persist crash points in turn, plus the clean control run.
# Every recovery must be bit-exact vs the replay oracle at the
# recovered epoch, never lose a completed persist, and the torn-newest
# fallback must serve the previous epoch after a one-byte corruption
# (fuzz family 31 runs the same family at random hits; this walk is
# the exhaustive five-point schedule)
JAX_PLATFORMS=cpu python -c '
import os, shutil, subprocess, sys, tempfile
from roaringbitmap_tpu.durable import recover
from roaringbitmap_tpu.durable import recovery as drecovery
from roaringbitmap_tpu.fuzz import _durable_plan
from roaringbitmap_tpu.serve import ingest as singest

plan_seed = 7
bms, muts = _durable_plan(plan_seed)
n_flips = len(muts)
child = ("import sys; from roaringbitmap_tpu.fuzz import _durable_child; "
         "_durable_child(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))")
env = dict(os.environ)
env["JAX_PLATFORMS"] = "cpu"
env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")

def oracle_at(k):
    ob = [b.clone() for b in bms]
    singest.apply_batches(
        ob, [singest.MutationBatch("fz-durable", m) for m in muts[:k]]
    )
    return ob

def check_bitexact(rec, where):
    want = oracle_at(rec.epoch)
    got = rec.corpus.bitmaps()
    torn = len(got) != len(want) or any(
        g.to_mutable() != w for g, w in zip(got, want)
    )
    del got
    if torn:
        raise SystemExit("%s: recovered corpus diverges from the replay "
                         "oracle at epoch %d" % (where, rec.epoch))

clean_root = newest_dir = None
recovered_at = {}
for kill_hit in range(0, 6):
    root = tempfile.mkdtemp(prefix="ci_durable_")
    proc = subprocess.run(
        [sys.executable, "-c", child, root, str(plan_seed), str(kill_hit)],
        capture_output=True, text=True, env=env, timeout=300,
    )
    logged = [int(l.split()[1]) for l in proc.stdout.splitlines()
              if l.startswith("PERSISTED ")]
    if kill_hit == 0:
        if proc.returncode != 0:
            raise SystemExit("clean child failed: %s" % proc.stderr[-400:])
    elif proc.returncode != 137:
        raise SystemExit("killed child (hit %d) exited %d, expected the "
                         "os._exit(137) power cut"
                         % (kill_hit, proc.returncode))
    last_logged = max(logged) if logged else 0
    rec = recover(root)
    if rec is None:
        if last_logged:
            raise SystemExit("DURABILITY LOST at kill hit %d: child "
                             "persisted epoch %d, recovery found nothing"
                             % (kill_hit, last_logged))
        recovered_at[kill_hit] = None
        shutil.rmtree(root)
        continue
    if not last_logged <= rec.epoch <= n_flips:
        raise SystemExit("kill hit %d recovered epoch %d outside "
                         "[%d, %d]" % (kill_hit, rec.epoch,
                                       last_logged, n_flips))
    check_bitexact(rec, "kill hit %d" % kill_hit)
    recovered_at[kill_hit] = rec.epoch
    if kill_hit == 0:
        if rec.epoch != n_flips:
            raise SystemExit("clean run recovered epoch %d, wanted the "
                             "final %d" % (rec.epoch, n_flips))
        man = drecovery.verify_manifest(rec.dir)
        if man["epoch"] != n_flips:
            raise SystemExit("re-verified manifest names epoch %r"
                             % man.get("epoch"))
        clean_root, newest_dir = root, rec.dir
        rec.close()
    else:
        rec.close()
        shutil.rmtree(root)
# hits 1-4 kill the first persist before its publish: nothing may be on
# disk; hit 5 lands after the rename, so epoch 1 must have survived
for hit in (1, 2, 3, 4):
    if recovered_at[hit] is not None:
        raise SystemExit("kill hit %d published epoch %r before the "
                         "rename" % (hit, recovered_at[hit]))
if recovered_at[5] != 1:
    raise SystemExit("kill hit 5 (post-publish) lost epoch 1: %r"
                     % recovered_at[5])
# torn-newest fallback: one flipped byte in the newest corpus must fail
# the sha256 re-verification and recovery must serve the previous epoch
with open(os.path.join(newest_dir, "corpus.rbd"), "r+b") as f:
    f.seek(-1, 2)
    b = f.read(1)
    f.seek(-1, 2)
    f.write(bytes([b[0] ^ 0xFF]))
try:
    drecovery.verify_manifest(newest_dir)
    raise SystemExit("sha256 re-verification accepted a corrupted corpus")
except ValueError:
    pass
rec2 = recover(clean_root)
if rec2 is None or rec2.epoch != n_flips - 1:
    raise SystemExit("torn newest artifact did not fall back to epoch %d: "
                     "%r" % (n_flips - 1, drecovery.LAST))
if (drecovery.LAST or {}).get("torn_skipped") != 1:
    raise SystemExit("torn fallback not surfaced in provenance: %r"
                     % drecovery.LAST)
check_bitexact(rec2, "torn fallback")
rec2.close()
shutil.rmtree(clean_root)
print("durable kill-walk ok (plan seed %d, %d flips; hits 1-4 fail closed, "
      "hit 5 survives publish; clean run recovers epoch %d; corrupted "
      "newest falls back to epoch %d with torn_skipped=1)"
      % (plan_seed, n_flips, n_flips, n_flips - 1))'
# the durable metric names must pass the naming convention, the
# durable.persist fault site and the two sentinel rules must be
# registered, and the persist-stage label set must be the declared four
JAX_PLATFORMS=cpu python -c '
from roaringbitmap_tpu import observe
from roaringbitmap_tpu.durable import PERSIST_STAGES
from roaringbitmap_tpu.robust import faults
for name, suffix in ((observe.DURABLE_PERSIST_TOTAL, "_total"),
                     (observe.DURABLE_PERSIST_STAGE_SECONDS, "_seconds"),
                     (observe.DURABLE_PERSIST_WALL_SECONDS, "_seconds"),
                     (observe.DURABLE_PERSIST_BYTES_TOTAL, "_total"),
                     (observe.DURABLE_EPOCH_COUNT, "_count"),
                     (observe.DURABLE_ARTIFACT_BYTES, "_bytes"),
                     (observe.DURABLE_PENDING_COUNT, "_count"),
                     (observe.DURABLE_RECOVERY_TOTAL, "_total"),
                     (observe.DURABLE_DEMOTE_TOTAL, "_total")):
    if not (name.startswith("rb_tpu_") and name.endswith(suffix)):
        raise SystemExit("durable metric violates naming convention: %r" % name)
import roaringbitmap_tpu.durable  # registers the persist metrics
st = observe.REGISTRY.get(observe.DURABLE_PERSIST_STAGE_SECONDS)
if st is None or st.labelnames != ("stage",):
    raise SystemExit("persist stage label set is not the declared (stage,)")
if PERSIST_STAGES != ("snapshot", "lineage", "manifest", "publish"):
    raise SystemExit("declared persist stage set drifted: %r"
                     % (PERSIST_STAGES,))
if "durable.persist" not in faults.SITES:
    raise SystemExit("durable.persist fault site not registered")
from roaringbitmap_tpu.observe import health
rules = {r.name: r for r in health.DEFAULT_RULES}
for rn in ("epoch-persist-stall", "recovery-manifest-torn"):
    if rn not in rules:
        raise SystemExit("rule table lacks %s" % rn)
print("durable metric names ok (suffixes + stage label set; fault site + "
      "both sentinel rules registered)")'

step "rb_top observatory report (schema rb_tpu_top/10, ISSUE 9 + 11-19)"
# the snapshot CLI must produce a schema-valid JSON report with every
# panel populated from its in-process demo workload — incl. the regret
# panel (per-site joins from the decision-outcome ledger), the health
# panel (sentinel status + the committed rule table, judged green), the
# fusion panel (window occupancy + shared-subexpression hit ratio from
# the demo's fused window), and the epoch panel (current epoch, mutlog
# depth, freshness, flip stages, lineage from the demo's read-write
# window), the structure panel (container census, drift ratio,
# maintenance-pass rows from the demo's forced pass), and the durable
# panel (persisted epoch, stage walls, recovery provenance from the
# demo's persisted flip + recovery scan)
JAX_PLATFORMS=cpu RB_TPU_ARTIFACT_DIR=/tmp/ci_artifacts \
  python scripts/rb_top.py --demo --json > /tmp/ci_rb_top.json
python -c '
import json
r = json.load(open("/tmp/ci_rb_top.json"))
if r.get("schema") != "rb_tpu_top/10":
    raise SystemExit("rb_top: bad schema %r" % r.get("schema"))
need = {"schema", "generated_utc", "source", "counters", "latency",
        "locks", "breakers", "cache", "decisions_tail", "regret", "health",
        "fusion", "serving", "epochs", "structure", "durable", "analysis"}
missing = need - set(r)
if missing:
    raise SystemExit("rb_top report lacks %s" % sorted(missing))
ep = r["epochs"]
if not (ep.get("epoch", 0) >= 1 and ep.get("mutlog_depth") == 0):
    raise SystemExit("rb_top demo epoch panel lacks a published flip: %r"
                     % {k: ep.get(k) for k in ("epoch", "mutlog_depth")})
if not ep.get("flips", {}).get("flipped"):
    raise SystemExit("rb_top demo recorded no flip outcome: %r" % ep.get("flips"))
if not any(row.get("p99", 0) > 0 for row in (ep.get("freshness") or {}).values()):
    raise SystemExit("rb_top demo freshness p99 missing: %r" % ep.get("freshness"))
for stage in ("drain", "repack", "publish", "reclaim"):
    if not (ep.get("flip_stages", {}).get(stage, {}).get("count", 0) >= 1):
        raise SystemExit("rb_top demo flip stage %r unrecorded" % stage)
if not (ep.get("lineage") and ep["lineage"][-1].get("epoch") == ep["epoch"]):
    raise SystemExit("rb_top demo epoch lineage missing/stale: %r" % ep.get("lineage"))
sv = r["serving"]
if not sv.get("tenants"):
    raise SystemExit("rb_top demo served no tenants: %r" % sv)
for tenant, row in sv["tenants"].items():
    ex = (row.get("latency") or {}).get("execute") or {}
    if not (row.get("qps", 0) >= 0 and ex.get("count", 0) > 0 and ex.get("p99", 0) > 0):
        raise SystemExit("rb_top serving row for %s lacks QPS/p99: %r" % (tenant, row))
if not sv.get("admit"):
    raise SystemExit("rb_top demo recorded no admission verdicts: %r" % sv)
if not isinstance(sv.get("admission_live"), dict):
    raise SystemExit("rb_top serving panel lacks live admission stats")
fu = r["fusion"]
if not fu.get("batches", {}).get("fused"):
    raise SystemExit("rb_top demo drained no fused window: %r" % fu)
if not (fu.get("occupancy") and fu["occupancy"] >= 2):
    raise SystemExit("rb_top fusion occupancy not a real window: %r" % fu)
if not (fu.get("dedup_hit_ratio") and fu["dedup_hit_ratio"] > 0):
    raise SystemExit("rb_top demo shared subexpression never deduped: %r" % fu)
# latency-class panel data (ISSUE 19, schema /10): the demo interactive
# tenant must carry its declared budget, the hedge verdict volume must
# be live, and the window auto-tune state must render
ws = fu.get("window_state")
if not (isinstance(ws, dict) and ws.get("effective", 0) >= 2
        and ws.get("base", 0) >= ws.get("min", 0) >= 2):
    raise SystemExit("rb_top fusion panel lacks window auto-tune state: %r" % ws)
if not fu.get("hedges", {}).get("solo"):
    raise SystemExit("rb_top demo interactive tenant never hedged: %r"
                     % fu.get("hedges"))
inter = sv["tenants"].get("demo-inter", {})
if not inter.get("slo_budget_s", 0) > 0:
    raise SystemExit("rb_top serving row lacks the declared p99 budget: %r"
                     % inter)
st = r["structure"]
sneed = {"containers", "bytes", "drift_ratio", "accretion_depth", "passes",
         "last_pass", "authority"}
smiss = sneed - set(st)
if smiss:
    raise SystemExit("rb_top structure panel lacks %s" % sorted(smiss))
if not sum((st.get("containers") or {}).values()) > 0:
    raise SystemExit("rb_top structure census saw no containers: %r"
                     % st.get("containers"))
if not ((st.get("bytes") or {}).get("actual", 0) > 0
        and st["bytes"].get("optimal", 0) > 0):
    raise SystemExit("rb_top structure byte census empty: %r" % st.get("bytes"))
if not st.get("drift_ratio", 0) > 0:
    raise SystemExit("rb_top structure drift ratio missing: %r" % st)
if not st.get("passes", {}).get("compacted", 0) >= 1:
    raise SystemExit("rb_top demo maintenance pass never compacted: %r"
                     % st.get("passes"))
lp = st.get("last_pass") or {}
if lp.get("outcome") != "compacted" or not lp.get("rewritten_keys", 0) > 0:
    raise SystemExit("rb_top last maintenance pass malformed: %r" % lp)
du = r["durable"]
if not (du.get("epoch") and du["epoch"] == du.get("serving_epoch")):
    raise SystemExit("rb_top durable panel not caught up: %r"
                     % {k: du.get(k) for k in ("epoch", "serving_epoch")})
if not du.get("persists", {}).get("persisted"):
    raise SystemExit("rb_top demo persisted no epoch: %r" % du.get("persists"))
if not du.get("artifact_bytes", 0) > 0:
    raise SystemExit("rb_top durable artifact bytes missing: %r" % du)
for stage in ("snapshot", "lineage", "manifest", "publish"):
    if not (du.get("persist_stages", {}).get(stage, {}).get("count", 0) >= 1):
        raise SystemExit("rb_top durable persist stage %r unrecorded" % stage)
if not du.get("recoveries", {}).get("recovered"):
    raise SystemExit("rb_top demo recovery scan found nothing: %r"
                     % du.get("recoveries"))
rl = du.get("recovery_last") or {}
if not (rl.get("epoch") == du["epoch"] and rl.get("torn_skipped") == 0):
    raise SystemExit("rb_top durable recovery provenance malformed: %r" % rl)
if not r["locks"]:
    raise SystemExit("rb_top demo recorded no lock waits")
if not r["counters"]["compile"]:
    raise SystemExit("rb_top demo recorded no compiles")
if r["cache"]["hbm"].get("ledger_drift_bytes") != 0:
    raise SystemExit("rb_top demo shows accounting drift: %r" % r["cache"]["hbm"])
if not r["decisions_tail"]:
    raise SystemExit("rb_top demo decision log is empty")
reg = r["regret"]
if not reg.get("sites"):
    raise SystemExit("rb_top demo joined no decision outcomes: %r" % reg)
if "provenance" not in reg:
    raise SystemExit("rb_top regret panel lacks model provenance: %r" % sorted(reg))
h = r["health"]
if h.get("status_name") != "green":
    raise SystemExit("rb_top demo health not green: %r" % h.get("status_name"))
if not h.get("rules"):
    raise SystemExit("rb_top health panel carries no rule states")
for rule, st in h["rules"].items():
    if not ({"level", "level_name", "warn", "critical"} <= set(st)):
        raise SystemExit("rb_top health rule %s lacks thresholds: %r" % (rule, st))
sites = {d["site"] for d in r["decisions_tail"]}
print("rb_top ok (locks %s; %d decisions over sites %s; regret sites %s; "
      "health %s over %d rules; serving tenants %s)"
      % (sorted(r["locks"]), len(r["decisions_tail"]), sorted(sites),
         sorted(reg["sites"]), h["status_name"], len(h["rules"]),
         sorted(sv["tenants"])))'
# the sidecar-sourced rendering must parse the bench artifact too
python scripts/rb_top.py --from /tmp/ci_bench_metrics.json --json > /dev/null

step "bench trend gate (>15% vs best comparable prior round)"
python scripts/bench_trend.py --check

step "graft entry + 8-device virtual-mesh dryrun"
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python __graft_entry__.py

step "all green (total $((SECONDS - t0))s)"
