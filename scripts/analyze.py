#!/usr/bin/env python
"""Project-native static analysis CLI (ISSUE 3; whole-program contract
tier ISSUE 18) — the analysis half of the reference's per-push gate
(.github/workflows/java-all-versions.yml runs checkstyle-style analysis
beside the JDK test matrix; scripts/ci.sh runs this beside pytest).

Usage::

    python scripts/analyze.py                  # lexical tier, report, exit 0
    python scripts/analyze.py --check          # exit 1 on non-baselined findings
    python scripts/analyze.py --contracts      # + whole-program contract tier
    python scripts/analyze.py --diff origin/main  # lexical tier over changed
                                               # files only (contracts, when
                                               # requested, always whole-tree)
    python scripts/analyze.py --json           # machine-readable output
    python scripts/analyze.py --update-baseline
    python scripts/analyze.py --rules lock-discipline,epoch-pin
    python scripts/analyze.py --write-knobs    # regenerate KNOBS.md
    python scripts/analyze.py --check-knobs    # exit 1 when KNOBS.md drifted

Default scan root is the ``roaringbitmap_tpu`` package. The baseline
(ANALYSIS_BASELINE.json) holds fingerprints of accepted findings so
pre-existing debt never blocks while anything new fails CI — both tiers
share it (and the ``# rb-ok:`` pragma mechanism). Per-rule finding
counts are reported into the observe registry
(``rb_tpu_analysis_findings_total{rule}`` for the lexical tier,
``rb_tpu_analysis_contract_findings_total{rule}`` for the contract tier)
for the metrics sidecar.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from roaringbitmap_tpu import observe
from roaringbitmap_tpu.analysis import (
    all_contract_rule_ids,
    all_rule_ids,
    baseline,
    fingerprints,
    get_project,
    knobs as knobs_mod,
    run_checks,
    run_contract_checks,
)
from roaringbitmap_tpu.analysis.core import CHECKERS, CONTRACT_CHECKERS

DEFAULT_PATHS = [os.path.join(REPO_ROOT, "roaringbitmap_tpu")]
DEFAULT_BASELINE = os.path.join(REPO_ROOT, baseline.DEFAULT_BASELINE_NAME)
KNOBS_PATH = os.path.join(REPO_ROOT, knobs_mod.KNOBS_DOC)

_FINDINGS_TOTAL = observe.counter(
    observe.ANALYSIS_FINDINGS_TOTAL,
    "Static-analysis findings by rule (includes baselined)",
    ("rule",),
)
_CONTRACT_FINDINGS_TOTAL = observe.counter(
    observe.ANALYSIS_CONTRACT_FINDINGS_TOTAL,
    "Whole-program contract-analysis findings by rule (includes baselined)",
    ("rule",),
)


def _changed_files(ref: str):
    """Package .py files changed vs ``ref`` (git diff --name-only), as
    absolute paths. Deleted files are skipped. Returns None on git
    failure — the caller falls back loudly, not silently."""
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", ref, "--", "roaringbitmap_tpu"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    paths = []
    for line in out.stdout.splitlines():
        line = line.strip()
        if line.endswith(".py"):
            ap = os.path.join(REPO_ROOT, line)
            if os.path.isfile(ap):
                paths.append(ap)
    return paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", help="files/dirs (default: the package)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if any non-baselined finding exists")
    ap.add_argument("--contracts", action="store_true",
                    help="also run the whole-program contract tier")
    ap.add_argument("--diff", metavar="REF", default=None,
                    help="lexical tier over files changed vs REF only "
                         "(contract tier, when requested, stays whole-tree)")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: %(default)s)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="accept every current finding into the baseline")
    ap.add_argument("--write-knobs", action="store_true",
                    help="regenerate KNOBS.md from the knob extractor")
    ap.add_argument("--check-knobs", action="store_true",
                    help="exit 1 when KNOBS.md drifted from the tree")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in all_rule_ids():
            print(f"{rid}: {CHECKERS[rid].description}")
        for rid in all_contract_rule_ids():
            print(f"{rid}: {CONTRACT_CHECKERS[rid].description}  [contract]")
        return 0

    if args.write_knobs or args.check_knobs:
        project = get_project(REPO_ROOT)
        try:
            rendered = knobs_mod.render(project)
        except ValueError as e:
            print(f"analyze: {e}", file=sys.stderr)
            return 2
        if args.write_knobs:
            with open(KNOBS_PATH, "w", encoding="utf-8") as f:
                f.write(rendered)
            print(f"wrote {os.path.relpath(KNOBS_PATH, REPO_ROOT)} "
                  f"({len(project.knobs)} knobs)")
            return 0
        try:
            with open(KNOBS_PATH, encoding="utf-8") as f:
                current = f.read()
        except OSError:
            current = ""
        if current != rendered:
            print("analyze: KNOBS.md has drifted from the tree — run "
                  "scripts/analyze.py --write-knobs", file=sys.stderr)
            return 1
        print(f"KNOBS.md is current ({len(project.knobs)} knobs)")
        return 0

    lex_rules = None
    contract_rules = None
    if args.rules:
        all_rule_ids()  # side effect: lazily registers both checker tiers
        wanted = [r.strip() for r in args.rules.split(",") if r.strip()]
        lex_rules = [r for r in wanted if r in CHECKERS] or None
        contract_rules = [r for r in wanted if r in CONTRACT_CHECKERS] or None
        unknown = [
            r for r in wanted
            if r not in CHECKERS and r not in CONTRACT_CHECKERS
        ]
        if unknown:
            print(
                f"analyze: unknown rule(s) {unknown}; known: "
                f"{all_rule_ids() + all_contract_rule_ids()}",
                file=sys.stderr,
            )
            return 2
        if contract_rules and not args.contracts:
            args.contracts = True
        if lex_rules is None:
            # contract-only selection: skip the lexical tier entirely
            lex_rules = []

    paths = args.paths or DEFAULT_PATHS
    if args.diff is not None:
        changed = _changed_files(args.diff)
        if changed is None:
            print(f"analyze: git diff vs {args.diff!r} failed; falling back "
                  "to a full scan", file=sys.stderr)
        else:
            paths = changed

    try:
        if lex_rules == [] or not paths:
            from roaringbitmap_tpu.analysis import RunResult
            result = RunResult()
        else:
            result = run_checks(paths, rules=lex_rules or None, root=REPO_ROOT)
    except ValueError as e:  # unknown rule id / bad path
        print(f"analyze: {e}", file=sys.stderr)
        return 2

    ran_contracts = []
    if args.contracts:
        project = get_project(REPO_ROOT)
        try:
            cres = run_contract_checks(project, rules=contract_rules)
        except ValueError as e:
            print(f"analyze: {e}", file=sys.stderr)
            return 2
        ran_contracts = contract_rules or all_contract_rule_ids()
        result.findings.extend(cres.findings)
        result.suppressed += cres.suppressed
        result.files = max(result.files, cres.files)
        for e in cres.parse_errors:
            if e not in result.parse_errors:
                result.parse_errors.append(e)
        result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    ran_lexical = (
        (lex_rules or all_rule_ids()) if (lex_rules != [] and paths) else []
    )
    for rid in ran_lexical:
        # inc(0) still materializes the series, so the sidecar shows a
        # clean rule as an explicit zero rather than an absence
        _FINDINGS_TOTAL.inc(
            sum(1 for f in result.findings if f.rule == rid), (rid,)
        )
    for rid in ran_contracts:
        _CONTRACT_FINDINGS_TOTAL.inc(
            sum(1 for f in result.findings if f.rule == rid), (rid,)
        )

    if args.update_baseline:
        if args.paths or args.rules or args.diff is not None:
            # a scoped run sees only a subset of findings; dumping it would
            # silently drop accepted fingerprints outside the scope and
            # break the next full --check
            print("analyze: --update-baseline requires a full default run "
                  "(no path, --rules, or --diff arguments)", file=sys.stderr)
            return 2
        if result.parse_errors:
            # an unparsed file was never scanned: its findings are unknown,
            # so "accept everything current" would be a lie
            for e in result.parse_errors:
                print(f"parse error: {e}", file=sys.stderr)
            print("analyze: refusing to update baseline with unscanned files",
                  file=sys.stderr)
            return 2
        doc = baseline.dump(args.baseline, result.findings)
        print(f"baseline updated: {len(doc['findings'])} finding(s) "
              f"accepted into {os.path.relpath(args.baseline, REPO_ROOT)}")
        return 0

    try:
        known = baseline.load(args.baseline)
    except (ValueError, json.JSONDecodeError) as e:
        print(f"analyze: bad baseline: {e}", file=sys.stderr)
        return 2
    new, old = baseline.partition(result.findings, known)

    if args.json:
        fps = fingerprints(result.findings)
        old_ids = {id(f) for f in old}
        out = {
            "files": result.files,
            "rules": list(ran_lexical) + list(ran_contracts),
            "suppressed": result.suppressed,
            "parse_errors": result.parse_errors,
            "findings": [
                {**f.to_dict(), "fingerprint": fp, "baselined": id(f) in old_ids}
                for f, fp in zip(result.findings, fps)
            ],
            "new": len(new),
            "baselined": len(old),
        }
        json.dump(out, sys.stdout, indent=2)
        print()
    else:
        for f in new:
            print(f.render())
        for f in old:
            print(f"{f.render()}  [baselined]")
        for e in result.parse_errors:
            print(f"parse error: {e}", file=sys.stderr)
        tiers = "lexical" + ("+contracts" if ran_contracts else "")
        print(
            f"analyze: {len(result.findings)} finding(s) "
            f"({len(new)} new, {len(old)} baselined, "
            f"{result.suppressed} pragma-suppressed) across "
            f"{result.files} files [{tiers}]"
        )

    if result.parse_errors:
        return 2
    if args.check and new:
        if not args.json:
            print("analyze: FAIL — new findings above are not in the baseline "
                  f"({os.path.relpath(args.baseline, REPO_ROOT)}); fix them or "
                  "run --update-baseline with justification", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
