#!/usr/bin/env python
"""Project-native static analysis CLI (ISSUE 3) — the analysis half of the
reference's per-push gate (.github/workflows/java-all-versions.yml runs
checkstyle-style analysis beside the JDK test matrix; scripts/ci.sh runs
this beside pytest).

Usage::

    python scripts/analyze.py                  # report findings, exit 0
    python scripts/analyze.py --check          # exit 1 on non-baselined findings
    python scripts/analyze.py --json           # machine-readable output
    python scripts/analyze.py --update-baseline
    python scripts/analyze.py --rules lock-discipline,metric-naming pkg/dir

Default scan root is the ``roaringbitmap_tpu`` package. The baseline
(ANALYSIS_BASELINE.json) holds fingerprints of accepted findings so
pre-existing debt never blocks while anything new fails CI. Per-rule
finding counts are reported into the observe registry
(``rb_tpu_analysis_findings_total{rule}``) for the metrics sidecar.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from roaringbitmap_tpu import observe
from roaringbitmap_tpu.analysis import all_rule_ids, baseline, fingerprints, run_checks
from roaringbitmap_tpu.analysis.core import CHECKERS

DEFAULT_PATHS = [os.path.join(REPO_ROOT, "roaringbitmap_tpu")]
DEFAULT_BASELINE = os.path.join(REPO_ROOT, baseline.DEFAULT_BASELINE_NAME)

_FINDINGS_TOTAL = observe.counter(
    observe.ANALYSIS_FINDINGS_TOTAL,
    "Static-analysis findings by rule (includes baselined)",
    ("rule",),
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", help="files/dirs (default: the package)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if any non-baselined finding exists")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: %(default)s)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="accept every current finding into the baseline")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in all_rule_ids():
            print(f"{rid}: {CHECKERS[rid].description}")
        return 0

    rules = [r.strip() for r in args.rules.split(",")] if args.rules else None
    paths = args.paths or DEFAULT_PATHS
    try:
        result = run_checks(paths, rules=rules, root=REPO_ROOT)
    except ValueError as e:  # unknown rule id
        print(f"analyze: {e}", file=sys.stderr)
        return 2

    for rid in rules or all_rule_ids():
        # inc(0) still materializes the series, so the sidecar shows a
        # clean rule as an explicit zero rather than an absence
        _FINDINGS_TOTAL.inc(
            sum(1 for f in result.findings if f.rule == rid), (rid,)
        )

    if args.update_baseline:
        if args.paths or args.rules:
            # a scoped run sees only a subset of findings; dumping it would
            # silently drop accepted fingerprints outside the scope and
            # break the next full --check
            print("analyze: --update-baseline requires a full default run "
                  "(no path or --rules arguments)", file=sys.stderr)
            return 2
        if result.parse_errors:
            # an unparsed file was never scanned: its findings are unknown,
            # so "accept everything current" would be a lie
            for e in result.parse_errors:
                print(f"parse error: {e}", file=sys.stderr)
            print("analyze: refusing to update baseline with unscanned files",
                  file=sys.stderr)
            return 2
        doc = baseline.dump(args.baseline, result.findings)
        print(f"baseline updated: {len(doc['findings'])} finding(s) "
              f"accepted into {os.path.relpath(args.baseline, REPO_ROOT)}")
        return 0

    try:
        known = baseline.load(args.baseline)
    except (ValueError, json.JSONDecodeError) as e:
        print(f"analyze: bad baseline: {e}", file=sys.stderr)
        return 2
    new, old = baseline.partition(result.findings, known)

    if args.json:
        fps = fingerprints(result.findings)
        old_ids = {id(f) for f in old}
        out = {
            "files": result.files,
            "rules": rules or all_rule_ids(),
            "suppressed": result.suppressed,
            "parse_errors": result.parse_errors,
            "findings": [
                {**f.to_dict(), "fingerprint": fp, "baselined": id(f) in old_ids}
                for f, fp in zip(result.findings, fps)
            ],
            "new": len(new),
            "baselined": len(old),
        }
        json.dump(out, sys.stdout, indent=2)
        print()
    else:
        for f in new:
            print(f.render())
        for f in old:
            print(f"{f.render()}  [baselined]")
        for e in result.parse_errors:
            print(f"parse error: {e}", file=sys.stderr)
        print(
            f"analyze: {len(result.findings)} finding(s) "
            f"({len(new)} new, {len(old)} baselined, "
            f"{result.suppressed} pragma-suppressed) across "
            f"{result.files} files"
        )

    if result.parse_errors:
        return 2
    if args.check and new:
        if not args.json:
            print("analyze: FAIL — new findings above are not in the baseline "
                  f"({os.path.relpath(args.baseline, REPO_ROOT)}); fix them or "
                  "run --update-baseline with justification", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
