#!/usr/bin/env python
"""rb_top — one-shot resource observatory report (ISSUE 9).

Renders the framework's observability surface as a single console or
JSON report: registry counters (kernel dispatch, layouts, pack cache,
degradations, compiles), latency histograms with p50/p99, lock-wait
quantiles over the framework locks, circuit-breaker states, pack-cache
residency + device-memory accounting drift, the decision-log tail, the
regret panel (ISSUE 11: per-site routing regret and predicted-vs-
measured error from the decision-outcome ledger), and — since ISSUE 12
— the **health panel**: the sentinel's process status (green/yellow/
red), every firing rule with its current value against its committed
thresholds, and the last actuations (auto-refits with per-authority
provenance, alerts, flight bundles) — "is this process healthy, and
what did the supervisor do about it" in one artifact.

Three sources::

    python scripts/rb_top.py --demo            # run a small in-process
                                               # workload, report live state
    python scripts/rb_top.py --from BENCH_METRICS.json
                                               # render a bench sidecar
    python scripts/rb_top.py                   # live state of THIS process
                                               # (useful when imported:
                                               #  rb_top.report())

Since ISSUE 13 the report also carries the **fusion panel**: the
micro-batching executor's window occupancy, shared-subexpression hit
ratio, in-flight dedup joins, and queue depth (batch regret rides the
regret panel under the ``fusion.batch`` site).

Since ISSUE 14 the report carries the **serving panel**: per-tenant
QPS/p50/p99, queue depth and in-flight, shed counts, saturation, byte
shares, and the admission curve's joined regret (which rides the regret
panel under the ``serve.admit`` site).

Since ISSUE 15 the report carries the **epoch panel**: the current
epoch, live mutation-log depth, per-tenant freshness p50/p99
(ingest->queryable lag), the last flip's stage breakdown, flip volume by
outcome, and the live EpochStore's lineage tail (flip regret rides the
regret panel under the ``epoch.flip`` site).

Since ISSUE 16 the report carries the **structure panel**: the
container-format census over the watched working sets, the
actual-vs-optimal serialized-bytes drift ratio, run fragmentation p99,
epoch-delta accretion depth, the last maintenance pass's outcome +
reclaimed bytes, and the compaction authority's provenance (pass regret
rides the regret panel under the ``serve.maintain`` site).

Since ISSUE 19 the report carries the **latency-class panel**: each
declared tenant's measured p99 against its declared p99 budget (the
latency-class contract), the hedged-solo-dispatch rate, and the fusion
window's auto-tune state (effective vs base vs floor — effective below
base means the serving-p99-pressure actuation has shrunk the window).

``--json`` emits the machine-readable report (schema ``rb_tpu_top/10``:
the fusion ``hedges``/``window`` fields and per-tenant ``slo_budget_s``
landed in /10, ``analysis`` in /8–/9, the ``structure`` key in /7,
``epochs`` in /6, ``serving`` in /5, ``fusion`` in /4, ``health`` in
/3, ``regret`` in /2; scripts/ci.sh validates it).
Breaker states, the decision log, the outcome ledger, sentinel rule
states, and epoch lineage are process-local, so a sidecar-sourced
report carries the sidecar's registry view of them (counter totals + the
``regret``/``health``/``fusion``/``epochs``/``structure`` blocks
derived in export.py) rather than live states.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

SCHEMA = "rb_tpu_top/10"


def _live_report(tail: int) -> dict:
    from roaringbitmap_tpu import insights, observe
    from roaringbitmap_tpu.observe import export as obs_export

    side = obs_export.sidecar_snapshot()
    obs = insights.observatory()
    return {
        "schema": SCHEMA,
        "generated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "source": "live",
        "counters": {
            "kernel": side["kernel"],
            "layout": side["layout"],
            "pack_cache": insights.pack_cache_counters(),
            "robust": insights.robust_counters(),
            "compile": side["compile"],
            "decisions": side["decisions"],
        },
        "latency": side["latency"],
        "locks": obs["locks"],
        "lock_timing": obs["lock_timing"],
        "breakers": obs["breakers"],
        "cache": {"stats": obs["pack_cache"], "hbm": obs["hbm"]},
        "decisions_tail": insights.decisions(tail),
        # decision-outcome ledger (ISSUE 11): per-site regret + error
        # rollup, coefficient drift, model provenance
        "regret": insights.regret_summary(),
        # health sentinel (ISSUE 12): status + per-rule states vs their
        # committed thresholds + the recent actuation log
        "health": insights.health(),
        # cross-query fusion (ISSUE 13): window occupancy, dedup hit
        # ratio, in-flight joins, queue depth
        "fusion": insights.fusion_counters(),
        # serving tier (ISSUE 14): per-tenant QPS/p50/p99, admission
        # verdicts, queue/in-flight depth, saturation, byte shares
        "serving": insights.serving(),
        # epoch ledger (ISSUE 15): current epoch, mutlog depth, freshness
        # p50/p99, flip stage breakdown, live lineage tail
        "epochs": insights.epochs(),
        # structure observatory (ISSUE 16): format census, drift ratio,
        # fragmentation/accretion, last maintenance pass, authority
        "structure": insights.structure(),
        # durable epochs (ISSUE 17): persisted vs serving epoch, artifact
        # bytes, persist stage walls, recovery provenance, demotions
        "durable": insights.durable(),
        # static analysis (ISSUE 18): per-rule finding counts from the
        # lexical and whole-program contract tiers, when the analyzer ran
        # in this process (or is present in the sidecar registry)
        "analysis": side["analysis"],
    }


def _sidecar_report(path: str, tail: int) -> dict:
    with open(path) as f:
        side = json.load(f)
    reg = side.get("registry", {})

    def counter_map(name):
        out = {}
        for s in reg.get(name, {}).get("samples", []):
            out["/".join(s["labels"].values())] = s.get("value")
        return out

    return {
        "schema": SCHEMA,
        "generated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "source": "sidecar:" + path,
        "counters": {
            "kernel": side.get("kernel", {}),
            "layout": side.get("layout", {}),
            "pack_cache": {
                "hits": counter_map("rb_tpu_pack_cache_hits_total"),
                "misses": counter_map("rb_tpu_pack_cache_misses_total"),
                "resident_bytes": counter_map("rb_tpu_pack_cache_resident_bytes"),
            },
            "robust": {
                "degrade": counter_map("rb_tpu_degrade_total"),
                "breaker": counter_map("rb_tpu_breaker_transitions_total"),
            },
            "compile": side.get("compile", {}),
            "decisions": side.get("decisions", {}),
        },
        "latency": side.get("latency", {}),
        # lock-wait quantiles ride in the sidecar latency block; the flat
        # count/total view is the lock_wait block
        "locks": side.get("latency", {}).get("rb_tpu_lock_wait_seconds", {}),
        "lock_timing": bool(side.get("lock_wait")),
        "breakers": counter_map("rb_tpu_breaker_transitions_total"),
        "cache": {"stats": None, "hbm": counter_map("rb_tpu_hbm_accounting_drift_bytes")},
        "decisions_tail": [],
        # the sidecar's registry-derived regret block (sites carry
        # regret_s + error means; joins/orphans/anomalies/drift ride
        # alongside) — rendered under the same panel as the live rollup
        "regret": side.get("regret", {}),
        # the sidecar's registry-derived health block (status enum +
        # per-rule state enums + actuation counters, export.py)
        "health": side.get("health", {}),
        # the sidecar's registry-derived fusion block (export.py)
        "fusion": side.get("fusion", {}),
        # the sidecar's registry-derived serving block (export.py)
        "serving": side.get("serving", {}),
        # the sidecar's registry-derived epochs block (export.py; lineage
        # is process-local and absent from a sidecar rendering)
        "epochs": side.get("epochs", {}),
        # the sidecar's registry-derived structure block (export.py; the
        # live ledger stats and last-pass record are process-local)
        "structure": side.get("structure", {}),
        # the sidecar's registry-derived durable block (export.py; the
        # live store stats and recovery provenance are process-local)
        "durable": side.get("durable", {}),
        # the sidecar's registry-derived analysis block (export.py)
        "analysis": side.get("analysis", {}),
    }


# the demo's epoch store must outlive _demo_workload: the epoch panel
# reads the CURRENT store through a weakref (serve/epochs.py), so a
# garbage-collected demo store would render an empty lineage
_DEMO_KEEPALIVE = []


def _demo_workload() -> None:
    """A small end-to-end exercise so the live report has every panel
    populated: lock timing on, folds (cpu + forced-device), a planned
    query, a delta repack, and an HBM reconciliation."""
    import numpy as np

    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.observe import lockstats
    from roaringbitmap_tpu.parallel import aggregation, store
    from roaringbitmap_tpu.query import Q, execute

    lockstats.install()
    rng = np.random.default_rng(7)
    bms = [
        RoaringBitmap(
            np.sort(rng.choice(1 << 18, 1500, replace=False)).astype(np.uint32)
        )
        for _ in range(8)
    ]
    aggregation.FastAggregation.or_(*bms, mode="cpu")
    aggregation.FastAggregation.or_(*bms, mode="device")
    execute((Q.leaf(bms[0]) & Q.leaf(bms[1])) | Q.leaf(bms[2]))
    # a fused window so the fusion panel reports real occupancy/dedup
    # numbers (shared hot AND under different predicates, ISSUE 13)
    from roaringbitmap_tpu.query import execute_fused

    # the shared AND rides under an OR so the flatten rewrite cannot
    # absorb it — it stays ONE hash-consed node across all three plans
    hot = Q.leaf(bms[0]) & Q.leaf(bms[1])
    execute_fused([hot | Q.leaf(bms[i]) for i in (2, 3, 4)])
    hb = int(bms[0].high_low_container.keys[0])
    bms[0].add((hb << 16) | 4242)
    store.packed_for(bms)
    store.hbm_reconciliation()
    # a tiny serving window so the serving panel reports real tenants
    # (admission + SLO accounting through the harness); the interactive
    # profile gives the latency-class panel a declared budget and a
    # hedged solo verdict to render (ISSUE 19)
    from roaringbitmap_tpu.serve import LoadHarness, TenantProfile, build_requests

    profiles = [
        TenantProfile("demo-gold", weight=2.0, quota_qps=500),
        TenantProfile("demo-bronze", weight=1.0, quota_qps=250),
        TenantProfile(
            "demo-inter", weight=1.0, quota_qps=250,
            latency_class="interactive",
        ),
    ]
    harness = LoadHarness(bms, profiles, threads=2, window=4)
    harness.run(build_requests(bms, profiles, 12, seed=11))
    # a read-write window over an epoch store so the epoch panel reports
    # a real flip: a writer tenant interleaves mutation batches, the flip
    # publishes, freshness + flip stages land in the registry (ISSUE 15)
    from roaringbitmap_tpu.serve import EpochStore

    rw_profiles = [
        TenantProfile("demo-gold", weight=2.0, quota_qps=500),
        TenantProfile("demo-writer", weight=1.0, quota_qps=500, writes=0.6),
    ]
    es = EpochStore(bms)
    _DEMO_KEEPALIVE.append(es)
    rw_harness = LoadHarness(
        bms, rw_profiles, threads=2, window=4, epoch_store=es
    )
    rw_harness.run(build_requests(bms, rw_profiles, 12, seed=13))
    # a watched working set + one forced maintenance pass so the
    # structure panel reports a real census and pass record (ISSUE 16);
    # a dense drift span first (full chunks held as 8 KiB bitmap
    # containers that the size rule wants as runs) so the pass actually
    # rewrites containers instead of auditing an already-optimal corpus
    from roaringbitmap_tpu.observe import structure as _structure
    from roaringbitmap_tpu.serve import maintain as _maintain

    bms[0] |= RoaringBitmap(np.arange(0x400 << 16, (0x400 << 16) + 2 * 65536))
    _structure.LEDGER.watch("demo", bms)
    _structure.LEDGER.refresh()
    _maintain.run_pass(store=es, reason="demo", force=True)
    # one persisted flip + a recovery scan so the durable panel reports a
    # real frozen epoch, stage walls, and provenance (ISSUE 17)
    import tempfile

    from roaringbitmap_tpu import durable as _durable

    droot = tempfile.mkdtemp(prefix="rb_top_durable_")
    dstore = _durable.DurableStore(droot)
    _DEMO_KEEPALIVE.append(dstore)
    es.attach_durable(dstore)
    es.submit("demo-writer", {0: [4243, 4244]})
    es.flip(reason="demo-durable")
    rec = _durable.recover(droot)
    if rec is not None:
        _DEMO_KEEPALIVE.append(rec)
    # a couple of sentinel ticks so the health panel reports a judged
    # status (hysteresis needs consecutive evaluations), not "never ran"
    from roaringbitmap_tpu.observe import sentinel

    sentinel.SENTINEL.tick()
    sentinel.SENTINEL.tick()


def _fmt_table(rows, indent="  "):
    if not rows:
        return [indent + "(none)"]
    w = max(len(str(k)) for k, _ in rows)
    return [f"{indent}{str(k):<{w}}  {v}" for k, v in rows]


def _render_console(r: dict) -> str:
    lines = [f"rb_top — {r['source']}  ({r['generated_utc']})"]

    def section(title, rows):
        lines.append("")
        lines.append(title)
        lines.extend(_fmt_table(rows))

    c = r["counters"]
    section("kernel dispatch", sorted(c.get("kernel", {}).items()))
    section("layouts", sorted(c.get("layout", {}).items()))
    pc = c.get("pack_cache", {})
    section(
        "pack cache",
        [(k, pc[k]) for k in sorted(pc) if pc[k]],
    )
    section("compiles (rb_tpu_compile_total)", sorted(c.get("compile", {}).items()))
    section(
        "locks (wait p99 s)" if r.get("lock_timing") else "locks (timing off)",
        sorted(
            (k, v.get("p99", v.get("mean_ms"))) for k, v in r.get("locks", {}).items()
        ),
    )
    section("breakers", sorted(r.get("breakers", {}).items()))
    cache = r.get("cache", {})
    hbm = cache.get("hbm") or {}
    section("hbm accounting", sorted(hbm.items()))
    lat = r.get("latency", {})
    lat_rows = []
    for metric in sorted(lat):
        for series, st in sorted(lat[metric].items()):
            lat_rows.append(
                (f"{metric}{{{series}}}",
                 f"n={st['count']} p50={st['p50']:.6f} p99={st['p99']:.6f}")
            )
    section("latency (p50/p99 s)", lat_rows[:40])
    # regret panel (ISSUE 11): per-site wall lost to wrong verdicts +
    # predicted-vs-measured error, then the worst recent decision with
    # the inputs that drove it (live reports) — the "which pricing
    # authority is lying, and how badly" view
    reg = r.get("regret", {}) or {}
    reg_rows = []
    worst_rows = []
    for site, s in sorted((reg.get("sites") or {}).items()):
        if "count" in s:  # live rollup shape
            err = s.get("error_ratio_geomean")
            reg_rows.append(
                (site,
                 f"joins={s['count']} regret={s['regret_s']:.6f}s"
                 + (f" err_geomean={err}" if err is not None else ""))
            )
            w = s.get("worst")
            if w and w.get("regret_s"):
                worst_rows.append(
                    (site,
                     f"{w.get('engine')} measured={w['measured_s']:.6f}s "
                     f"regret={w['regret_s']:.6f}s inputs={w.get('inputs', {})}")
                )
        else:  # sidecar registry shape
            reg_rows.append(
                (site,
                 f"regret={s.get('regret_s', 0)}s over "
                 f"{s.get('regret_events', 0)} event(s), "
                 f"err_mean={s.get('error_ratio_mean')}")
            )
    for cell, v in sorted((reg.get("drift") or {}).items()):
        reg_rows.append((f"drift {cell}", v))
    orphans = reg.get("orphans")
    if orphans:
        reg_rows.append(("orphans", dict(orphans)))
    prov = reg.get("provenance")
    if prov:
        reg_rows.append(("provenance", prov))
    section("regret (decision-outcome ledger)", reg_rows)
    if worst_rows:
        section("worst recent decisions", worst_rows)
    # health panel (ISSUE 12): process status, firing rules with current
    # value vs the committed thresholds, then the last actuations (auto-
    # refit provenance included) — live reports carry rule dicts, sidecar
    # reports carry the registry's state enums
    h = r.get("health", {}) or {}
    h_rows = []
    status = h.get("status_name") or h.get("status")
    h_rows.append(("status", status if status is not None else "(no sentinel tick)"))
    rules = h.get("rules") or {}
    for rule, st in sorted(rules.items()):
        if isinstance(st, dict):  # live rule-state shape
            if st.get("level", 0) or st.get("flapping"):
                h_rows.append(
                    (rule,
                     f"{st.get('level_name')} value={st.get('value')} "
                     f"warn>={st.get('warn')} crit>={st.get('critical')}"
                     + (" FLAPPING" if st.get("flapping") else ""))
                )
        elif st:  # sidecar enum shape: nonzero = firing
            h_rows.append((rule, f"state={st}"))
    act_rows = []
    for a in (h.get("actuations") or [])[-8:] if isinstance(
            h.get("actuations"), list) else []:
        desc = a.get("kind", "?")
        if a.get("kind") == "refit":
            provs = {
                name: rep.get("provenance")
                for name, rep in (a.get("authorities") or {}).items()
                if rep.get("moved")
            }
            desc += f" rule={a.get('rule')} moved={provs}"
        elif a.get("kind") == "bundle":
            desc += f" rules={a.get('rules')} path={a.get('path')}"
        else:
            desc += f" rule={a.get('rule')} value={a.get('value')}"
        act_rows.append((f"tick {a.get('tick')}", desc))
    if isinstance(h.get("actuations"), dict):  # sidecar counter shape
        for key, v in sorted(h["actuations"].items()):
            act_rows.append((key, v))
    section("health (sentinel)", h_rows)
    if act_rows:
        section("health actuations", act_rows)
    # fusion panel (ISSUE 13): window occupancy, shared-subexpression hit
    # ratio, in-flight dedup joins, queue depth — batch regret rides the
    # regret panel above under the fusion.batch site
    f = r.get("fusion", {}) or {}
    f_rows = []
    for outcome, v in sorted((f.get("batches") or {}).items()):
        f_rows.append((f"batches[{outcome}]", v))
    if f.get("queries"):
        f_rows.append(("queries", f["queries"]))
    if f.get("occupancy") is not None:
        f_rows.append(("window occupancy", f["occupancy"]))
    if f.get("dedup_hit_ratio") is not None:
        f_rows.append(("shared-subexpr hit ratio", f["dedup_hit_ratio"]))
    for kind, v in sorted((f.get("steps") or {}).items()):
        f_rows.append((f"steps[{kind}]", v))
    for event, v in sorted((f.get("inflight") or {}).items()):
        f_rows.append((f"inflight[{event}]", v))
    if f.get("queue_depth") is not None:
        f_rows.append(("queue depth", f["queue_depth"]))
    section("fusion (cross-query micro-batching)", f_rows)
    # serving panel (ISSUE 14): per-tenant QPS/p50/p99, admission
    # verdicts, queue/in-flight depth, saturation, byte shares
    sv = r.get("serving", {}) or {}
    sv_rows = []
    for tenant, row in sorted((sv.get("tenants") or {}).items()):
        lat = row.get("latency") or {}
        ex = lat.get("execute") or {}
        qu = lat.get("queue") or {}
        sv_rows.append(
            (tenant,
             f"qps={row.get('qps')} exec p50={ex.get('p50')} "
             f"p99={ex.get('p99')} queue p99={qu.get('p99')} "
             f"sat={row.get('saturation')} bytes={row.get('bytes')}")
        )
    for key, v in sorted((sv.get("admit") or {}).items()):
        sv_rows.append((f"admit[{key}]", v))
    if sv.get("queue_depth") is not None:
        sv_rows.append(("queue depth", sv["queue_depth"]))
    if sv.get("inflight") is not None:
        sv_rows.append(("in-flight", sv["inflight"]))
    live_adm = sv.get("admission_live")
    if isinstance(live_adm, dict):
        sv_rows.append(
            ("admission", f"inflight {live_adm.get('inflight')}/"
             f"{live_adm.get('max_inflight')} queued {live_adm.get('queued')}")
        )
    section("serving (per-tenant SLO)", sv_rows)
    # latency-class panel (ISSUE 19): per-tenant p99 vs its DECLARED
    # budget (the end-to-end queue+execute wall the class contract is
    # judged on), the hedge verdict volume/rate, and the window
    # auto-tune state — effective below base means serving-p99-pressure
    # has shrunk the window and the regrow has not yet happened
    lc_rows = []
    for tenant, row in sorted((sv.get("tenants") or {}).items()):
        budget_s = row.get("slo_budget_s")
        if not budget_s:
            continue
        lat = row.get("latency") or {}
        worst_p99 = max(
            (ph.get("p99") or 0.0 for ph in lat.values()), default=0.0
        )
        verdict = "ok" if worst_p99 <= budget_s else "OVER"
        lc_rows.append(
            (tenant,
             f"p99={round(worst_p99 * 1e3, 3)}ms "
             f"budget={round(budget_s * 1e3, 1)}ms {verdict}")
        )
    for verdict, v in sorted((f.get("hedges") or {}).items()):
        lc_rows.append((f"hedge[{verdict}]", v))
    if f.get("hedge_rate") is not None:
        lc_rows.append(("hedge rate", f["hedge_rate"]))
    ws = f.get("window_state")
    if isinstance(ws, dict):
        lc_rows.append(
            ("window",
             f"effective={ws.get('effective')} base={ws.get('base')} "
             f"min={ws.get('min')} hedge={'on' if ws.get('hedge_enabled') else 'off'}")
        )
    elif f.get("window") is not None:
        lc_rows.append(("window", f"effective={f['window']}"))
    section("latency classes (SLO budgets & hedging)", lc_rows)
    # epoch panel (ISSUE 15): current epoch, log depth, per-tenant
    # freshness p50/p99, last flip's stage breakdown, lineage tail
    ep = r.get("epochs", {}) or {}
    ep_rows = []
    if ep.get("epoch") is not None:
        ep_rows.append(("current epoch", ep["epoch"]))
    if ep.get("mutlog_depth") is not None:
        ep_rows.append(("mutation-log depth", ep["mutlog_depth"]))
    for outcome, v in sorted((ep.get("flips") or {}).items()):
        ep_rows.append((f"flips[{outcome}]", v))
    for tenant, row in sorted((ep.get("freshness") or {}).items()):
        ep_rows.append(
            (f"freshness[{tenant}]",
             f"n={row.get('count')} p50={row.get('p50')} p99={row.get('p99')}")
        )
    for stage_name, row in sorted((ep.get("flip_stages") or {}).items()):
        ep_rows.append(
            (f"stage[{stage_name}]",
             f"n={row.get('count')} sum={row.get('sum')}s p99={row.get('p99')}")
        )
    for rec in (ep.get("lineage") or [])[-4:]:
        ep_rows.append(
            (f"epoch {rec.get('epoch')}",
             f"parent={rec.get('parent')} batches={rec.get('batches')} "
             f"values={rec.get('values')} wall={rec.get('wall_s')}s "
             f"delta_rows={rec.get('delta', {}).get('delta_rows')}")
        )
    section("epochs (ingest & freshness)", ep_rows)
    # structure panel (ISSUE 16): format census, bytes-vs-optimal drift
    # ratio, fragmentation p99, accretion depth, the last maintenance
    # pass, the compaction authority's provenance — pass regret rides the
    # regret panel above under the serve.maintain site
    st = r.get("structure", {}) or {}
    st_rows = []
    for fmt, v in sorted((st.get("containers") or {}).items()):
        st_rows.append((f"containers[{fmt}]", v))
    for kind, v in sorted((st.get("bytes") or {}).items()):
        st_rows.append((f"bytes[{kind}]", v))
    if st.get("drift_ratio") is not None:
        st_rows.append(("drift ratio (actual/optimal)", st["drift_ratio"]))
    if st.get("fragmentation_p99") is not None:
        st_rows.append(("run fragmentation p99", st["fragmentation_p99"]))
    if st.get("accretion_depth") is not None:
        st_rows.append(("delta accretion depth", st["accretion_depth"]))
    for outcome, v in sorted((st.get("passes") or {}).items()):
        st_rows.append((f"passes[{outcome}]", v))
    if st.get("reclaimed_bytes"):
        st_rows.append(("reclaimed bytes", st["reclaimed_bytes"]))
    lp = st.get("last_pass")
    if isinstance(lp, dict) and lp:
        st_rows.append(
            ("last pass",
             f"{lp.get('outcome')} keys={lp.get('rewritten_keys')} "
             f"reclaimed={lp.get('reclaimed_bytes')}B "
             f"anomalies={lp.get('anomalies')} wall={lp.get('wall_s')}s")
        )
    if st.get("authority"):
        st_rows.append(("authority", st["authority"]))
    section("structure (corpus shape & compaction)", st_rows)
    # durable panel (ISSUE 17): persisted vs serving epoch, the frozen
    # artifact's size, persist volume + last wall, the persist stage
    # decomposition, recovery provenance, and residency demotions
    du = r.get("durable", {}) or {}
    du_rows = []
    if du.get("epoch") is not None or du.get("serving_epoch") is not None:
        du_rows.append(
            ("epoch (persisted/serving)",
             f"{du.get('epoch')}/{du.get('serving_epoch')}")
        )
    if du.get("pending_epochs") is not None:
        du_rows.append(("pending epochs", du["pending_epochs"]))
    if du.get("artifact_bytes") is not None:
        du_rows.append(("artifact bytes", du["artifact_bytes"]))
    if du.get("persist_wall_s") is not None:
        du_rows.append(("last persist wall", f"{du['persist_wall_s']}s"))
    for outcome, v in sorted((du.get("persists") or {}).items()):
        du_rows.append((f"persists[{outcome}]", v))
    for stage_name, row in sorted((du.get("persist_stages") or {}).items()):
        du_rows.append(
            (f"stage[{stage_name}]",
             f"n={row.get('count')} sum={row.get('sum')}s")
        )
    for outcome, v in sorted((du.get("recoveries") or {}).items()):
        du_rows.append((f"recoveries[{outcome}]", v))
    for rung, v in sorted((du.get("demotions") or {}).items()):
        du_rows.append((f"demotions[{rung}]", v))
    sl = du.get("store_live")
    if isinstance(sl, dict) and sl:
        du_rows.append(
            ("store", f"root={sl.get('root')} keep={sl.get('keep')} "
             f"persists={sl.get('persists')}")
        )
    rl = du.get("recovery_last")
    if isinstance(rl, dict) and rl:
        du_rows.append(
            ("recovered from",
             f"{rl.get('dir')} epoch={rl.get('epoch')} "
             f"torn_skipped={rl.get('torn_skipped')} wall={rl.get('wall_s')}s")
        )
    section("durable (frozen epochs & recovery)", du_rows)
    # analysis panel (ISSUE 18): per-rule finding counts from the last
    # analyzer run that exported into this registry — zeros are shown
    # (rule ran, found nothing); absent rules never ran in this process
    an = r.get("analysis", {}) or {}
    an_rows = []
    for rule, v in sorted((an.get("lexical") or {}).items()):
        an_rows.append((rule, v))
    for rule, v in sorted((an.get("contracts") or {}).items()):
        an_rows.append((f"{rule} [contract]", v))
    if an_rows:
        an_rows.append(("total findings", an.get("total", 0)))
    section("analysis (static-analysis findings)", an_rows)
    dec_rows = [
        (d.get("trace") or "-",
         f"{d['site']}: {d['decision']} {d.get('inputs', '')}")
        for d in r.get("decisions_tail", [])
    ]
    section("decision log (tail)", dec_rows)
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true", help="JSON report")
    ap.add_argument("--from", dest="from_path", default=None,
                    help="render a metrics sidecar file instead of live state")
    ap.add_argument("--demo", action="store_true",
                    help="run a small in-process workload first")
    ap.add_argument("--tail", type=int, default=16,
                    help="decision-log tail length (default %(default)s)")
    args = ap.parse_args(argv)

    if args.from_path:
        r = _sidecar_report(args.from_path, args.tail)
    else:
        if args.demo:
            _demo_workload()
        r = _live_report(args.tail)
        if args.demo:
            r["source"] = "demo"
    if args.json:
        print(json.dumps(r, indent=1, default=str))
    else:
        print(_render_console(r), end="")
    return 0


def report(tail: int = 16) -> dict:
    """Library entry: the live observatory report for this process."""
    return _live_report(tail)


if __name__ == "__main__":
    raise SystemExit(main())
