#!/bin/bash
# Everything that needs the real chip, in one run — executed automatically
# by scripts/tunnel_watch.sh when the axon tunnel comes back.
#
# Round-4 contract (VERDICT r3 #1): every hardware claim must leave a
# machine-readable artifact in git. Each tool writes JSON into
# chip_artifacts/<utc-stamp>/ and this script commits the directory, so a
# completed (or even partially completed) chip session is reproducible
# evidence from the repo alone.
set -u
cd /root/repo
export PYTHONPATH=/root/repo:${PYTHONPATH:-}
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
ART=chip_artifacts/$STAMP
mkdir -p "$ART"
LOG=${1:-$ART/chip_suite.log}
# CHIP_SUITE.log must exist from the start: git commit (unlike git diff)
# fatals on a pathspec matching no file known to git, which would turn
# every intermediate commit into a silent no-op until the final cp
# (code-review r4)
touch CHIP_SUITE.log

python - "$ART/meta.json" <<'EOF'
import json, subprocess, sys, time
meta = {"generated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_head": subprocess.run(["git", "rev-parse", "HEAD"], capture_output=True,
                                   text=True).stdout.strip()}
try:
    import jax
    meta["jax_version"] = jax.__version__
    meta["backend"] = jax.default_backend()
    meta["devices"] = [str(d) for d in jax.devices()]
    meta["device_kind"] = jax.devices()[0].device_kind if jax.devices() else None
except Exception as e:
    meta["backend_error"] = repr(e)[:300]
json.dump(meta, open(sys.argv[1], "w"), indent=1)
print(meta)
EOF

commit_artifacts() {
  # commit whatever has landed so far; artifacts are generated data, so the
  # verification gate does not apply (scripts/ci.sh covers the code).
  # pathspecs added separately: one unmatched pathspec (CHIP_SUITE.log
  # before the final cp) would otherwise fatal the whole add and turn every
  # intermediate commit into a silent no-op (code-review r4)
  git add -A chip_artifacts/ 2>/dev/null
  git add CHIP_SUITE.log 2>/dev/null || true
  # pathspec-limited commit: an operator's unrelated staged WIP must not be
  # swept into this automated artifact commit (code-review r4)
  if ! git diff --cached --quiet -- chip_artifacts CHIP_SUITE.log; then
    git commit -q -m "Record on-chip validation artifacts ($STAMP)

Machine-readable chip evidence: kernel-check family results, tile-sweep
table, bench.py meta+result, BSI north-star suite — written by
scripts/chip_suite.sh on the real TPU backend.

No-Verification-Needed: machine-generated benchmark artifacts, no code change" \
      -- chip_artifacts CHIP_SUITE.log \
      && echo "committed $ART"
  fi
}
trap commit_artifacts EXIT

{
  echo "=== chip suite start: $(date -u +%FT%TZ) -> $ART"
  echo "--- kernel check (all pallas + MXU families on chip)"
  timeout 1200 python -u scripts/tpu_kernel_check.py --json "$ART/kernel_check.json" 2>&1 | grep -v WARNING
  commit_artifacts
  echo "--- tile sweep (incl. flagship [66,1450,2048] + gap-closing variants)"
  timeout 2400 python -u scripts/tile_sweep.py --json "$ART/tile_sweep.json" 2>&1 | grep -v WARNING
  if [ -f "$ART/tile_sweep.json" ]; then
    echo "--- sweep digest (flagship Pallas-vs-XLA verdict)"
    python scripts/sweep_digest.py "$ART/tile_sweep.json" --json "$ART/sweep_digest.json" || true
  fi
  commit_artifacts
  echo "--- bench.py (north star)"
  timeout 900 env BENCH_JSON_OUT="$ART/bench_tpu.json" python -u bench.py 2>&1 | grep -v WARNING
  commit_artifacts
  echo "--- BSI north star on chip (10M rows to bound build time)"
  timeout 1800 python -u -m benchmarks.bsi 10000000 2>&1 | grep -v WARNING | tee "$ART/bsi_northstar.jsonl"
  commit_artifacts
  echo "--- filtered-ANN (BASELINE config 5: 1M docs, incl. steady-state block)"
  # tee the per-measurement stdout lines: --json only flushes at the END of
  # the whole suite, so a timeout kill would leave no artifact at all
  # (code-review r5)
  timeout 900 python -u -m benchmarks.run filtered_ann --reps 3 2>&1 | grep -v WARNING | tee "$ART/filtered_ann.jsonl"
  echo "=== chip suite done: $(date -u +%FT%TZ)"
} >> "$LOG" 2>&1
cp -f "$LOG" CHIP_SUITE.log 2>/dev/null || true
commit_artifacts
