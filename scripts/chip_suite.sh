#!/bin/bash
# Everything that needs the real chip, in one run — executed automatically
# by scripts/tunnel_watch.sh when the axon tunnel comes back.
set -u
cd /root/repo
export PYTHONPATH=/root/repo:${PYTHONPATH:-}
LOG=${1:-/tmp/chip_suite.log}
{
  echo "=== chip suite start: $(date -u +%FT%TZ)"
  echo "--- kernel check (wide/grouped/oneil pallas on chip)"
  timeout 900 python -u scripts/tpu_kernel_check.py 2>&1 | grep -v WARNING
  echo "--- tile sweep (honest fetch-forced timing)"
  timeout 900 python -u scripts/tile_sweep.py 2>&1 | grep -v WARNING
  echo "--- bench.py (north star)"
  timeout 900 python -u bench.py 2>&1 | grep -v WARNING
  echo "--- BSI north star on chip (10M rows to bound build time)"
  timeout 1800 python -u -m benchmarks.bsi 10000000 2>&1 | grep -v WARNING
  echo "=== chip suite done: $(date -u +%FT%TZ)"
} >> "$LOG" 2>&1
