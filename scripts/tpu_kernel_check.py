"""Validate the Pallas kernels lower and run correctly on the real chip.

Run on the default (axon/TPU) backend:  timeout 600 python scripts/tpu_kernel_check.py
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import device as dev
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    print("backend:", jax.default_backend(), jax.devices())
    rng = np.random.default_rng(0)

    # wide: N=10_000 rows
    host = rng.integers(0, 1 << 32, size=(10_000, 2048), dtype=np.uint64).astype(np.uint32)
    arr = jnp.asarray(host)
    t0 = time.time()
    red, card = pk.wide_reduce_cardinality_pallas(arr, op="or")
    jax.block_until_ready((red, card))
    print(f"wide pallas compile+run: {time.time()-t0:.1f}s")
    want = np.bitwise_or.reduce(host, axis=0)
    assert np.array_equal(np.asarray(red), want), "wide mismatch"
    assert int(card) == int(np.unpackbits(want.view(np.uint8)).sum())
    print("wide pallas: OK")

    # grouped: G=66 (the round-2 crash shape class), M=151
    g, m = 66, 151
    host3 = rng.integers(0, 1 << 32, size=(g, m, 2048), dtype=np.uint64).astype(np.uint32)
    arr3 = jnp.asarray(host3)
    t0 = time.time()
    red3, cards = pk.grouped_reduce_cardinality_pallas(arr3, op="or")
    jax.block_until_ready((red3, cards))
    print(f"grouped pallas compile+run: {time.time()-t0:.1f}s")
    want3 = np.bitwise_or.reduce(host3, axis=1)
    assert np.array_equal(np.asarray(red3), want3), "grouped mismatch"
    want_cards = [int(np.unpackbits(want3[i].view(np.uint8)).sum()) for i in range(g)]
    assert np.asarray(cards).tolist() == want_cards
    print("grouped pallas: OK")

    # all three ops, both kernels, via the probing dispatchers
    for op, fold in [("or", np.bitwise_or), ("and", np.bitwise_and), ("xor", np.bitwise_xor)]:
        r, c = pk.best_wide_reduce(arr, op=op)
        jax.block_until_ready((r, c))
        assert np.array_equal(np.asarray(r), fold.reduce(host, axis=0)), op
        r3, c3 = pk.best_grouped_reduce(arr3, op=op)
        jax.block_until_ready((r3, c3))
        assert np.array_equal(np.asarray(r3), fold.reduce(host3, axis=1)), op
    print("dispatchers: OK")

    # fused O'Neil compare (the BSI north-star kernel), incl. dual RANGE
    from roaringbitmap_tpu.models.bsi import o_neil_math

    s, k = 32, 66
    slices = rng.integers(0, 1 << 32, size=(s, k, 2048), dtype=np.uint64).astype(np.uint32)
    ebm = np.bitwise_or.reduce(slices, axis=0)
    fixed = rng.integers(0, 1 << 32, size=(k, 2048), dtype=np.uint64).astype(np.uint32)
    predicate, hi_pred = 0xA5A5A5A5 & ((1 << s) - 1), 0xC3C3C3C3 & ((1 << s) - 1)
    bits = np.array([(predicate >> i) & 1 for i in range(s - 1, -1, -1)], dtype=bool)
    bits_hi = np.array([(hi_pred >> i) & 1 for i in range(s - 1, -1, -1)], dtype=bool)
    for op, b in [("GE", bits), ("EQ", bits), ("RANGE", np.stack([bits, bits_hi]))]:
        t0 = time.time()
        got_out, got_cards = pk.oneil_compare_pallas(
            jnp.asarray(slices), jnp.asarray(b), jnp.asarray(ebm), jnp.asarray(fixed), op=op
        )
        got_out, got_cards = np.asarray(got_out), np.asarray(got_cards)
        print(f"oneil pallas {op}: {time.time()-t0:.1f}s (compile+run)")
        want_out, want_cards = o_neil_math(
            jnp.asarray(slices), jnp.asarray(b), jnp.asarray(ebm), jnp.asarray(fixed), op
        )
        assert np.array_equal(got_out, np.asarray(want_out)), f"oneil {op} mismatch"
        assert np.array_equal(got_cards, np.asarray(want_cards)), f"oneil {op} cards"
    print("oneil pallas: OK")

    # one-pass segmented scan (the skewed-layout kernel)
    n = 5_000
    rows = rng.integers(0, 1 << 32, size=(n, 2048), dtype=np.uint64).astype(np.uint32)
    offs = np.unique(np.concatenate([[0], rng.integers(1, n, size=60)]))
    seg = np.zeros(n, dtype=bool)
    seg[offs] = True
    t0 = time.time()
    vals = np.asarray(pk.segmented_reduce_pallas(jnp.asarray(rows), jnp.asarray(seg), op="or"))
    print(f"segmented pallas compile+run: {time.time()-t0:.1f}s")
    bounds = np.append(offs, n)
    for s_i, e_i in zip(bounds[:-1], bounds[1:]):
        want = np.bitwise_or.reduce(rows[s_i:e_i], axis=0)
        assert np.array_equal(vals[e_i - 1], want), ("segmented", s_i, e_i)
    print("segmented pallas: OK")

    # large-N segmented: exercises the bit-packed whole-array SMEM flags
    # (n/8 bytes resident) well past the old unpacked layout's comfort zone
    n = 200_000
    rows = rng.integers(0, 1 << 32, size=(n, 2048), dtype=np.uint32)
    offs = np.unique(np.concatenate([[0], rng.integers(1, n, size=500)]))
    seg = np.zeros(n, dtype=bool)
    seg[offs] = True
    t0 = time.time()
    vals = np.asarray(pk.segmented_reduce_pallas(jnp.asarray(rows), jnp.asarray(seg), op="or"))
    print(f"segmented pallas large-N ({n} rows) compile+run: {time.time()-t0:.1f}s")
    bounds = np.append(offs, n)
    ends = bounds[1:] - 1
    want_ends = np.stack(
        [np.bitwise_or.reduce(rows[s_i:e_i], axis=0) for s_i, e_i in zip(bounds[:-1], bounds[1:])]
    )
    assert np.array_equal(vals[ends], want_ends), "segmented large-N mismatch"
    print("segmented pallas large-N: OK")

    # pairwise overlap matrix: the MXU bit-matmul vs the VPU broadcast
    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.parallel import batch

    srng = np.random.default_rng(7)
    sets = [
        RoaringBitmap(np.unique(srng.integers(0, 1 << 22, 5000)).astype(np.uint32))
        for _ in range(128)
    ]
    L, R = sets[:64], sets[64:]
    t0 = time.time()
    mx = batch.pairwise_and_cardinality(L, R, impl="mxu")
    print(f"pairwise MXU 64x64 compile+run: {time.time()-t0:.1f}s")
    t0 = time.time()
    mx2 = batch.pairwise_and_cardinality(L, R, impl="mxu")
    t_mxu = time.time() - t0
    vp = batch.pairwise_and_cardinality(L, R, impl="vpu")
    assert mx.tolist() == vp.tolist() == mx2.tolist(), "pairwise matrix mismatch"
    print(f"pairwise matrix MXU==VPU: OK (mxu steady {t_mxu*1e3:.0f} ms per dispatch)")
    print("dispatch counts:", dict(pk.DISPATCH_COUNTS))


if __name__ == "__main__":
    main()
