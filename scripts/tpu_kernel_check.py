"""Validate every Pallas/MXU kernel family on the real chip, with a
machine-readable record per family.

Run on the default (axon/TPU) backend:
    timeout 900 python scripts/tpu_kernel_check.py --json chip_artifacts/<ts>/kernel_check.json

Each family runs under try/except so one failure cannot hide the others'
results (the round-2 lesson: a single bad lowering took the whole bench
down). The JSON artifact is the repo-committed evidence that the kernels
executed on hardware (VERDICT r3 #1/#4).
"""

import argparse
import json
import os
import sys
import time
import traceback

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RECORDS = []
JSON_OUT = None  # set by main(); each completed family flushes the artifact


def _flush_json(partial: bool) -> None:
    """Write the artifact after every family: a timeout or tunnel death
    mid-suite must not erase the families that DID run (the JSON is the
    committed hardware evidence, so partial > nothing)."""
    if not JSON_OUT:
        return
    import jax

    result = {
        "generated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "backend": jax.default_backend(),
        "devices": [str(d) for d in jax.devices()],
        "jax_version": jax.__version__,
        "partial": partial,
        "ok": all(r["ok"] for r in RECORDS) and bool(RECORDS),
        "families": RECORDS,
    }
    try:
        from roaringbitmap_tpu.ops import pallas_kernels as pk
    except ImportError:
        pass
    else:
        result["dispatch_counts"] = {f"{k[0]}/{k[1]}": v for k, v in pk.DISPATCH_COUNTS.items()}
    os.makedirs(os.path.dirname(JSON_OUT) or ".", exist_ok=True)
    tmp = JSON_OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=1)
    os.replace(tmp, JSON_OUT)


def family(name):
    """Decorator: run the check, record {family, ok, seconds, detail|error}."""

    def deco(fn):
        def run():
            t0 = time.time()
            try:
                detail = fn() or {}
                rec = {"family": name, "ok": True, "seconds": round(time.time() - t0, 1), **detail}
            except Exception as e:
                rec = {
                    "family": name,
                    "ok": False,
                    "seconds": round(time.time() - t0, 1),
                    "error": repr(e)[:500],
                    "traceback": traceback.format_exc()[-1500:],
                }
            RECORDS.append(rec)
            try:
                _flush_json(partial=True)
            except Exception as e:  # flush must never kill the suite it protects
                print(f"partial flush failed: {e!r}", flush=True)
            print(f"{name}: {'OK' if rec['ok'] else 'FAIL ' + rec.get('error', '')}", flush=True)
            return rec

        return run

    return deco


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", help="write machine-readable results to this path")
    args = ap.parse_args()
    global JSON_OUT
    JSON_OUT = args.json

    import jax
    import jax.numpy as jnp

    from roaringbitmap_tpu.ops import device as dev  # noqa: F401
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    backend = jax.default_backend()
    devices = [str(d) for d in jax.devices()]
    print("backend:", backend, devices, flush=True)
    rng = np.random.default_rng(0)

    @family("wide_pallas")
    def check_wide():
        host = rng.integers(0, 1 << 32, size=(10_000, 2048), dtype=np.uint64).astype(np.uint32)
        arr = jnp.asarray(host)
        t0 = time.time()
        red, card = pk.wide_reduce_cardinality_pallas(arr, op="or")
        jax.block_until_ready((red, card))
        compile_s = time.time() - t0
        want = np.bitwise_or.reduce(host, axis=0)
        assert np.array_equal(np.asarray(red), want), "wide mismatch"
        assert int(card) == int(np.unpackbits(want.view(np.uint8)).sum())
        return {"compile_s": round(compile_s, 1), "shape": [10_000, 2048]}

    @family("wide_pallas_variants")
    def check_wide_variants():
        # the sweep-staged w-split / linear-fold / dimsem variants must also
        # lower and run correctly on the real chip, not just in interpret mode
        host = rng.integers(0, 1 << 32, size=(2048, 2048), dtype=np.uint64).astype(np.uint32)
        arr = jnp.asarray(host)
        want = np.bitwise_or.reduce(host, axis=0)
        variants = [
            {"w_tile": 512},
            {"fold": "linear"},
            {"w_tile": 1024, "fold": "linear", "dimsem": True},
        ]
        ok = []
        for kw in variants:
            red, _ = pk.wide_reduce_cardinality_pallas(arr, op="or", **kw)
            assert np.array_equal(np.asarray(red), want), f"wide variant {kw} mismatch"
            ok.append(kw)
        return {"variants": ok}

    @family("grouped_pallas")
    def check_grouped():
        g, m = 66, 151  # the round-2 crash shape class
        host3 = rng.integers(0, 1 << 32, size=(g, m, 2048), dtype=np.uint64).astype(np.uint32)
        arr3 = jnp.asarray(host3)
        t0 = time.time()
        red3, cards = pk.grouped_reduce_cardinality_pallas(arr3, op="or")
        jax.block_until_ready((red3, cards))
        compile_s = time.time() - t0
        want3 = np.bitwise_or.reduce(host3, axis=1)
        assert np.array_equal(np.asarray(red3), want3), "grouped mismatch"
        want_cards = [int(np.unpackbits(want3[i].view(np.uint8)).sum()) for i in range(g)]
        assert np.asarray(cards).tolist() == want_cards
        return {"compile_s": round(compile_s, 1), "shape": [g, m, 2048]}

    @family("grouped_pallas_variants")
    def check_grouped_variants():
        g, m = 66, 151
        host3 = rng.integers(0, 1 << 32, size=(g, m, 2048), dtype=np.uint64).astype(np.uint32)
        arr3 = jnp.asarray(host3)
        want3 = np.bitwise_or.reduce(host3, axis=1)
        variants = [
            {"fold": "linear"},
            {"w_tile": 512},
            {"w_tile": 512, "fold": "linear", "dimsem": True},
        ]
        ok = []
        for kw in variants:
            red3, _ = pk.grouped_reduce_cardinality_pallas(arr3, op="or", **kw)
            assert np.array_equal(np.asarray(red3), want3), f"grouped variant {kw} mismatch"
            ok.append(kw)
        return {"variants": ok}

    @family("dispatchers")
    def check_dispatchers():
        host = rng.integers(0, 1 << 32, size=(10_000, 2048), dtype=np.uint64).astype(np.uint32)
        arr = jnp.asarray(host)
        host3 = rng.integers(0, 1 << 32, size=(66, 151, 2048), dtype=np.uint64).astype(np.uint32)
        arr3 = jnp.asarray(host3)
        for op, fold in [("or", np.bitwise_or), ("and", np.bitwise_and), ("xor", np.bitwise_xor)]:
            r, c = pk.best_wide_reduce(arr, op=op)
            jax.block_until_ready((r, c))
            assert np.array_equal(np.asarray(r), fold.reduce(host, axis=0)), op
            r3, c3 = pk.best_grouped_reduce(arr3, op=op)
            jax.block_until_ready((r3, c3))
            assert np.array_equal(np.asarray(r3), fold.reduce(host3, axis=1)), op
        return {"ops": ["or", "and", "xor"]}

    @family("oneil_pallas")
    def check_oneil():
        from roaringbitmap_tpu.models.bsi import o_neil_math

        s, k = 32, 66
        slices = rng.integers(0, 1 << 32, size=(s, k, 2048), dtype=np.uint64).astype(np.uint32)
        ebm = np.bitwise_or.reduce(slices, axis=0)
        fixed = rng.integers(0, 1 << 32, size=(k, 2048), dtype=np.uint64).astype(np.uint32)
        predicate, hi_pred = 0xA5A5A5A5 & ((1 << s) - 1), 0xC3C3C3C3 & ((1 << s) - 1)
        bits = np.array([(predicate >> i) & 1 for i in range(s - 1, -1, -1)], dtype=bool)
        bits_hi = np.array([(hi_pred >> i) & 1 for i in range(s - 1, -1, -1)], dtype=bool)
        times = {}
        for op, b in [("GE", bits), ("EQ", bits), ("RANGE", np.stack([bits, bits_hi]))]:
            t0 = time.time()
            got_out, got_cards = pk.oneil_compare_pallas(
                jnp.asarray(slices), jnp.asarray(b), jnp.asarray(ebm), jnp.asarray(fixed), op=op
            )
            got_out, got_cards = np.asarray(got_out), np.asarray(got_cards)
            times[op] = round(time.time() - t0, 1)
            want_out, want_cards = o_neil_math(
                jnp.asarray(slices), jnp.asarray(b), jnp.asarray(ebm), jnp.asarray(fixed), op
            )
            assert np.array_equal(got_out, np.asarray(want_out)), f"oneil {op} mismatch"
            assert np.array_equal(got_cards, np.asarray(want_cards)), f"oneil {op} cards"
        return {"compile_s_per_op": times, "shape": [s, k, 2048]}

    @family("oneil_batched")
    def check_oneil_batched():
        # the vmapped multi-predicate walk (bsi._o_neil_counts_batched) on
        # real hardware: [Q] thresholds in one dispatch vs the CPU engine
        from roaringbitmap_tpu.models.bsi import (
            Operation,
            RoaringBitmapSliceIndex,
        )

        cols = np.sort(rng.choice(4_000_000, size=300_000, replace=False)).astype(
            np.uint32
        )
        vals = rng.integers(0, 1 << 24, size=cols.size)
        bsi = RoaringBitmapSliceIndex()
        bsi.set_values((cols, vals))
        qs = np.quantile(vals, np.linspace(0.05, 0.95, 8)).astype(np.int64)
        times = {}
        for op in (Operation.GE, Operation.NEQ):
            t0 = time.time()
            got = bsi.compare_cardinality_many(op, qs, mode="device")
            times[op.value] = round(time.time() - t0, 1)
            want = [
                bsi.compare_cardinality(op, int(v), 0, None, mode="cpu") for v in qs
            ]
            assert got.tolist() == want, f"batched {op} mismatch"
        got = bsi.compare_cardinality_many(
            Operation.RANGE, qs, ends=qs + 100_000, mode="device"
        )
        want = [
            bsi.compare_cardinality(Operation.RANGE, int(v), int(v) + 100_000, None, "cpu")
            for v in qs
        ]
        assert got.tolist() == want, "batched RANGE mismatch"
        return {"rows": int(cols.size), "batch": int(qs.size), "seconds_per_op": times}

    @family("segmented_pallas")
    def check_segmented():
        n = 5_000
        rows = rng.integers(0, 1 << 32, size=(n, 2048), dtype=np.uint64).astype(np.uint32)
        offs = np.unique(np.concatenate([[0], rng.integers(1, n, size=60)]))
        seg = np.zeros(n, dtype=bool)
        seg[offs] = True
        vals = np.asarray(pk.segmented_reduce_pallas(jnp.asarray(rows), jnp.asarray(seg), op="or"))
        bounds = np.append(offs, n)
        for s_i, e_i in zip(bounds[:-1], bounds[1:]):
            want = np.bitwise_or.reduce(rows[s_i:e_i], axis=0)
            assert np.array_equal(vals[e_i - 1], want), ("segmented", s_i, e_i)
        return {"shape": [n, 2048], "segments": len(offs)}

    @family("segmented_pallas_large_n")
    def check_segmented_large():
        # exercises the bit-packed whole-array SMEM flags (n/8 bytes resident)
        n = 200_000
        rows = rng.integers(0, 1 << 32, size=(n, 2048), dtype=np.uint32)
        offs = np.unique(np.concatenate([[0], rng.integers(1, n, size=500)]))
        seg = np.zeros(n, dtype=bool)
        seg[offs] = True
        vals = np.asarray(pk.segmented_reduce_pallas(jnp.asarray(rows), jnp.asarray(seg), op="or"))
        bounds = np.append(offs, n)
        ends = bounds[1:] - 1
        want_ends = np.stack(
            [np.bitwise_or.reduce(rows[s_i:e_i], axis=0) for s_i, e_i in zip(bounds[:-1], bounds[1:])]
        )
        assert np.array_equal(vals[ends], want_ends), "segmented large-N mismatch"
        return {"shape": [n, 2048], "segments": len(offs)}

    @family("mxu_pairwise")
    def check_mxu():
        # the MXU bit-matmul overlap engine vs the VPU broadcast engine
        # (VERDICT r3 #4: the one kernel family with zero hardware evidence)
        from roaringbitmap_tpu import RoaringBitmap
        from roaringbitmap_tpu.parallel import batch

        srng = np.random.default_rng(7)
        sets = [
            RoaringBitmap(np.unique(srng.integers(0, 1 << 22, 5000)).astype(np.uint32))
            for _ in range(128)
        ]
        L, R = sets[:64], sets[64:]
        t0 = time.time()
        mx = batch.pairwise_and_cardinality(L, R, impl="mxu")
        compile_s = time.time() - t0
        t0 = time.time()
        mx2 = batch.pairwise_and_cardinality(L, R, impl="mxu")
        t_mxu = time.time() - t0
        vp = batch.pairwise_and_cardinality(L, R, impl="vpu")
        # exactness: int32 accumulation over <= 2^22-bit universes is exact on
        # the MXU path (guarded in batch.py); any drift is a real bug
        assert mx.tolist() == vp.tolist() == mx2.tolist(), "pairwise matrix mismatch"
        jac = batch.pairwise_jaccard(L, R)
        assert np.all((np.asarray(jac) >= 0) & (np.asarray(jac) <= 1)), "jaccard out of range"
        return {
            "matrix": [64, 64],
            "compile_s": round(compile_s, 1),
            "mxu_dispatch_ms": round(t_mxu * 1e3, 1),
        }

    for run in (
        check_wide,
        check_wide_variants,
        check_grouped,
        check_grouped_variants,
        check_dispatchers,
        check_oneil,
        check_segmented,
        check_segmented_large,
        check_mxu,
    ):
        run()

    ok = all(r["ok"] for r in RECORDS)
    print("all families ok:" if ok else "FAILURES:", ok, flush=True)
    if args.json:
        _flush_json(partial=False)
        print("wrote", args.json, flush=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
