#!/bin/bash
# Probe the axon tunnel every ~4 minutes; when it answers, run the chip
# suite once and exit. Leaves a heartbeat in /tmp/tunnel_watch.log.
# chip_suite.sh commits its chip_artifacts/<stamp>/ directory itself (in
# stages, so a tunnel that dies mid-suite still leaves the completed
# artifacts in git — VERDICT r3 #1).
set -u
cd /root/repo
export PYTHONPATH=/root/repo:${PYTHONPATH:-}
for i in $(seq 1 200); do
  if timeout 60 python -c "import jax; assert jax.default_backend() != 'cpu', 'cpu fallback is not the tunnel'" > /dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) tunnel UP (probe $i) — running chip suite" >> /tmp/tunnel_watch.log
    bash scripts/chip_suite.sh
    echo "$(date -u +%FT%TZ) chip suite finished" >> /tmp/tunnel_watch.log
    exit 0
  fi
  echo "$(date -u +%FT%TZ) tunnel down (probe $i)" >> /tmp/tunnel_watch.log
  sleep 240
done
echo "$(date -u +%FT%TZ) gave up after 200 probes" >> /tmp/tunnel_watch.log
exit 1
