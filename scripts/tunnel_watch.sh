#!/bin/bash
# Probe the axon tunnel every ~4 minutes; when it answers AND the chip
# suite has not yet run at the current HEAD, run it (again). Keeps
# watching after a successful run so later commits still get chip
# coverage within the probe budget. Heartbeat in /tmp/tunnel_watch.log.
# chip_suite.sh commits its chip_artifacts/<stamp>/ directory itself (in
# stages, so a tunnel that dies mid-suite still leaves the completed
# artifacts in git — VERDICT r3 #1).
set -u
cd /root/repo
export PYTHONPATH=/root/repo:${PYTHONPATH:-}
LAST_RUN_HEAD=""
for i in $(seq 1 220); do
  if timeout 60 python -c "import jax; assert jax.default_backend() != 'cpu', 'cpu fallback is not the tunnel'" > /dev/null 2>&1; then
    HEAD=$(git rev-parse HEAD)
    if [ "$HEAD" != "$LAST_RUN_HEAD" ]; then
      echo "$(date -u +%FT%TZ) tunnel UP (probe $i) — running chip suite at $HEAD" >> /tmp/tunnel_watch.log
      if bash scripts/chip_suite.sh; then
        # chip_suite.sh commits its own artifacts, advancing HEAD; record
        # the post-run HEAD or every probe would see "new" commits and
        # re-run the multi-hour suite forever. Only on success — a
        # mid-suite death must leave this HEAD eligible for a retry
        # (code-review r5)
        LAST_RUN_HEAD=$(git rev-parse HEAD)
        echo "$(date -u +%FT%TZ) chip suite finished" >> /tmp/tunnel_watch.log
      else
        echo "$(date -u +%FT%TZ) chip suite FAILED (will retry this HEAD)" >> /tmp/tunnel_watch.log
      fi
    else
      echo "$(date -u +%FT%TZ) tunnel up, suite already ran at $HEAD (probe $i)" >> /tmp/tunnel_watch.log
    fi
  else
    echo "$(date -u +%FT%TZ) tunnel down (probe $i)" >> /tmp/tunnel_watch.log
  fi
  sleep 240
done
echo "$(date -u +%FT%TZ) probe budget exhausted" >> /tmp/tunnel_watch.log
exit 0
