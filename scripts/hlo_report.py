"""Collective-layout evidence for the multi-chip path (VERDICT r3 weak #7).

"The sharded ops are ICI-efficient" was a design claim with no artifact
behind it: the dryrun proves the ops compile and agree with host oracles,
but nothing in the repo showed WHERE XLA placed the collectives. This
script compiles every distributed op family on the 8-device virtual CPU
mesh (4 containers x 2 words — make_mesh(8)'s default split, the same
shape the driver dryrun uses), extracts the optimized HLO, and records the collective instructions
per family: op kind, count, and replica groups.

What the design predicts (parallel/sharding.py):
  * wide/grouped reduce: one all-gather on the containers axis (the OR
    tree has no psum primitive) + one all-reduce (psum) of popcounts on
    the words axis; no all-to-all, no collective-permute anywhere;
  * BSI compare/sum: zero container-axis collectives (chunks are
    independent) + one words-axis all-reduce for the cardinalities.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/hlo_report.py --json MULTICHIP_HLO_r04.json
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", help="write the report to this path")
    args = ap.parse_args()

    import jax

    # force CPU BEFORE any device query: this report is virtual-mesh-only
    # by design, and with a hung TPU tunnel even jax.default_backend()
    # blocks forever (env vars are too late once the axon site hook
    # pre-imports jax — the benchmarks/bsi.py __main__ pattern)
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    if jax.device_count() < 8:
        raise SystemExit(
            "need 8 virtual devices: run with "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    import jax.numpy as jnp

    from roaringbitmap_tpu.parallel import sharding
    from roaringbitmap_tpu.parallel.sharding import collective_details

    mesh = sharding.make_mesh(8)
    w = 8 * 128  # tiny words axis, divisible by the 2-way words mesh dim
    rng = np.random.default_rng(0)
    families = {}

    def record(name, jitted, *arg_arrays, expect=None):
        lowered = jitted.lower(*arg_arrays)
        hlo = lowered.compile().as_text()
        cols = collective_details(hlo)
        counts = {}
        for c in cols:
            counts[c["op"]] = counts.get(c["op"], 0) + 1
        families[name] = {
            "collectives": cols,
            "counts": counts,
            "hlo_instructions": hlo.count("\n"),
        }
        print(f"{name:<28} {counts or 'NO COLLECTIVES'}")
        if expect is not None:
            missing = {k: v for k, v in expect.items() if counts.get(k, 0) != v}
            forbidden = {
                k for k in ("all-to-all", "collective-permute") if counts.get(k)
            }
            families[name]["expected"] = expect
            families[name]["ok"] = not missing and not forbidden
            if missing or forbidden:
                print(f"  MISMATCH: missing={missing} forbidden={forbidden}")
        return cols

    rows = jnp.asarray(rng.integers(0, 1 << 32, (16, w), dtype=np.uint64).astype(np.uint32))
    record(
        "wide_or_cardinality",
        sharding.distributed_wide_or_cardinality(mesh),
        rows,
        expect={"all-gather": 1, "all-reduce": 1},
    )
    g3 = jnp.asarray(rng.integers(0, 1 << 32, (4, 16, w), dtype=np.uint64).astype(np.uint32))
    for op in ("or", "and", "xor"):
        record(
            f"grouped_{op}",
            sharding.distributed_grouped_reduce(mesh, op),
            g3,
            expect={"all-gather": 1, "all-reduce": 1},
        )
    s, k = 8, 16
    slices = jnp.asarray(rng.integers(0, 1 << 32, (s, k, w), dtype=np.uint64).astype(np.uint32))
    ebm = jnp.asarray(np.bitwise_or.reduce(np.asarray(slices), axis=0))
    fixed = jnp.ones_like(ebm)
    bits = jnp.asarray(np.ones(s, dtype=bool))
    bits2 = jnp.asarray(np.stack([np.ones(s, dtype=bool)] * 2))
    record(
        "bsi_compare_GE",
        sharding.distributed_bsi_compare(mesh, "GE"),
        slices, bits, ebm, fixed,
        expect={"all-reduce": 1},
    )
    record(
        "bsi_compare_RANGE",
        sharding.distributed_bsi_compare(mesh, "RANGE"),
        slices, bits2, ebm, fixed,
        expect={"all-reduce": 1},
    )
    record(
        "bsi_sum",
        sharding.distributed_bsi_sum(mesh),
        slices, fixed,
        expect={"all-reduce": 1},
    )
    bits_q = jnp.asarray(np.stack([np.ones(s, dtype=bool)] * 5))
    record(
        "bsi_counts_many_GE",
        sharding.distributed_bsi_counts_many(mesh, "GE"),
        slices, bits_q, ebm, fixed,
        expect={"all-reduce": 1},
    )

    ok = all(f.get("ok", True) for f in families.values())
    report = {
        "generated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "backend": jax.default_backend(),
        "n_devices": jax.device_count(),
        "mesh": {"containers": int(mesh.shape["containers"]), "words": int(mesh.shape["words"])},
        "jax_version": jax.__version__,
        "note": (
            "virtual CPU mesh (no TPU in this environment); the collective "
            "placement shown is what XLA compiles for this mesh shape — on "
            "real hardware the same program rides ICI. all-to-all and "
            "collective-permute are forbidden by design in every family."
        ),
        "ok": ok,
        "families": families,
    }
    print("all families match design:", ok)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
        print("wrote", args.json)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
