#!/usr/bin/env python
"""Bench trend regression detector (ISSUE 6 satellite).

Reads the committed ``BENCH_r0*.json`` series and does two jobs:

1. **Prints the headline trajectory with its provenance.** ``vs_baseline``
   is a *ratio* whose denominator (``cpu_fold_s``) rides every CPU-side
   win and every dataset change — the 12.96 → 8.02 → 3.59 slide across
   r05→r07 is mostly the denominator improving (columnar fold) and the
   corpus changing (real census1881 73k containers → synthetic 308k), not
   the device path regressing. The report prints, per round, the ratio
   NEXT TO its denominator, dataset, container count, and backend so the
   number can never slide silently again (ROADMAP re-anchor note).

2. **Gates the newest round.** Each gated row of the latest artifact is
   compared against the best prior round measured on the same
   ``(backend, dataset, n_bitmaps)`` triple — cross-machine/corpus
   comparisons are meaningless, so rounds from other triples are ignored.
   A lower-is-better row more than 15 % slower than the best prior (or
   the throughput ``value`` more than 15 % below the best prior) is a
   regression; ``--check`` exits 1 unless it is acknowledged in
   ``TREND_BASELINE.json`` (the ANALYSIS_BASELINE discipline: a known
   regression is recorded with a reason, not silenced). Regenerate the
   baseline with ``--update-baseline`` after editing the reasons.

   **Variance-aware gating (ISSUE 11 satellite):** ms-scale rows
   (``delta_repack_s``/``pack_warm_s``) oscillate around the fixed 15 %
   gate across same-code runs — two rounds running needed
   TREND_BASELINE acknowledgements for pure host noise. bench.py now
   measures those rows min-of-k and records the observed rep spread in
   ``meta.host_noise`` ({row: {reps, min, max, spread_pct}}); the gate
   for a row widens to ``max(15 %, measured spread)`` using the larger
   of the latest round's and the comparison rounds' recorded bands — a
   row is only a regression when it moved more than the host itself
   moves on identical code. Rows without a recorded band keep the fixed
   15 % gate; each flagged regression reports the threshold it tripped
   (``threshold_pct``).

Artifact shapes: rounds 1-5 are driver captures (``{tail, parsed}`` with
the meta JSON embedded in the stderr tail); rounds 6+ are bench.py's own
``{result, meta}`` files. Both normalize here.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE_PATH = os.path.join(REPO, "TREND_BASELINE.json")
THRESHOLD = 0.15

# lower-is-better wall-clock rows; gated when present in latest AND a
# comparable prior round
GATED_LOWER = (
    "cpu_fold_s",
    "pack_s",
    "bucket_build_s",
    "tpu_reduce_s",
    "pack_warm_s",
    "delta_repack_s",
)
# higher-is-better rows
GATED_HIGHER = ("value",)


def _round_of(path: str) -> Optional[int]:
    m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else None


def _meta_from_tail(tail: str) -> dict:
    """Rounds 1-5: bench.py printed meta as a JSON line on stderr; the
    driver capture interleaves it with warnings. Take the last line that
    parses as an object carrying a 'dataset' key."""
    meta = {}
    for line in tail.splitlines():
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and "dataset" in obj:
            meta = obj
    return meta


def load_round(path: str) -> Optional[dict]:
    with open(path) as f:
        data = json.load(f)
    rnd = _round_of(path)
    if "meta" in data and "result" in data:  # r06+ shape
        meta, result = data["meta"], data["result"]
    elif "parsed" in data:  # r01-r05 driver capture
        # a failed capture (rc != 0) has parsed=None; keep whatever meta
        # made it into the tail so the trajectory still shows the round
        meta, result = _meta_from_tail(data.get("tail", "")), data["parsed"] or {}
    else:
        return None
    rows: Dict[str, float] = {}
    for k in GATED_LOWER:
        v = meta.get(k)
        if isinstance(v, (int, float)) and v > 0:
            rows[k] = float(v)
    v = result.get("value")
    if isinstance(v, (int, float)) and v > 0:
        rows["value"] = float(v)
    noise = meta.get("host_noise")
    host = meta.get("host")
    return {
        "round": rnd,
        "path": os.path.basename(path),
        "backend": meta.get("backend", "?"),
        "dataset": meta.get("dataset", "?"),
        "n_bitmaps": meta.get("n_bitmaps"),
        "n_containers": meta.get("n_containers"),
        "vs_baseline": result.get("vs_baseline"),
        "denominator_s": meta.get("cpu_fold_s"),
        "baseline_block": meta.get("baseline"),
        "rows": rows,
        # recorded per-row host-noise bands (ISSUE 11 satellite): absent
        # in pre-r13 artifacts, which keep the fixed 15% gate
        "host_noise": noise if isinstance(noise, dict) else {},
        # host provenance (ISSUE 14 satellite): cpu_count / device kind
        # recorded per round so the ROADMAP debt-(a) multi-core/TPU
        # re-measure campaign compares like-for-like — absent in pre-r16
        # artifacts, which stay comparable to everything on their triple
        "host": host if isinstance(host, dict) else None,
    }


def load_series(root: str = REPO) -> List[dict]:
    rounds = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        if _round_of(path) is None:
            continue
        r = load_round(path)
        if r is not None:
            rounds.append(r)
    rounds.sort(key=lambda r: r["round"])
    return rounds


def _triple(r: dict):
    return (r["backend"], r["dataset"], r["n_bitmaps"])


def _host_key(r: dict):
    """The comparability half of the recorded host provenance: CPU core
    count and accelerator kind (ISSUE 14 satellite). None when the round
    predates meta.host."""
    h = r.get("host")
    if not isinstance(h, dict):
        return None
    return (h.get("cpu_count"), h.get("device_kind"))


def _comparable(a: dict, b: dict) -> bool:
    """Rounds are comparable when their (backend, dataset, n_bitmaps)
    triples match AND, when BOTH rounds record host provenance, their
    host keys match too — a 1-core laptop round must not gate a 96-core
    TPU-host round (or vice versa). Rounds without provenance (pre-r16)
    stay comparable on the triple alone."""
    if _triple(a) != _triple(b):
        return False
    ha, hb = _host_key(a), _host_key(b)
    return ha is None or hb is None or ha == hb


# a recorded band wider than this caps at it: a 10x rep spread means the
# row is unmeasurable on that host, and an unbounded band would turn the
# gate off entirely instead of flagging that
MAX_NOISE_BAND = 1.0


def _noise_band(rounds: List[dict], row: str) -> float:
    """The widest recorded host-noise spread for ``row`` across the
    given rounds, as a fraction (0.0 when none recorded), capped at
    ``MAX_NOISE_BAND``."""
    band = 0.0
    for r in rounds:
        rec = (r.get("host_noise") or {}).get(row)
        if isinstance(rec, dict):
            try:
                band = max(band, float(rec.get("spread_pct", 0.0)) / 100.0)
            except (TypeError, ValueError):
                continue
    return min(band, MAX_NOISE_BAND)


def find_regressions(rounds: List[dict], threshold: float = THRESHOLD) -> List[dict]:
    """Gate the newest round against the best comparable prior round.
    Per-row threshold = ``max(threshold, recorded host-noise spread)``
    over the latest + comparison rounds (variance-aware gating)."""
    if len(rounds) < 2:
        return []
    latest = rounds[-1]
    priors = [r for r in rounds[:-1] if _comparable(r, latest)]
    if not priors:
        return []
    out = []
    for row, cur in sorted(latest["rows"].items()):
        vals = [r["rows"][row] for r in priors if row in r["rows"]]
        if not vals:
            continue
        row_threshold = max(threshold, _noise_band([latest] + priors, row))
        if row in GATED_HIGHER:
            best = max(vals)
            regressed = cur < best / (1 + row_threshold)
            pct = (best / cur - 1) * 100
        else:
            best = min(vals)
            regressed = cur > best * (1 + row_threshold)
            pct = (cur / best - 1) * 100
        if regressed:
            out.append(
                {
                    "round": latest["round"],
                    "row": row,
                    "value": cur,
                    "best_prior": best,
                    "regression_pct": round(pct, 1),
                    "threshold_pct": round(row_threshold * 100, 1),
                }
            )
    return out


def load_baseline(path: str = BASELINE_PATH) -> List[dict]:
    if not os.path.isfile(path):
        return []
    with open(path) as f:
        data = json.load(f)
    return data.get("acknowledged", [])


def _acknowledged(reg: dict, baseline: List[dict]) -> Optional[dict]:
    for b in baseline:
        if b.get("round") == reg["round"] and b.get("row") == reg["row"]:
            return b
    return None


def print_trajectory(rounds: List[dict], out=sys.stdout) -> None:
    print("vs_baseline trajectory (ratio next to its denominator):", file=out)
    print(
        f"  {'round':>5}  {'vs_base':>8}  {'cpu_fold_s':>10}  "
        f"{'containers':>10}  {'backend':>7}  dataset",
        file=out,
    )
    for r in rounds:
        vb = r["vs_baseline"]
        den = r["denominator_s"]
        print(
            f"  r{r['round']:02d}    {vb if vb is not None else '-':>8}  "
            f"{den if den is not None else '-':>10}  "
            f"{r['n_containers'] if r['n_containers'] else '-':>10}  "
            f"{r['backend']:>7}  {r['dataset']}",
            file=out,
        )
    print(
        "  (vs_baseline = cpu_fold_s / tpu_reduce_s — the denominator rides\n"
        "   every CPU win and every dataset change; compare rows only within\n"
        "   one backend+dataset+size triple)",
        file=out,
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on unacknowledged >15%% regressions")
    ap.add_argument("--update-baseline", action="store_true",
                    help="record current regressions in TREND_BASELINE.json")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument("--root", default=REPO)
    args = ap.parse_args(argv)

    rounds = load_series(args.root)
    if not rounds:
        print("no BENCH_r*.json artifacts found", file=sys.stderr)
        return 2
    regressions = find_regressions(rounds)
    baseline = load_baseline(os.path.join(args.root, "TREND_BASELINE.json"))

    if args.update_baseline:
        payload = {
            "_comment": "Acknowledged bench regressions (scripts/bench_trend.py). "
                        "Each entry needs a human reason; delete entries once fixed.",
            "acknowledged": [
                {**r, "reason": (_acknowledged(r, baseline) or {}).get(
                    "reason", "TODO: explain this regression")}
                for r in regressions
            ],
        }
        path = os.path.join(args.root, "TREND_BASELINE.json")
        with open(path, "w") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")
        print(f"wrote {len(regressions)} acknowledged regression(s) to {path}")
        return 0

    fresh = [r for r in regressions if _acknowledged(r, baseline) is None]
    if args.json:
        print(json.dumps(
            {"rounds": rounds, "regressions": regressions, "fresh": fresh},
            indent=1,
        ))
    else:
        print_trajectory(rounds)
        latest = rounds[-1]
        priors = [r for r in rounds[:-1] if _comparable(r, latest)]
        names = (
            ", ".join("r%02d" % r["round"] for r in priors)
            if priors
            else "no comparable prior round"
        )
        print("\ngate: r%02d vs best of %s" % (latest["round"], names))
        for reg in regressions:
            ack = _acknowledged(reg, baseline)
            tag = f"acknowledged: {ack['reason']}" if ack else "NEW"
            print(
                f"  {reg['row']}: {reg['value']} vs best prior "
                f"{reg['best_prior']} (+{reg['regression_pct']}%) [{tag}]"
            )
        if not regressions:
            print("  no gated row regressed >15% vs the best comparable prior")
    if args.check and fresh:
        print(
            f"\nFAIL: {len(fresh)} unacknowledged regression(s) >15% — fix, "
            "or record a reason via --update-baseline + edit TREND_BASELINE.json",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
