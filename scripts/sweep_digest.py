"""Digest a tile_sweep.json artifact into the decision VERDICT r3 #2 asks
for: at each swept shape, the best Pallas config vs the XLA reduce, with
the flagship [66,1450,2048] verdict called out — the input to either
flipping GROUPED_PREFER_XLA (Pallas wins) or writing the why-XLA-wins
post-mortem (it doesn't).

Run:  python scripts/sweep_digest.py chip_artifacts/<stamp>/tile_sweep.json [--json OUT]
"""

import argparse
import json


def digest(sweep: dict) -> dict:
    by_shape: dict = {}
    for rec in sweep.get("records", []):
        if "gbps" not in rec:
            continue
        key = (rec["kind"], tuple(rec["shape"]))
        entry = by_shape.setdefault(key, {"xla": None, "best_pallas": None})
        if rec["config"].startswith("xla") and "2stage" not in rec["config"]:
            entry["xla"] = rec
        elif rec["config"].startswith("pallas"):
            if entry["best_pallas"] is None or rec["gbps"] > entry["best_pallas"]["gbps"]:
                entry["best_pallas"] = rec
        elif rec["config"].startswith("xla 2stage"):
            if entry.get("best_2stage") is None or rec["gbps"] > entry["best_2stage"]["gbps"]:
                entry["best_2stage"] = rec
    rows = []
    for (kind, shape), entry in sorted(by_shape.items()):
        xla, pal = entry["xla"], entry["best_pallas"]
        row = {
            "kind": kind,
            "shape": list(shape),
            "xla_gbps": xla and xla["gbps"],
            "best_pallas_gbps": pal and pal["gbps"],
            "best_pallas_config": pal and pal["config"],
            "best_pallas_params": pal and pal.get("params"),
            "pallas_over_xla": (
                round(pal["gbps"] / xla["gbps"], 3) if pal and xla and xla["gbps"] else None
            ),
        }
        if entry.get("best_2stage"):
            row["best_2stage_gbps"] = entry["best_2stage"]["gbps"]
            row["best_2stage_config"] = entry["best_2stage"]["config"]
            row["best_2stage_params"] = entry["best_2stage"].get("params")
        rows.append(row)
    # largest wide shape = the flagship flat workload the WIDE_DISPATCH
    # knob targets (not whichever shape happens to sort first)
    import math

    wides = [r for r in rows if r["kind"] == "wide"]
    wide = max(wides, key=lambda r: math.prod(r["shape"]), default=None)
    wide_verdict = None
    if wide and wide["xla_gbps"]:
        candidates = {"xla": wide["xla_gbps"]}
        if wide["best_pallas_gbps"]:
            candidates["pallas"] = wide["best_pallas_gbps"]
        if wide.get("best_2stage_gbps"):
            candidates["two_stage"] = wide["best_2stage_gbps"]
        winner = max(candidates, key=candidates.get)
        # near-parity guard (same rule as the flagship verdict): do not
        # recommend an engine switch on a within-noise edge over xla
        if winner != "xla" and candidates[winner] < candidates["xla"] * 1.02:
            winner = "xla"
        cfg = {
            "pallas": wide.get("best_pallas_params") or wide.get("best_pallas_config"),
            "two_stage": wide.get("best_2stage_params") or wide.get("best_2stage_config"),
            "xla": None,
        }[winner]
        wide_verdict = (
            f"wide family winner at {wide['shape']}: {winner} at "
            f"{candidates[winner]} GB/s (candidates: {candidates}"
            + (f"; others within 2% of xla treated as parity" if winner == "xla" and len(candidates) > 1 else "")
            + f") — set WIDE_DISPATCH={winner!r}"
            # always state the full WIDE_CONFIG: the dispatcher validates its
            # keys against the active policy, so stale tiling keys from a
            # previous winner would raise (e.g. pallas keys under 'xla')
            + f" and WIDE_CONFIG={cfg if cfg else {}}"
        )
    flagship = next(
        (r for r in rows if r["kind"] == "grouped" and r["shape"] == [66, 1450, 2048]),
        None,
    )
    verdict = None
    if flagship and flagship["pallas_over_xla"] is not None:
        # decide on the raw GB/s, not the display-rounded ratio: a
        # 0.9996 ratio rounds to 1.0 and must NOT read as a Pallas win
        # (code-review r4)
        if flagship["best_pallas_gbps"] >= flagship["xla_gbps"]:
            verdict = (
                f"PALLAS WINS the flagship shape ({flagship['best_pallas_config']}, "
                f"{flagship['pallas_over_xla']}x XLA): flip GROUPED_PREFER_XLA to "
                f"False AND set GROUPED_PALLAS_CONFIG = "
                f"{flagship['best_pallas_params'] or flagship['best_pallas_config']} "
                "(flipping alone serves the default tiling, not this winner), "
                "citing this artifact"
            )
        else:
            verdict = (
                f"XLA holds the flagship shape ({flagship['pallas_over_xla']}x); "
                "record the per-variant table as the VERDICT r3 #2 post-mortem "
                "evidence and keep GROUPED_PREFER_XLA=True"
            )
    return {
        "generated_from": sweep.get("generated_utc"),
        "backend": sweep.get("backend"),
        "shapes": rows,
        "wide_verdict": wide_verdict,
        "flagship": flagship,
        "flagship_verdict": verdict,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("sweep_json")
    ap.add_argument("--json", help="write the digest here")
    args = ap.parse_args()
    with open(args.sweep_json) as f:
        out = digest(json.load(f))
    for r in out["shapes"]:
        print(
            f"{r['kind']:<8} {str(r['shape']):<18} xla {r['xla_gbps'] or '-':>7} "
            f"best-pallas {r['best_pallas_gbps'] or '-':>7} "
            f"ratio {r['pallas_over_xla'] or '-'}  ({r['best_pallas_config'] or '-'})"
        )
    if out["wide_verdict"]:
        print("\n" + out["wide_verdict"])
    if out["flagship_verdict"]:
        print("\n" + out["flagship_verdict"])
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)
        print("wrote", args.json)


if __name__ == "__main__":
    main()
