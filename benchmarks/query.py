"""Query-engine suite: planned vs naive evaluation on realdata-style
workloads, cache warm vs cold (ISSUE 2 satellite).

The workload is the serving-system shape the tentpole exists for — a
nested boolean expression over many corpus bitmaps,
``(or(A) & or(B)) \\ or(C) | threshold_2(head)`` — evaluated four ways:

* ``queryNaive`` — recursive pairwise set algebra (query.evaluate_naive),
  the reference baseline a caller without a planner pays;
* ``queryPlanned`` — planner + executor, memoization disabled: what the
  rewrites + operand ordering + engine choice buy on their own;
* ``queryPlannedColdCache`` — a fresh result cache every repetition
  (planning + execution + store costs, no reuse);
* ``queryPlannedWarmCache`` — a shared cache warmed before timing: the
  steady-state repeated-query hot path (dict probes + one root clone).
* ``queryPlannedColdPack`` / ``queryPlannedWarmPack`` — device engines with
  the result cache OFF, against the resident pack cache (ISSUE 4) cleared
  every rep vs warm: what pack residency alone buys a repeated query that
  cannot reuse results (e.g. a mutating leaf elsewhere evicted them).

Correctness of the planned result against the naive fold is asserted
before any timing is trusted (the test_benchmarks discipline).
"""

from __future__ import annotations

from typing import List

from roaringbitmap_tpu.parallel import store
from roaringbitmap_tpu.query import Q, ResultCache, evaluate_naive, execute, plan

from . import common
from .common import Result


def _expression(bms):
    third = max(1, len(bms) // 3)
    a = Q.or_(*[Q.leaf(b) for b in bms[:third]])
    b = Q.or_(*[Q.leaf(b) for b in bms[third : 2 * third]])
    c = Q.or_(*[Q.leaf(b) for b in bms[2 * third :]])
    head = [Q.leaf(x) for x in bms[: min(8, len(bms))]]
    return (a & b) - c | Q.threshold(2, *head)


def _suite(dataset: str, reps: int, limit: int) -> List[Result]:
    bms = common.corpus_bitmaps(dataset, limit=limit)
    q = _expression(bms)
    want = evaluate_naive(q)
    got = execute(q, cache=None)
    assert got == want, "planned evaluation diverged from naive algebra"
    out = []
    extra = {"n_bitmaps": len(bms), "steps": len(plan(q).steps)}

    def bench(name, fn):
        ns = common.min_of(reps, fn)
        out.append(Result(name, dataset, ns, "ns/op", dict(extra)))

    bench("queryNaive", lambda: evaluate_naive(q))
    bench("queryPlanned", lambda: execute(q, cache=None))

    def cold():
        execute(q, cache=ResultCache(max_entries=64))

    bench("queryPlannedColdCache", cold)

    warm_cache = ResultCache(max_entries=64)
    execute(q, cache=warm_cache)  # warm outside the timed region
    bench("queryPlannedWarmCache", lambda: execute(q, cache=warm_cache))

    # resident pack cache (ISSUE 4): device engines, result cache OFF —
    # cold pays the host transpose+pack every rep, warm rides HBM
    got_dev = execute(q, cache=None, mode="device")
    assert got_dev == want, "device-engine evaluation diverged from naive algebra"

    def cold_pack():
        store.PACK_CACHE.close()
        execute(q, cache=None, mode="device")

    bench("queryPlannedColdPack", cold_pack)
    execute(q, cache=None, mode="device")  # warm the pack cache
    bench("queryPlannedWarmPack", lambda: execute(q, cache=None, mode="device"))
    return out


def run(reps: int = 5, datasets=None, limit: int = 48, **_) -> List[Result]:
    results = []
    for ds in datasets or common.DEFAULT_DATASETS:
        results.extend(_suite(ds, reps, limit))
    return results
