"""Writer/ingestion suites — twin of jmh writer benchmarks
(jmh/src/jmh/.../writer/: WriteSequential, WriteUnordered,
RoaringBitmapWriterBenchmark wizard configs).

Times bulk construction through each ingest path: naive add loop,
add_many, the writer wizard (array-optimised, run-optimised,
constant-memory), and partially-sorted input.
"""

from __future__ import annotations

from typing import List

import numpy as np

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.models.writer import RoaringBitmapWriter

from . import common
from .common import Result

N = 1_000_000


def run(reps: int = 3, **_) -> List[Result]:
    rng = np.random.default_rng(0xFEEF1F0)
    sequential = np.arange(N, dtype=np.uint32) * 7
    unordered = rng.permutation(sequential)
    out = []

    def bench(name, fn, per=N):
        ns = common.min_of(reps, fn) / per
        out.append(Result(name, "synthetic", ns, "ns/value", {"n": per}))

    def via_writer(cfg, vals):
        w = cfg.get()
        w.add_many(vals)
        return w.get()

    n_loop = min(100_000, N)  # the python add loop is too slow for all of N
    bench("addLoopSequential", lambda: _add_loop(sequential[:n_loop]), per=n_loop)
    bench("addManySequential", lambda: RoaringBitmap(sequential))
    bench("addManyUnordered", lambda: RoaringBitmap(unordered))
    bench(
        "writerArrays",
        lambda: via_writer(RoaringBitmapWriter.writer().optimise_for_arrays(), sequential),
    )
    bench(
        "writerRuns",
        lambda: via_writer(RoaringBitmapWriter.writer().optimise_for_runs(), sequential),
    )
    bench(
        "writerConstantMemory",
        lambda: via_writer(RoaringBitmapWriter.writer().constant_memory(), sequential),
    )
    bench(
        "writerPartiallySorted",
        lambda: via_writer(
            RoaringBitmapWriter.writer().partially_sort_values(), unordered
        ),
    )
    return out


def _add_loop(vals):
    b = RoaringBitmap()
    for v in vals:
        b.add(int(v))
    return b
