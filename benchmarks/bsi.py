"""BSI suites — the bit-sliced-index range-query workload
(BASELINE.md: "bsi/ 32-slice range query → TPU AND-chain"; reference
bsi/.../RoaringBitmapSliceIndex.java:432-513 O'Neil compare, :581 sum).

Builds a BSI over a synthetic int column and times EQ/GT/LT/RANGE
compares (CPU path vs the fused device O'Neil kernel chain), sum, and
top_k — the filtered-range-query north-star family.
"""

from __future__ import annotations

from typing import List

import numpy as np

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.models.bsi import Operation, RoaringBitmapSliceIndex

from . import common
from .common import Result

N_ROWS = 1_000_000


def _build(seed=0xFEEF1F0):
    rng = np.random.default_rng(seed)
    cols = np.arange(N_ROWS, dtype=np.int64)
    vals = rng.integers(0, 1 << 31, size=N_ROWS).astype(np.int64)
    bsi = RoaringBitmapSliceIndex()
    bsi.set_values(list(zip(cols.tolist(), vals.tolist())))
    found = RoaringBitmap(
        rng.choice(N_ROWS, size=N_ROWS // 20, replace=False).astype(np.uint32)
    )
    return bsi, found, vals


def run(reps: int = 5, **_) -> List[Result]:
    bsi, found, vals = _build()
    med = int(np.median(vals))
    out = []

    def bench(name, fn):
        out.append(
            Result(name, "synthetic-1M", common.min_of(reps, fn), "ns/op", {"rows": N_ROWS})
        )

    for mode in ("cpu", "device"):
        bench(f"compareGE_{mode}", lambda m=mode: bsi.compare(Operation.GE, med, 0, None, mode=m))
        bench(f"compareLT_{mode}", lambda m=mode: bsi.compare(Operation.LT, med, 0, None, mode=m))
        bench(
            f"compareRange_{mode}",
            lambda m=mode: bsi.compare(Operation.RANGE, med // 2, med * 2, None, mode=m),
        )
        bench(
            f"compareGEFiltered_{mode}",
            lambda m=mode: bsi.compare(Operation.GE, med, 0, found, mode=m),
        )
    bench("compareEQ", lambda: bsi.compare(Operation.EQ, med, 0, None))
    bench("sum", lambda: bsi.sum(found))
    bench("topK", lambda: bsi.top_k(found, 100))
    return out
