"""BSI suites — the bit-sliced-index range-query workload
(BASELINE.md: "bsi/ 32-slice range query → TPU AND-chain"; reference
bsi/.../RoaringBitmapSliceIndex.java:432-513 O'Neil compare, :581 sum).

Builds a BSI over a synthetic int column and times EQ/GT/LT/RANGE
compares (CPU path vs the fused device O'Neil kernel chain), sum, and
top_k — the filtered-range-query north-star family.
"""

from __future__ import annotations

from typing import List

import numpy as np

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.models.bsi import Operation, RoaringBitmapSliceIndex

from . import common
from .common import Result

N_ROWS = 1_000_000


def _build(seed=0xFEEF1F0):
    rng = np.random.default_rng(seed)
    cols = np.arange(N_ROWS, dtype=np.int64)
    vals = rng.integers(0, 1 << 31, size=N_ROWS).astype(np.int64)
    bsi = RoaringBitmapSliceIndex()
    bsi.set_values(list(zip(cols.tolist(), vals.tolist())))
    found = RoaringBitmap(
        rng.choice(N_ROWS, size=N_ROWS // 20, replace=False).astype(np.uint32)
    )
    return bsi, found, vals


def run(reps: int = 5, **_) -> List[Result]:
    bsi, found, vals = _build()
    med = int(np.median(vals))
    out = []

    def bench(name, fn):
        out.append(
            Result(name, "synthetic-1M", common.min_of(reps, fn), "ns/op", {"rows": N_ROWS})
        )

    for mode in ("cpu", "device"):
        bench(f"compareGE_{mode}", lambda m=mode: bsi.compare(Operation.GE, med, 0, None, mode=m))
        bench(f"compareLT_{mode}", lambda m=mode: bsi.compare(Operation.LT, med, 0, None, mode=m))
        bench(
            f"compareRange_{mode}",
            lambda m=mode: bsi.compare(Operation.RANGE, med // 2, med * 2, None, mode=m),
        )
        bench(
            f"compareGEFiltered_{mode}",
            lambda m=mode: bsi.compare(Operation.GE, med, 0, found, mode=m),
        )
    bench("compareEQ", lambda: bsi.compare(Operation.EQ, med, 0, None))
    bench("sum", lambda: bsi.sum(found))
    bench("topK", lambda: bsi.top_k(found, 100))

    # batched multi-predicate counts: Q thresholds per dispatch vs a loop
    # of single-predicate counts (the vmapped walk amortizes the HBM pass)
    q_vals = np.quantile(vals, np.linspace(0.05, 0.95, 64)).astype(np.int64)
    for mode in ("cpu", "device"):
        many = common.min_of(
            reps,
            lambda m=mode: bsi.compare_cardinality_many(
                Operation.GE, q_vals, found_set=found, mode=m
            ),
        )
        out.append(
            Result(
                f"compareCardinalityMany64_{mode}",
                "synthetic-1M",
                many / q_vals.size,
                "ns/query",
                {"rows": N_ROWS, "batch": int(q_vals.size)},
            )
        )
    loop = common.min_of(
        max(1, reps // 2),
        lambda: [
            bsi.compare_cardinality(Operation.GE, int(v), 0, found, mode="device")
            for v in q_vals
        ],
    )
    out.append(
        Result(
            "compareCardinalityLoop64_device",
            "synthetic-1M",
            loop / q_vals.size,
            "ns/query",
            {"rows": N_ROWS, "batch": int(q_vals.size)},
        )
    )
    return out


def run_northstar(n_rows: int = 100_000_000, reps: int = 3) -> List[Result]:
    """BASELINE.md config 4: 32-slice int column, 100M rows, CPU vs device
    O'Neil compare (VERDICT r2 #4 — this config had never been executed).

    The device tensor is ``[32, ceil(n/65536), 2048]`` uint32 — ~400 MB at
    100M rows — packed once and cached; comfortable in v5e-1's 16 GB HBM.
    Run directly:  python -m benchmarks.bsi [n_rows]
    """
    import time

    rng = np.random.default_rng(0xFEEF1F0)
    out: List[Result] = []
    t0 = time.time()
    cols = np.arange(n_rows, dtype=np.uint32)
    vals = rng.integers(0, 1 << 32, size=n_rows, dtype=np.uint64).astype(np.int64)
    bsi = RoaringBitmapSliceIndex()
    bsi.set_values((cols, vals))
    build_s = time.time() - t0
    found = RoaringBitmap(
        rng.choice(n_rows, size=n_rows // 20, replace=False).astype(np.uint32)
    )
    med = int(np.median(vals))
    extra_base = {
        "rows": n_rows,
        "slices": bsi.bit_count(),
        "build_s": round(build_s, 1),
    }

    queries = [
        ("GE_med", Operation.GE, med, 0, None),
        ("RANGE_midhalf", Operation.RANGE, med // 2, med + med // 2, None),
        ("GE_med_filtered5pct", Operation.GE, med, 0, found),
    ]
    results_by_mode = {}
    for mode in ("cpu", "device"):
        for qname, op, a, b, fs in queries:
            t_best, card = None, None
            for _ in range(reps):
                t0 = time.time()
                res = bsi.compare(op, a, b, fs, mode=mode)
                dt = time.time() - t0
                t_best = dt if t_best is None else min(t_best, dt)
                card = res.get_cardinality()
            results_by_mode[(mode, qname)] = card
            out.append(
                Result(
                    f"northstar_{qname}_{mode}",
                    f"synthetic-{n_rows//1_000_000}M",
                    t_best * 1e9,
                    "ns/op",
                    {**extra_base, "cardinality": card, "rows_per_s": round(n_rows / t_best)},
                )
            )
    for qname, *_ in queries:
        assert (
            results_by_mode[("cpu", qname)] == results_by_mode[("device", qname)]
        ), f"cpu/device mismatch on {qname}"

    # batched multi-predicate counts on the resident pack: 64 thresholds in
    # ONE dispatch vs a 64-dispatch loop — through the axon tunnel each
    # dispatch pays the ~145 ms RPC floor, so this is where the batching
    # shows up end-to-end (ns/query, device engine only; the CPU loop at
    # this scale would add minutes for no information)
    q_vals = np.quantile(vals, np.linspace(0.05, 0.95, 64)).astype(np.int64)
    t_many = None
    for _ in range(reps):
        t0 = time.time()
        many_counts = bsi.compare_cardinality_many(Operation.GE, q_vals, mode="device")
        dt = time.time() - t0
        t_many = dt if t_many is None else min(t_many, dt)
    # warm the single-query count path so its cold JIT compile is not
    # charged to the timed loop (the batched side above already got its
    # compile absorbed by best-of-reps)
    bsi.compare_cardinality(Operation.GE, int(q_vals[0]), 0, None, "device")
    t0 = time.time()
    loop_counts = np.array(
        [bsi.compare_cardinality(Operation.GE, int(v), 0, None, "device") for v in q_vals],
        dtype=np.int64,
    )
    t_loop = time.time() - t0
    assert np.array_equal(many_counts, loop_counts), "batched != looped counts"
    for name, t in (("batchedGE64_oneDispatch", t_many), ("batchedGE64_loop", t_loop)):
        out.append(
            Result(
                f"northstar_{name}_device",
                f"synthetic-{n_rows//1_000_000}M",
                t / q_vals.size * 1e9,
                "ns/query",
                {**extra_base, "batch": int(q_vals.size)},
            )
        )

    out.extend(
        _northstar_steady_state(
            bsi, med, n_rows, extra_base, results_by_mode[("cpu", "GE_med")]
        )
    )
    return out


def _northstar_steady_state(bsi, med, n_rows, extra_base, expected_card):
    """On TPU, also report the O'Neil kernel's steady-state throughput:
    through the axon tunnel the end-to-end numbers above are fetch-bound
    (~0.3 s per query regardless of size while the kernel itself is ~1 ms),
    so K compares run inside one jitted scan with the carry-dependent seed
    XOR'd into the EQ init (whole walk depends on it — perturbing only the
    final mask would let XLA hoist the slice scan). XLA fused scan and the
    Pallas VMEM-resident kernel are both measured."""
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    if not pk.on_tpu():
        return []
    import jax.numpy as jnp

    from roaringbitmap_tpu.models.bsi import o_neil_math

    from .common import steady_state_reduce

    keys, ebm_w, slices_w = bsi._pack_dense()
    s_count = bsi.bit_count()
    bits = np.array([(med >> i) & 1 for i in range(s_count - 1, -1, -1)], dtype=bool)
    sl, bv, eb = jnp.asarray(slices_w), jnp.asarray(bits), jnp.asarray(ebm_w)
    nbytes = sl.size * 4
    out = []
    for impl, fn in (
        ("xla", lambda w, s: o_neil_math(w, bv, eb ^ s, eb, "GE")),
        ("pallas", lambda w, s: pk.oneil_compare_pallas(w, bv, eb, eb, op="GE", seed=s)),
    ):
        k_reps = 32
        try:
            t, total = steady_state_reduce(sl, fn, k=k_reps)
        except Exception as e:  # a lowering failure must not kill the suite
            print(f"# steady-state {impl} failed: {e!r}"[:200], flush=True)
            continue
        assert total == k_reps * expected_card, (
            f"steady-state {impl} total {total} != {k_reps}x{expected_card}"
        )
        out.append(
            Result(
                f"northstar_GE_kernel_steady_{impl}",
                f"synthetic-{n_rows//1_000_000}M",
                t * 1e9,
                "ns/op",
                {
                    **extra_base,
                    "rows_per_s": round(n_rows / t),
                    "hbm_gbps": round(nbytes / t / 1e9, 1),
                },
            )
        )
    return out


if __name__ == "__main__":
    import os
    import sys

    # the axon site hook registers the TPU plugin before user code and jax
    # then ignores a JAX_PLATFORMS env override; honor the caller's intent
    # via jax.config (same guard as __graft_entry__.py) so CPU runs don't
    # block on a hung tunnel
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000_000
    for r in run_northstar(n):
        print(r.json(), flush=True)
