"""Realdata in-place / derived-op suites — twin of the jmh realdata
families not covered by ``benchmarks/realdata.py`` (wide aggregations) or
``benchmarks/ops.py`` (pairwise and/or/xor/andNot):

* ``pairwiseIOr``      — RealDataBenchmarkIOr.java:17-23 (clone head, ior-fold
  the rest, final cardinality)
* ``flipLargeRange``   — RealDataBenchmarkInot.java:16-22 (flip [30000, 20M)
  on every bitmap, sum cardinalities)
* ``pairwiseOrNot``    — RealDataBenchmarkOrNot.java:19-27 (static orNot of
  successive pairs bounded by last())
* ``cardinality``      — RealDataBenchmarkCardinality.java:17-24
* ``forEach``          — RealDataBenchmarkForEach.java:18-24 (consumer sums
  every value)
* ``mappedWideOr``     — needwork/SlowMappedORaggregate1.java:32-35 (wide OR
  with every operand a zero-copy mapped ImmutableRoaringBitmap)
* ``limitIncludingAndNot`` — SelectTopValuesBenchmark.java:32-36 (peel the
  top-N off a bitmap via limit + andNot)

Each timed closure ends in a value derived from the result (cardinality
sums), mirroring the jmh Blackhole discipline so work cannot be elided.
"""

from __future__ import annotations

from typing import List

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.models.buffer import BufferFastAggregation, MutableRoaringBitmap
from roaringbitmap_tpu.models.immutable import ImmutableRoaringBitmap
from roaringbitmap_tpu.parallel.aggregation import FastAggregation

from . import common
from .common import Result


def _suite(dataset: str, reps: int) -> List[Result]:
    bms = common.corpus_bitmaps(dataset, limit=200)
    out = []

    def bench(name, fn, per=1, extra=None):
        ns = common.min_of(reps, fn)
        out.append(Result(name, dataset, ns / max(1, per), "ns/op", extra or {}))

    def pairwise_ior():
        acc = bms[0].clone()
        for b in bms[1:]:
            acc.ior(b)
        return acc.get_cardinality()

    bench("pairwiseIOr", pairwise_ior, extra={"n_bitmaps": len(bms)})

    def flip_large_range():
        total = 0
        for b in bms:
            total += RoaringBitmap.flip(b, 30_000, 20_000_000).get_cardinality()
        return total

    bench("flipLargeRange", flip_large_range, per=len(bms))

    def pairwise_or_not():
        total = 0
        for k in range(len(bms) - 1):
            total += RoaringBitmap.or_not(
                bms[k], bms[k + 1], int(bms[k].last()) + 1
            ).get_cardinality()
        return total

    bench("pairwiseOrNot", pairwise_or_not, per=max(1, len(bms) - 1))

    bench(
        "cardinality",
        lambda: sum(b.get_cardinality() for b in bms),
        per=len(bms),
    )

    def for_each():
        total = 0
        for b in bms:
            box = [0]

            def add(v, box=box):
                box[0] += v

            b.for_each(add)
            total += box[0]
        return total

    total_vals = sum(b.get_cardinality() for b in bms)
    ns = common.min_of(max(1, reps // 2), for_each)
    out.append(Result("forEach", dataset, ns / max(1, total_vals), "ns/value"))

    # wide OR where every operand is a zero-copy mapped immutable bitmap —
    # the "slow mapped OR aggregate" the reference keeps as a known-hard case
    mapped = [ImmutableRoaringBitmap(b.serialize()) for b in bms]
    heap_card = FastAggregation.or_(*bms, mode="cpu").get_cardinality()
    mapped_card = BufferFastAggregation.or_(*mapped, mode="cpu").get_cardinality()
    assert mapped_card == heap_card, (mapped_card, heap_card)
    bench(
        "mappedWideOr",
        lambda: BufferFastAggregation.or_(*mapped, mode="cpu").get_cardinality(),
        extra={"n_bitmaps": len(mapped)},
    )
    return out


def _select_top_values(reps: int) -> List[Result]:
    # SelectTopValuesBenchmark's synthetic state: values i*100, peel top n
    base = MutableRoaringBitmap.bitmap_of(*range(0, 1_000_000, 100))
    n = 1000

    def limit_including_andnot():
        bm = base.clone()
        turnoff = bm.limit(n)
        bm.iandnot(turnoff)
        return bm.get_cardinality()

    expect = base.get_cardinality() - n
    assert limit_including_andnot() == expect
    ns = common.min_of(reps, limit_including_andnot)
    return [Result("limitIncludingAndNot", "synthetic", ns, "ns/op", {"n": n})]


def run(reps: int = 5, datasets=None, **_) -> List[Result]:
    results = []
    for ds in datasets or common.DEFAULT_DATASETS:
        results.extend(_suite(ds, reps))
    results.extend(_select_top_values(reps))
    return results
