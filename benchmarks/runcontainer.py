"""Run-container suites — twin of jmh runcontainer benchmarks
(jmh/src/jmh/.../runcontainer/: run-heavy AND/OR/contains and
runOptimize costs over RLE-friendly shapes).

Shapes are long-run bitmaps (interval data) where RunContainer wins, the
reference's motivating case for RLE (README.md run compression).
"""

from __future__ import annotations

from typing import List

import numpy as np

from roaringbitmap_tpu import RoaringBitmap

from . import common
from .common import Result


def _run_heavy(rng, n_runs=400, span=1 << 22):
    starts = np.sort(rng.choice(span, size=n_runs, replace=False)).astype(np.int64)
    parts = [np.arange(s, s + int(rng.integers(100, 4000)), dtype=np.int64) for s in starts]
    return np.unique(np.concatenate(parts)).astype(np.uint32)


def run(reps: int = 10, **_) -> List[Result]:
    rng = np.random.default_rng(0xFEEF1F0)
    a_vals, b_vals = _run_heavy(rng), _run_heavy(rng)
    a, b = RoaringBitmap(a_vals), RoaringBitmap(b_vals)
    a_opt, b_opt = a.clone(), b.clone()
    a_opt.run_optimize()
    b_opt.run_optimize()
    probe = rng.integers(0, 1 << 22, size=10_000).astype(np.uint32)
    out = []

    def bench(name, fn):
        out.append(Result(name, "run-heavy", common.min_of(reps, fn), "ns/op"))

    bench("runOptimize", lambda: a.clone().run_optimize())
    bench("andRunRun", lambda: RoaringBitmap.and_(a_opt, b_opt))
    bench("orRunRun", lambda: RoaringBitmap.or_(a_opt, b_opt))
    bench("xorRunRun", lambda: RoaringBitmap.xor(a_opt, b_opt))
    bench("andNoRuns", lambda: RoaringBitmap.and_(a, b))
    bench("orNoRuns", lambda: RoaringBitmap.or_(a, b))
    bench("containsRun", lambda: [a_opt.contains(int(v)) for v in probe[:1000]])
    bench("iterateRun", lambda: a_opt.to_array())
    out.append(
        Result(
            "compressionRatio",
            "run-heavy",
            a.get_size_in_bytes() / max(1, a_opt.get_size_in_bytes()),
            "x",
            {"plain_bytes": a.get_size_in_bytes(), "run_bytes": a_opt.get_size_in_bytes()},
        )
    )
    return out
