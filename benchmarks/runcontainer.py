"""Run-container suites — twin of the jmh runcontainer benchmarks
(jmh/src/jmh/.../runcontainer/: BasicAnd/Or/Xor/AndNotContainerBenchmark,
RunArrayAnd/Or/Xor/AndNotBenchmark, ArrayContainerAndNotRunContainer,
AllRunHorizontalOrBenchmark, BasicHorizontalOrBenchmark,
BitmapToRuncontainerConversions, RunContainerRealDataBenchmarkRunOptimize).

Covers the full operand-type matrix the run-space interval algebra serves:
run x run, run x array, run x bitmap — for and/or/xor/andNot — plus the
words-path "before" twin for each run x run op (the same data held as
bitmap containers), which makes the interval-algebra speedup a visible
before/after in the numbers (VERDICT r2 #7), horizontal OR over all-run
sets, conversion costs, and runOptimize over real corpora.
"""

from __future__ import annotations

from typing import List

import numpy as np

from roaringbitmap_tpu import FastAggregation, RoaringBitmap

from . import common
from .common import Result
from .ops import OPS as _ALL_OPS

# the four pairwise ops of the shared benchmark op table (benchmarks/ops.py)
OPS = {k: _ALL_OPS[k] for k in ("and", "or", "xor", "andNot")}


def _run_heavy(rng, n_runs=400, span=1 << 22):
    starts = np.sort(rng.choice(span, size=n_runs, replace=False)).astype(np.int64)
    parts = [np.arange(s, s + int(rng.integers(100, 4000)), dtype=np.int64) for s in starts]
    return np.unique(np.concatenate(parts)).astype(np.uint32)


def _sparse(rng, span=1 << 22, n=30_000):
    return np.sort(rng.choice(span, size=n, replace=False)).astype(np.uint32)


def _dense(rng, span=1 << 19):
    return np.flatnonzero(rng.random(span) < 0.4).astype(np.uint32)


def run(reps: int = 10, datasets=None, **_) -> List[Result]:
    rng = np.random.default_rng(0xFEEF1F0)
    out: List[Result] = []

    def bench(name, fn, dataset="run-heavy", extra=None):
        out.append(Result(name, dataset, common.min_of(reps, fn), "ns/op", extra or {}))

    # operand zoo: run-optimized, plain (array/bitmap word path), sparse, dense
    a_vals, b_vals = _run_heavy(rng), _run_heavy(rng)
    run_a, run_b = RoaringBitmap(a_vals), RoaringBitmap(b_vals)
    words_a, words_b = run_a.clone(), run_b.clone()  # same data, no run form
    run_a.run_optimize()
    run_b.run_optimize()
    arr = RoaringBitmap(_sparse(rng))
    dense = RoaringBitmap(_dense(rng))

    # the op matrix: run x {run, array, bitmap} for all four ops, with the
    # words-path "before" twin for run x run (interval algebra before/after)
    for opname, op in OPS.items():
        bench(f"{opname}RunRun", lambda op=op: op(run_a, run_b))
        bench(
            f"{opname}RunRun_wordsPath",
            lambda op=op: op(words_a, words_b),
            extra={"twin_of": f"{opname}RunRun", "note": "same data, no RLE form"},
        )
        bench(f"{opname}RunArray", lambda op=op: op(run_a, arr))
        bench(f"{opname}ArrayRun", lambda op=op: op(arr, run_a))
        bench(f"{opname}RunBitmap", lambda op=op: op(run_a, dense))

    # horizontal OR over all-run / mixed sets
    all_run = []
    for _ in range(32):
        bm = RoaringBitmap(_run_heavy(rng, n_runs=120))
        bm.run_optimize()
        all_run.append(bm)
    bench("allRunHorizontalOr", lambda: FastAggregation.horizontal_or(*all_run))
    mixed = all_run[:16] + [RoaringBitmap(_sparse(rng, n=5000)) for _ in range(16)]
    bench("basicHorizontalOr", lambda: FastAggregation.horizontal_or(*mixed))

    # conversions (BitmapToRuncontainerConversions)
    bench("runOptimize", lambda: words_a.clone().run_optimize())
    bench("toEfficientNonRun", lambda: run_a.clone().remove_run_compression())

    probe = rng.integers(0, 1 << 22, size=1_000).astype(np.uint32)
    bench("containsRun", lambda: [run_a.contains(int(v)) for v in probe])
    bench("iterateRun", lambda: run_a.to_array())
    out.append(
        Result(
            "compressionRatio",
            "run-heavy",
            words_a.get_size_in_bytes() / max(1, run_a.get_size_in_bytes()),
            "x",
            {
                "plain_bytes": words_a.get_size_in_bytes(),
                "run_bytes": run_a.get_size_in_bytes(),
            },
        )
    )

    # runOptimize over real corpora (RunContainerRealDataBenchmarkRunOptimize)
    for ds in datasets or ["census1881", "wikileaks-noquotes"]:
        bms = common.corpus_bitmaps(ds, limit=100, optimize=False)

        def opt_all(bms=bms):
            for b in bms:
                b.clone().run_optimize()

        ns = common.min_of(max(1, reps // 2), opt_all) / max(1, len(bms))
        out.append(Result("runOptimize", ds, ns, "ns/bitmap"))
    return out
