"""Container + L0-kernel micro suite — twins of the reference's
jmh `arraycontainer/AddBenchmark`, `bitmapcontainer/SelectBenchmark`,
`bithacking/SelectBenchmark`+`UnsignedVSFlip`, `UtilBenchmark` (galloping
intersect / union kernels), and `cardinality64/` groups
(jmh/src/jmh/java/org/roaringbitmap/).

Each L0 kernel is timed twice where a native (C) implementation exists:
the numpy fallback and the ctypes path, so the native speedups claimed in
BENCH_NOTES stay measured, not asserted.
"""

from __future__ import annotations

from typing import List

import numpy as np

from roaringbitmap_tpu.models.container import (
    ArrayContainer,
    BitmapContainer,
    RunContainer,
)
from roaringbitmap_tpu.utils import bits
from roaringbitmap_tpu import native

from . import common
from .common import Result


def run(reps: int = 10, **_) -> List[Result]:
    rng = np.random.default_rng(0xFEEF1F0)
    out: List[Result] = []

    def bench(name, fn, extra=None):
        out.append(Result(name, "synthetic", common.min_of(reps, fn), "ns/op", extra or {}))

    # ---- arraycontainer/AddBenchmark: range iadds into a fresh container ----
    ranges = []
    pos = 0
    while pos < (1 << 16) - 512 and len(ranges) < 128:
        width = int(rng.integers(1, 256))
        ranges.append((pos, pos + width))
        pos += width + int(rng.integers(1, 512))

    def array_add_ranges():
        c = ArrayContainer()
        for s, e in ranges:
            c = c.add_range(s, e)
        return c

    bench("arrayContainerAddRanges", array_add_ranges, {"n_ranges": len(ranges)})

    sparse_vals = np.sort(
        rng.choice(1 << 16, size=2048, replace=False).astype(np.uint16)
    )

    def array_add_points():
        c = ArrayContainer()
        for v in sparse_vals[:256]:
            c = c.add(int(v))
        return c

    bench("arrayContainerAddPoints", array_add_points, {"n": 256})

    # ---- bitmapcontainer/SelectBenchmark + rank ----
    dense = BitmapContainer(bits.words_from_values(
        np.sort(rng.choice(1 << 16, size=40_000, replace=False).astype(np.uint16))
    ))
    js = rng.integers(0, dense.cardinality, size=64)

    def bitmap_select():
        t = 0
        for j in js:
            t += dense.select(int(j))
        return t

    bench("bitmapContainerSelect", bitmap_select, {"n_queries": len(js)})
    xs = rng.integers(0, 1 << 16, size=64)
    bench("bitmapContainerRank", lambda: sum(dense.rank(int(x)) for x in xs))

    # ---- bithacking/SelectBenchmark: select-in-word over 1024 words ----
    words = dense.words
    ks = rng.integers(0, 1000, size=64)

    def select_in_words():
        t = 0
        for k in ks:
            t += bits.select_in_words(words, int(k))
        return t

    bench("selectInWords", select_in_words, {"n_queries": len(ks)})

    # ---- UtilBenchmark: the sorted-set kernels, numpy vs native ----
    a = np.sort(rng.choice(1 << 16, size=4096, replace=False).astype(np.uint16))
    b = np.sort(rng.choice(1 << 16, size=512, replace=False).astype(np.uint16))
    kernels = [
        ("intersectSorted", bits.intersect_sorted, native.intersect_sorted),
        ("mergeSortedUnique", bits.merge_sorted_unique, native.merge_sorted_unique),
        ("differenceSorted", bits.difference_sorted, native.difference_sorted),
        ("xorSorted", bits.xor_sorted, native.xor_sorted),
    ]
    for name, np_fn, nat_fn in kernels:
        bench(f"util{name}_numpy", lambda f=np_fn: f(a, b))
        if native.available():
            got, want = nat_fn(a, b), np_fn(a, b)
            assert np.array_equal(got, want), name
            bench(f"util{name}_native", lambda f=nat_fn: f(a, b))

    # ---- runcontainer interval kernel at container level (micro twin) ----
    starts = np.arange(0, 1 << 16, 1024, dtype=np.uint16)[:32]
    rc = RunContainer(starts, np.full(32, 255, dtype=np.uint16))
    rc2 = RunContainer(starts + 128, np.full(32, 255, dtype=np.uint16))
    bench("runContainerAndRun", lambda: rc.and_(rc2))
    bench("runContainerOrRun", lambda: rc.or_(rc2))

    # ---- cardinality64: Roaring64 cardinality after wide construction ----
    from roaringbitmap_tpu.models.roaring64 import Roaring64NavigableMap

    vals64 = (rng.integers(0, 1 << 40, size=100_000, dtype=np.uint64)).astype(np.int64)
    r64 = Roaring64NavigableMap()
    r64.add_many(vals64)
    bench("cardinality64", r64.get_long_cardinality, {"n": len(vals64)})
    probe = vals64[rng.integers(0, len(vals64), size=64)]
    bench("contains64", lambda: sum(r64.contains(int(v)) for v in probe))

    return out
