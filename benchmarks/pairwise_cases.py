"""Synthetic pairwise-op grids — the aggregation/{and,andnot}/{bestcase,
identical,worstcase} jmh twins (jmh/src/jmh/java/org/roaringbitmap/
aggregation/and/bestcase/RoaringBitmapBenchmark.java:21-37 and siblings,
both widths), plus the N-way ior fold of aggregation/or/
RoaringBitmapBenchmark.java:20-41.

Case shapes (k = 2^16, exactly the reference setups):

* ``bestcase``  — operands own almost entirely disjoint key ranges with a
                  50-key overlap band (the key-skip fast path dominates)
* ``identical`` — the same 10k single-value containers on both sides
* ``worstcase`` — interleaved adjacent values in shared containers

Per (case, op, width): the static op, the in-place op on a clone, and
``justclone`` (the jmh baseline row that prices the clone out of the
in-place number). Static and in-place results are asserted equal before
timing. or/xor grids are recorded too (the reference only ships and/
andnot grids; same shapes, marked beyond=true).

Engine twins (ISSUE 5): the unsuffixed 32-bit rows pin the PER-CONTAINER
engine (``columnar.disabled()``), keeping their historical meaning across
BENCH_CPU_SWEEP rounds; each gains a ``columnar:`` twin calling the
batched engine DIRECTLY on the same inputs, asserted value-equal first.
(These grids are 10k single-value containers — the shape the cutoff
model deliberately keeps on the per-container walk; the twin rows are
the measured justification.) Since ISSUE 10 each case also records a
``routed:`` twin — the default path through the cutoff model — which
must sit within noise of the per-container floor (no case below 0.9x:
the router's own cost on a kept-per-container pair is a count compare).

Run:  python -m benchmarks.run pairwise_cases --reps 5
"""

from __future__ import annotations

from typing import List

import numpy as np

from roaringbitmap_tpu import Roaring64Bitmap, RoaringBitmap, columnar

from . import common
from .common import Result

K = 1 << 16


def _cases_values():
    """(case, values1, values2) triples shared by both widths."""
    i = np.arange(10_000, dtype=np.uint64)
    j = np.arange(10_000, 10_050, dtype=np.uint64)
    tail = np.arange(10_050, 20_000, dtype=np.uint64)
    best1 = np.concatenate([i * K, j * K + 13, [np.uint64(20_000 * K)]])
    best2 = np.concatenate([j * K, tail * K])
    ident = i * K
    worst1 = 2 * i * K
    worst2 = 2 * i * K + 1
    return [
        ("bestcase", best1, best2),
        ("identical", ident, ident.copy()),
        ("worstcase", worst1, worst2),
    ]


_OPS32 = {
    "and": (RoaringBitmap.and_, "iand"),
    "or": (RoaringBitmap.or_, "ior"),
    "xor": (RoaringBitmap.xor, "ixor"),
    "andnot": (RoaringBitmap.andnot, "iandnot"),
}
_OPS64 = {
    "and": (Roaring64Bitmap.and_, "iand"),
    "or": (Roaring64Bitmap.or_, "ior"),
    "xor": (Roaring64Bitmap.xor, "ixor"),
    "andnot": (Roaring64Bitmap.andnot, "iandnot"),
}
# the reference grid only ships and/andnot; or/xor rows are extra coverage
_REFERENCE_OPS = {"and", "andnot"}


def run(reps: int = 5, datasets=None, **_) -> List[Result]:
    out: List[Result] = []

    def rec(name, dataset, value, **extra):
        out.append(Result(name, dataset, value, "ns/op", {"suite": "pairwise_cases", **extra}))

    for case, v1, v2 in _cases_values():
        for width, ctor, ops in (
            (32, lambda v: RoaringBitmap(v.astype(np.uint32)), _OPS32),
            (64, Roaring64Bitmap, _OPS64),
        ):
            ds = f"synthetic-{width}"
            b1, b2 = ctor(v1), ctor(v2)
            rec(f"{case}:justclone", ds, common.min_of(reps, b1.clone))
            for opname, (static_op, inplace_name) in ops.items():
                inplace = getattr(type(b1), inplace_name)
                want = static_op(b1, b2)
                got = inplace(b1.clone(), b2)
                assert got == want, (case, width, opname)
                extra = {} if opname in _REFERENCE_OPS else {"beyond": True}

                def percontainer(fn=static_op):
                    with columnar.disabled():
                        return fn(b1, b2)

                def percontainer_inplace(fn=inplace):
                    with columnar.disabled():
                        return fn(b1.clone(), b2)

                rec(f"{case}:{opname}", ds, common.min_of(reps, percontainer), **extra)
                rec(
                    f"{case}:inplace_{opname}",
                    ds,
                    common.min_of(reps, percontainer_inplace),
                    **extra,
                )
                if width == 32:  # columnar engine twin (direct engine call)
                    assert columnar.pairwise(opname, b1, b2) == want, (
                        case, opname, "columnar",
                    )
                    rec(
                        f"columnar:{case}:{opname}",
                        ds,
                        common.min_of(
                            reps, lambda: columnar.pairwise(opname, b1, b2)
                        ),
                        **extra,
                    )
                    # routed twin (ISSUE 10): the DEFAULT path through the
                    # cutoff model — these grids must price within noise
                    # of the per-container floor (the router keeps them
                    # per-container; the row is the measured proof that
                    # routing itself costs nothing here)
                    rec(
                        f"routed:{case}:{opname}",
                        ds,
                        common.min_of(reps, lambda: static_op(b1, b2)),
                        **extra,
                    )

    # buffer twins of the and/andnot grids (buffer/aggregation/{and,andnot}/
    # {bestcase,identical,worstcase}/MutableRoaringBitmapBenchmark.java):
    # static ops on the buffer facade, one operand an immutable mapped view
    # (the mixed-input case the buffer layer exists for)
    from roaringbitmap_tpu.models.buffer import MutableRoaringBitmap
    from roaringbitmap_tpu.models.immutable import ImmutableRoaringBitmap

    for case, v1, v2 in _cases_values():
        b1 = MutableRoaringBitmap(v1.astype(np.uint32))
        b2 = ImmutableRoaringBitmap(
            RoaringBitmap(v2.astype(np.uint32)).serialize()
        )
        for opname in ("and", "andnot"):
            static_op = getattr(MutableRoaringBitmap, opname + ("_" if opname == "and" else ""))
            oracle = getattr(RoaringBitmap, opname + ("_" if opname == "and" else ""))(
                RoaringBitmap(v1.astype(np.uint32)), RoaringBitmap(v2.astype(np.uint32))
            )
            assert static_op(b1, b2) == oracle, (case, "buffer", opname)
            rec(
                f"{case}:buffer_{opname}",
                "synthetic-buffer",
                common.min_of(reps, lambda: static_op(b1, b2)),
            )

    # N-way in-place OR fold (aggregation/or/RoaringBitmapBenchmark.java:
    # @Param {10, 50, 100} random bitmaps, b1.or(each) into an accumulator)
    rng = np.random.default_rng(0xFEEF1F0)
    pool = [
        RoaringBitmap(np.unique(rng.integers(0, 1 << 24, 1 << 12)).astype(np.uint32))
        for _ in range(100)
    ]
    for n in (10, 50, 100):

        def fold(n=n):
            acc = RoaringBitmap()
            for bm in pool[:n]:
                acc.ior(bm)
            return acc

        from roaringbitmap_tpu.parallel.aggregation import FastAggregation

        assert fold() == FastAggregation.or_(*pool[:n], mode="cpu")
        rec("orFold:ior", "synthetic-32", common.min_of(reps, fold), n_bitmaps=n)
    return out
