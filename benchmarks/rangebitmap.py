"""RangeBitmap suites — twin of jmh RangeBitmap benchmarks
(jmh/src/jmh/.../rangebitmap/: RangeBitmapBenchmark lt/lte/gt/gte/between
+Cardinality variants over appended value columns).

Builds a sealed RangeBitmap over a synthetic value column (uniform +
zipf-ish mix like the jmh states) and times point/range predicates with
and without a pre-filter context.
"""

from __future__ import annotations

from typing import List

import numpy as np

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.models.range_bitmap import RangeBitmap

from . import common
from .common import Result

N_ROWS = 200_000


def _build(seed=0xFEEF1F0):
    rng = np.random.default_rng(seed)
    uniform = rng.integers(0, 1 << 24, size=N_ROWS // 2)
    skewed = (rng.pareto(1.5, size=N_ROWS - N_ROWS // 2) * 1000).astype(np.int64)
    values = np.concatenate([uniform, np.minimum(skewed, (1 << 24) - 1)])
    app = RangeBitmap.appender((1 << 24) - 1)
    app.add_many(values.tolist())
    rb = app.build()
    ctx = RoaringBitmap(rng.choice(N_ROWS, size=N_ROWS // 10, replace=False).astype(np.uint32))
    return rb, ctx, values


def run(reps: int = 10, **_) -> List[Result]:
    rb, ctx, values = _build()
    med = int(np.median(values))
    lo, hi = med // 2, med * 2
    out = []

    def bench(name, fn):
        out.append(Result(name, "synthetic", common.min_of(reps, fn), "ns/op", {"rows": N_ROWS}))

    bench("lt", lambda: rb.lt(med))
    bench("lte", lambda: rb.lte(med))
    bench("gt", lambda: rb.gt(med))
    bench("gte", lambda: rb.gte(med))
    bench("eq", lambda: rb.eq(med))
    bench("between", lambda: rb.between(lo, hi))
    bench("betweenCardinality", lambda: rb.between_cardinality(lo, hi))
    bench("ltWithContext", lambda: rb.lt(med, context=ctx))
    bench("betweenWithContext", lambda: rb.between(lo, hi, context=ctx))
    return out
