"""Real-data aggregation suites — twin of jmh realdata
(jmh/src/jmh/.../realdata/: RealDataBenchmarkWideOrNaive, …WideOr,
…WideAndNaive, …WideXor, …HorizontalOr, ParallelAggregatorBenchmark).

Each benchmark folds the *whole* corpus (all bitmaps of a dataset) and is
measured as ns per wide aggregation; the device engines additionally report
aggregate throughput.  Correctness of every engine against the naive fold is
asserted by tests/test_benchmarks.py before numbers are trusted, mirroring
jmh/src/test/.../RealDataBenchmarkOrTest.

Engine twins (ISSUE 5): ``wideOr``/``wideXor``/``parallelOr`` pin the
pre-columnar pooled word fold (``columnar.disabled()``), keeping their
historical meaning; the ``columnar:`` twins measure the routed batched
fold on the same corpus, asserted equal first. AND has no twin — its
fold deliberately stays on the lazy per-group path (aggregation.py), so
there is no second engine to measure.
"""

from __future__ import annotations

from typing import List

from roaringbitmap_tpu import columnar
from roaringbitmap_tpu.parallel.aggregation import FastAggregation, ParallelAggregation

from . import common
from .common import Result


def _suite(dataset: str, reps: int) -> List[Result]:
    bms = common.corpus_bitmaps(dataset)
    out = []

    def bench(name, fn):
        ns = common.min_of(reps, fn)
        out.append(Result(name, dataset, ns, "ns/op", {"n_bitmaps": len(bms)}))

    def percontainer(fn):
        def run():
            with columnar.disabled():
                return fn()

        return run

    bench("wideOrNaive", lambda: FastAggregation.naive_or(*bms))
    bench("wideOr", percontainer(lambda: FastAggregation.or_(*bms, mode="cpu")))
    bench("columnar:wideOr", lambda: FastAggregation.or_(*bms, mode="cpu"))
    bench("wideOrDevice", lambda: FastAggregation.or_(*bms, mode="device"))
    bench("wideAndNaive", lambda: FastAggregation.naive_and(*bms))
    bench("wideAnd", lambda: FastAggregation.workshy_and(*bms, mode="cpu"))
    bench("wideAndDevice", lambda: FastAggregation.workshy_and(*bms, mode="device"))
    bench("wideXor", percontainer(lambda: FastAggregation.xor(*bms, mode="cpu")))
    bench("columnar:wideXor", lambda: FastAggregation.xor(*bms, mode="cpu"))
    bench("horizontalOr", lambda: FastAggregation.horizontal_or(*bms))
    bench("priorityQueueOr", lambda: FastAggregation.priorityqueue_or(*bms))
    bench("parallelOr", percontainer(lambda: ParallelAggregation.or_(*bms, mode="cpu")))
    bench("columnar:parallelOr", lambda: ParallelAggregation.or_(*bms, mode="cpu"))
    bench("parallelOrDevice", lambda: ParallelAggregation.or_(*bms, mode="device"))
    bench("parallelXor", lambda: ParallelAggregation.xor(*bms, mode="cpu"))
    # cardinality-only N-way (device path fetches only per-group popcounts)
    bench("wideOrCardinalityDevice", lambda: FastAggregation.or_cardinality(*bms, mode="device"))
    bench("wideAndCardinalityDevice", lambda: FastAggregation.and_cardinality(*bms, mode="device"))
    return out


def run(reps: int = 5, datasets=None, **_) -> List[Result]:
    results = []
    for ds in datasets or common.DEFAULT_DATASETS:
        results.extend(_suite(ds, reps))
    return results
