"""Benchmark CLI — the jmh/run.sh analogue.

    python -m benchmarks.run [suite ...] [--reps N] [--datasets a,b]
                             [--profile] [--json PATH]

Suites: realdata ops iteration serialization rangebitmap writer
runcontainer bsi simplebenchmark (default: all).  Emits one JSON line per
measurement (and optionally appends them to --json); --profile wraps the
run in a jax.profiler trace written to /tmp/rb_tpu_trace.
"""

from __future__ import annotations

import argparse
import importlib
import sys

from . import SUITES, common


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="benchmarks.run")
    p.add_argument("suites", nargs="*", default=None)
    p.add_argument("--reps", type=int, default=None)
    p.add_argument("--datasets", type=str, default=None)
    p.add_argument("--profile", action="store_true")
    p.add_argument("--json", type=str, default=None)
    p.add_argument(
        "--cpu",
        action="store_true",
        help="force the CPU backend (e.g. when the TPU tunnel is unreachable)",
    )
    args = p.parse_args(argv)

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    names = args.suites or SUITES + ["simplebenchmark"]
    datasets = args.datasets.split(",") if args.datasets else None
    results = []
    with common.maybe_profile(args.profile):
        for name in names:
            mod = importlib.import_module(f"benchmarks.{name}")
            kwargs = {"datasets": datasets}
            if args.reps:
                kwargs["reps"] = args.reps
            for r in mod.run(**kwargs):
                r.extra["suite"] = name
                print(r.json(), flush=True)
                results.append(r)
    if args.json:
        with open(args.json, "a") as f:
            for r in results:
                f.write(r.json() + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
