"""64-bit wide-aggregation suite: FastAggregation64 / or_navigable vs the
pairwise folds the reference is limited to (Roaring64NavigableMap
naivelazyor), across multi-bucket synthetic working sets."""

from __future__ import annotations

from typing import List

import numpy as np

from roaringbitmap_tpu import FastAggregation64, Roaring64NavigableMap
from roaringbitmap_tpu.models.roaring64art import Roaring64Bitmap
from roaringbitmap_tpu.parallel.aggregation64 import or_navigable

from . import common
from .common import Result

N_BITMAPS = 64


def _build(rng):
    arts, navs = [], []
    for i in range(N_BITMAPS):
        parts = [
            rng.integers(0, 1 << 20, size=20_000, dtype=np.uint64),
            (np.uint64(3 + (i % 4)) << np.uint64(32))
            + rng.integers(0, 1 << 20, size=15_000, dtype=np.uint64),
            (np.uint64(9) << np.uint64(40))
            + rng.integers(0, 1 << 18, size=5_000, dtype=np.uint64),
        ]
        vals = np.concatenate(parts)
        arts.append(Roaring64Bitmap(vals))
        navs.append(Roaring64NavigableMap(vals))
    return arts, navs


def run(reps: int = 5, **_) -> List[Result]:
    rng = np.random.default_rng(0xFEEF1F0)
    arts, navs = _build(rng)
    out: List[Result] = []

    def bench(name, fn):
        out.append(
            Result(name, "synthetic-64bit", common.min_of(reps, fn), "ns/op", {"n_bitmaps": N_BITMAPS})
        )

    def pairwise_art():
        acc = arts[0].clone()
        for b in arts[1:]:
            acc.ior(b)
        return acc

    def pairwise_nav():
        acc = navs[0].clone()
        for b in navs[1:]:
            acc.ior(b)
        return acc

    bench("wideOr64Pairwise(art)", pairwise_art)
    bench("wideOr64(art,cpu)", lambda: FastAggregation64.or_(*arts, mode="cpu"))
    bench("wideOr64(art,device)", lambda: FastAggregation64.or_(*arts, mode="device"))
    bench("wideAnd64(art,cpu)", lambda: FastAggregation64.and_(*arts, mode="cpu"))
    bench("wideOr64Pairwise(navigable)", pairwise_nav)
    bench("wideOr64(navigable,cpu)", lambda: or_navigable(*navs, mode="cpu"))
    bench("wideOr64(navigable,device)", lambda: or_navigable(*navs, mode="device"))
    return out
