"""Filtered-ANN suite — BASELINE.json config 5: "1M-doc Roaring docID
filter ∩ top-k candidate set".

The retrieval pattern: an ANN index returns per-query candidate docID
lists; a Roaring filter (ACL / tenant / freshness) intersects each list,
and surviving candidates keep their rank order. Engines measured:

* cpu        — per-query RoaringBitmap.and_ + rank walk (reference shape)
* device     — ALL queries' candidate words packed [Q, K, 2048] once per
               batch, one fused AND + per-query popcount dispatch
* contains   — vectorized filter.contains on the raw docID arrays (the
               numpy/native path an ANN stack would actually call)
"""

from __future__ import annotations

from typing import List

import numpy as np

from roaringbitmap_tpu import RoaringBitmap

from . import common
from .common import Result

N_DOCS = 1_000_000
N_QUERIES = 64
TOP_K = 1000
FILTER_DENSITY = 0.3


def _build(seed=0xFEEF1F0):
    rng = np.random.default_rng(seed)
    filter_docs = rng.choice(N_DOCS, size=int(N_DOCS * FILTER_DENSITY), replace=False)
    doc_filter = RoaringBitmap(np.sort(filter_docs).astype(np.uint32))
    queries = [
        np.sort(rng.choice(N_DOCS, size=TOP_K, replace=False)).astype(np.uint32)
        for _ in range(N_QUERIES)
    ]
    return doc_filter, queries


def run(reps: int = 5, **_) -> List[Result]:
    from roaringbitmap_tpu.parallel import batch

    doc_filter, queries = _build()
    cand_bitmaps = [RoaringBitmap(q) for q in queries]
    out = []

    def bench(name, fn, per=N_QUERIES):
        ns = common.min_of(reps, fn) / per
        out.append(
            Result(
                name,
                "1M-docs",
                ns,
                "ns/query",
                {"queries": N_QUERIES, "top_k": TOP_K},
            )
        )

    def cpu_path():
        return [RoaringBitmap.and_(doc_filter, c) for c in cand_bitmaps]

    def contains_path():
        return [q[doc_filter.contains_many(q)] for q in queries]

    # marshal once; time the steady-state retrieval loop
    device_path = batch.prepare_batched_cardinality(doc_filter, cand_bitmaps)

    # correctness gate before timing (jmh smoke-test discipline)
    want = [RoaringBitmap.and_(doc_filter, c).get_cardinality() for c in cand_bitmaps]
    assert device_path().tolist() == want, "device filtered-ANN mismatch"

    bench("cpuAndPerQuery", cpu_path)
    bench("deviceBatchedAnd", device_path)
    bench("containsMany", contains_path)

    # many-vs-many: the all-pairs overlap matrix (similarity join). The
    # reference's only expression of this is an n*m pairwise loop.
    # (needs at least two candidates to form a left/right split)
    half = min(24, len(cand_bitmaps) // 2)
    if half >= 1:
        pair_left = cand_bitmaps[:half]
        pair_right = cand_bitmaps[half : 2 * half]

        def matrix_device():
            return batch.pairwise_and_cardinality(pair_left, pair_right)

        def matrix_cpu_loop():
            return [
                [RoaringBitmap.and_cardinality(a, b) for b in pair_right]
                for a in pair_left
            ]

        got = matrix_device()
        assert got.tolist() == matrix_cpu_loop(), "pairwise matrix mismatch"
        n_pairs = len(pair_left) * len(pair_right)
        shape = f"{half}x{half}"
        bench(f"pairwiseMatrixDevice{shape}", matrix_device, per=n_pairs)
        bench(f"pairwiseMatrixCpuLoop{shape}", matrix_cpu_loop, per=n_pairs)
        out.extend(
            _steady_state_block(device_path, want, pair_left, pair_right, got)
        )
    return out


def _steady_state_block(device_path, want_cards, pair_left, pair_right, want_matrix):
    """On TPU, the honest config-5 numbers: per-dispatch timing through the
    axon tunnel is RPC-bound (~150 ms floor), so K retrieval batches run
    inside ONE jitted scan with the carry-dependent seed XOR'd into the
    filter read (see benchmarks.common.steady_state_reduce). Reuses the
    tensors device_path already marshalled (run.device_tensors/.step)."""
    from roaringbitmap_tpu.ops import pallas_kernels as pk

    if not pk.on_tpu():
        return []
    from roaringbitmap_tpu.parallel import batch as B

    from .common import steady_state_reduce

    out = []
    k_reps = 32
    n_q = len(want_cards)

    # the steady retrieval loop: filter AND over every query's candidates,
    # on the tensors the per-dispatch path already shipped
    batch_arr, filt = device_path.device_tensors
    step = device_path.step

    def with_seed(w, seed):
        b, f = w
        return None, step(b, f ^ seed)

    t, total = steady_state_reduce((batch_arr, filt), with_seed, k=k_reps)
    assert total == k_reps * sum(want_cards), "steady filtered-AND total mismatch"
    out.append(
        Result(
            "deviceBatchedAnd_steady",
            "1M-docs",
            t / n_q * 1e9,
            "ns/query",
            {"queries": n_q, "scan_k": k_reps, "queries_per_s": round(n_q / t)},
        )
    )

    # the MXU overlap matrix at steady state (the similarity-join engine)
    matrix = B.prepare_pairwise_mxu(pair_left, pair_right)
    if matrix.device_tensors is not None:
        mxu = matrix.step

        def mxu_seed(w, seed):
            left, right = w
            return None, mxu(left ^ seed, right)

        t2, total2 = steady_state_reduce(matrix.device_tensors, mxu_seed, k=k_reps)
        assert total2 == k_reps * int(np.asarray(want_matrix).sum()), "steady MXU total mismatch"
        n_pairs = len(pair_left) * len(pair_right)
        out.append(
            Result(
                f"pairwiseMatrixMXU_steady_{len(pair_left)}x{len(pair_right)}",
                "1M-docs",
                t2 / n_pairs * 1e9,
                "ns/pair",
                {"scan_k": k_reps, "pairs_per_s": round(n_pairs / t2)},
            )
        )
    return out
