"""Benchmark harnesses mirroring the reference's perf modules.

The reference externalizes all performance work to two modules
(SURVEY.md §5 "Tracing / profiling"):

* ``jmh/`` — 128 JMH suites (realdata wide-OR/AND, per-op matrices,
  iteration, serialization, RangeBitmap, ParallelAggregation, writer,
  runcontainer; jmh/run.sh drives them with ``-wi 5 -i 5 -f 1``).
* ``simplebenchmark/`` — dependency-free min-of-100-reps nanos harness
  over the real datasets (simplebenchmark.java:52-112).

This package is the TPU build's twin: one suite module per jmh suite
family over the same real-roaring-dataset corpora, a ``simplebenchmark``
clone, and a CLI runner (``python -m benchmarks.run``) that emits one
JSON line per measurement.  Optional ``--profile`` wraps timed sections
in ``jax.profiler.trace`` so device work is inspectable in TensorBoard —
the tracing story the reference delegates to JMH's infra.

Smoke-testing strategy follows jmh/src/test (RealDataBenchmark*Test):
``tests/test_benchmarks.py`` runs every suite with tiny reps and asserts
each benchmark's aggregation output matches a naive reference before any
timing is trusted.
"""

from . import common  # noqa: F401

SUITES = [
    "realdata",
    "realdata_ops",
    "ops",
    "iteration",
    "serialization",
    "rangebitmap",
    "writer",
    "runcontainer",
    "micro",
    "containers",
    "aggregation64",
    "bsi",
    "bitsetutil",
    "filtered_ann",
    "query",
    "formats",
    "bithacking",
    "longlong",
    "pairwise_cases",
]
