"""Pairwise op matrix suites — twin of the jmh per-op suites
(jmh/src/jmh/.../{and,or,xor,andnot}/ Bestcase/Identical/Worstcase pairs
plus the realdata pairwise Ands/Ors/Xors benchmarks).

Shapes:
* bestcase  — disjoint key ranges (no container overlap; pure key merge)
* identical — the same bitmap twice (every container pair hits)
* worstcase — interleaved dense/sparse/run mix over shared keys
* realdata  — successive pairs of a real corpus (RealDataBenchmarkAnds-style)
"""

from __future__ import annotations

from typing import List

import numpy as np

from roaringbitmap_tpu import RoaringBitmap

from . import common
from .common import Result

OPS = {
    "and": RoaringBitmap.and_,
    "or": RoaringBitmap.or_,
    "xor": RoaringBitmap.xor,
    "andNot": RoaringBitmap.andnot,
    "andCardinality": RoaringBitmap.and_cardinality,
    "orCardinality": RoaringBitmap.or_cardinality,
}


def _shape_pairs(rng):
    dense = np.flatnonzero(rng.random(1 << 18) < 0.5).astype(np.uint32)
    sparse = rng.choice(1 << 22, size=3000, replace=False).astype(np.uint32)
    runs = np.concatenate(
        [np.arange(b, b + 4000, dtype=np.uint32) for b in range(0, 1 << 21, 1 << 17)]
    )
    mixed = np.unique(np.concatenate([dense, sparse, runs]))
    bestcase = (RoaringBitmap(dense), RoaringBitmap(dense + np.uint32(1 << 24)))
    ident_b = RoaringBitmap(mixed)
    worst_a, worst_b = RoaringBitmap(mixed[::2].copy()), RoaringBitmap(mixed[1::2].copy())
    for b in (*bestcase, ident_b, worst_a, worst_b):
        b.run_optimize()
    return {
        "bestcase": bestcase,
        "identical": (ident_b, ident_b),
        "worstcase": (worst_a, worst_b),
    }


def run(reps: int = 20, datasets=None, **_) -> List[Result]:
    results = []
    shapes = _shape_pairs(np.random.default_rng(0xFEEF1F0))
    for shape, (a, b) in shapes.items():
        for opname, op in OPS.items():
            ns = common.min_of(reps, lambda: op(a, b))
            results.append(Result(f"{opname}_{shape}", "synthetic", ns, "ns/op"))
    for ds in datasets or common.DEFAULT_DATASETS:
        bms = common.corpus_bitmaps(ds, limit=200)
        for opname in ("and", "or", "xor", "andNot"):
            op = OPS[opname]

            def all_pairs(op=op):
                for i in range(len(bms) - 1):
                    op(bms[i], bms[i + 1])

            ns = common.min_of(max(1, reps // 4), all_pairs) / max(1, len(bms) - 1)
            results.append(Result(f"pairwise_{opname}", ds, ns, "ns/op"))
    return results
