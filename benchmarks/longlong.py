"""64-bit micro-op suite — twin of the reference's jmh longlong/ and
cardinality64/ families (jmh/src/jmh/java/org/roaringbitmap/longlong/,
cardinality64/), which compare the two 64-bit designs on point ops, bulk
algebra, rank/select (the cardinality64 suite exists because
Roaring64NavigableMap caches cumulative cardinalities,
Roaring64NavigableMap.java:66-72, while the ART design recomputes), and
both wire formats.

Every pair of rows "<op>_navmap" / "<op>_art" measures the same logical
operation on Roaring64NavigableMap (high-32 bucketing) and Roaring64Bitmap
(ART, high-48 keying); outputs are asserted equal before timing.
"""

from __future__ import annotations

from typing import List

import numpy as np

from roaringbitmap_tpu.models.roaring64 import Roaring64NavigableMap
from roaringbitmap_tpu.models.roaring64art import Roaring64Bitmap

from . import common
from .common import Result

N = 80_000  # values per operand; the benchmark smoke test shrinks this


def _values(rng, n: int) -> np.ndarray:
    """64-bit values spanning many high buckets: a dense band, a sparse
    scatter across 2^40, and a cluster above 2^63 (unsigned-order edge)."""
    parts = [
        rng.integers(0, 1 << 20, size=n // 2, dtype=np.uint64),
        rng.integers(0, 1 << 40, size=n // 2, dtype=np.uint64),
        (np.uint64(1 << 63) + rng.integers(0, 1 << 18, size=n // 8, dtype=np.uint64)),
    ]
    return np.unique(np.concatenate(parts))


def run(reps: int = 10, datasets=None, **_) -> List[Result]:
    rng = np.random.default_rng(0xFEEF1F0)
    out: List[Result] = []

    def bench(name, fn, extra=None):
        out.append(Result(name, "synthetic", common.min_of(reps, fn), "ns/op", extra or {}))

    vals_a = _values(rng, N)
    vals_b = _values(np.random.default_rng(42), N)

    # --- bulk ingest
    bench("addMany_navmap", lambda: Roaring64NavigableMap.bitmap_of(*[]).add_many(vals_a))
    bench("addMany_art", lambda: Roaring64Bitmap.bitmap_of(*[]).add_many(vals_a))

    nav_a, nav_b = Roaring64NavigableMap(), Roaring64NavigableMap()
    art_a, art_b = Roaring64Bitmap(), Roaring64Bitmap()
    nav_a.add_many(vals_a), nav_b.add_many(vals_b)
    art_a.add_many(vals_a), art_b.add_many(vals_b)
    assert nav_a.get_cardinality() == art_a.get_cardinality() == vals_a.size

    # --- pairwise algebra (outputs cross-checked between designs)
    for op in ("or_", "and_", "xor", "andnot"):
        nav_res = getattr(Roaring64NavigableMap, op)(nav_a, nav_b)
        art_res = getattr(Roaring64Bitmap, op)(art_a, art_b)
        assert np.array_equal(nav_res.to_array(), art_res.to_array()), op
        bench(f"{op.rstrip('_')}_navmap", lambda op=op: getattr(Roaring64NavigableMap, op)(nav_a, nav_b))
        bench(f"{op.rstrip('_')}_art", lambda op=op: getattr(Roaring64Bitmap, op)(art_a, art_b))

    # --- point probes: bulk contains (one bucket probe per distinct high
    # key) and scalar contains
    probes = np.concatenate([vals_a[:2000], vals_b[:2000]])
    want_hits = int(np.isin(probes, vals_a).sum())
    assert int(nav_a.contains_many(probes).sum()) == want_hits
    assert int(art_a.contains_many(probes).sum()) == want_hits
    bench("containsMany_navmap", lambda: nav_a.contains_many(probes), extra={"n": probes.size})
    bench("containsMany_art", lambda: art_a.contains_many(probes), extra={"n": probes.size})
    scalar_probes = [int(v) for v in probes[:500]]
    bench("contains_x500_navmap", lambda: [nav_a.contains(v) for v in scalar_probes])
    bench("contains_x500_art", lambda: [art_a.contains(v) for v in scalar_probes])

    # --- rank/select (cardinality64 twin: navmap's cached cumulative
    # cardinalities vs the ART walk)
    card = nav_a.get_cardinality()
    rank_pts = [int(v) for v in vals_a[:: max(1, vals_a.size // 200)][:200]]
    want_ranks = [nav_a.rank(v) for v in rank_pts]
    assert [art_a.rank(v) for v in rank_pts] == want_ranks
    bench("rank_x200_navmap", lambda: [nav_a.rank(v) for v in rank_pts])
    bench("rank_x200_art", lambda: [art_a.rank(v) for v in rank_pts])
    rank_arr = np.array(rank_pts, dtype=np.uint64)
    assert nav_a.rank_many(rank_arr).tolist() == want_ranks
    assert art_a.rank_many(rank_arr).tolist() == want_ranks
    bench("rankMany_x200_navmap", lambda: nav_a.rank_many(rank_arr))
    bench("rankMany_x200_art", lambda: art_a.rank_many(rank_arr))
    sel_pts = list(range(0, card, max(1, card // 200)))[:200]
    assert [nav_a.select(j) for j in sel_pts] == [art_a.select(j) for j in sel_pts]
    bench("select_x200_navmap", lambda: [nav_a.select(j) for j in sel_pts])
    bench("select_x200_art", lambda: [art_a.select(j) for j in sel_pts])
    bench("nextValue_x200_navmap", lambda: [nav_a.next_value(v + 1) for v in rank_pts])
    bench("nextValue_x200_art", lambda: [art_a.next_value(v + 1) for v in rank_pts])

    # --- materialization + iteration
    assert np.array_equal(nav_a.to_array(), art_a.to_array())
    bench("toArray_navmap", lambda: nav_a.to_array())
    bench("toArray_art", lambda: art_a.to_array())

    def iterate_navmap():
        it = nav_a.get_long_iterator()
        return sum(1 for _ in zip(range(20_000), it))

    def iterate_art():
        it = art_a.get_long_iterator()
        return sum(1 for _ in zip(range(20_000), it))

    bench("iterate_20k_navmap", iterate_navmap)
    bench("iterate_20k_art", iterate_art)

    # --- both wire formats (legacy + portable, Roaring64NavigableMap.java:35-52)
    portable = nav_a.serialize_portable()
    legacy = nav_a.serialize_legacy()
    art_bytes = art_a.serialize()
    assert Roaring64NavigableMap.deserialize_portable(portable) == nav_a
    assert Roaring64NavigableMap.deserialize_legacy(legacy) == nav_a
    assert Roaring64Bitmap.deserialize(art_bytes) == art_a
    bench("serialize_portable_navmap", lambda: nav_a.serialize_portable(), extra={"bytes": len(portable)})
    bench("serialize_legacy_navmap", lambda: nav_a.serialize_legacy(), extra={"bytes": len(legacy)})
    bench("serialize_art", lambda: art_a.serialize(), extra={"bytes": len(art_bytes)})
    bench("deserialize_portable_navmap", lambda: Roaring64NavigableMap.deserialize_portable(portable))
    bench("deserialize_legacy_navmap", lambda: Roaring64NavigableMap.deserialize_legacy(legacy))
    bench("deserialize_art", lambda: Roaring64Bitmap.deserialize(art_bytes))
    return out
