"""BitSetUtil conversion suite — twin of jmh BitSetUtilBenchmark.java over
the real raw-bitset corpus (real-roaring-dataset/bitsets_1925630_96.gz,
format documented in its README.md:24).

Measures long[]-bitset -> RoaringBitmap conversion: the naive bit-by-bit
path vs the block-wise bulk path (BitSetUtil.bitmapOf,
BitSetUtil.java:174), plus the reverse bitmap -> long[] extraction.
"""

from __future__ import annotations

from typing import List

import numpy as np

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.models.bitset import bitmap_of_words
from roaringbitmap_tpu.utils import datasets

from . import common
from .common import Result

N_ROWS = 2000


def _rows() -> "tuple[List[np.ndarray], str]":
    if datasets.bitset_matrix_available():
        rows = datasets.fetch_bitset_matrix(limit=N_ROWS)
        ds = "bitsets_1925630_96"
    else:  # synthetic fallback keeps the suite runnable without the corpus
        rng = np.random.default_rng(0xFEEF1F0)
        rows = [
            rng.integers(0, 1 << 64, size=int(rng.integers(1, 96)), dtype=np.uint64)
            for _ in range(N_ROWS)
        ]
        ds = "synthetic-bitsets"
    return rows, ds


def run(reps: int = 5, **_) -> List[Result]:
    rows, ds = _rows()
    out: List[Result] = []

    def naive(words: np.ndarray) -> RoaringBitmap:
        bm = RoaringBitmap()
        for w_i, w in enumerate(words.tolist()):
            base = w_i << 6
            while w:
                bm.add(base + (w & -w).bit_length() - 1)
                w &= w - 1
        return bm

    def bench(name, fn):
        ns = common.min_of(reps, fn) / len(rows)
        out.append(Result(name, ds, ns, "ns/bitset", {"rows": len(rows)}))

    total_card = sum(
        int(np.unpackbits(r.view(np.uint8)).sum()) for r in rows
    )

    def via_util():
        acc = 0
        for r in rows:
            acc += bitmap_of_words(r).get_cardinality()
        assert acc == total_card

    sample = rows[: max(1, len(rows) // 10)]  # naive is ~100x slower

    def via_naive():
        for r in sample:
            naive(r).get_cardinality()

    bench("bitsetToRoaringUsingBitSetUtil", via_util)
    out.append(
        Result(
            "bitsetToRoaringBitByBit",
            ds,
            common.min_of(reps, via_naive) / len(sample),
            "ns/bitset",
            {"rows": len(sample)},
        )
    )

    from roaringbitmap_tpu.models.bitset import words_of_bitmap

    bms = [bitmap_of_words(r) for r in rows if r.size]

    def back_to_words():
        for bm in bms:
            words_of_bitmap(bm)

    bench("roaringToLongArray", back_to_words)
    return out
