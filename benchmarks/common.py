"""Shared benchmark plumbing: timing, corpus cache, result records.

Timing follows simplebenchmark.java:76-83 — take the *minimum* over a
number of repetitions (noise on a shared machine only ever adds time).
Suites report nanoseconds per operation like their jmh counterparts.
"""

from __future__ import annotations

import contextlib
import json
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.utils import datasets

DEFAULT_DATASETS = ["census1881", "census1881_srt", "uscensus2000", "wikileaks-noquotes"]


@dataclass
class Result:
    benchmark: str
    dataset: str
    value: float
    unit: str
    extra: Dict = field(default_factory=dict)

    def json(self) -> str:
        rec = {
            "benchmark": self.benchmark,
            "dataset": self.dataset,
            "value": round(self.value, 3),
            "unit": self.unit,
        }
        rec.update(self.extra)
        return json.dumps(rec)


def min_of(reps: int, fn: Callable[[], object]) -> float:
    """Best-of-reps wall time of fn() in nanoseconds."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter_ns()
        fn()
        best = min(best, time.perf_counter_ns() - t0)
    return float(best)


def fetch_device(out):
    """Force device completion by materializing results on host.

    Through the axon tunnel, ``jax.block_until_ready`` returns before the
    remote step finishes (observed: 512 MiB "reduced" in 0.03 ms = 20x HBM
    peak, impossible), so only a host fetch gives a truthful timestamp.
    Shared by bench.py and the tile sweep so the workaround lives once."""
    import jax

    return jax.tree.map(lambda x: np.asarray(x), out)


def time_device(fn, reps: int = 10) -> float:
    """Best-of-reps seconds for a device closure, compile excluded,
    completion forced via fetch_device."""
    fetch_device(fn())  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fetch_device(fn())
        best = min(best, time.perf_counter() - t0)
    return best


def steady_state_reduce(words, reduce_with_seed, k: int = 64, reps: int = 3):
    """Seconds per aggregation at steady state: ``k`` reductions run inside
    ONE jitted ``lax.scan`` so the tunnel's per-dispatch RPC latency
    (~25-75 ms, >10x the kernel itself) is amortized out of the measurement.

    ``reduce_with_seed(words, seed) -> (reduced, cards)`` must mix the
    carry-dependent uint32 ``seed`` (always zero at runtime, but opaque to
    the compiler: popcount-sum >> 31) into its input read — XLA paths XOR it
    outside (fuses into the reduction read), Pallas kernels take it as an
    SMEM operand — making the loop body carry-dependent so XLA cannot hoist
    it while leaving HBM traffic unchanged. Returns
    (seconds_per_aggregation, total_cardinality_sum) — the caller should
    check ``total == k * expected_cardinality``."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    @functools.partial(jax.jit, static_argnames=("k",))
    def multi(w, k):
        # per-iteration sums come back as scan outputs and are totalled
        # host-side in int64: an int32 carry would wrap at k*cardinality
        # >= 2^31 (each iteration's own sum is bounded by 32 bits per word
        # x the reduced row count, well inside int32)
        def body(seed, _):
            red, cards = reduce_with_seed(w, seed)
            c = cards.sum()
            return (c >> 31).astype(jnp.uint32), c

        _, cs = lax.scan(body, jnp.uint32(0), None, length=k)
        return cs

    def total_of(cs):  # fetching all k sums forces every iteration
        return int(np.asarray(cs).astype(np.int64).sum())

    total = total_of(multi(words, k))  # compile + warm + correctness
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        total_of(multi(words, k))
        best = min(best, time.perf_counter() - t0)
    return best / k, total


def steady_state_grouped(words3, op: str = "or", k: int = 64, reps: int = 3):
    """Steady-state seconds per grouped aggregation on the XLA path (the
    bench.py headline). See steady_state_reduce for the methodology."""
    from roaringbitmap_tpu.ops import device as dev

    def with_seed(w3, seed):
        return dev.grouped_reduce_with_cardinality(w3 ^ seed, op=op)

    return steady_state_reduce(words3, with_seed, k=k, reps=reps)


def steady_state_bucketed(bucket_arrs, op: str = "or", k: int = 64, reps: int = 3):
    """Steady-state seconds per aggregation over a ragged-batched working
    set (store.padded_buckets_device): all buckets reduced per iteration
    inside the one scanned jit, seed-mixed like the single-block path."""
    from roaringbitmap_tpu.ops import device as dev

    def with_seed(ws, seed):
        import jax.numpy as jnp

        cards = [dev.grouped_reduce_with_cardinality(w3 ^ seed, op=op)[1] for w3 in ws]
        all_cards = jnp.concatenate(cards)
        # same (reduced, cards) contract; the scan body only consumes cards
        return None, all_cards

    return steady_state_reduce(tuple(bucket_arrs), with_seed, k=k, reps=reps)


_corpus_cache: Dict[str, List[np.ndarray]] = {}


def corpus(name: str, limit: Optional[int] = None) -> List[np.ndarray]:
    """Bit-position arrays of a corpus (real when mounted, else seeded
    synthetic — datasets.load_or_synthesize)."""
    if name not in _corpus_cache:
        _corpus_cache[name], _ = datasets.load_or_synthesize(name)
    vals = _corpus_cache[name]
    return vals[:limit] if limit else vals


_bitmap_cache: Dict[str, List[RoaringBitmap]] = {}


def corpus_bitmaps(name: str, limit: Optional[int] = None, optimize: bool = True):
    key = f"{name}:{optimize}"
    if key not in _bitmap_cache:
        bms = [RoaringBitmap(v) for v in corpus(name)]
        if optimize:
            for b in bms:
                b.run_optimize()
        _bitmap_cache[key] = bms
    bms = _bitmap_cache[key]
    return bms[:limit] if limit else bms


@contextlib.contextmanager
def maybe_profile(enabled: bool, logdir: str = "/tmp/rb_tpu_trace"):
    """jax.profiler trace around a timed section (SURVEY.md §5 tracing)."""
    if not enabled:
        yield
        return
    import jax

    with jax.profiler.trace(logdir):
        yield
