"""Cross-format realdata comparison — the analogue of the reference's
Roaring-vs-Concise/EWAH/WAH wrappers (jmh/src/jmh/java/org/roaringbitmap/
realdata/wrapper/: each format wrapped behind one interface, then the same
wide-OR/AND workload measured across formats on the real datasets).

Concise/EWAH/WAH have no Python ports here, so the honest competitors are
the formats a Python/numpy practitioner would actually reach for:

* ``roaring``       — this framework (run-optimized), serialized bytes
* ``numpy_dense``   — one uint64 bitset word array per set spanning the
                      dataset universe (the uncompressed-bitmap baseline)
* ``sorted_array``  — one sorted uint32 array per set (4 B/value; the
                      columnar/array baseline)
* ``python_set``    — builtin set of ints (the dict-era baseline)

Per (dataset, format): storage bits/value plus wide-OR and wide-AND wall
time over the whole corpus, appended to BENCH_CPU_SWEEP.jsonl alongside
the other suites. Every format's wide-OR/AND cardinalities are asserted
equal to the roaring result before any number is reported (the
RealDataBenchmarkOrTest discipline).

Run:  python -m benchmarks.run formats --reps 3 --datasets census1881
"""

from __future__ import annotations

import sys
from typing import List

import numpy as np

from roaringbitmap_tpu.parallel.aggregation import FastAggregation

from . import common
from .common import Result

# dense bitsets for the biggest corpora would not fit comfortably in RAM on
# the bench host; cap the per-dataset dense allocation and subsample the
# corpus (recorded in the result rows) when it would exceed the budget
DENSE_BUDGET_BYTES = 1 << 30


def _suite(dataset: str, reps: int) -> List[Result]:
    corpus = [np.unique(v) for v in common.corpus(dataset)]
    universe = int(max(int(v[-1]) for v in corpus if v.size)) + 1
    n_words = (universe + 63) >> 6
    limit = len(corpus)
    if n_words * 8 * limit > DENSE_BUDGET_BYTES:
        limit = max(8, DENSE_BUDGET_BYTES // (n_words * 8))
    corpus = corpus[:limit]
    n_values = sum(int(v.size) for v in corpus)
    out: List[Result] = []

    def rec(fmt, name, value, unit="ns/op", **extra):
        out.append(
            Result(
                f"{fmt}:{name}",
                dataset,
                value,
                unit,
                {"n_bitmaps": len(corpus), "suite": "formats", **extra},
            )
        )

    # ---- roaring (the format under test) --------------------------------
    # every format's timed closure ends in the union/intersection
    # cardinality so the measured work is symmetric across formats
    bms = common.corpus_bitmaps(dataset, limit)
    want_or = FastAggregation.or_(*bms, mode="cpu").get_cardinality()
    want_and = FastAggregation.workshy_and(*bms, mode="cpu").get_cardinality()
    size_bits = 8 * sum(b.serialized_size_in_bytes() for b in bms)

    def roaring_or():
        return FastAggregation.or_(*bms, mode="cpu").get_cardinality()

    def roaring_and():
        return FastAggregation.workshy_and(*bms, mode="cpu").get_cardinality()

    rec("roaring", "bitsPerValue", size_bits / n_values, unit="bits/value")
    rec("roaring", "wideOr", common.min_of(reps, roaring_or))
    rec("roaring", "wideAnd", common.min_of(reps, roaring_and))

    # ---- numpy dense bitset ---------------------------------------------
    # filled in place: a per-bitmap list + np.stack would double the peak
    # allocation the DENSE_BUDGET_BYTES cap exists to bound
    stack = np.zeros((len(corpus), n_words), dtype=np.uint64)
    for i, v in enumerate(corpus):
        idx = v >> 6
        bit = np.uint64(1) << (v.astype(np.uint64) & np.uint64(63))
        np.bitwise_or.at(stack[i], idx, bit)

    def dense_or():
        return int(np.unpackbits(np.bitwise_or.reduce(stack, axis=0).view(np.uint8)).sum())

    def dense_and():
        return int(np.unpackbits(np.bitwise_and.reduce(stack, axis=0).view(np.uint8)).sum())

    assert dense_or() == want_or and dense_and() == want_and, (dataset, "dense")
    rec("numpy_dense", "bitsPerValue", 64.0 * n_words * len(corpus) / n_values, unit="bits/value")
    rec("numpy_dense", "wideOr", common.min_of(reps, dense_or))
    rec("numpy_dense", "wideAnd", common.min_of(reps, dense_and))
    del stack

    # ---- sorted uint32 array --------------------------------------------
    arrays = [v.astype(np.uint32) for v in corpus]

    def arr_or():
        return int(np.unique(np.concatenate(arrays)).size)

    def arr_and():
        acc = arrays[0]
        for a in arrays[1:]:
            acc = acc[np.isin(acc, a, assume_unique=True)]
            if not acc.size:
                break
        return int(acc.size)

    assert arr_or() == want_or and arr_and() == want_and, (dataset, "sorted_array")
    rec("sorted_array", "bitsPerValue", 32.0, unit="bits/value")
    rec("sorted_array", "wideOr", common.min_of(reps, arr_or))
    rec("sorted_array", "wideAnd", common.min_of(reps, arr_and))

    # ---- builtin set -----------------------------------------------------
    sets = [set(v.tolist()) for v in corpus]

    def set_or():
        return len(set().union(*sets))

    def set_and():
        return len(set.intersection(*sets))

    assert set_or() == want_or and set_and() == want_and, (dataset, "python_set")
    # storage estimate: the set's own table plus one boxed int per element
    set_bits = 8 * sum(
        sys.getsizeof(s) + sum(sys.getsizeof(x) for x in list(s)[:64]) * len(s) // max(1, min(len(s), 64))
        for s in sets
    )
    rec("python_set", "bitsPerValue", set_bits / n_values, unit="bits/value")
    rec("python_set", "wideOr", common.min_of(reps, set_or))
    rec("python_set", "wideAnd", common.min_of(reps, set_and))
    return out


def run(reps: int = 3, datasets=None, **_) -> List[Result]:
    results = []
    for ds in datasets or common.DEFAULT_DATASETS:
        results.extend(_suite(ds, reps))
    return results
