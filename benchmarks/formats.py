"""Cross-format realdata comparison — the analogue of the reference's
Roaring-vs-Concise/EWAH/WAH wrappers (jmh/src/jmh/java/org/roaringbitmap/
realdata/wrapper/BitmapFactory.java:1: each format wrapped behind one
interface, then the same workload measured across formats on the real
datasets).

Formats compared:

* ``roaring``       — this framework (run-optimized), serialized bytes
* ``wah``           — Word-Aligned Hybrid, 32-bit words / 31-bit payload
                      (the compressed-bitmap incumbent the reference's
                      README headline is measured against), implemented
                      below from the algorithm
* ``ewah``          — Enhanced WAH, 64-bit words with RLW markers (the
                      second wrapper format), implemented below
* ``numpy_dense``   — one uint64 bitset word array per set spanning the
                      dataset universe (the uncompressed-bitmap baseline)
* ``sorted_array``  — one sorted uint32 array per set (4 B/value; the
                      columnar/array baseline)
* ``python_set``    — builtin set of ints (the dict-era baseline)

Per (dataset, format): storage bits/value plus wide-OR, wide-AND, and a
``contains`` sweep (one shared ~32·N-value probe set tested against
every bitmap) over the whole corpus, appended to
BENCH_CPU_SWEEP.jsonl alongside the other suites. Every format's
wide-OR/AND cardinalities (and contains hit counts) are asserted equal to
the roaring result before any number is reported (the
RealDataBenchmarkOrTest discipline). The WAH/EWAH folds get their best
vectorized shot — np.repeat run expansion into a reusable accumulator,
not word-at-a-time Python — and their ``contains`` pays the linear
marker scan the formats structurally require (no random access), which
is exactly the asymmetry the reference's headline claim rests on.

Run:  python -m benchmarks.run formats --reps 3 --datasets census1881
"""

from __future__ import annotations

import sys
from typing import List

import numpy as np

from roaringbitmap_tpu.parallel.aggregation import FastAggregation

from . import common
from .common import Result

# dense bitsets for the biggest corpora would not fit comfortably in RAM on
# the bench host; cap the per-dataset dense allocation and subsample the
# corpus (recorded in the result rows) when it would exceed the budget
DENSE_BUDGET_BYTES = 1 << 30

# ---------------------------------------------------------------------------
# WAH — Word-Aligned Hybrid (Wu/Otoo/Shoshani), 32-bit words, 31-bit payload.
# Word forms: MSB clear -> literal (31 payload bits); MSB set -> fill:
# bit 30 = fill bit, bits 0-29 = run length in 31-bit groups.
# ---------------------------------------------------------------------------
_WAH_PAYLOAD = 31
_WAH_FULL = np.uint32((1 << 31) - 1)
_WAH_FILL_FLAG = np.uint32(1 << 31)
_WAH_FILL_ONE = np.uint32(1 << 30)


def _dense_groups(values: np.ndarray, n_groups: int, payload: int, dtype) -> np.ndarray:
    """Pack sorted values into dense payload-bit groups (the encoder input)."""
    out = np.zeros(n_groups, dtype=dtype)
    if values.size:
        idx = values // payload
        bit = dtype(1) << (values % payload).astype(dtype)
        np.bitwise_or.at(out, idx, bit)
    return out


def _runs(flags: np.ndarray):
    """(start, length) of maximal equal-value runs of a 1-D array."""
    bounds = np.flatnonzero(np.diff(flags)) + 1
    starts = np.concatenate(([0], bounds))
    lengths = np.diff(np.concatenate((starts, [len(flags)])))
    return starts, lengths


def wah_encode(values: np.ndarray, n_groups: int) -> np.ndarray:
    """Compress sorted uint32 values into a WAH uint32 stream (vectorized:
    runs classified once, fills and literal blocks scattered into the
    output by offset arithmetic — no per-word Python loop)."""
    groups = _dense_groups(values, n_groups, _WAH_PAYLOAD, np.uint32)
    if not n_groups:
        return np.empty(0, dtype=np.uint32)
    # classify each group: 0 = zero-fill, 1 = one-fill, 2 = literal
    cls = np.full(n_groups, 2, dtype=np.int8)
    cls[groups == 0] = 0
    cls[groups == _WAH_FULL] = 1
    starts, lengths = _runs(cls)
    kinds = cls[starts]
    assert int(lengths.max(initial=0)) < (1 << 30), "fill run overflows WAH length"
    out_len = np.where(kinds == 2, lengths, 1)
    offsets = np.concatenate(([0], np.cumsum(out_len)))
    out = np.empty(int(offsets[-1]), dtype=np.uint32)
    fill = kinds != 2
    if fill.any():
        out[offsets[:-1][fill]] = (
            _WAH_FILL_FLAG
            | np.where(kinds[fill] == 1, _WAH_FILL_ONE, np.uint32(0))
            | lengths[fill].astype(np.uint32)
        )
    lit = ~fill
    if lit.any():
        dst = np.concatenate(
            [np.arange(o, o + n) for o, n in zip(offsets[:-1][lit], lengths[lit])]
        )
        src = np.concatenate(
            [np.arange(s, s + n) for s, n in zip(starts[lit], lengths[lit])]
        )
        out[dst] = groups[src]
    return out


def wah_decode_into(stream: np.ndarray, acc: np.ndarray, op) -> None:
    """Expand a WAH stream and fold it into ``acc`` (31-bit groups) with
    ``op`` — one np.repeat does the whole run expansion."""
    is_fill = (stream & _WAH_FILL_FLAG) != 0
    lengths = np.where(is_fill, stream & np.uint32((1 << 30) - 1), 1).astype(np.int64)
    vals = np.where(
        is_fill,
        np.where((stream & _WAH_FILL_ONE) != 0, _WAH_FULL, np.uint32(0)),
        stream & _WAH_FULL,
    )
    op(acc, np.repeat(vals, lengths), out=acc)


def wah_contains_many(stream: np.ndarray, probes: np.ndarray) -> np.ndarray:
    """Membership for sorted probe values. WAH has no random access: the
    linear pass over the compressed words to recover group offsets is the
    format's structural query cost (then one searchsorted per batch)."""
    is_fill = (stream & _WAH_FILL_FLAG) != 0
    lengths = np.where(is_fill, stream & np.uint32((1 << 30) - 1), 1).astype(np.int64)
    ends = np.cumsum(lengths)  # group index one past each entry
    g = probes // _WAH_PAYLOAD
    entry = np.searchsorted(ends, g, side="right")
    hit = entry < len(stream)
    entry = np.minimum(entry, len(stream) - 1 if len(stream) else 0)
    w = stream[entry]
    f = is_fill[entry]
    bit = np.uint32(1) << (probes % _WAH_PAYLOAD).astype(np.uint32)
    lit_hit = (w & bit) != 0
    fill_hit = (w & _WAH_FILL_ONE) != 0
    return hit & np.where(f, fill_hit, lit_hit)


# ---------------------------------------------------------------------------
# EWAH — Enhanced WAH (Lemire/Kaser/Aouiche), 64-bit words. The stream is a
# sequence of (RLW marker, literal words...): marker bit 0 = clean-run bit,
# bits 1-32 = clean-run length in words, bits 33-63 = literal word count.
# ---------------------------------------------------------------------------
_EWAH_FULL = np.uint64(0xFFFFFFFFFFFFFFFF)


def ewah_encode(values: np.ndarray, n_words: int) -> np.ndarray:
    """Compress sorted values into an EWAH uint64 stream. Run detection is
    vectorized; emission walks the (clean-run, literal-run) pairs — a few
    entries per container's worth of data, not per word."""
    words = _dense_groups(values, n_words, 64, np.uint64)
    if not n_words:
        return np.empty(0, dtype=np.uint64)
    cls = np.full(n_words, 2, dtype=np.int8)
    cls[words == 0] = 0
    cls[words == _EWAH_FULL] = 1
    starts, lengths = _runs(cls)
    kinds = cls[starts]
    out: List[np.ndarray] = []
    i, n = 0, len(kinds)
    while i < n:
        run_bit, run_len = 0, 0
        if kinds[i] != 2:
            run_bit, run_len = int(kinds[i]), int(lengths[i])
            i += 1
        lit = np.empty(0, dtype=np.uint64)
        if i < n and kinds[i] == 2:
            s, l = int(starts[i]), int(lengths[i])
            lit = words[s : s + l]
            i += 1
        assert run_len < (1 << 32) and len(lit) < (1 << 31)
        marker = np.uint64(run_bit) | np.uint64(run_len << 1) | np.uint64(len(lit) << 33)
        out.append(np.array([marker], dtype=np.uint64))
        out.append(lit)
    return np.concatenate(out) if out else np.empty(0, dtype=np.uint64)


def _ewah_segments(stream: np.ndarray):
    """Yield (run_bit, run_len, literal_slice) per RLW. The marker chain is
    sequential by construction — each marker's position depends on the
    previous literal count — so this scan is the format's decode cost."""
    pos, n = 0, len(stream)
    while pos < n:
        marker = int(stream[pos])
        run_bit = marker & 1
        run_len = (marker >> 1) & 0xFFFFFFFF
        n_lit = marker >> 33
        yield run_bit, run_len, stream[pos + 1 : pos + 1 + n_lit]
        pos += 1 + n_lit


def ewah_decode_into(stream: np.ndarray, acc: np.ndarray, op) -> None:
    """Expand an EWAH stream into ``acc`` (uint64 words) with ``op``."""
    pieces = []
    for run_bit, run_len, lit in _ewah_segments(stream):
        if run_len:
            pieces.append(
                np.full(run_len, _EWAH_FULL if run_bit else np.uint64(0))
            )
        if len(lit):
            pieces.append(lit)
    dense = np.concatenate(pieces) if pieces else np.empty(0, dtype=np.uint64)
    op(acc[: len(dense)], dense, out=acc[: len(dense)])
    if op is np.bitwise_and and len(dense) < len(acc):
        acc[len(dense):] = 0


def ewah_contains_many(stream: np.ndarray, probes: np.ndarray) -> np.ndarray:
    """Membership via the sequential marker scan + one searchsorted batch."""
    ends, vals = [], []
    total = 0
    for run_bit, run_len, lit in _ewah_segments(stream):
        if run_len:
            total += run_len
            ends.append(total)
            vals.append(_EWAH_FULL if run_bit else np.uint64(0))
        for w in lit:
            total += 1
            ends.append(total)
            vals.append(w)
    if not ends:
        return np.zeros(len(probes), dtype=bool)
    ends_a = np.asarray(ends, dtype=np.int64)
    vals_a = np.asarray(vals, dtype=np.uint64)
    g = probes >> 6
    entry = np.searchsorted(ends_a, g, side="right")
    hit = entry < len(ends_a)
    entry = np.minimum(entry, len(ends_a) - 1)
    bit = np.uint64(1) << (probes & 63).astype(np.uint64)
    return hit & ((vals_a[entry] & bit) != 0)


def _suite(dataset: str, reps: int) -> List[Result]:
    corpus = [np.unique(v) for v in common.corpus(dataset)]
    universe = int(max(int(v[-1]) for v in corpus if v.size)) + 1
    n_words = (universe + 63) >> 6
    limit = len(corpus)
    if n_words * 8 * limit > DENSE_BUDGET_BYTES:
        limit = max(8, DENSE_BUDGET_BYTES // (n_words * 8))
    corpus = corpus[:limit]
    n_values = sum(int(v.size) for v in corpus)
    out: List[Result] = []

    def rec(fmt, name, value, unit="ns/op", **extra):
        out.append(
            Result(
                f"{fmt}:{name}",
                dataset,
                value,
                unit,
                {"n_bitmaps": len(corpus), "suite": "formats", **extra},
            )
        )

    # shared contains workload: ONE global probe set of ~32·N values (half
    # drawn from the corpus, half uniform — the RealDataBenchmarkContains
    # mix), probed in full against EVERY bitmap, so each contains row
    # measures N·|probes| membership tests (n_probes recorded per row);
    # same probes for every format, and each format reports total hits for
    # the cross-format equality assert
    rng = np.random.default_rng(0xC0FFEE)
    probe_pool = np.unique(
        np.concatenate(
            [
                rng.choice(np.concatenate(corpus[:8]), 16 * len(corpus)),
                rng.integers(0, universe, 16 * len(corpus), dtype=np.uint64).astype(
                    corpus[0].dtype if corpus else np.uint32
                ),
            ]
        )
    )
    probes = np.sort(rng.choice(probe_pool, min(32 * len(corpus), probe_pool.size), replace=False))

    # ---- roaring (the format under test) --------------------------------
    # every format's timed closure ends in the union/intersection
    # cardinality so the measured work is symmetric across formats
    bms = common.corpus_bitmaps(dataset, limit)
    want_or = FastAggregation.or_(*bms, mode="cpu").get_cardinality()
    want_and = FastAggregation.workshy_and(*bms, mode="cpu").get_cardinality()
    size_bits = 8 * sum(b.serialized_size_in_bytes() for b in bms)

    def roaring_or():
        return FastAggregation.or_(*bms, mode="cpu").get_cardinality()

    def roaring_and():
        return FastAggregation.workshy_and(*bms, mode="cpu").get_cardinality()

    def roaring_contains():
        return sum(int(b.contains_many(probes).sum()) for b in bms)

    want_contains = roaring_contains()
    rec("roaring", "bitsPerValue", size_bits / n_values, unit="bits/value")
    rec("roaring", "wideOr", common.min_of(reps, roaring_or))
    rec("roaring", "wideAnd", common.min_of(reps, roaring_and))
    rec("roaring", "contains", common.min_of(reps, roaring_contains), n_probes=int(probes.size))

    # ---- WAH / EWAH (the reference headline's competitors) ---------------
    n_groups = (universe + _WAH_PAYLOAD - 1) // _WAH_PAYLOAD
    wah_streams = [wah_encode(v, n_groups) for v in corpus]
    ewah_streams = [ewah_encode(v, n_words) for v in corpus]

    def _wah_fold(op, init):
        acc = np.full(n_groups, init, dtype=np.uint32)
        for s in wah_streams:
            wah_decode_into(s, acc, op)
        return int(np.unpackbits(acc.view(np.uint8)).sum())

    def wah_or():
        return _wah_fold(np.bitwise_or, 0)

    def wah_and():
        return _wah_fold(np.bitwise_and, _WAH_FULL)

    def wah_contains():
        return sum(int(wah_contains_many(s, probes).sum()) for s in wah_streams)

    assert wah_or() == want_or and wah_and() == want_and, (dataset, "wah")
    assert wah_contains() == want_contains, (dataset, "wah contains")
    wah_bits = 32.0 * sum(s.size for s in wah_streams)
    rec("wah", "bitsPerValue", wah_bits / n_values, unit="bits/value")
    rec("wah", "wideOr", common.min_of(reps, wah_or))
    rec("wah", "wideAnd", common.min_of(reps, wah_and))
    rec("wah", "contains", common.min_of(reps, wah_contains), n_probes=int(probes.size))

    def _ewah_fold(op, init):
        acc = np.full(n_words, init, dtype=np.uint64)
        for s in ewah_streams:
            ewah_decode_into(s, acc, op)
        return int(np.unpackbits(acc.view(np.uint8)).sum())

    def ewah_or():
        return _ewah_fold(np.bitwise_or, np.uint64(0))

    def ewah_and():
        return _ewah_fold(np.bitwise_and, _EWAH_FULL)

    def ewah_contains():
        return sum(int(ewah_contains_many(s, probes).sum()) for s in ewah_streams)

    assert ewah_or() == want_or and ewah_and() == want_and, (dataset, "ewah")
    assert ewah_contains() == want_contains, (dataset, "ewah contains")
    ewah_bits = 64.0 * sum(s.size for s in ewah_streams)
    rec("ewah", "bitsPerValue", ewah_bits / n_values, unit="bits/value")
    rec("ewah", "wideOr", common.min_of(reps, ewah_or))
    rec("ewah", "wideAnd", common.min_of(reps, ewah_and))
    rec("ewah", "contains", common.min_of(reps, ewah_contains), n_probes=int(probes.size))
    del wah_streams, ewah_streams

    # ---- numpy dense bitset ---------------------------------------------
    # filled in place: a per-bitmap list + np.stack would double the peak
    # allocation the DENSE_BUDGET_BYTES cap exists to bound
    stack = np.zeros((len(corpus), n_words), dtype=np.uint64)
    for i, v in enumerate(corpus):
        idx = v >> 6
        bit = np.uint64(1) << (v.astype(np.uint64) & np.uint64(63))
        np.bitwise_or.at(stack[i], idx, bit)

    def dense_or():
        return int(np.unpackbits(np.bitwise_or.reduce(stack, axis=0).view(np.uint8)).sum())

    def dense_and():
        return int(np.unpackbits(np.bitwise_and.reduce(stack, axis=0).view(np.uint8)).sum())

    def dense_contains():
        bit = np.uint64(1) << (probes & np.uint64(63) if probes.dtype == np.uint64 else (probes & 63).astype(np.uint64))
        return int(((stack[:, probes >> 6] & bit) != 0).sum())

    assert dense_or() == want_or and dense_and() == want_and, (dataset, "dense")
    assert dense_contains() == want_contains, (dataset, "dense contains")
    rec("numpy_dense", "bitsPerValue", 64.0 * n_words * len(corpus) / n_values, unit="bits/value")
    rec("numpy_dense", "wideOr", common.min_of(reps, dense_or))
    rec("numpy_dense", "wideAnd", common.min_of(reps, dense_and))
    rec("numpy_dense", "contains", common.min_of(reps, dense_contains), n_probes=int(probes.size))
    del stack

    # ---- sorted uint32 array --------------------------------------------
    arrays = [v.astype(np.uint32) for v in corpus]

    def arr_or():
        return int(np.unique(np.concatenate(arrays)).size)

    def arr_and():
        acc = arrays[0]
        for a in arrays[1:]:
            acc = acc[np.isin(acc, a, assume_unique=True)]
            if not acc.size:
                break
        return int(acc.size)

    def arr_contains():
        hits = 0
        for a in arrays:
            pos = np.searchsorted(a, probes)
            ok = pos < a.size
            hits += int((a[np.minimum(pos, a.size - 1)][ok] == probes[ok]).sum()) if a.size else 0
        return hits

    assert arr_or() == want_or and arr_and() == want_and, (dataset, "sorted_array")
    assert arr_contains() == want_contains, (dataset, "sorted_array contains")
    rec("sorted_array", "bitsPerValue", 32.0, unit="bits/value")
    rec("sorted_array", "wideOr", common.min_of(reps, arr_or))
    rec("sorted_array", "wideAnd", common.min_of(reps, arr_and))
    rec("sorted_array", "contains", common.min_of(reps, arr_contains), n_probes=int(probes.size))

    # ---- builtin set -----------------------------------------------------
    sets = [set(v.tolist()) for v in corpus]

    def set_or():
        return len(set().union(*sets))

    def set_and():
        return len(set.intersection(*sets))

    def set_contains():
        pl = probes.tolist()
        return sum(sum(1 for x in pl if x in s) for s in sets)

    assert set_or() == want_or and set_and() == want_and, (dataset, "python_set")
    assert set_contains() == want_contains, (dataset, "python_set contains")
    # storage estimate: the set's own table plus one boxed int per element
    set_bits = 8 * sum(
        sys.getsizeof(s) + sum(sys.getsizeof(x) for x in list(s)[:64]) * len(s) // max(1, min(len(s), 64))
        for s in sets
    )
    rec("python_set", "bitsPerValue", set_bits / n_values, unit="bits/value")
    rec("python_set", "wideOr", common.min_of(reps, set_or))
    rec("python_set", "wideAnd", common.min_of(reps, set_and))
    rec("python_set", "contains", common.min_of(reps, set_contains), n_probes=int(probes.size))
    return out


def run(reps: int = 3, datasets=None, **_) -> List[Result]:
    results = []
    for ds in datasets or common.DEFAULT_DATASETS:
        results.extend(_suite(ds, reps))
    return results
