"""Serialization suites — twin of jmh serialization benchmarks
(jmh/src/jmh/.../serialization/: SerializationBenchmark,
DeserializationBenchmark over portable-format bytes) plus the zero-copy
ImmutableRoaringBitmap map path (buffer package, SURVEY.md §3.4).

Reports ns/op and MB/s over a whole corpus, plus bits per value
(the compression headline the papers report).
"""

from __future__ import annotations

from typing import List

from roaringbitmap_tpu.models.immutable import ImmutableRoaringBitmap
from roaringbitmap_tpu import RoaringBitmap

from . import common
from .common import Result


def run(reps: int = 5, datasets=None, **_) -> List[Result]:
    results = []
    for ds in datasets or common.DEFAULT_DATASETS:
        bms = common.corpus_bitmaps(ds)
        blobs = [b.serialize() for b in bms]
        total_bytes = sum(len(x) for x in blobs)
        total_vals = sum(b.get_cardinality() for b in bms)

        ns = common.min_of(reps, lambda: [b.serialize() for b in bms])
        results.append(
            Result(
                "serialize",
                ds,
                ns / len(bms),
                "ns/op",
                {"mb_per_s": round(total_bytes / max(ns, 1) * 1e3, 1)},
            )
        )
        ns = common.min_of(reps, lambda: [RoaringBitmap.deserialize(x) for x in blobs])
        results.append(
            Result(
                "deserialize",
                ds,
                ns / len(bms),
                "ns/op",
                {"mb_per_s": round(total_bytes / max(ns, 1) * 1e3, 1)},
            )
        )
        # zero-copy map: parse metadata only, containers stay buffer views
        ns = common.min_of(reps, lambda: [ImmutableRoaringBitmap(x) for x in blobs])
        results.append(Result("mapImmutable", ds, ns / len(bms), "ns/op"))

        # query THROUGH the mapped form (jmh map/ suite: mapped operands in
        # pairwise algebra + point probes, no materialization)
        mapped = [ImmutableRoaringBitmap(x) for x in blobs]

        def mapped_pairwise():
            for i in range(len(mapped) - 1):
                RoaringBitmap.and_(mapped[i], mapped[i + 1])

        ns = common.min_of(max(1, reps // 2), mapped_pairwise) / max(1, len(mapped) - 1)
        results.append(Result("mappedPairwiseAnd", ds, ns, "ns/op"))

        probes = [int(b.first()) for b in bms[:200]]

        def mapped_contains():
            for m, p in zip(mapped, probes):
                m.contains(p)

        ns = common.min_of(reps, mapped_contains) / max(1, len(probes))
        results.append(Result("mappedContains", ds, ns, "ns/op"))
        results.append(
            Result(
                "bitsPerValue",
                ds,
                total_bytes * 8.0 / max(1, total_vals),
                "bits/value",
                {"bytes": total_bytes, "values": total_vals},
            )
        )
    return results
