"""ART scale benchmark — memory/lookup behavior at >= 10M keys
(VERDICT r2 #8: prove the two-representation adaptive design holds where
the reference uses four node classes, art/Node4|16|48|256.java).

The trie's physical forms: sorted byte-array + child list for <= 48
children (covering the reference's Node4/16/48 widths) and a 256-slot
dispatch table beyond (Node256), with upgrade at 48 and downgrade at 36.
This suite inserts >= 10M distinct high-48-bit keys in three distributions
(sequential, random, clustered), then reports insert ns/key, hit and miss
lookup ns, ordered-walk ns/key, tracemalloc bytes/key, and the node-width
histogram so the adaptivity is visible in the numbers.
"""

from __future__ import annotations

import time
import tracemalloc
from typing import List

import numpy as np

from roaringbitmap_tpu.models.art import Art

from .common import Result

N_KEYS = 10_000_000


def _keys(dist: str, n: int) -> np.ndarray:
    rng = np.random.default_rng(0xFEEF1F0)
    if dist == "sequential":
        vals = np.arange(n, dtype=np.uint64)
    elif dist == "random":
        vals = rng.choice(np.uint64(1) << np.uint64(48), size=n, replace=False).astype(
            np.uint64
        )
    else:  # clustered: 4096 dense islands
        base = (rng.choice(1 << 24, size=4096, replace=False).astype(np.uint64)) << np.uint64(24)
        per = n // 4096
        vals = (base[:, None] + np.arange(per, dtype=np.uint64)[None, :]).ravel()[:n]
    return vals


def _key_bytes(vals: np.ndarray) -> List[bytes]:
    # 6 big-endian bytes of the high-48 value (LongUtils high48 split)
    raw = vals.astype(">u8").tobytes()
    return [raw[i * 8 + 2 : i * 8 + 8] for i in range(len(vals))]


def run(reps: int = 1, n_keys: int = N_KEYS, **_) -> List[Result]:
    out: List[Result] = []
    for dist in ("sequential", "random", "clustered"):
        vals = _keys(dist, n_keys)
        kb = _key_bytes(vals)
        n_eff = len(kb)  # clustered may round down to a multiple of 4096

        tracemalloc.start()
        base = tracemalloc.get_traced_memory()[0]
        art = Art()
        t0 = time.perf_counter_ns()
        for i, k in enumerate(kb):
            art.insert(k, i)
        insert_ns = (time.perf_counter_ns() - t0) / n_eff
        mem = tracemalloc.get_traced_memory()[0] - base
        tracemalloc.stop()

        rng = np.random.default_rng(7)
        probe_idx = rng.integers(0, n_eff, size=100_000)
        probes = [kb[i] for i in probe_idx]
        t0 = time.perf_counter_ns()
        for p in probes:
            art.find(p)
        hit_ns = (time.perf_counter_ns() - t0) / len(probes)

        miss_probes = [bytes(np.random.default_rng(int(i)).integers(0, 256, 6, dtype=np.uint8)) for i in range(20_000)]
        t0 = time.perf_counter_ns()
        for p in miss_probes:
            art.find(p)
        miss_ns = (time.perf_counter_ns() - t0) / len(miss_probes)

        t0 = time.perf_counter_ns()
        n_walked = sum(1 for _ in art.items())
        walk_ns = (time.perf_counter_ns() - t0) / max(1, n_walked)
        assert n_walked == len(art)

        # backward shuttle at scale (art/BackwardShuttle.java:1): timing
        # untraced (tracemalloc hooks every yielded tuple and would inflate
        # the ns/key 1.3-2x vs the untraced forward number), then a second
        # traced pass for the O(depth) live-memory bound
        t0 = time.perf_counter_ns()
        n_rev = sum(1 for _ in art.items_reverse())
        rev_ns = (time.perf_counter_ns() - t0) / max(1, n_rev)
        assert n_rev == n_walked
        tracemalloc.start()
        for _ in art.items_reverse():
            pass
        rev_peak = tracemalloc.get_traced_memory()[1]
        tracemalloc.stop()

        hist = art.node_width_histogram()
        extra = {
            "n_keys": n_eff,
            "insert_ns_per_key": round(insert_ns, 1),
            "hit_ns": round(hit_ns, 1),
            "miss_ns": round(miss_ns, 1),
            "walk_ns_per_key": round(walk_ns, 1),
            "reverse_walk_ns_per_key": round(rev_ns, 1),
            "reverse_walk_peak_bytes": int(rev_peak),
            "node_width_histogram": {str(k): v for k, v in hist.items()},
        }
        out.append(Result("artScale_bytesPerKey", f"dist-{dist}", mem / n_eff, "bytes/key", extra))
        del art, kb
    return out


if __name__ == "__main__":
    import sys

    n = int(sys.argv[1]) if len(sys.argv) > 1 else N_KEYS
    for r in run(n_keys=n):
        print(r.json(), flush=True)
