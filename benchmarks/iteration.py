"""Iteration suites — twin of jmh iteration benchmarks
(jmh/src/jmh/.../iteration/: IteratorsBenchmark, BatchIteratorsBenchmark,
advance/rank iterator suites over realdata).

Measures full forward walk, reverse walk, batch (buffer-filling) walk, and
to_array bulk extraction, reported as ns per value.
"""

from __future__ import annotations

from typing import List

from . import common
from .common import Result


def _shape_bitmaps():
    """One bitmap per container shape (BasicIteratorBenchmark's run/array/
    bitmap split)."""
    import numpy as np

    from roaringbitmap_tpu import RoaringBitmap

    rng = np.random.default_rng(0xFEEF1F0)
    run_bm = RoaringBitmap(
        np.concatenate(
            [np.arange(s, s + 3000, dtype=np.uint32) for s in range(0, 1 << 20, 1 << 17)]
        )
    )
    run_bm.run_optimize()
    arr_bm = RoaringBitmap(rng.choice(1 << 22, size=30_000, replace=False).astype(np.uint32))
    dense_bm = RoaringBitmap(np.flatnonzero(rng.random(1 << 19) < 0.4).astype(np.uint32))
    return {"run": run_bm, "array": arr_bm, "bitmap": dense_bm}


def run(reps: int = 3, datasets=None, **_) -> List[Result]:
    results = []

    # per-container-shape walks + advanceIfNeeded skip iteration
    # (AdvanceIfNeededBenchmark)
    for shape, bm in _shape_bitmaps().items():
        card = bm.get_cardinality()

        def walk(bm=bm):
            it = bm.get_int_iterator()
            while it.has_next():
                it.next()

        results.append(
            Result("intIterator", f"shape-{shape}", common.min_of(reps, walk) / card, "ns/value")
        )

        import numpy as np

        last = bm.last()
        buf = np.empty(256, dtype=np.uint32)
        step = max(1, last // 64)
        targets = range(0, last, step)

        def skip_walk(bm=bm, buf=buf, targets=targets):
            it = bm.get_batch_iterator()
            for target in targets:
                it.advance_if_needed(target)
                if it.has_next():
                    it.next_batch(buf)

        results.append(
            Result(
                "advanceIfNeeded",
                f"shape-{shape}",
                common.min_of(reps, skip_walk) / len(targets),
                "ns/skip",
            )
        )

    for ds in datasets or common.DEFAULT_DATASETS:
        bms = common.corpus_bitmaps(ds, limit=100)
        total = sum(b.get_cardinality() for b in bms)

        def walk_int():
            n = 0
            for b in bms:
                it = b.get_int_iterator()
                while it.has_next():
                    it.next()
                    n += 1
            return n

        def walk_reverse():
            for b in bms:
                it = b.get_reverse_int_iterator()
                while it.has_next():
                    it.next()

        def walk_batch():
            for b in bms:
                for _batch in b.batch_iterator(256):
                    pass

        def walk_array():
            for b in bms:
                b.to_array()

        for name, fn in [
            ("intIterator", walk_int),
            ("reverseIterator", walk_reverse),
            ("batchIterator", walk_batch),
            ("toArray", walk_array),
        ]:
            ns = common.min_of(reps, fn) / max(1, total)
            results.append(Result(name, ds, ns, "ns/value", {"values": total}))
    return results
