"""Iteration suites — twin of jmh iteration benchmarks
(jmh/src/jmh/.../iteration/: IteratorsBenchmark, BatchIteratorsBenchmark,
advance/rank iterator suites over realdata).

Measures full forward walk, reverse walk, batch (buffer-filling) walk, and
to_array bulk extraction, reported as ns per value.
"""

from __future__ import annotations

from typing import List

from . import common
from .common import Result


def run(reps: int = 3, datasets=None, **_) -> List[Result]:
    results = []
    for ds in datasets or ["census1881"]:
        bms = common.corpus_bitmaps(ds, limit=100)
        total = sum(b.get_cardinality() for b in bms)

        def walk_int():
            n = 0
            for b in bms:
                it = b.get_int_iterator()
                while it.has_next():
                    it.next()
                    n += 1
            return n

        def walk_reverse():
            for b in bms:
                it = b.get_reverse_int_iterator()
                while it.has_next():
                    it.next()

        def walk_batch():
            for b in bms:
                for _batch in b.batch_iterator(256):
                    pass

        def walk_array():
            for b in bms:
                b.to_array()

        for name, fn in [
            ("intIterator", walk_int),
            ("reverseIterator", walk_reverse),
            ("batchIterator", walk_batch),
            ("toArray", walk_array),
        ]:
            ns = common.min_of(reps, fn) / max(1, total)
            results.append(Result(name, ds, ns, "ns/value", {"values": total}))
    return results
