"""simplebenchmark twin — dependency-free timing harness over the real
datasets (reference simplebenchmark/src/main/java/simplebenchmark.java:52-112).

For each corpus, for both the heap (`RoaringBitmap`) and buffer
(`ImmutableRoaringBitmap`, zero-copy over serialized bytes) variants,
reports exactly what the reference reports:

  bits/value · successive 2-by-2 AND ns · 2-by-2 OR ns · wide OR ns ·
  contains(present value) ns

using the minimum over ``reps`` repetitions (the reference uses 100).

Run standalone: ``python -m benchmarks.simplebenchmark [--reps N]``.
"""

from __future__ import annotations

import sys
from typing import List

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.models.buffer import BufferFastAggregation
from roaringbitmap_tpu.models.immutable import ImmutableRoaringBitmap
from roaringbitmap_tpu.parallel.aggregation import FastAggregation

from . import common
from .common import Result


def _variant_suite(name: str, dataset: str, bms, wide_or, reps: int) -> List[Result]:
    and_ = type(bms[0]).and_ if hasattr(type(bms[0]), "and_") else RoaringBitmap.and_
    or_ = type(bms[0]).or_ if hasattr(type(bms[0]), "or_") else RoaringBitmap.or_
    pairs = list(zip(bms[:-1], bms[1:]))
    probes = [(b, b.first()) for b in bms[:200]]
    out = []

    def bench(metric, fn, per):
        ns = common.min_of(reps, fn) / max(1, per)
        out.append(Result(f"{name}_{metric}", dataset, ns, "ns/op"))

    bench("and2by2", lambda: [and_(a, b) for a, b in pairs], len(pairs))
    bench("or2by2", lambda: [or_(a, b) for a, b in pairs], len(pairs))
    bench("wideOr", wide_or, 1)
    bench("contains", lambda: [b.contains(v) for b, v in probes], len(probes))
    return out


def run(reps: int = 20, datasets=None, **_) -> List[Result]:
    results = []
    for ds in datasets or common.DEFAULT_DATASETS:
        heap = common.corpus_bitmaps(ds)
        blobs = [b.serialize() for b in heap]
        buffer = [ImmutableRoaringBitmap(x) for x in blobs]
        total_bits = sum(len(x) * 8 for x in blobs)
        total_vals = sum(b.get_cardinality() for b in heap)
        results.append(
            Result("bitsPerValue", ds, total_bits / max(1, total_vals), "bits/value")
        )
        results.extend(
            _variant_suite("heap", ds, heap, lambda: FastAggregation.naive_or(*heap), reps)
        )
        results.extend(
            _variant_suite(
                "buffer", ds, buffer, lambda: BufferFastAggregation.or_(*buffer), reps
            )
        )
    return results


if __name__ == "__main__":
    reps = int(sys.argv[sys.argv.index("--reps") + 1]) if "--reps" in sys.argv else 20
    for r in run(reps=reps):
        print(r.json())
