"""L0 word/array kernel suite — twin of the reference's bithacking/ and
UtilBenchmark families (jmh/src/jmh/java/org/roaringbitmap/bithacking/,
UtilBenchmark.java), which time the static Util.java kernels the whole
library stands on (unsignedIntersect2by2 Util.java:890, unsignedUnion2by2
:1116, select(long,int) :564, cardinalityInBitmapRange :415,
setBitmapRange :616).

Here the same kernels exist in two host tiers (`utils/bits.py` numpy and
the compiled `native/` tier that actually serves the CPU fast path), so
every row is measured twice: the dispatched kernel as the library runs it
and the `_numpy` twin, making the native tier's win (or loss — see
lower_bound, where ctypes overhead loses to np.searchsorted) a recorded
number instead of a docstring claim.
"""

from __future__ import annotations

from typing import List

import numpy as np

from roaringbitmap_tpu.utils import bits

from . import common
from .common import Result


def _sorted_u16(rng, n: int) -> np.ndarray:
    return np.sort(rng.choice(1 << 16, size=n, replace=False)).astype(np.uint16)


def run(reps: int = 20, datasets=None, **_) -> List[Result]:
    rng = np.random.default_rng(0xFEEF1F0)
    out: List[Result] = []
    # touch a dispatched kernel once so the trampoline resolves and
    # backend_tier() reports the tier that actually served the timings
    bits.cardinality_of_words(bits.new_words())
    from roaringbitmap_tpu import native

    tier = native.backend_tier()

    def bench(name, fn, check=None, extra=None):
        if check is not None:
            assert check(fn()), name
        meta = {"tier": tier}
        meta.update(extra or {})
        out.append(Result(name, "synthetic", common.min_of(reps, fn), "ns/op", meta))

    def both(name, native_fn, numpy_fn, check=None, extra=None):
        # the two tiers must compute the same thing before their timings
        # are published as comparable rows
        res_native, res_numpy = native_fn(), numpy_fn()
        if isinstance(res_native, np.ndarray):
            assert np.array_equal(res_native, res_numpy), name
        elif isinstance(res_native, tuple):
            assert all(np.array_equal(a, b) for a, b in zip(res_native, res_numpy)), name
        else:
            assert res_native == res_numpy, name
        if check is not None:
            assert check(res_numpy), name + "_numpy"
        bench(name, native_fn, check=check, extra=extra)
        out.append(
            Result(
                name + "_numpy",
                "synthetic",
                common.min_of(reps, numpy_fn),
                "ns/op",
                dict(extra or {}, tier="numpy"),
            )
        )

    # --- sorted-array kernels (galloping intersect / merges), two density
    # regimes like the reference's best/worst-case matrices: similar-sized
    # operands and a 50x size skew (where galloping pays off)
    a = _sorted_u16(rng, 4000)
    b = _sorted_u16(rng, 3000)
    tiny = _sorted_u16(rng, 80)
    expect_and = np.intersect1d(a.astype(np.int64), b.astype(np.int64)).size

    both(
        "intersect_balanced",
        lambda: bits.intersect_sorted(a, b),
        lambda: bits.intersect_sorted_numpy(a, b),
        check=lambda r: r.size == expect_and,
        extra={"n": int(a.size + b.size)},
    )
    both(
        "intersect_skewed",
        lambda: bits.intersect_sorted(tiny, a),
        lambda: bits.intersect_sorted_numpy(tiny, a),
    )
    both(
        "union2by2",
        lambda: bits.merge_sorted_unique(a, b),
        lambda: bits.merge_sorted_unique_numpy(a, b),
        check=lambda r: r.size == np.union1d(a.astype(np.int64), b.astype(np.int64)).size,
    )
    both(
        "xor2by2",
        lambda: bits.xor_sorted(a, b),
        lambda: bits.xor_sorted_numpy(a, b),
    )
    both(
        "difference2by2",
        lambda: bits.difference_sorted(a, b),
        lambda: bits.difference_sorted_numpy(a, b),
    )
    both(
        "lower_bound",
        lambda: bits.lower_bound(a, 30_000),
        lambda: bits.lower_bound_numpy(a, 30_000),
    )

    # --- word-bitmap kernels over the 1024-word container form
    dense_vals = np.sort(rng.choice(1 << 16, size=40_000, replace=False)).astype(np.uint16)
    words = bits.words_from_values(dense_vals)

    both(
        "popcount_container",
        lambda: bits.cardinality_of_words(words),
        lambda: bits.cardinality_of_words_numpy(words),
        check=lambda c: c == dense_vals.size,
    )
    both(
        "cardinalityInBitmapRange",
        lambda: bits.cardinality_in_range(words, 5_000, 60_000),
        lambda: bits.cardinality_in_range_numpy(words, 5_000, 60_000),
    )
    both(
        "select_in_words",
        lambda: bits.select_in_words(words, dense_vals.size // 2),
        lambda: bits.select_in_words_numpy(words, dense_vals.size // 2),
        check=lambda v: v == int(dense_vals[dense_vals.size // 2]),
    )
    both(
        "words_from_values",
        lambda: bits.words_from_values(dense_vals),
        lambda: bits.words_from_values_numpy(dense_vals),
    )
    both(
        "values_from_words",
        lambda: bits.values_from_words(words),
        lambda: bits.values_from_words_numpy(words),
    )
    both(
        "num_runs_in_words",
        lambda: bits.num_runs_in_words(words),
        lambda: bits.num_runs_in_words_numpy(words),
    )

    def set_range():
        w = bits.new_words()
        bits.set_bitmap_range(w, 3_000, 61_000)
        return w

    bench("setBitmapRange", set_range, check=lambda w: bits.cardinality_of_words(w) == 58_000)

    # --- run kernels (interval -> words fill: the 20x native win recorded
    # in BENCH_NOTES; runs_from_values extraction)
    starts = np.sort(rng.choice(1 << 15, size=500, replace=False)).astype(np.uint16) * 2
    ends = starts + 2  # disjoint half-open [start, start+2) intervals, 2 values each
    both(
        "words_from_intervals",
        lambda: bits.words_from_intervals(starts, ends),
        lambda: bits.words_from_intervals_numpy(starts, ends),
        check=lambda w: bits.cardinality_of_words(w) == 1000,
    )
    runny = bits.values_from_words(bits.words_from_intervals(starts, ends)).astype(np.uint16)
    both(
        "runs_from_values",
        lambda: bits.runs_from_values(runny),
        lambda: bits.runs_from_values_numpy(runny),
    )
    return out
