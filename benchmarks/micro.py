"""Facade micro-op suite — twins of the reference's single-op jmh files:
AddOffsetBenchmark, BitmapOfRangeBenchmark, CheckedAddBenchmark,
contains/ (ContainsBenchmark), equals/, deserialization/,
RangeOperationBenchmark, SelectTopValuesBenchmark, AdvanceIfNeededBenchmark
(jmh/src/jmh/java/org/roaringbitmap/).
"""

from __future__ import annotations

from typing import List

import numpy as np

from roaringbitmap_tpu import RoaringBitmap

from . import common
from .common import Result


def run(reps: int = 10, datasets=None, **_) -> List[Result]:
    rng = np.random.default_rng(0xFEEF1F0)
    out: List[Result] = []

    def bench(name, fn, dataset="synthetic", extra=None):
        out.append(Result(name, dataset, common.min_of(reps, fn), "ns/op", extra or {}))

    mixed = RoaringBitmap(
        np.unique(
            np.concatenate(
                [
                    rng.choice(1 << 22, size=50_000, replace=False),
                    np.arange(1 << 20, (1 << 20) + 30_000),
                ]
            )
        ).astype(np.uint32)
    )
    mixed.run_optimize()
    twin = RoaringBitmap.deserialize(mixed.serialize())

    # addOffset (RoaringBitmap.addOffset, AddOffsetBenchmark)
    bench("addOffset_aligned", lambda: RoaringBitmap.add_offset(mixed, 1 << 16))
    bench("addOffset_unaligned", lambda: RoaringBitmap.add_offset(mixed, 12_345))
    bench("addOffset_negative", lambda: RoaringBitmap.add_offset(mixed, -12_345))

    # bitmapOfRange (BitmapOfRangeBenchmark)
    bench("bitmapOfRange_small", lambda: RoaringBitmap.bitmap_of_range(1000, 70_000))
    bench("bitmapOfRange_large", lambda: RoaringBitmap.bitmap_of_range(0, 1 << 26))

    # checkedAdd / checkedRemove (CheckedAddBenchmark)
    def checked_add():
        bm = mixed.clone()
        for v in range(0, 100_000, 997):
            bm.checked_add(v)

    bench("checkedAdd", checked_add)

    # contains: hit + miss probes (contains/ suite)
    arr = mixed.to_array()
    hits = arr[:: max(1, arr.size // 1000)][:1000].tolist()
    misses = [int(v) for v in rng.integers(1 << 23, 1 << 24, size=1000)]
    bench("contains_hit_x1000", lambda: [mixed.contains(v) for v in hits])
    bench("contains_miss_x1000", lambda: [mixed.contains(v) for v in misses])
    lo, hi = int(arr[100]), int(arr[-100])
    bench("containsRange", lambda: mixed.contains_range(lo, lo + 1000))

    # equals (equals/ suite): equal pair + first-container mismatch
    near = mixed.clone()
    near.flip_range(0, 1)
    bench("equals_identical", lambda: mixed == twin)
    bench("equals_differFirst", lambda: mixed == near)

    # serialization + deserialization (deserialization/ suite)
    data = mixed.serialize()
    bench("serialize", lambda: mixed.serialize(), extra={"bytes": len(data)})
    bench("deserialize", lambda: RoaringBitmap.deserialize(data))

    # rangeCardinality + range ops (RangeOperationBenchmark, TestRangeCardinality)
    bench("rangeCardinality", lambda: mixed.range_cardinality(lo, hi))

    # containsRange vs the rank-pair route (range/ContainsRange.java:
    # contains() vs containsViaRank())
    r_lo, r_hi = int(arr[100]), int(arr[100]) + 1000
    assert mixed.contains_range(r_lo, r_hi) == (
        mixed.rank_long(r_hi - 1) - mixed.rank_long(r_lo - 1) == r_hi - r_lo
    )
    bench("containsRange_viaRank", lambda: mixed.rank_long(r_hi - 1) - mixed.rank_long(r_lo - 1) == r_hi - r_lo)

    # bitmap concatenation (iteration/Concatenation.java: shift-and-or via
    # addOffset vs rebuilding from values)
    piece = RoaringBitmap(np.arange(0, 50_000, 3, dtype=np.uint32))

    def concat_offset():
        out_bm = mixed.clone()
        out_bm.ior(RoaringBitmap.add_offset(piece, 1 << 23))
        return out_bm

    def concat_naive():
        return RoaringBitmap(
            np.concatenate([mixed.to_array(), piece.to_array().astype(np.int64) + (1 << 23)]).astype(np.uint32)
        )

    assert concat_offset() == concat_naive()
    bench("concatenate_viaOffset", concat_offset)
    bench("concatenate_naive", concat_naive)

    def flip_range():
        bm = mixed.clone()
        bm.flip_range(lo, hi)

    bench("flipRange", flip_range)

    # select/top values (SelectTopValuesBenchmark)
    card = mixed.get_cardinality()
    bench("select_spread_x100", lambda: [mixed.select(j) for j in range(0, card, max(1, card // 100))])
    # bulk order-statistic twins: whole probe arrays in one vectorized pass
    rank_probes = np.asarray(hits, dtype=np.uint32)
    sel_ranks = np.arange(0, card, max(1, card // 1000), dtype=np.int64)
    assert mixed.rank_many(rank_probes).tolist() == [mixed.rank_long(int(v)) for v in hits]
    bench("rankMany_x1000", lambda: mixed.rank_many(rank_probes))
    bench("selectMany_x1000", lambda: mixed.select_many(sel_ranks))
    bench("limit_1000", lambda: mixed.limit(1000))

    # first/last/next (BitmapNextBenchmark)
    bench("nextValue_x1000", lambda: [mixed.next_value(v + 1) for v in hits])
    bench("nextAbsentValue_x1000", lambda: [mixed.next_absent_value(v) for v in hits])

    # value mapping (map/MapBenchmark.java: apply int->int to every member
    # into a fresh bitmap; the reference walks forEach + add). The
    # vectorized twin is the TPU-idiomatic path: to_array -> numpy -> bulk
    # constructor.
    def map_foreach():
        out_bm = RoaringBitmap()
        mixed.for_each(lambda x: out_bm.add((x * 3) % 77_333_333))
        return out_bm

    def map_vectorized():
        return RoaringBitmap((mixed.to_array().astype(np.uint64) * 3) % 77_333_333)

    assert map_foreach() == map_vectorized()
    # forEach pays ~700 ms of per-value adds; cap its reps so the suite's
    # wall clock stays bounded (min-of timing needs few reps to converge)
    out.append(
        Result(
            "mapValues_forEach",
            "synthetic",
            common.min_of(max(1, reps // 5), map_foreach),
            "ns/op",
        )
    )
    bench("mapValues_vectorized", map_vectorized)

    # combined cardinalities (inclusion-exclusion over one and_cardinality
    # walk, like the reference) vs materialize-then-count baselines
    # (combinedcardinality/CombinedCardinalityBenchmark)
    other = RoaringBitmap(
        np.unique(rng.integers(0, 1 << 22, size=60_000)).astype(np.uint32)
    )
    for name, fused, baseline in (
        (
            "orCardinality",
            lambda: RoaringBitmap.or_cardinality(mixed, other),
            lambda: RoaringBitmap.or_(mixed, other).get_cardinality(),
        ),
        (
            "xorCardinality",
            lambda: RoaringBitmap.xor_cardinality(mixed, other),
            lambda: RoaringBitmap.xor(mixed, other).get_cardinality(),
        ),
        (
            "andNotCardinality",
            lambda: RoaringBitmap.andnot_cardinality(mixed, other),
            lambda: RoaringBitmap.andnot(mixed, other).get_cardinality(),
        ),
    ):
        assert fused() == baseline(), name
        bench(name, fused)
        bench(f"{name}Materialized", baseline)
    return out
